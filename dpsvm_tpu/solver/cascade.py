"""Three-stage cascade solver: approx warm-start -> SV screening ->
exact dual polish (``SVMConfig.solver = "cascade"``, docs/APPROX.md
"Cascade").

The bench record prices the trade this module closes: the approx
primal solver is ~7x faster than the exact dual solver at 100k rows
but gives up a fraction of a percent of accuracy. The cascade spends
the cheap approx solution to PREDICT the support-vector set, then buys
exactness back on a subproblem a fraction of the size:

1. **approx warm-start** — ``approx-rff`` (RBF) / ``approx-nystrom``
   (other vector kernels) trained to a LOOSE tolerance, in memory or
   out of core (``fit_approx_stream`` — the data never materializes);
2. **SV screening** (``approx/screening.py``) — every row scored with
   the approx decision function, streamed shard-by-shard through
   ``data/stream.py`` for shard-directory datasets; the margins are
   first CALIBRATED against a small exact probe solve (the squared-
   hinge approx compresses them ~0.67x — ``screening.margin_scale``),
   rows clearing the rescaled band ``y f > 1 + screen_margin`` are
   dropped, a hard cap (``screen_cap``, derived from
   ``--mem-budget-mb`` when set) bounds the survivors, and the
   SCREENED SUBPROBLEM is the thing that must fit in memory;
3. **exact dual polish** — ``api.warm_start`` (the refinement
   mechanism the polish schedule already uses) runs the exact
   SMO/decomposition solver on the kept rows, then every screened-OUT
   row is KKT-verified against the polished model (``alpha = 0``
   demands ``y f >= 1 - 2 epsilon``) and violators are re-admitted
   for a bounded number of repair rounds — the safety net that makes
   the result exact, not approximate. The first round enters from
   ZERO duals (a margin-implied ramp start was measured and rejected:
   under the reference's independent clip it converges to a visibly
   DRIFTED relaxation — see the inline note at stage 2); repair
   rounds warm-start from the previous round's polished alphas.

The combination is the "polishing" move of "Recipe for Fast
Large-scale SVM Training" (arXiv:2207.01016) plus the parallel
adaptive shrinking screen of arXiv:1406.5161.

Resume contract: with ``checkpoint_path`` set, every stage boundary
lands a durable state file (``<path>.cascade.npz`` + the stage-1
approx model beside it) and a re-run of the same command auto-resumes
at the last completed boundary — bitwise-identically, because each
stage is a deterministic function of the previous boundary's artifact
(the saved approx model reloads bit-exactly, screening is pure NumPy
over it, and each polish round re-derives f from its warm-start alphas
via one fresh kernel pass). ``DPSVM_FAULT_CASCADE_STOP_STAGE=k`` is
the deterministic kill point the drill tests use. Stage files are
removed on success.

Tracing: ``trace_out`` records ONE cascade trace — manifest
(solver="cascade"), ``screen``/``polish``/``readmit`` events
(vocabulary + ordering rules in ``observability/schema.py``) and a
summary whose phase split (approx/screen/polish/verify) ``dpsvm
report`` renders. The stage sub-runs are internal and do not write
traces of their own.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from dpsvm_tpu.approx import screening
from dpsvm_tpu.config import (SCREEN_MARGIN_DEFAULT, SVMConfig,
                              TrainResult)
from dpsvm_tpu.models.svm import SVMModel
from dpsvm_tpu.resilience import faultinject

# Repair-round bound: every round re-admits ALL current violators, so
# the kept set grows monotonically and the loop converges in one or
# two rounds on anything but an adversarially mis-screened problem;
# exhausting the bound raises (never silently returns an inexact
# model).
MAX_READMIT_ROUNDS = 5

# Stage-1 looseness: the approx run only needs to LOCATE the margin,
# not certify it — its gradient-norm tolerance is relaxed to
# max(3 * epsilon, _APPROX_EPS_FLOOR) and its iteration budget capped
# (approx iterations are epochs, not SMO pair steps). The floor is
# measured, not guessed: at 1e-2 the approx margins correlate 0.78
# with the exact ones and screening leaks hundreds of violators into
# the repair loop (whose re-polish costs most of a fresh solve); at
# 3e-3 correlation is 0.90 for ~1.5x the (cheap) approx time — the
# total-cascade optimum on the planted 30k bench shape.
_APPROX_EPS_FLOOR = 3e-3
_APPROX_MAX_ITER = 5000

# Progressive polishing (the adaptive-shrinking move of
# arXiv:1406.5161): the FIRST polish round runs at a loose tolerance
# (_LOOSE_FACTOR * epsilon) and its verify uses the matching slack —
# deep violators (true screening misses) surface and re-admit after
# only the cheap head of the convergence curve, and the expensive
# tail runs ONCE, with the final kept set aboard. Without this, a
# repair round re-converges the whole subproblem from the warm start
# (measured: +45% polish iterations at 100k/C=10 for 121 re-admitted
# rows). The LAST round always runs at the full epsilon; the final
# verify always uses the full 2-epsilon bar.
_LOOSE_FACTOR = 5.0

# Tiered verification: intermediate repair rounds scan only the
# NEAR-BAND WINDOW — screened-out rows whose calibrated margin is
# within _VERIFY_WINDOW of the band edge. Violators are noise-tail
# events of the approx/exact margin correlation (sigma ~0.1-0.15), so
# a 1.0-wide window covers the loose round's extra model bias on top
# (a 0.35 window was measured to MISS 13 loose-round violators, which
# then surfaced at the certification scan and cost a late repair
# round); the FINAL verify (the one a clean full-epsilon round must
# pass to break the loop) always scans every screened-out row, so
# the certificate never depends on the window — a deep-field miss
# just costs one extra round.
_VERIFY_WINDOW = 1.0

# Margin-scale calibration probe (screening.margin_scale): the approx
# stage solves the SQUARED hinge, whose margins are systematically
# compressed relative to the exact hinge dual's (measured ~0.67x on
# the planted bench shapes), so the raw band over-keeps 2-3x the true
# SV set. A small exact solve on _PROBE_ROWS subsampled rows measures
# the compression and the band tests the RESCALED margins. Skipped
# below _PROBE_MIN_N rows, where the probe would be a large fraction
# of the problem and the uncalibrated band is already cheap.
_PROBE_ROWS = 4096
_PROBE_MIN_N = 3 * _PROBE_ROWS
_PROBE_MAX_ITER = 100_000

_STATE_FORMAT = "dpsvm-cascade-state-v1"


class CascadeError(RuntimeError):
    """Base class for cascade orchestration failures."""


class CascadeInterrupted(CascadeError):
    """Raised by the deterministic stage-boundary kill point
    (``DPSVM_FAULT_CASCADE_STOP_STAGE`` — the kill->resume drill).
    The stage state is durable; re-running the same command resumes."""

    def __init__(self, stage: int):
        self.stage = stage
        super().__init__(
            f"cascade stopped after stage-{stage} boundary (injected); "
            "re-run to resume from the durable stage state")


class CascadeRepairError(CascadeError):
    """The re-admission loop exhausted its round budget with KKT
    violators still outstanding — the screening band is too tight for
    this problem; raise ``screen_margin`` (or the cap) and re-run."""


class CascadeStateError(ValueError):
    """A stage-state file on disk does not match this run's problem or
    config — stale state from a different run; delete it to restart."""


def _log(msg: str) -> None:
    print(f"CASCADE: {msg}", file=sys.stderr, flush=True)


@dataclasses.dataclass
class CascadeResult(TrainResult):
    """TrainResult + the cascade's own diagnostics.

    ``n_iter`` sums the approx epochs and every polish round's SMO
    iterations; ``alpha`` is full-length (scattered, zeros for
    screened-out rows) on the in-memory path and kept-length on the
    streaming path (where the full vector has nowhere to live).
    """

    n_total: int = 0            # dataset rows screened
    n_band: int = 0             # rows inside the margin band
    n_kept: int = 0             # final exact-subproblem rows
    readmit_rounds: int = 0     # polish rounds run (1 = no repair)
    n_readmitted: int = 0       # rows the KKT verify re-admitted
    kkt_violators: int = 0      # violators after the last round (0 on
                                # success — the exactness certificate)
    approx_iters: int = 0
    polish_iters: int = 0
    stage_seconds: dict = dataclasses.field(default_factory=dict)


# ---------------------------------------------------------------------
# data sources: one screening/verify contract for arrays and shard dirs
# ---------------------------------------------------------------------

class _ArraySource:
    """In-memory (x, y): blocks are fixed-size slices."""

    kind = "memory"

    def __init__(self, x: np.ndarray, y: np.ndarray, block: int = 8192):
        self.x = x
        self.y = np.asarray(y)
        self.n, self.d = x.shape
        self.block = block
        self.notify_quarantine: Optional[Callable] = None

    def fit_approx(self, cfg: SVMConfig, init_w=None):
        from dpsvm_tpu.approx.primal import fit_approx
        return fit_approx(self.x, self.y, cfg, init_w=init_w)

    def blocks(self, model) -> Iterator[Tuple[int, np.ndarray,
                                              np.ndarray, np.ndarray]]:
        from dpsvm_tpu.models.svm import decision_function
        for lo in range(0, self.n, self.block):
            hi = min(lo + self.block, self.n)
            xb = self.x[lo:hi]
            yield lo, xb, self.y[lo:hi], np.asarray(
                decision_function(model, xb))

    def iter_out(self, model, kept_idx: np.ndarray,
                 window_idx: Optional[np.ndarray] = None):
        """(global idx, x, y, decisions) over the screened-OUT rows
        only — the KKT verify pass. Scoring just the complement saves
        the kept fraction of every verify sweep (measured: the
        all-rows verify was 50 s of a 205 s 100k cascade). With
        ``window_idx`` the scan narrows further to those rows minus
        the kept set (the tiered intermediate verify)."""
        from dpsvm_tpu.models.svm import decision_function
        if window_idx is not None:
            mask = np.zeros(self.n, bool)
            mask[window_idx] = True
        else:
            mask = np.ones(self.n, bool)
        mask[kept_idx] = False
        out_idx = np.flatnonzero(mask)
        if not len(out_idx):
            return
        x_out = np.ascontiguousarray(self.x[out_idx])
        y_out = np.asarray(self.y)[out_idx]
        dec = np.asarray(decision_function(model, x_out))
        for lo in range(0, len(out_idx), self.block):
            hi = min(lo + self.block, len(out_idx))
            yield (out_idx[lo:hi], x_out[lo:hi], y_out[lo:hi],
                   dec[lo:hi])

    def gather(self, idx: np.ndarray):
        return (np.ascontiguousarray(self.x[idx]),
                np.asarray(self.y)[idx])


class _ShardSource:
    """A ``data.stream.ShardedDataset``: blocks are shards, read
    through the integrity-checked policy path — screening works on
    datasets that never fit in memory, and a quarantined shard drops
    out of every pass exactly as it does in streaming training."""

    kind = "stream"

    def __init__(self, ds, config: SVMConfig, allow_nonfinite: bool):
        self.ds = ds
        self.n, self.d = ds.n, ds.d
        self.policy = config.on_bad_shard
        self.allow_nonfinite = allow_nonfinite
        self.notify_quarantine: Optional[Callable] = None

    def _read(self, k: int):
        return self.ds.read_shard_checked(
            k, on_bad_shard=self.policy,
            allow_nonfinite=self.allow_nonfinite,
            on_quarantine=self.notify_quarantine)

    def fit_approx(self, cfg: SVMConfig, init_w=None):
        from dpsvm_tpu.approx.primal import fit_approx_stream
        return fit_approx_stream(self.ds, cfg, task="svc",
                                 allow_nonfinite=self.allow_nonfinite,
                                 init_w=init_w)

    def blocks(self, model):
        from dpsvm_tpu.models.svm import decision_function
        for k in range(self.ds.n_shards):
            got = self._read(k)
            if got is None:
                continue
            xk, yk = got
            yield (self.ds.row_offset(k), xk, yk,
                   np.asarray(decision_function(model, xk)))

    def iter_out(self, model, kept_idx: np.ndarray,
                 window_idx: Optional[np.ndarray] = None):
        """Screened-out rows per shard. Decisions are computed on the
        FULL fixed-shape shard block (the compile-economy contract:
        one program per shard geometry) and subset on the host — only
        the host-side work shrinks here, unlike the in-memory path.
        With ``window_idx``, shards holding no window rows are not
        even read (the tiered intermediate verify skips their I/O)."""
        from dpsvm_tpu.models.svm import decision_function
        for k in range(self.ds.n_shards):
            base = self.ds.row_offset(k)
            rows_k = self.ds.shard_rows(k)
            if window_idx is not None:
                wlo = np.searchsorted(window_idx, base)
                whi = np.searchsorted(window_idx, base + rows_k)
                if wlo == whi:
                    continue
            got = self._read(k)
            if got is None:
                continue
            xk, yk = got
            if window_idx is not None:
                mask = np.zeros(len(yk), bool)
                mask[window_idx[wlo:whi] - base] = True
            else:
                mask = np.ones(len(yk), bool)
            lo = np.searchsorted(kept_idx, base)
            hi = np.searchsorted(kept_idx, base + rows_k)
            mask[kept_idx[lo:hi] - base] = False
            if not mask.any():
                continue
            dec = np.asarray(decision_function(model, xk))
            yield (base + np.flatnonzero(mask), xk[mask],
                   np.asarray(yk)[mask], dec[mask])

    def gather(self, idx: np.ndarray):
        """Rows at sorted global ``idx``, one shard sweep (reads only
        the shards that hold kept rows)."""
        idx = np.asarray(idx, np.int64)
        out_x = np.empty((len(idx), self.d), np.float32)
        out_y = None
        rps = self.ds.rows_per_shard
        for k in range(self.ds.n_shards):
            base = self.ds.row_offset(k)
            lo = np.searchsorted(idx, base)
            hi = np.searchsorted(idx, base + rps)
            if lo == hi:
                continue
            got = self._read(k)
            if got is None:
                raise CascadeError(
                    f"shard {k} holds {hi - lo} screened-in row(s) but "
                    "is unreadable/quarantined — the kept subproblem "
                    "cannot be assembled (re-screen after repairing "
                    "the shard)")
            xk, yk = got
            local = idx[lo:hi] - base
            out_x[lo:hi] = xk[local]
            if out_y is None:
                out_y = np.empty((len(idx),), np.asarray(yk).dtype)
            out_y[lo:hi] = np.asarray(yk)[local]
        if out_y is None:
            raise CascadeError("no kept rows could be gathered")
        return out_x, out_y


# ---------------------------------------------------------------------
# stage-boundary state (the kill->resume contract)
# ---------------------------------------------------------------------

class _StageState:
    """Durable stage-boundary state under ``checkpoint_path``.

    ``<path>.cascade.npz`` carries the stage number, the config/problem
    fingerprint, the kept set + alphas, and the counters; the stage-1
    approx model lives beside it (``<path>.cascade.approx.npz``, the
    ordinary approx model format — reloads bit-exactly). Writes are
    atomic (tmp + rename, the checkpoint writer's policy)."""

    def __init__(self, base: str, fingerprint: dict):
        self.path = base + ".cascade.npz"
        self.approx_path = base + ".cascade.approx.npz"
        self.fingerprint = fingerprint

    def load(self) -> Optional[dict]:
        if not os.path.exists(self.path):
            return None
        try:
            with np.load(self.path, allow_pickle=False) as z:
                if str(z["format"]) != _STATE_FORMAT:
                    raise KeyError("format")
                got = {k: z[k] for k in z.files}
        except Exception as e:
            raise CascadeStateError(
                f"{self.path}: unreadable cascade stage state "
                f"({type(e).__name__}: {e}) — delete it to restart"
            ) from e
        for k, want in self.fingerprint.items():
            if k not in got:
                raise CascadeStateError(
                    f"{self.path}: stage state predates the "
                    f"{k!r} fingerprint field — stale state from an "
                    "older run; delete it to restart")
            have = got[k]
            have = (str(have) if isinstance(want, str)
                    else type(want)(have))
            if have != want:
                raise CascadeStateError(
                    f"{self.path}: stage state was written for "
                    f"{k}={have!r}, this run has {k}={want!r} — stale "
                    "state from a different problem/config; delete it "
                    "to restart")
        st = {"stage": int(got["stage"]),
              "counters": np.asarray(got["counters"], np.int64)}
        if st["stage"] >= 2:
            st["kept_idx"] = np.asarray(got["kept_idx"], np.int64)
            st["alpha"] = np.asarray(got["alpha"], np.float32)
            st["n_band"] = int(got["n_band"])
            st["wnd_idx"] = (np.asarray(got["wnd_idx"], np.int64)
                             if "wnd_idx" in got else None)
        if st["stage"] >= 3:
            st["b_lo"] = float(got["b_lo"])
            st["b_hi"] = float(got["b_hi"])
            st["converged"] = bool(got["converged"])
        _log(f"resuming from stage-{st['stage']} boundary state "
             f"({self.path})")
        return st

    def save(self, stage: int, counters, *, kept_idx=None, alpha=None,
             n_band: int = 0, b_lo: float = 0.0, b_hi: float = 0.0,
             converged: bool = False, wnd_idx=None) -> None:
        arrays = dict(format=np.str_(_STATE_FORMAT),
                      stage=np.int64(stage),
                      counters=np.asarray(counters, np.int64),
                      n_band=np.int64(n_band),
                      b_lo=np.float64(b_lo), b_hi=np.float64(b_hi),
                      converged=np.bool_(converged))
        for k, v in self.fingerprint.items():
            arrays[k] = np.str_(v) if isinstance(v, str) else v
        if kept_idx is not None:
            arrays["kept_idx"] = np.asarray(kept_idx, np.int64)
            arrays["alpha"] = np.asarray(alpha, np.float32)
        if wnd_idx is not None:
            # The tiered-verify window: persisted so a resumed run
            # scans exactly the rows the uninterrupted run would —
            # the bitwise-resume contract covers the repair ORDER.
            arrays["wnd_idx"] = np.asarray(wnd_idx, np.int64)
        import tempfile
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz")
        os.close(fd)
        try:
            np.savez(tmp, **arrays)
            os.replace(tmp, self.path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    def save_approx_model(self, model) -> None:
        from dpsvm_tpu.approx.model import save_approx_model
        save_approx_model(model, self.approx_path)

    def load_approx_model(self):
        from dpsvm_tpu.approx.model import load_approx_model
        return load_approx_model(self.approx_path)

    def cleanup(self) -> None:
        for p in (self.path, self.approx_path):
            try:
                os.unlink(p)
            except OSError:
                pass


def _fingerprint(config: SVMConfig, n: int, d: int, gamma: float,
                 approx_init_w=None) -> dict:
    # The warm-start vector is part of the trajectory's identity: a
    # stage file written under a different (or no) init must read as
    # stale, never silently resume a different cascade.
    import zlib
    init_crc = (0 if approx_init_w is None else zlib.crc32(
        np.ascontiguousarray(approx_init_w, np.float32).tobytes()))
    return dict(n=np.int64(n), d=np.int64(d),
                c=np.float64(config.c), gamma=np.float64(gamma),
                epsilon=np.float64(config.epsilon),
                kernel=str(config.kernel),
                screen_margin=np.float64(config.screen_margin),
                screen_cap=np.int64(config.screen_cap),
                approx_dim=np.int64(config.approx_dim),
                approx_seed=np.int64(config.approx_seed),
                weight_pos=np.float64(config.weight_pos),
                weight_neg=np.float64(config.weight_neg),
                init_crc=np.int64(init_crc))


# ---------------------------------------------------------------------
# stage sub-configs
# ---------------------------------------------------------------------

def _approx_config(config: SVMConfig) -> SVMConfig:
    """Stage-1 sub-config: the matching approx solver at a loose
    tolerance, every dual-family and orchestration knob reset (the
    stage is internal — its artifacts are the warm start, not the
    run's outputs)."""
    kind = "approx-rff" if config.kernel == "rbf" else "approx-nystrom"
    return dataclasses.replace(
        config, solver=kind,
        epsilon=max(3.0 * float(config.epsilon), _APPROX_EPS_FLOOR),
        max_iter=min(int(config.max_iter), _APPROX_MAX_ITER),
        selection="first-order", select_impl="argminmax",
        working_set=2, inner_iters=0, grow_working_set=False,
        shrinking=False, cache_size=0, use_pallas="auto", polish=False,
        screen_margin=SCREEN_MARGIN_DEFAULT, screen_cap=0,
        trace_out=None, checkpoint_path=None, checkpoint_every=0,
        resume_from=None, profile_dir=None, metrics_port=None,
        metrics_out=None, on_divergence="raise", health_window=0)


def _polish_config(config: SVMConfig, budget: int,
                   epsilon: Optional[float] = None) -> SVMConfig:
    """Stage-3 sub-config: the exact dual solver with the user's
    dual-family knobs intact (selection/working_set/shrinking/clip/
    precision all pass through to the subproblem solve). Checkpoint/
    trace/profile stay with the orchestrator; ``on_divergence=
    "rollback"`` degrades to raise (the sub-run has no checkpoint of
    its own — the cascade's stage files are the recovery unit)."""
    shrink = config.shrinking is True
    return dataclasses.replace(
        config, solver="exact", polish=False,
        screen_margin=SCREEN_MARGIN_DEFAULT, screen_cap=0,
        max_iter=int(budget),
        epsilon=(float(epsilon) if epsilon is not None
                 else config.epsilon),
        trace_out=None, checkpoint_path=None, checkpoint_every=0,
        resume_from=None, profile_dir=None, metrics_port=None,
        metrics_out=None,
        # The shrinking manager runs its own dispatch loop, so the
        # shared-driver guards cannot ride it (config.py's shrinking
        # table) — and rollback needs a checkpoint the sub-run does
        # not have (the cascade's stage files are the recovery unit).
        health_window=0 if shrink else config.health_window,
        on_divergence=("raise" if shrink
                       or config.on_divergence == "rollback"
                       else config.on_divergence))


def _calibrate(source, config: SVMConfig, model_a) -> float:
    """The screening calibration factor (see ``_PROBE_ROWS`` comment
    and ``screening.margin_scale``): solve ``_PROBE_ROWS`` subsampled
    rows exactly, compare both models' margins on them. Deterministic
    in ``approx_seed``, so a resumed run re-derives the same band."""
    if source.n < _PROBE_MIN_N:
        return 1.0
    from dpsvm_tpu.api import fit
    from dpsvm_tpu.models.svm import decision_function

    rng = np.random.default_rng(int(config.approx_seed) + 1)
    idx = np.sort(rng.choice(source.n, size=_PROBE_ROWS,
                             replace=False).astype(np.int64))
    xp, yp = source.gather(idx)
    probe_cfg = dataclasses.replace(
        _polish_config(config, min(int(config.max_iter),
                                   _PROBE_MAX_ITER)),
        shards=1, shard_x=True)
    m_probe, r_probe = fit(xp, yp, probe_cfg)
    ypf = np.asarray(yp, np.float32)
    yf_probe = np.asarray(decision_function(m_probe, xp)) * ypf
    yf_a = np.asarray(decision_function(model_a, xp)) * ypf
    scale = screening.margin_scale(yf_probe, yf_a)
    _log(f"calibration probe: {len(idx)} rows, "
         f"{r_probe.n_iter} exact iter(s) -> approx-margin scale "
         f"{scale:.3f}")
    return scale


def _screen_cap(config: SVMConfig, d: int) -> int:
    """The effective stage-2 row cap: the explicit ``screen_cap``,
    tightened by what ``mem_budget_mb`` admits (the screened
    subproblem must materialize — ``data/stream.py`` budget math)."""
    cap = int(config.screen_cap)
    if config.mem_budget_mb:
        from dpsvm_tpu.data.stream import budget_admit_rows
        admits = budget_admit_rows(config.mem_budget_mb, d)
        cap = min(cap, admits) if cap else admits
    return cap


# ---------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------

def _begin_trace(config: SVMConfig, n: int, d: int, gamma: float):
    if not config.trace_out:
        return None
    from dpsvm_tpu.observability.record import RunTrace
    from dpsvm_tpu.solver.driver import trace_env
    return RunTrace(config.trace_out, config=config, n=n, d=d,
                    gamma=gamma, solver="cascade", env=trace_env())


def fit_cascade(x: np.ndarray, y: np.ndarray,
                config: Optional[SVMConfig] = None, *,
                approx_init_w=None
                ) -> Tuple[SVMModel, CascadeResult]:
    """In-memory cascade (module docstring). Returns an ordinary
    ``SVMModel`` plus a ``CascadeResult`` whose ``alpha`` is the
    full-length dual vector (zeros at screened-out rows), so
    ``--check-kkt`` and ``SVMModel.from_train_result`` consume it like
    any exact result."""
    from dpsvm_tpu.api import _check_xy

    config = config or SVMConfig()
    config.validate()
    if config.solver != "cascade":
        raise ValueError("fit_cascade needs solver='cascade'")
    x, y = _check_xy(x, y)
    model, result = _run_cascade(_ArraySource(x, y), config,
                                 approx_init_w=approx_init_w)
    full = np.zeros((x.shape[0],), np.float32)
    full[result._kept_idx] = result.alpha
    result.alpha = full
    return model, result


def fit_cascade_stream(ds, config: Optional[SVMConfig] = None,
                       allow_nonfinite: bool = False, *,
                       approx_init_w=None
                       ) -> Tuple[SVMModel, CascadeResult]:
    """Out-of-core cascade over a ``data.stream.ShardedDataset``: the
    approx stage trains via ``fit_approx_stream``, screening and KKT
    verification sweep shard-by-shard, and only the screened
    subproblem ever materializes (budget-guarded). ``result.alpha`` is
    kept-length — the full vector has nowhere to live."""
    config = config or SVMConfig()
    config.validate()
    if config.solver != "cascade":
        raise ValueError("fit_cascade_stream needs solver='cascade'")
    if config.shards != 1:
        raise ValueError("the streaming cascade is single-process "
                         "(config.shards must be 1), like "
                         "fit_approx_stream")
    return _run_cascade(_ShardSource(ds, config, allow_nonfinite),
                        config, approx_init_w=approx_init_w)


def _run_cascade(source, config: SVMConfig, *,
                 approx_init_w=None
                 ) -> Tuple[SVMModel, CascadeResult]:
    n, d = source.n, source.d
    gamma = float(config.resolve_gamma(d))
    margin = float(config.screen_margin)
    kkt_tol = 2.0 * float(config.epsilon)
    t_start = time.perf_counter()
    phases = {"approx": 0.0, "screen": 0.0, "polish": 0.0,
              "verify": 0.0}
    plan = faultinject.current()
    state = (_StageState(config.checkpoint_path,
                         _fingerprint(config, n, d, gamma,
                                      approx_init_w))
             if config.checkpoint_path else None)
    st = state.load() if state is not None else None
    trace = _begin_trace(config, n, d, gamma)
    if trace is not None and source.kind == "stream":
        source.notify_quarantine = (
            lambda k, reason: trace.event(
                "quarantine", shard=int(k), reason=reason,
                rows=source.ds.shard_rows(k)))
    try:
        if st is not None and trace is not None:
            trace.event("cascade_resume", stage=int(st["stage"]))

        # -- stage 1: approx warm-start ----------------------------
        approx_iters = 0
        model_a = None
        if st is None:
            t0 = time.perf_counter()
            model_a, res_a = source.fit_approx(_approx_config(config),
                                               init_w=approx_init_w)
            approx_iters = int(res_a.n_iter)
            phases["approx"] = time.perf_counter() - t0
            _log(f"approx warm-start: {approx_iters} iter(s) in "
                 f"{phases['approx']:.2f}s "
                 f"(converged={res_a.converged})")
            if state is not None:
                state.save_approx_model(model_a)
                state.save(1, [approx_iters, 0, 0, 0])
                if plan is not None and plan.cascade_stop_now(1):
                    raise CascadeInterrupted(1)
        else:
            approx_iters = int(st["counters"][0])
            if st["stage"] == 1:
                model_a = state.load_approx_model()

        # -- stage 2: margin-band screening ------------------------
        if st is not None and st["stage"] >= 2:
            kept_idx = st["kept_idx"]
            alpha = st["alpha"]
            n_band = int(st["n_band"])
            wnd_idx = st.get("wnd_idx")
        else:
            t0 = time.perf_counter()
            # Calibrate the band: the squared-hinge approx margins are
            # scale-compressed vs the exact hinge dual's; the band
            # tests the RESCALED margin yf / scale (see _PROBE_ROWS).
            scale = _calibrate(source, config, model_a)
            band_idx_parts, band_yf_parts = [], []
            wnd_parts = []
            # Fallback pair: the 2 globally worst-margin rows, so a
            # too-tight band can never leave the SMO pair solver an
            # empty subproblem.
            worst: list = []
            for off, _xb, yb, dec in source.blocks(model_a):
                yf = (np.asarray(dec, np.float32)
                      * np.asarray(yb, np.float32)
                      / np.float32(scale))
                keep = yf <= np.float32(1.0 + margin)
                band_idx_parts.append(off + np.flatnonzero(keep))
                band_yf_parts.append(yf[keep])
                wnd_parts.append(off + np.flatnonzero(
                    yf <= np.float32(1.0 + margin + _VERIFY_WINDOW)))
                for j in np.argsort(yf, kind="stable")[:2]:
                    worst.append((float(yf[j]), off + int(j)))
                worst = sorted(worst)[:2]
            wnd_idx = (np.concatenate(wnd_parts) if wnd_parts
                       else np.empty(0, np.int64))
            band_idx = (np.concatenate(band_idx_parts)
                        if band_idx_parts else np.empty(0, np.int64))
            band_yf = (np.concatenate(band_yf_parts)
                       if band_yf_parts else np.empty(0, np.float32))
            n_band = int(len(band_idx))
            if n_band < 2:
                extra = np.array(sorted(i for _v, i in worst),
                                 np.int64)
                extra_yf = np.array(
                    [v for v, _i in sorted(worst)], np.float32)
                mask = ~np.isin(extra, band_idx)
                band_idx = np.concatenate([band_idx, extra[mask]])
                band_yf = np.concatenate([band_yf, extra_yf[mask]])
                order = np.argsort(band_idx, kind="stable")
                band_idx, band_yf = band_idx[order], band_yf[order]
            cap = _screen_cap(config, d)
            kept_idx, capped = screening.apply_cap(band_idx, band_yf,
                                                   cap)
            from dpsvm_tpu.data.stream import (_fmt_mb,
                                               check_materialize_budget,
                                               materialize_bytes)
            check_materialize_budget(
                config.mem_budget_mb, n=len(kept_idx), d=d,
                what="cascade screened subproblem")
            msg = (f"screen: kept {len(kept_idx):,}/{n:,} rows "
                   f"(band {n_band:,} at margin <= "
                   f"{scale:g}*(1+{margin:g})"
                   + (f", capped to {cap:,}" if capped else "") + ")")
            if config.mem_budget_mb:
                msg += (f" — screened subproblem "
                        f"{_fmt_mb(materialize_bytes(len(kept_idx), d))}"
                        f" fits --mem-budget-mb "
                        f"{config.mem_budget_mb:g}")
            _log(msg)
            x_kept, y_kept = source.gather(kept_idx)
            # The polish enters from ZERO duals — the classic SMO
            # init. A warm start at "alphas implied by the approx
            # margins" was built and measured, and REJECTED: with the
            # reference's independent clip the injected point
            # converges (fast) to a KKT point of a visibly drifted
            # relaxation — sum(alpha y) landed at -296 vs the
            # from-zero run's -3.9, a 13.9 max decision delta vs
            # 0.011 — and damping the ramp only shrinks, never
            # removes, the drift. Zero is on the constraint, and the
            # SUBPROBLEM (not the start) is where the cascade's
            # speedup lives. Repair rounds DO warm-start: the
            # previous round's polished alphas extend with zeros,
            # which preserves their constraint value exactly.
            alpha = np.zeros((len(kept_idx),), np.float32)
            phases["screen"] = time.perf_counter() - t0
            if trace is not None:
                trace.event("screen", n_iter=approx_iters,
                            n_kept=int(len(kept_idx)), n_total=int(n),
                            band=n_band, scale=round(float(scale), 4),
                            capped=bool(capped))
            if state is not None:
                state.save(2, [approx_iters, 0, 0, 0],
                           kept_idx=kept_idx, alpha=alpha,
                           n_band=n_band, wnd_idx=wnd_idx)
                if plan is not None and plan.cascade_stop_now(2):
                    raise CascadeInterrupted(2)
        if st is not None and st["stage"] >= 2:
            x_kept, y_kept = source.gather(kept_idx)
            if trace is not None:
                trace.event("screen", n_iter=approx_iters,
                            n_kept=int(len(kept_idx)), n_total=int(n),
                            band=n_band, resumed=True)

        # -- stage 3: exact polish + KKT re-admission repair -------
        from dpsvm_tpu.api import warm_start

        counters = (st["counters"] if st is not None
                    else np.array([approx_iters, 0, 0, 0], np.int64))
        polish_iters = int(counters[1])
        rounds_done = int(counters[2])
        readmitted_total = int(counters[3])
        res_p: Optional[TrainResult] = None
        need_polish = True
        if st is not None and st["stage"] >= 3:
            # The saved round's outcome IS the polished state — do not
            # re-run the solver (an incremental-f trajectory and a
            # fresh-f recompute differ in low-order bits; reusing the
            # artifact is what makes resume bitwise).
            res_p = TrainResult(
                alpha=alpha, b=(st["b_lo"] + st["b_hi"]) / 2.0,
                n_iter=polish_iters, converged=st["converged"],
                b_lo=st["b_lo"], b_hi=st["b_hi"], train_seconds=0.0,
                gamma=gamma, n_sv=int(np.sum(alpha > 0)),
                kernel=config.kernel, coef0=float(config.coef0),
                degree=int(config.degree))
            need_polish = False
        last_vio = 0
        while True:
            # Progressive schedule (see _LOOSE_FACTOR): round 1 runs
            # loose, every later round at the full epsilon. Both the
            # round's solve tolerance and its verify slack derive from
            # rounds_done alone, so a stage-3 resume re-derives them.
            if need_polish:
                budget = int(config.max_iter) - polish_iters
                if budget <= 0:
                    _log("polish budget exhausted (max_iter); "
                         "returning the last round unrepaired")
                    break
                round_eps = (float(config.epsilon) * _LOOSE_FACTOR
                             if rounds_done == 0 else
                             float(config.epsilon))
                t0 = time.perf_counter()
                res_p = warm_start(x_kept, y_kept, alpha,
                                   _polish_config(config, budget,
                                                  epsilon=round_eps))
                phases["polish"] += time.perf_counter() - t0
                alpha = np.asarray(res_p.alpha, np.float32)
                polish_iters += int(res_p.n_iter)
                rounds_done += 1
                _log(f"polish round {rounds_done}: "
                     f"{res_p.n_iter} iter(s) on {len(kept_idx):,} "
                     f"rows at eps={round_eps:g} "
                     f"(converged={res_p.converged})")
                if trace is not None:
                    trace.event("polish",
                                n_iter=approx_iters + polish_iters,
                                round=rounds_done,
                                n_kept=int(len(kept_idx)),
                                converged=bool(res_p.converged))
                if state is not None:
                    state.save(3, [approx_iters, polish_iters,
                                   rounds_done, readmitted_total],
                               kept_idx=kept_idx, alpha=alpha,
                               n_band=n_band, b_lo=res_p.b_lo,
                               b_hi=res_p.b_hi,
                               converged=res_p.converged,
                               wnd_idx=wnd_idx)
                    if (plan is not None
                            and plan.cascade_stop_now(3)):
                        raise CascadeInterrupted(3)
            need_polish = True
            model = SVMModel.from_train_result(
                x_kept, y_kept, dataclasses.replace(res_p, alpha=alpha))
            # KKT verify of the screened-OUT rows: alpha = 0 demands
            # y f >= 1 - 2 eps against the polished model. A LOOSE
            # round's model only certifies its own looser slack, so
            # its verify uses the matching tolerance — it exists to
            # surface DEEP violators (true screening misses) before
            # the expensive convergence tail, not to certify.
            round_was_loose = rounds_done == 1
            tol_r = kkt_tol * (_LOOSE_FACTOR if round_was_loose
                               else 1.0)
            t0 = time.perf_counter()

            def _scan(window):
                parts = ([], [], [])
                for oidx, xb, yb, dec in source.iter_out(
                        model, kept_idx, window_idx=window):
                    bad = screening.kkt_zero_violations(dec, yb, tol_r)
                    if bad.any():
                        parts[0].append(oidx[bad])
                        parts[1].append(np.asarray(xb)[bad])
                        parts[2].append(np.asarray(yb)[bad])
                return parts

            # Tiered verify (_VERIFY_WINDOW): scan the near-band
            # window first; only a clean FULL-epsilon round pays the
            # full certification scan — the break below can only
            # follow a clean scan of EVERY screened-out row. After a
            # full-epsilon round whose readmission was tiny (the
            # model barely moved), the window tier is almost surely
            # clean too — go straight to the certification scan
            # instead of paying both.
            tiny_repair = (not round_was_loose
                           and 0 <= last_vio <= 8 and rounds_done > 1)
            use_window = wnd_idx is not None and not tiny_repair
            vio_idx_parts, vio_x, vio_y = (
                _scan(wnd_idx) if use_window else _scan(None))
            if (not vio_idx_parts and use_window
                    and not round_was_loose):
                vio_idx_parts, vio_x, vio_y = _scan(None)
            phases["verify"] += time.perf_counter() - t0
            n_vio = sum(len(p) for p in vio_idx_parts)
            last_vio = int(n_vio)
            if n_vio == 0:
                if not round_was_loose:
                    break
                # Loose round came back clean: the full-epsilon round
                # is still owed (it pays only the convergence tail,
                # warm-started from the loose optimum).
                continue
            if rounds_done >= MAX_READMIT_ROUNDS:
                raise CascadeRepairError(
                    f"{n_vio} screened-out row(s) still violate the "
                    f"zero-alpha KKT condition after "
                    f"{MAX_READMIT_ROUNDS} repair rounds — the "
                    f"screening band (screen_margin={margin:g}"
                    + (f", screen_cap={config.screen_cap}"
                       if config.screen_cap else "") +
                    ") is too tight for this problem; widen it and "
                    "re-run")
            new_idx = np.concatenate(vio_idx_parts)
            new_x = np.concatenate(vio_x)
            new_y = np.concatenate(vio_y)
            all_idx = np.concatenate([kept_idx, new_idx])
            order = np.argsort(all_idx, kind="stable")
            kept_idx = all_idx[order]
            x_kept = np.concatenate([x_kept, new_x])[order]
            y_kept = np.concatenate([np.asarray(y_kept),
                                     new_y])[order]
            # Warm restart: previous polished alphas, zeros for the
            # re-admitted rows (extends the dual feasibly WITHOUT
            # moving its equality-constraint value — see the zero-
            # start note at stage 2).
            alpha = np.concatenate(
                [alpha, np.zeros((len(new_idx),), np.float32)])[order]
            readmitted_total += int(n_vio)
            _log(f"readmit round {rounds_done}: {n_vio} KKT "
                 f"violator(s) re-admitted (kept now "
                 f"{len(kept_idx):,})")
            if trace is not None:
                trace.event("readmit",
                            n_iter=approx_iters + polish_iters,
                            round=rounds_done,
                            n_readmitted=int(n_vio))

        # -- finish ------------------------------------------------
        train_seconds = time.perf_counter() - t_start
        converged = bool(res_p is not None and res_p.converged
                         and last_vio == 0
                         # a budget-stopped run whose only round was
                         # the loose one is NOT certified at epsilon
                         and rounds_done >= 2)
        model = SVMModel.from_train_result(
            x_kept, y_kept, dataclasses.replace(
                res_p if res_p is not None else _empty_result(
                    gamma, config), alpha=alpha))
        result = CascadeResult(
            alpha=alpha,
            b=float(res_p.b) if res_p is not None else 0.0,
            n_iter=approx_iters + polish_iters,
            converged=converged,
            b_lo=float(res_p.b_lo) if res_p is not None else 0.0,
            b_hi=float(res_p.b_hi) if res_p is not None else 0.0,
            train_seconds=train_seconds,
            gamma=gamma, n_sv=model.n_sv, kernel=config.kernel,
            coef0=float(config.coef0), degree=int(config.degree),
            n_total=int(n), n_band=int(n_band),
            n_kept=int(len(kept_idx)),
            readmit_rounds=rounds_done,
            n_readmitted=readmitted_total,
            kkt_violators=last_vio,
            approx_iters=approx_iters, polish_iters=polish_iters,
            stage_seconds=dict(phases))
        result._kept_idx = kept_idx        # fit_cascade scatters
        if trace is not None:
            trace.summary(converged=result.converged,
                          n_iter=result.n_iter, b=result.b,
                          b_lo=result.b_lo, b_hi=result.b_hi,
                          n_sv=result.n_sv,
                          train_seconds=train_seconds,
                          phases=dict(phases),
                          n_kept=result.n_kept,
                          n_readmitted=result.n_readmitted)
        if state is not None:
            state.cleanup()
        return model, result
    finally:
        if trace is not None and not trace.closed:
            trace.close()


def _empty_result(gamma: float, config: SVMConfig) -> TrainResult:
    return TrainResult(alpha=np.zeros(0, np.float32), b=0.0, n_iter=0,
                       converged=False, b_lo=0.0, b_hi=0.0,
                       train_seconds=0.0, gamma=gamma, n_sv=0,
                       kernel=config.kernel,
                       coef0=float(config.coef0),
                       degree=int(config.degree))

"""Compile-on-first-use loader for the native C++ helpers.

Builds ``csv_loader.cpp`` into a shared library with g++ the first time it
is needed (or whenever the source is newer than the cached .so) and loads
it via ctypes. Everything degrades gracefully: if no compiler is present
or the build fails, callers get ``None`` and fall back to pure-NumPy
implementations, so the framework has no hard native dependency.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "csv_loader.cpp")
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_LIB = os.path.join(_BUILD_DIR, "libdpsvm_native.so")

_lock = threading.Lock()
_cached: Optional[ctypes.CDLL] = None
_failed = False


def _compile() -> bool:
    cxx = shutil.which("g++") or shutil.which("c++") or shutil.which("clang++")
    if cxx is None:
        return False
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = _LIB + ".tmp"
    # gnu++17 (not c++17): the strict dialect hides POSIX prototypes
    # like getline(3) that the loader depends on.
    cmd = [cxx, "-O3", "-shared", "-fPIC", "-std=gnu++17", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.SubprocessError, OSError):
        return False
    os.replace(tmp, _LIB)
    return True


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c_float_p = ctypes.POINTER(ctypes.c_float)
    c_int_p = ctypes.POINTER(ctypes.c_int)
    c_long_p = ctypes.POINTER(ctypes.c_long)

    lib.dpsvm_csv_shape.argtypes = [ctypes.c_char_p, c_long_p, c_long_p]
    lib.dpsvm_csv_shape.restype = ctypes.c_int

    lib.dpsvm_parse_csv.argtypes = [
        ctypes.c_char_p, c_float_p, c_int_p, ctypes.c_long, ctypes.c_long,
    ]
    lib.dpsvm_parse_csv.restype = ctypes.c_long

    lib.dpsvm_write_model.argtypes = [
        ctypes.c_char_p, ctypes.c_double, ctypes.c_double,
        c_float_p, c_int_p, c_float_p, ctypes.c_long, ctypes.c_long,
    ]
    lib.dpsvm_write_model.restype = ctypes.c_long

    lib.dpsvm_libsvm_stats.argtypes = [ctypes.c_char_p, ctypes.c_long,
                                       c_long_p]
    lib.dpsvm_libsvm_stats.restype = ctypes.c_long

    lib.dpsvm_parse_libsvm.argtypes = [
        ctypes.c_char_p, c_float_p, c_float_p, ctypes.c_long,
        ctypes.c_long,
    ]
    lib.dpsvm_parse_libsvm.restype = ctypes.c_long

    c_double_p = ctypes.POINTER(ctypes.c_double)
    lib.dpsvm_model_shape.argtypes = [
        ctypes.c_char_p, c_long_p, c_long_p,
        ctypes.POINTER(ctypes.c_int), c_double_p, c_double_p,
    ]
    lib.dpsvm_model_shape.restype = ctypes.c_int

    lib.dpsvm_parse_model.argtypes = [
        ctypes.c_char_p, c_float_p, c_int_p, c_float_p,
        ctypes.c_long, ctypes.c_long, ctypes.c_int,
    ]
    lib.dpsvm_parse_model.restype = ctypes.c_long
    return lib


def load_native_lib() -> Optional[ctypes.CDLL]:
    """Return the native helper library, building it if necessary.

    Returns None (and remembers the failure) when the library cannot be
    built or loaded; callers must fall back to pure-Python paths.
    """
    global _cached, _failed
    if os.environ.get("DPSVM_NO_NATIVE"):
        return None
    if _cached is not None:
        return _cached
    if _failed:
        return None
    with _lock:
        if _cached is not None or _failed:
            return _cached
        try:
            stale = (not os.path.exists(_LIB)
                     or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
            if stale and not _compile():
                _failed = True
                return None
            _cached = _bind(ctypes.CDLL(_LIB))
        except (OSError, AttributeError):
            # AttributeError: a stale cached .so (e.g. archive-preserved
            # mtimes defeating the staleness check) missing newer symbols
            # must degrade to the Python paths, not crash the loaders.
            _failed = True
            _cached = None
            return None
    return _cached

// Native CSV data loader / model writer for dpsvm_tpu.
//
// TPU-native equivalent of the reference's C++ data path:
//   * parse.cpp:10-43  (populate_data: dense "label,f1,...,fd" CSV ->
//     flat row-major float x[n*d] + int y[n])
//   * svmTrainMain.cpp:386-416 (write_out_model: gamma line, b line,
//     one "alpha,y,x..." line per support vector)
//
// Exposed as a plain C ABI consumed from Python via ctypes (no pybind11 in
// this image). The Python wrapper in dpsvm_tpu/data/loader.py compiles this
// file on first use with g++ and falls back to a pure-NumPy parser when no
// compiler is available, so the framework never hard-depends on the binary.
//
// Unlike the reference loader, which exits the process on a missing file
// (parse.cpp:17) and trusts the caller-supplied -a/-x shape flags, this one
// returns error codes and can discover the shape itself (dpsvm_csv_shape).

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>

namespace {

// Read one '\n'-terminated line of unbounded length into buf (grown as
// needed). Returns length, or -1 on EOF with nothing read, -2 on alloc
// failure. POSIX getline(3) does the buffered read + realloc dance in
// one call — the original fgetc-per-character loop made a 76 MB model
// file cost ~3 s in stdio locking alone (measured; getline reads the
// same file in tenths).
long read_line(FILE* f, char** buf, size_t* cap) {
    ssize_t len = getline(buf, cap, f);
    if (len < 0) return feof(f) ? -1 : -2;
    if (len > 0 && (*buf)[len - 1] == '\n') (*buf)[--len] = '\0';
    if (len > 0 && (*buf)[len - 1] == '\r') (*buf)[--len] = '\0';
    return (long)len;
}

bool blank(const char* s) {
    for (; *s; ++s)
        if (*s != ' ' && *s != '\t' && *s != '\r') return false;
    return true;
}

}  // namespace

extern "C" {

// Discover (rows, cols) of a dense CSV. cols includes the label column.
// Returns 0 on success, -1 if the file cannot be opened, -2 on alloc failure.
int dpsvm_csv_shape(const char* path, long* rows, long* cols) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    char* buf = nullptr;
    size_t cap = 0;
    long n = 0, d = 0;
    for (;;) {
        long len = read_line(f, &buf, &cap);
        if (len == -2) { fclose(f); free(buf); return -2; }
        if (len < 0) break;
        if (blank(buf)) continue;
        if (n == 0) {
            d = 1;
            for (const char* p = buf; *p; ++p)
                if (*p == ',') ++d;
        }
        ++n;
    }
    free(buf);
    fclose(f);
    *rows = n;
    *cols = d;
    return 0;
}

// Parse up to max_rows lines of "label,f1,...,fd" into x_out (row-major
// n*d floats) and y_out (n ints). d = num_attributes (label not counted).
// Returns the number of rows parsed, or a negative error code:
//   -1 open failure, -2 alloc failure, -3 malformed row (too few fields).
long dpsvm_parse_csv(const char* path, float* x_out, int* y_out,
                     long max_rows, long num_attributes) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    char* buf = nullptr;
    size_t cap = 0;
    long n = 0;
    while (n < max_rows) {
        long len = read_line(f, &buf, &cap);
        if (len == -2) { fclose(f); free(buf); return -2; }
        if (len < 0) break;
        if (blank(buf)) continue;
        char* p = buf;
        char* end = nullptr;
        // Label: the reference stores it as int (parse.cpp reads into
        // vector<int> y); accept float spellings like "1.0" or "+1".
        float label = strtof(p, &end);
        if (end == p) { fclose(f); free(buf); return -3; }
        y_out[n] = (int)label;
        p = end;
        float* row = x_out + n * num_attributes;
        for (long j = 0; j < num_attributes; ++j) {
            while (*p == ',' || *p == ' ' || *p == '\t') ++p;
            if (*p == '\0' || *p == '\r') { fclose(f); free(buf); return -3; }
            row[j] = strtof(p, &end);
            if (end == p) { fclose(f); free(buf); return -3; }
            p = end;
        }
        ++n;
    }
    free(buf);
    fclose(f);
    return n;
}

// Write a model file: gamma line, b line, then one "alpha,y,x1,...,xd" line
// per support vector (alpha > 0). Matches the (fixed) reference format of
// svmTrainMain.cpp:386-416. Returns the number of SVs written, or -1 on
// open failure.
long dpsvm_write_model(const char* path, double gamma, double b,
                       const float* alpha, const int* y, const float* x,
                       long n, long d) {
    FILE* f = fopen(path, "wb");
    if (!f) return -1;
    // %.9g: float32 round-trips exactly; %g (6 digits) loses
    // ~1e-5 absolute on O(1) intercepts (one-class rho).
    fprintf(f, "%.9g\n", gamma);
    fprintf(f, "%.9g\n", b);
    long n_sv = 0;
    for (long i = 0; i < n; ++i) {
        if (!(alpha[i] > 0.0f)) continue;
        fprintf(f, "%.9g,%d", alpha[i], y[i]);
        const float* row = x + i * d;
        for (long j = 0; j < d; ++j) fprintf(f, ",%.9g", row[j]);
        fputc('\n', f);
        ++n_sv;
    }
    fclose(f);
    return n_sv;
}

// --- libsvm / svmlight sparse format ("<label> idx:val idx:val ...") ---
// The reference could only consume this format via an offline Python
// convert step (scripts/convert_adult.py); the framework's loaders accept
// it natively, and this is the fast path behind data/loader.py::load_libsvm
// (the pure-Python parser remains the fallback and the source of
// line-numbered error messages). Acceptance must not be LOOSER than the
// Python parser (a file must not load with g++ present but error without),
// so the float parse is stricter than bare strtof: no leading whitespace
// (Python tokenizes on whitespace first) and no hex literals (Python's
// float() rejects "0x1A").

static int strict_double(char* p, char** end, double* out) {
    // mirrors strict_float: Python's float() rejects hex literals
    if (*p == ' ' || *p == '\t') return 0;
    double v = strtod(p, end);
    if (*end == p) return 0;
    for (char* q = p; q < *end; ++q) {
        if (*q == 'x' || *q == 'X') return 0;
    }
    *out = v;
    return 1;
}

static int strict_float(char* p, char** end, float* out) {
    if (*p == ' ' || *p == '\t') return 0;
    float v = strtof(p, end);
    if (*end == p) return 0;
    for (char* q = p; q < *end; ++q) {
        if (*q == 'x' || *q == 'X') return 0;
    }
    *out = v;
    return 1;
}

// Parse one libsvm row in place. Returns 1 on success, 0 on a malformed
// row, -1 for a blank/comment line. Shared by the scan and fill passes so
// the two cannot disagree on which rows are valid. `row` may be null
// (scan pass: only label/max_index are produced); num_attributes < 0
// means "no column bound" (scan pass).
static int parse_libsvm_row(char* buf, float* label, float* row,
                            long num_attributes, long* max_index) {
    char* p = buf;
    while (*p == ' ' || *p == '\t') ++p;
    if (*p == '\0' || *p == '\r' || *p == '#') return -1;
    char* end = nullptr;
    if (!strict_float(p, &end, label)) return 0;
    p = end;
    for (;;) {
        while (*p == ' ' || *p == '\t') ++p;
        if (*p == '\0' || *p == '\r') return 1;
        long idx = strtol(p, &end, 10);
        if (end == p || *end != ':' || idx < 1) return 0;
        p = end + 1;
        float val;
        if (!strict_float(p, &end, &val)) return 0;
        p = end;
        if (idx > *max_index) *max_index = idx;
        if (row && idx <= num_attributes) row[idx - 1] = val;
    }
}

// Scan pass: count data rows (blank lines and '#' comments skipped) and the
// maximum 1-based feature index. max_rows <= 0 means "all". Returns the row
// count, or -1 open failure, -2 alloc failure, -3 malformed line / bad index.
long dpsvm_libsvm_stats(const char* path, long max_rows, long* max_index) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    char* buf = nullptr;
    size_t cap = 0;
    long n = 0, mi = 0;
    while (max_rows <= 0 || n < max_rows) {
        long len = read_line(f, &buf, &cap);
        if (len == -2) { fclose(f); free(buf); return -2; }
        if (len < 0) break;
        float label;
        int r = parse_libsvm_row(buf, &label, nullptr, -1, &mi);
        if (r == 0) { fclose(f); free(buf); return -3; }
        if (r > 0) ++n;
    }
    free(buf);
    fclose(f);
    *max_index = mi;
    return n;
}

// Fill pass: x_out must be (max_rows, num_attributes) ZEROED by the caller
// (absent features stay 0); labels land as float (the Python wrapper owns
// integer-label validation and bails back to Python for |label| >= 2^24,
// where float32 stops being exact). Features with index > num_attributes
// are dropped — the same column-narrowing semantics as the dense path and
// the reference converter (convert_adult.py:31). Returns rows parsed or
// the negative codes of dpsvm_libsvm_stats.
long dpsvm_parse_libsvm(const char* path, float* x_out, float* y_out,
                        long max_rows, long num_attributes) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    char* buf = nullptr;
    size_t cap = 0;
    long n = 0, mi = 0;
    while (n < max_rows) {
        long len = read_line(f, &buf, &cap);
        if (len == -2) { fclose(f); free(buf); return -2; }
        if (len < 0) break;
        float label;
        int r = parse_libsvm_row(buf, &label, x_out + n * num_attributes,
                                 num_attributes, &mi);
        if (r == 0) { fclose(f); free(buf); return -3; }
        if (r < 0) continue;
        y_out[n] = label;
        ++n;
    }
    free(buf);
    fclose(f);
    return n;
}

// --- reference-format model reader -----------------------------------
// The common big-model case (RBF, bare-gamma header — MNIST-scale files
// are tens of MB of text): a shape pass then a fill pass, mirroring the
// writer above. Extended layouts (our "kernel ..." header, "task"/
// "svidx" lines, LIBSVM "svm_type" files) return -4 so the Python
// reader — the format authority — handles them. Acceptance here must
// not be LOOSER than models/io.py::load_model: every field must parse
// and the field COUNT per SV line must be exactly d + 2 (Python's
// len(parts) check), so a short/garbage line errors instead of loading.
//
// dpsvm_model_shape returns 0 and fills n_sv/d/has_b/gamma/b, or:
//   -1 open failure, -2 alloc failure, -3 malformed, -4 extended format.
int dpsvm_model_shape(const char* path, long* n_sv, long* d, int* has_b,
                      double* gamma_out, double* b_out) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    char* buf = nullptr;
    size_t cap = 0;
    long n = 0, dd = -1;
    int state = 0, hb = 0;          // 0: want gamma, 1: maybe b, 2: SVs
    double g = 0.0, b = 0.0;
    for (;;) {
        long len = read_line(f, &buf, &cap);
        if (len == -2) { fclose(f); free(buf); return -2; }
        if (len < 0) break;
        if (blank(buf)) continue;
        if (state == 0) {
            char* end = nullptr;
            if (!strict_double(buf, &end, &g)) {
                fclose(f); free(buf); return -4;
            }
            while (*end == ' ' || *end == '\t') ++end;
            if (*end != '\0') { fclose(f); free(buf); return -4; }
            state = 1;
            continue;
        }
        if (state == 1) {
            state = 2;
            if (!strchr(buf, ',')) {        // lone scalar => b line
                char* end = nullptr;
                if (!strict_double(buf, &end, &b)) {
                    fclose(f); free(buf); return -3;
                }
                while (*end == ' ' || *end == '\t') ++end;
                if (*end != '\0') { fclose(f); free(buf); return -3; }
                hb = 1;
                continue;
            }
        }
        if (dd < 0) {
            long commas = 0;
            for (const char* p = buf; *p; ++p)
                if (*p == ',') ++commas;
            dd = commas - 1;
            if (dd < 1) { fclose(f); free(buf); return -3; }
        }
        ++n;
    }
    free(buf);
    fclose(f);
    if (n == 0) return -3;
    *n_sv = n;
    *d = dd;
    *has_b = hb;
    *gamma_out = g;
    *b_out = b;
    return 0;
}

// Fill alpha/y/x from the SV lines; n_sv/d/has_b must come from
// dpsvm_model_shape. Returns rows parsed, or a negative code as above.
long dpsvm_parse_model(const char* path, float* alpha_out, int* y_out,
                       float* x_out, long n_sv, long d, int has_b) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    char* buf = nullptr;
    size_t cap = 0;
    long skip = has_b ? 2 : 1;
    long n = 0;
    while (n < n_sv) {
        long len = read_line(f, &buf, &cap);
        if (len == -2) { fclose(f); free(buf); return -2; }
        if (len < 0) break;
        if (blank(buf)) continue;
        if (skip > 0) { --skip; continue; }
        char* p = buf;
        char* end = nullptr;
        float a;
        if (!strict_float(p, &end, &a) || *end != ',') {
            fclose(f); free(buf); return -3;
        }
        alpha_out[n] = a;
        p = end + 1;
        float yv;
        if (!strict_float(p, &end, &yv)) {
            fclose(f); free(buf); return -3;
        }
        y_out[n] = (int)yv;
        p = end;
        float* row = x_out + n * d;
        for (long j = 0; j < d; ++j) {
            if (*p != ',') { fclose(f); free(buf); return -3; }
            ++p;
            if (!strict_float(p, &end, row + j)) {
                fclose(f); free(buf); return -3;
            }
            p = end;
        }
        while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
        if (*p != '\0') { fclose(f); free(buf); return -3; }
        ++n;
    }
    free(buf);
    fclose(f);
    return n;
}

}  // extern "C"

// Native CSV data loader / model writer for dpsvm_tpu.
//
// TPU-native equivalent of the reference's C++ data path:
//   * parse.cpp:10-43  (populate_data: dense "label,f1,...,fd" CSV ->
//     flat row-major float x[n*d] + int y[n])
//   * svmTrainMain.cpp:386-416 (write_out_model: gamma line, b line,
//     one "alpha,y,x..." line per support vector)
//
// Exposed as a plain C ABI consumed from Python via ctypes (no pybind11 in
// this image). The Python wrapper in dpsvm_tpu/data/loader.py compiles this
// file on first use with g++ and falls back to a pure-NumPy parser when no
// compiler is available, so the framework never hard-depends on the binary.
//
// Unlike the reference loader, which exits the process on a missing file
// (parse.cpp:17) and trusts the caller-supplied -a/-x shape flags, this one
// returns error codes and can discover the shape itself (dpsvm_csv_shape).

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>

namespace {

// Read one '\n'-terminated line of unbounded length into buf (grown as
// needed). Returns length, or -1 on EOF with nothing read.
long read_line(FILE* f, char** buf, size_t* cap) {
    long len = 0;
    for (;;) {
        if ((size_t)len + 2 > *cap) {
            size_t ncap = (*cap == 0) ? 1 << 16 : (*cap * 2);
            char* nbuf = (char*)realloc(*buf, ncap);
            if (!nbuf) return -2;
            *buf = nbuf;
            *cap = ncap;
        }
        int c = fgetc(f);
        if (c == EOF) {
            if (len == 0) return -1;
            break;
        }
        if (c == '\n') break;
        (*buf)[len++] = (char)c;
    }
    (*buf)[len] = '\0';
    return len;
}

bool blank(const char* s) {
    for (; *s; ++s)
        if (*s != ' ' && *s != '\t' && *s != '\r') return false;
    return true;
}

}  // namespace

extern "C" {

// Discover (rows, cols) of a dense CSV. cols includes the label column.
// Returns 0 on success, -1 if the file cannot be opened, -2 on alloc failure.
int dpsvm_csv_shape(const char* path, long* rows, long* cols) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    char* buf = nullptr;
    size_t cap = 0;
    long n = 0, d = 0;
    for (;;) {
        long len = read_line(f, &buf, &cap);
        if (len == -2) { fclose(f); free(buf); return -2; }
        if (len < 0) break;
        if (blank(buf)) continue;
        if (n == 0) {
            d = 1;
            for (const char* p = buf; *p; ++p)
                if (*p == ',') ++d;
        }
        ++n;
    }
    free(buf);
    fclose(f);
    *rows = n;
    *cols = d;
    return 0;
}

// Parse up to max_rows lines of "label,f1,...,fd" into x_out (row-major
// n*d floats) and y_out (n ints). d = num_attributes (label not counted).
// Returns the number of rows parsed, or a negative error code:
//   -1 open failure, -2 alloc failure, -3 malformed row (too few fields).
long dpsvm_parse_csv(const char* path, float* x_out, int* y_out,
                     long max_rows, long num_attributes) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    char* buf = nullptr;
    size_t cap = 0;
    long n = 0;
    while (n < max_rows) {
        long len = read_line(f, &buf, &cap);
        if (len == -2) { fclose(f); free(buf); return -2; }
        if (len < 0) break;
        if (blank(buf)) continue;
        char* p = buf;
        char* end = nullptr;
        // Label: the reference stores it as int (parse.cpp reads into
        // vector<int> y); accept float spellings like "1.0" or "+1".
        float label = strtof(p, &end);
        if (end == p) { fclose(f); free(buf); return -3; }
        y_out[n] = (int)label;
        p = end;
        float* row = x_out + n * num_attributes;
        for (long j = 0; j < num_attributes; ++j) {
            while (*p == ',' || *p == ' ' || *p == '\t') ++p;
            if (*p == '\0' || *p == '\r') { fclose(f); free(buf); return -3; }
            row[j] = strtof(p, &end);
            if (end == p) { fclose(f); free(buf); return -3; }
            p = end;
        }
        ++n;
    }
    free(buf);
    fclose(f);
    return n;
}

// Write a model file: gamma line, b line, then one "alpha,y,x1,...,xd" line
// per support vector (alpha > 0). Matches the (fixed) reference format of
// svmTrainMain.cpp:386-416. Returns the number of SVs written, or -1 on
// open failure.
long dpsvm_write_model(const char* path, double gamma, double b,
                       const float* alpha, const int* y, const float* x,
                       long n, long d) {
    FILE* f = fopen(path, "wb");
    if (!f) return -1;
    // %.9g: float32 round-trips exactly; %g (6 digits) loses
    // ~1e-5 absolute on O(1) intercepts (one-class rho).
    fprintf(f, "%.9g\n", gamma);
    fprintf(f, "%.9g\n", b);
    long n_sv = 0;
    for (long i = 0; i < n; ++i) {
        if (!(alpha[i] > 0.0f)) continue;
        fprintf(f, "%.9g,%d", alpha[i], y[i]);
        const float* row = x + i * d;
        for (long j = 0; j < d; ++j) fprintf(f, ",%.9g", row[j]);
        fputc('\n', f);
        ++n_sv;
    }
    fclose(f);
    return n_sv;
}

// --- libsvm / svmlight sparse format ("<label> idx:val idx:val ...") ---
// The reference could only consume this format via an offline Python
// convert step (scripts/convert_adult.py); the framework's loaders accept
// it natively, and this is the fast path behind data/loader.py::load_libsvm
// (the pure-Python parser remains the fallback and the source of
// line-numbered error messages). Acceptance must not be LOOSER than the
// Python parser (a file must not load with g++ present but error without),
// so the float parse is stricter than bare strtof: no leading whitespace
// (Python tokenizes on whitespace first) and no hex literals (Python's
// float() rejects "0x1A").

static int strict_float(char* p, char** end, float* out) {
    if (*p == ' ' || *p == '\t') return 0;
    float v = strtof(p, end);
    if (*end == p) return 0;
    for (char* q = p; q < *end; ++q) {
        if (*q == 'x' || *q == 'X') return 0;
    }
    *out = v;
    return 1;
}

// Parse one libsvm row in place. Returns 1 on success, 0 on a malformed
// row, -1 for a blank/comment line. Shared by the scan and fill passes so
// the two cannot disagree on which rows are valid. `row` may be null
// (scan pass: only label/max_index are produced); num_attributes < 0
// means "no column bound" (scan pass).
static int parse_libsvm_row(char* buf, float* label, float* row,
                            long num_attributes, long* max_index) {
    char* p = buf;
    while (*p == ' ' || *p == '\t') ++p;
    if (*p == '\0' || *p == '\r' || *p == '#') return -1;
    char* end = nullptr;
    if (!strict_float(p, &end, label)) return 0;
    p = end;
    for (;;) {
        while (*p == ' ' || *p == '\t') ++p;
        if (*p == '\0' || *p == '\r') return 1;
        long idx = strtol(p, &end, 10);
        if (end == p || *end != ':' || idx < 1) return 0;
        p = end + 1;
        float val;
        if (!strict_float(p, &end, &val)) return 0;
        p = end;
        if (idx > *max_index) *max_index = idx;
        if (row && idx <= num_attributes) row[idx - 1] = val;
    }
}

// Scan pass: count data rows (blank lines and '#' comments skipped) and the
// maximum 1-based feature index. max_rows <= 0 means "all". Returns the row
// count, or -1 open failure, -2 alloc failure, -3 malformed line / bad index.
long dpsvm_libsvm_stats(const char* path, long max_rows, long* max_index) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    char* buf = nullptr;
    size_t cap = 0;
    long n = 0, mi = 0;
    while (max_rows <= 0 || n < max_rows) {
        long len = read_line(f, &buf, &cap);
        if (len == -2) { fclose(f); free(buf); return -2; }
        if (len < 0) break;
        float label;
        int r = parse_libsvm_row(buf, &label, nullptr, -1, &mi);
        if (r == 0) { fclose(f); free(buf); return -3; }
        if (r > 0) ++n;
    }
    free(buf);
    fclose(f);
    *max_index = mi;
    return n;
}

// Fill pass: x_out must be (max_rows, num_attributes) ZEROED by the caller
// (absent features stay 0); labels land as float (the Python wrapper owns
// integer-label validation and bails back to Python for |label| >= 2^24,
// where float32 stops being exact). Features with index > num_attributes
// are dropped — the same column-narrowing semantics as the dense path and
// the reference converter (convert_adult.py:31). Returns rows parsed or
// the negative codes of dpsvm_libsvm_stats.
long dpsvm_parse_libsvm(const char* path, float* x_out, float* y_out,
                        long max_rows, long num_attributes) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    char* buf = nullptr;
    size_t cap = 0;
    long n = 0, mi = 0;
    while (n < max_rows) {
        long len = read_line(f, &buf, &cap);
        if (len == -2) { fclose(f); free(buf); return -2; }
        if (len < 0) break;
        float label;
        int r = parse_libsvm_row(buf, &label, x_out + n * num_attributes,
                                 num_attributes, &mi);
        if (r == 0) { fclose(f); free(buf); return -3; }
        if (r < 0) continue;
        y_out[n] = label;
        ++n;
    }
    free(buf);
    fclose(f);
    return n;
}

}  // extern "C"

"""Native (C++) components of dpsvm_tpu, loaded via ctypes.

The reference framework's entire run path is native C++/CUDA; here the
compute path is XLA-compiled and the native layer covers host-side I/O
(CSV parsing, model serialization) where the reference used ``parse.cpp``
and ``write_out_model``. See ``build.py`` for the compile-on-first-use
machinery and ``csv_loader.cpp`` for the exported C ABI.
"""

from dpsvm_tpu.native.build import load_native_lib

__all__ = ["load_native_lib"]

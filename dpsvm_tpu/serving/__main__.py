"""``python -m dpsvm_tpu.serving`` — the serving selfcheck CI gate
(sibling of ``python -m dpsvm_tpu.telemetry`` and ``python -m
dpsvm_tpu.resilience``)."""

import sys

from dpsvm_tpu.serving import main

sys.exit(main())

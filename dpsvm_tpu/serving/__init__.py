"""Online serving subsystem: dynamic micro-batching inference on top
of the trained-model stack.

The ROADMAP's north star serves heavy traffic from millions of users;
until this package the repo could only do one-shot batch eval
(``dpsvm test``). The pieces (docs/SERVING.md):

* ``engine``   — ``PredictionEngine``: any saved model (binary SVC /
                 SVR / one-class / precomputed / multiclass directory)
                 packed into device-resident buffers once, served
                 through a pre-compiled bucket ladder of batch shapes —
                 zero steady-state retraces, bitwise parity with
                 ``decision_function``.
* ``batcher``  — ``MicroBatcher``: size-or-deadline request coalescing
                 with bounded-queue admission control (fast 429-style
                 reject under overload).
* ``registry`` — named multi-model registry with explicit, atomic hot
                 reload.
* ``pool``     — ``ReplicaPool``: N failure-isolated engine replicas
                 with per-replica circuit breakers (wedge/NaN eject ->
                 background rebuild -> half-open probe -> close) and
                 optional hedged re-dispatch (docs/SERVING.md
                 "Resilience").
* ``budget``   — per-request deadline budgets (blown budget = 504,
                 never a 400), the p99-based hedge delay, and the
                 tiered overload-degradation controller.
* ``lifecycle``— the self-healing model loop: KS drift detection on
                 the live score window -> supervised retrain -> eval
                 gate (accuracy floor + ``dpsvm compare``) -> atomic
                 hot-swap (docs/ROBUSTNESS.md).
* ``server``   — stdlib ``ThreadingHTTPServer``: ``POST /v1/predict``,
                 ``GET /healthz`` / ``/metricsz`` / ``/v1/models``,
                 ``POST /v1/reload``; SIGTERM graceful drain via the
                 ``resilience/preempt`` deferred-signal trap.
* ``frontdoor``— ``AsyncFrontDoor``: asyncio event-loop transport over
                 the same request core (``dpsvm serve --front-end
                 async``) — 10k connections without 10k threads,
                 bitwise-identical responses, same drain contract.
* ``fairqueue``— ``FairQueue``: deficit-round-robin weighted-fair
                 admission between the loop and the batcher; one lane
                 per resolved tenant label (``--tenant-weight``).
* ``sharded``  — ``ShardedDecider``: mesh-sharded decision path (SV
                 axis / feature-block axis over ``parallel/mesh``) the
                 engine selects when a packed model exceeds
                 ``--hbm-budget-mb`` per device; psum-reduced, bitwise
                 == its unsharded in-order blocked reference.
* ``loadgen``  — open/closed-loop generator printing one bench-harness
                 JSON row (throughput + p50/p95/p99 + the sequential
                 batch-1 baseline and coalescing speedup); ``--chaos``
                 fault-drill reporting and ``--saturate`` SLO probing.

CLI: ``dpsvm serve`` / ``dpsvm loadgen`` (``dpsvm_tpu/cli.py``).

CI gate: ``python -m dpsvm_tpu.serving --selfcheck`` — builds a model,
loads it through the engine, and asserts the properties the whole
design rests on: ZERO compile events across mixed-size post-warmup
traffic (via ``observability/compilewatch``), bitwise-identical
outputs between the batched engine and direct ``decision_function``
for the same rows, and the replica pool's failure isolation under
fault injection (wedge -> 504 -> eject -> rebuild -> recovery, zero
stray retraces). The sibling of the telemetry and resilience
selfchecks; wired into tier-1 by ``tests/test_serving.py``.

Importing this package (or ``batcher``/``registry``/``server``/
``loadgen``) initializes no backend; only ``engine`` pulls jax, and it
is imported lazily.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from dpsvm_tpu.serving.batcher import (KNOWN_OUTPUTS, BatcherClosedError,
                                       MicroBatcher, QueueFullError)
from dpsvm_tpu.serving.registry import ModelRegistry

from dpsvm_tpu.serving.budget import (Budget, DeadlineExceededError,
                                      DegradeController)

__all__ = [
    "KNOWN_OUTPUTS", "BatcherClosedError", "MicroBatcher",
    "QueueFullError", "ModelRegistry", "Budget",
    "DeadlineExceededError", "DegradeController", "PredictionEngine",
    "ReplicaPool", "PoolUnavailableError", "DriftDetector",
    "LifecycleLoop", "RetrainResult", "ServingServer", "bucket_ladder",
    "compact_model", "loadgen_row", "run_loadgen", "run_saturate",
    "selfcheck", "tenant_isolation_drill", "main",
    "AsyncFrontDoor", "FairQueue", "LaneFullError", "ShardedDecider",
    "front_door_drill",
]

_LAZY = {
    "PredictionEngine": ("dpsvm_tpu.serving.engine", "PredictionEngine"),
    "bucket_ladder": ("dpsvm_tpu.serving.engine", "bucket_ladder"),
    "compact_model": ("dpsvm_tpu.serving.engine", "compact_model"),
    "ServingServer": ("dpsvm_tpu.serving.server", "ServingServer"),
    "ReplicaPool": ("dpsvm_tpu.serving.pool", "ReplicaPool"),
    "PoolUnavailableError": ("dpsvm_tpu.serving.pool",
                             "PoolUnavailableError"),
    "DriftDetector": ("dpsvm_tpu.serving.lifecycle", "DriftDetector"),
    "LifecycleLoop": ("dpsvm_tpu.serving.lifecycle", "LifecycleLoop"),
    "RetrainResult": ("dpsvm_tpu.serving.lifecycle", "RetrainResult"),
    "run_loadgen": ("dpsvm_tpu.serving.loadgen", "run_loadgen"),
    "loadgen_row": ("dpsvm_tpu.serving.loadgen", "loadgen_row"),
    "run_saturate": ("dpsvm_tpu.serving.loadgen", "run_saturate"),
    "AsyncFrontDoor": ("dpsvm_tpu.serving.frontdoor", "AsyncFrontDoor"),
    "FairQueue": ("dpsvm_tpu.serving.fairqueue", "FairQueue"),
    "LaneFullError": ("dpsvm_tpu.serving.fairqueue", "LaneFullError"),
    "ShardedDecider": ("dpsvm_tpu.serving.sharded", "ShardedDecider"),
}


def __getattr__(name: str):
    """PEP 562 lazy re-exports: the engine (and with it jax) only loads
    when something actually asks for it — ``dpsvm loadgen`` and the
    pure-HTTP pieces stay accelerator-free."""
    try:
        mod, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}") from None
    import importlib
    return getattr(importlib.import_module(mod), attr)


def _mixed_sizes(max_batch: int) -> List[int]:
    """>= 20 request sizes covering every rung, the rung boundaries,
    and the multi-chunk path (> max_batch)."""
    sizes = [1, 2, 3, 4, 5, 7, 8, 9, 12, 15, 16, 17, 20, 24, 28, 31,
             32, 30, 6, 10, 2, 1]
    sizes = [min(s, max_batch) for s in sizes]
    sizes.append(max_batch + 3)             # chunked: full pass + pad
    return sizes


def selfcheck(tmp_dir: Optional[str] = None) -> List[str]:
    """Run the serving subsystem end to end on a synthetic model;
    return a list of problems (empty = healthy). See module docstring
    for what is asserted and why."""
    import os
    import tempfile

    import numpy as np

    problems: List[str] = []
    ctx = (tempfile.TemporaryDirectory() if tmp_dir is None else None)
    base = tmp_dir if tmp_dir is not None else ctx.name
    try:
        from dpsvm_tpu.models.calibration import save_platt, sigmoid_proba
        from dpsvm_tpu.models.io import load_model, save_model
        from dpsvm_tpu.models.svm import SVMModel, decision_function
        from dpsvm_tpu.observability import compilewatch
        from dpsvm_tpu.serving.engine import PredictionEngine

        rng = np.random.default_rng(7)
        n_sv, d, max_batch = 48, 6, 32
        model = SVMModel(
            x_sv=rng.standard_normal((n_sv, d)).astype(np.float32),
            alpha=rng.uniform(0.05, 2.0, n_sv).astype(np.float32),
            y_sv=np.where(rng.random(n_sv) < 0.5, -1, 1).astype(np.int32),
            b=0.25, gamma=0.5)
        path = os.path.join(base, "selfcheck.svm")
        save_model(model, path)
        save_platt(path, -1.2, 0.1)

        engine = PredictionEngine.load(path, max_batch=max_batch)
        if engine.warmup_compiles and len(engine.warmup_compiles) > \
                len(engine.buckets):
            problems.append(
                f"warmup compiled {len(engine.warmup_compiles)} programs "
                f"for a {len(engine.buckets)}-rung ladder")

        # 1) zero compiles across mixed-size post-warmup traffic
        compilewatch.drain()
        sizes = _mixed_sizes(max_batch)
        queries = [rng.standard_normal((s, d)).astype(np.float32)
                   for s in sizes]
        outs = [engine.infer(q, want=("labels", "decision", "proba"))
                for q in queries]
        stray = compilewatch.drain()
        if stray:
            progs = sorted({c["program"] for c in stray})
            problems.append(
                f"{len(stray)} compile event(s) across "
                f"{len(sizes)} post-warmup requests (programs: {progs}) "
                "— the bucket ladder is leaking retraces")

        # 2) bitwise parity with the direct evaluation path
        loaded = load_model(path)
        for q, out in zip(queries, outs):
            direct = decision_function(loaded, q)
            if not np.array_equal(
                    out["decision"].view(np.int32),
                    np.asarray(direct, np.float32).view(np.int32)):
                problems.append(
                    f"engine decision differs from decision_function "
                    f"at batch size {q.shape[0]} (max abs err "
                    f"{np.max(np.abs(out['decision'] - direct)):.3g})")
                break
            want_labels = np.where(direct < 0, -1, 1).astype(np.int32)
            if not np.array_equal(out["labels"], want_labels):
                problems.append(f"engine labels differ at batch size "
                                f"{q.shape[0]}")
                break
            want_proba = sigmoid_proba(direct, -1.2, 0.1)
            if not np.array_equal(out["proba"], want_proba):
                problems.append(f"engine proba differs at batch size "
                                f"{q.shape[0]}")
                break

        # 3) the batcher answers exactly like the engine it fronts
        from dpsvm_tpu.serving.batcher import MicroBatcher
        bat = MicroBatcher(engine.infer, max_batch=max_batch,
                           max_delay_ms=20.0, start=False)
        tickets = [bat.submit(q, want=("decision",)) for q in queries[:8]]
        bat.start()
        for q, t, out in zip(queries, tickets, outs):
            got = t.wait(timeout=30.0)["decision"]
            if not np.array_equal(got.view(np.int32),
                                  out["decision"].view(np.int32)):
                problems.append("batched submission answered differently "
                                "from a direct engine call")
                break
        st = bat.stats()
        if not any(int(k) > sizes[0] for k in
                   st["batch_rows_histogram"]):
            problems.append("staged queue did not coalesce "
                            f"(histogram: {st['batch_rows_histogram']})")
        bat.close()

        # 4) registry hot reload swaps generations atomically
        from dpsvm_tpu.serving.registry import ModelRegistry
        reg = ModelRegistry()
        reg.register("m", path, max_batch=8)
        import dataclasses
        save_model(dataclasses.replace(model, b=model.b + 1.0), path)
        reg.reload("m")
        man = reg.manifests()["m"]
        if man["generation"] != 2:
            problems.append(f"reload generation {man['generation']} != 2")
        row = queries[0][:1]
        d_old = decision_function(model, row)
        d_new = np.asarray(reg.engine("m").decision_values(row))
        if not np.allclose(d_new, d_old - 1.0, atol=1e-6):
            problems.append("hot reload did not serve the new artifact")

        # 5) replica pool: a wedged replica is a 504 for the dispatch
        # that hit it and an eject->rebuild->recovery for the pool —
        # with zero steady-state retraces across all survivors
        # (docs/SERVING.md "Resilience")
        import time as _time

        from dpsvm_tpu.resilience import faultinject
        from dpsvm_tpu.serving.budget import DeadlineExceededError
        from dpsvm_tpu.serving.pool import ReplicaPool

        faultinject.reset_serve_wedge()
        faultinject.install(faultinject.FaultPlan(serve_wedge_replica=1))
        pool = ReplicaPool(
            lambda i: PredictionEngine.load(path, max_batch=max_batch),
            3, name="selfcheck", deadline_s=1.5, watch_compiles=True)
        try:
            n_504 = n_ok = 0
            for q in queries:
                try:
                    pool.infer(q, ("labels", "decision"))
                    n_ok += 1
                except DeadlineExceededError:
                    n_504 += 1
            if n_504 != 1:
                problems.append(
                    f"expected exactly 1 deadline 504 from the wedged "
                    f"replica, got {n_504} (of {len(queries)})")
            if n_ok != len(queries) - 1:
                problems.append(
                    f"only {n_ok}/{len(queries) - 1} dispatches "
                    "survived one wedged replica")
            give_up = _time.perf_counter() + 30.0
            while (pool.replica_states() != [
                    "closed", "closed", "closed"]
                    and _time.perf_counter() < give_up):
                try:                       # traffic probes the rebuild
                    pool.infer(queries[0], ("labels",))
                except DeadlineExceededError:
                    pass
                _time.sleep(0.02)
            if pool.replica_states() != ["closed", "closed", "closed"]:
                problems.append(
                    "ejected replica did not recover to closed: "
                    f"{pool.replica_states()}")
            seq = [e["event"] for e in pool.events]
            if seq[:2] != ["eject", "rebuild"]:
                problems.append(
                    f"expected eject->rebuild event sequence, got {seq}")
            stray = pool.stray_compiles()
            if stray:
                problems.append(
                    f"{stray} stray compile(s) across pool traffic "
                    "incl. an ejection + rebuild — replicas are "
                    "leaking retraces")
        finally:
            faultinject.release_serve_wedge()
            faultinject.clear()
            pool.close()

        # 6) front door: the async transport answers bitwise-
        # identically to the threaded one over the same artifact; DRR
        # weights yield the promised service ratio; an over-budget
        # model serves mesh-sharded at bitwise parity with its
        # unsharded in-order reference (docs/SERVING.md "Front door")
        import json as _json
        import urllib.request

        from dpsvm_tpu.serving.fairqueue import drr_schedule
        from dpsvm_tpu.serving.frontdoor import AsyncFrontDoor
        from dpsvm_tpu.serving.server import ServingServer

        def _post(url, payload):
            req = urllib.request.Request(
                url + "/v1/predict",
                data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=15.0) as r:
                return _json.loads(r.read())

        reg_thr, reg_fd = ModelRegistry(), ModelRegistry()
        reg_thr.register("default", path, max_batch=16)
        reg_fd.register("default", path, max_batch=16)
        thr = ServingServer(reg_thr, port=0, max_batch=16,
                            max_delay_ms=0.5).start()
        fd = AsyncFrontDoor(
            ServingServer(reg_fd, port=0, max_batch=16,
                          max_delay_ms=0.5),
            tenant_weights={"gold": 8.0}).start()
        try:
            q6 = rng.standard_normal((9, d)).astype(np.float32)
            want6 = {"instances": q6.tolist(),
                     "return": ["labels", "decision"]}
            out_thr = _post(thr.url, want6)
            out_fd = _post(fd.url, want6)
            if (out_thr["decision"] != out_fd["decision"]
                    or out_thr["labels"] != out_fd["labels"]):
                problems.append(
                    "async front door answered differently from the "
                    "threaded transport over the same artifact")
        finally:
            fd.drain(timeout=10.0)
            thr.drain(timeout=10.0)

        # DRR ratio on the pure staged queue: 8:1 weights, everything
        # pushed up front -> one full round serves EXACTLY 64 gold + 8
        # bronze rows (one quantum grant per lane per turn). Exact, not
        # approximate: a tolerance here once hid a re-earning bug that
        # served the front lane to exhaustion (72/72 gold).
        pushes = ([("gold", 1)] * 80 + [("bronze", 1)] * 80)
        order = drr_schedule(pushes, {"gold": 8.0, "bronze": 1.0},
                             quantum=8)
        gold_first = sum(1 for t, _ in order[:72] if t == "gold")
        if gold_first != 64:
            problems.append(
                f"DRR served {gold_first}/72 gold rows for an 8:1 "
                "weight ratio (expected exactly 64: one full round)")

        # sharded decision path: force a budget far below the packed
        # model, assert the engine flips to the mesh decider and that
        # it is bitwise == its unsharded in-order blocked reference
        import jax as _jax
        if len(_jax.devices()) >= 2:
            eng_sh = PredictionEngine.load(path, max_batch=16,
                                           hbm_budget_mb=1e-4)
            if not eng_sh.sharded:
                problems.append(
                    "engine did not select the sharded decision path "
                    "under a forced 0.0001 MB HBM budget")
            else:
                sd = eng_sh._sharded_deciders[0]
                q_sh = rng.standard_normal((16, d)).astype(np.float32)
                got = np.asarray(sd.decide(q_sh), np.float32)
                ref = np.asarray(sd.reference(q_sh), np.float32)
                if not np.array_equal(got.view(np.int32),
                                      ref.view(np.int32)):
                    problems.append(
                        "sharded decision differs bitwise from its "
                        "unsharded in-order reference (max abs err "
                        f"{np.max(np.abs(got - ref)):.3g})")
                compilewatch.drain()
                for s in (1, 7, 16):
                    eng_sh.infer(rng.standard_normal(
                        (s, d)).astype(np.float32), want=("decision",))
                stray6 = compilewatch.drain()
                if stray6:
                    problems.append(
                        f"{len(stray6)} compile event(s) across post-"
                        "warmup sharded traffic — the sharded path is "
                        "leaking retraces")
    finally:
        if ctx is not None:
            ctx.cleanup()
    return problems


def tenant_isolation_drill(tmp_dir: Optional[str] = None,
                           trace_path: Optional[str] = None) -> dict:
    """The end-to-end noisy-neighbour drill (docs/OBSERVABILITY.md
    "Per-tenant attribution"): serve a multi-model registry, drive a
    skewed 8-tenant mix (t0 sends 80%), and prove the per-tenant
    observability chain identifies the hog — the ``tenant-fair-share``
    rule fires naming t0, the incident bundle's incident.json carries
    the tenant, and the cold tenants' p99 stays measurable on its own
    lane. Returns ONE JSON-able row (``metric: tenant_isolation``,
    headline = the cold tenants' p99 ms); ``ok`` is the verdict the
    burst runner and selfcheck gate on."""
    import json
    import os
    import tempfile
    import time as _time

    import numpy as np

    from dpsvm_tpu.models.io import save_model
    from dpsvm_tpu.models.svm import SVMModel
    from dpsvm_tpu.serving.loadgen import run_loadgen
    from dpsvm_tpu.serving.registry import ModelRegistry
    from dpsvm_tpu.serving.server import ServingServer

    ctx = (tempfile.TemporaryDirectory() if tmp_dir is None else None)
    base = tmp_dir if tmp_dir is not None else ctx.name
    ext_trace = trace_path is not None
    row: dict = {"metric": "tenant_isolation", "unit": "ms",
                 "tenants": 8, "hot_tenant_skew": 0.8, "ok": False}
    try:
        rng = np.random.default_rng(11)
        n_sv, d = 32, 5
        model = SVMModel(
            x_sv=rng.standard_normal((n_sv, d)).astype(np.float32),
            alpha=rng.uniform(0.05, 2.0, n_sv).astype(np.float32),
            y_sv=np.where(rng.random(n_sv) < 0.5, -1, 1).astype(
                np.int32),
            b=0.1, gamma=0.4)
        path = os.path.join(base, "drill.svm")
        save_model(model, path)
        if trace_path is None:
            # the v4 trace is part of the drill's evidence (span roots
            # carry the tenant) — always write one somewhere
            trace_path = os.path.join(base, "tenant_drill.jsonl")
        registry = ModelRegistry()
        registry.register("default", path, max_batch=32)
        registry.register("aux", path, max_batch=16)

        # tight per-tenant rules so the drill converges in seconds:
        # same shapes as default_serving_rules(), drill-speed windows
        rules = [
            {"name": "tenant-fair-share", "kind": "fair_share",
             "severity": "warn", "per_tenant": True, "window_s": 1.0,
             "share_above": 0.5, "min_tenants": 2, "for_s": 0.0,
             "clear_after_s": 10.0},
            {"name": "tenant-availability-burn", "kind": "burn_rate",
             "severity": "warn", "per_tenant": True,
             "good": "tenant:{tenant}:requests",
             "bad": "tenant:{tenant}:deadline_504",
             "objective": 0.999, "fast_window_s": 5.0,
             "slow_window_s": 30.0, "threshold": 14.4,
             "clear_after_s": 10.0},
        ]
        bundle_dir = os.path.join(base, "bundles")
        srv = ServingServer(
            registry, "127.0.0.1", 0, max_batch=32, max_delay_ms=0.5,
            trace_out=trace_path, trace_sample_rate=1.0,
            watch_rules=rules, bundle_dir=bundle_dir,
            tenant_budget=16).start()
        url = f"http://127.0.0.1:{srv.port}"
        rows = rng.standard_normal((64, d)).astype(np.float32)
        fired = False
        last = None
        errors = 0
        n_requests = 0
        try:
            give_up = _time.perf_counter() + 30.0
            while _time.perf_counter() < give_up:
                last = run_loadgen(
                    url, rows, model="default", requests=96, batch=1,
                    concurrency=8, mode="closed", want=("labels",),
                    timeout=10.0, spans=True, tenants=8,
                    hot_tenant_skew=0.8)
                errors += int(last.get("errors", 0))
                n_requests += int(last.get("requests", 0))
                fired = any(
                    s["state"] == "firing"
                    and s["rule"] == "tenant-fair-share[t0]"
                    and s.get("tenant") == "t0"
                    for s in srv.watch.states())
                if fired:
                    break
            m = srv.metrics()
        finally:
            srv.drain(timeout=10.0)
        row["fair_share_fired"] = fired
        row["requests"] = n_requests
        row["errors"] = errors
        per = (m.get("tenants") or {}).get("per_tenant") or {}
        hottest = max(per, key=lambda t: per[t]["requests"],
                      default=None)
        row["hot_tenant"] = hottest
        if last is not None:
            row["hot_p99_ms"] = last.get("hot_p99_ms")
            row["others_p99_ms"] = last.get("others_p99_ms")
            row["value"] = last.get("others_p99_ms")
        # the incident bundle must NAME the culprit tenant
        incident_tenant = None
        for ent in sorted(os.listdir(bundle_dir)
                          if os.path.isdir(bundle_dir) else []):
            inc = os.path.join(bundle_dir, ent, "incident.json")
            if os.path.exists(inc):
                with open(inc) as fh:
                    doc = json.load(fh)
                if doc.get("rule") == "tenant-fair-share[t0]":
                    incident_tenant = doc.get("tenant")
        row["incident_tenant"] = incident_tenant
        if ext_trace:
            row["trace"] = trace_path
        row["ok"] = bool(fired and hottest == "t0"
                         and incident_tenant == "t0" and errors == 0
                         and row.get("value") is not None)
    finally:
        if ctx is not None:
            ctx.cleanup()
    return row


def front_door_drill(tmp_dir: Optional[str] = None,
                     trace_path: Optional[str] = None,
                     threaded_connections: int = 20,
                     connection_factor: int = 10) -> dict:
    """The threaded-vs-async transport drill (docs/SERVING.md "Front
    door"): saturate the SAME model behind both front ends, the async
    one holding ``connection_factor``x the open keep-alive connections,
    and report ONE ``serving_slo_max_rps`` row — async's max sustained
    RPS under the p99 SLO, the threaded baseline, the connection
    ratio, and WHICH span stage sat at the knee (the fair-queue +
    shallow-batcher design keeps it out of ``queue_wait``). ``ok`` is
    the verdict the burst runner gates on."""
    import os
    import tempfile
    import urllib.request

    import numpy as np

    from dpsvm_tpu.models.io import save_model
    from dpsvm_tpu.models.svm import SVMModel
    from dpsvm_tpu.serving.frontdoor import AsyncFrontDoor
    from dpsvm_tpu.serving.loadgen import run_saturate
    from dpsvm_tpu.serving.registry import ModelRegistry
    from dpsvm_tpu.serving.server import ServingServer

    ctx = (tempfile.TemporaryDirectory() if tmp_dir is None else None)
    base = tmp_dir if tmp_dir is not None else ctx.name
    ext_trace = trace_path is not None
    c_thr = int(threaded_connections)
    c_asy = c_thr * int(connection_factor)
    row: dict = {"metric": "serving_slo_max_rps", "unit": "req/s",
                 "front_end": "async", "ok": False}
    try:
        rng = np.random.default_rng(17)
        n_sv, d = 32, 5
        model = SVMModel(
            x_sv=rng.standard_normal((n_sv, d)).astype(np.float32),
            alpha=rng.uniform(0.05, 2.0, n_sv).astype(np.float32),
            y_sv=np.where(rng.random(n_sv) < 0.5, -1, 1).astype(
                np.int32),
            b=0.1, gamma=0.4)
        path = os.path.join(base, "frontdoor.svm")
        save_model(model, path)
        if trace_path is None:
            trace_path = os.path.join(base, "front_door.jsonl")
        rows = rng.standard_normal((64, d)).astype(np.float32)
        sat = dict(p99_target_ms=250.0, start_rps=40.0, rps_factor=2.0,
                   max_steps=4, step_requests=80, concurrency=8,
                   timeout=15.0, trace=trace_path)

        reg_thr = ModelRegistry()
        reg_thr.register("default", path, max_batch=32)
        thr_srv = ServingServer(reg_thr, "127.0.0.1", 0, max_batch=32,
                                max_delay_ms=0.5).start()
        try:
            thr = run_saturate(thr_srv.url, rows, connections=c_thr,
                               **sat)
        finally:
            thr_srv.drain(timeout=10.0)

        reg_asy = ModelRegistry()
        reg_asy.register("default", path, max_batch=32)
        fd = AsyncFrontDoor(
            ServingServer(reg_asy, "127.0.0.1", 0, max_batch=32,
                          max_delay_ms=0.5, trace_out=trace_path,
                          trace_sample_rate=1.0),
            max_connections=max(4 * c_asy, 64)).start()
        try:
            # the front-door stats mid-run come from the same endpoint
            # any scraper would use — sampled before the held sockets
            # release
            asy = run_saturate(fd.url, rows, connections=c_asy, **sat)
            with urllib.request.urlopen(fd.url + "/metricsz",
                                        timeout=10.0) as r:
                import json as _json
                front = _json.loads(r.read()).get("front_door", {})
        finally:
            fd.drain(timeout=10.0)

        thr_open = int(thr.get("open_connections") or 0)
        asy_open = int(asy.get("open_connections") or 0)
        knee = None
        table = asy.get("span_p99_ms") or {}
        if table:
            knee = max(table, key=lambda k: table[k]["p99_ms"])
        row.update(
            value=asy.get("value"),
            slo_met=bool(asy.get("slo_met")),
            p99_target_ms=sat["p99_target_ms"],
            connections_threaded=thr_open,
            connections_async=asy_open,
            connection_ratio=(round(asy_open / thr_open, 2)
                              if thr_open else None),
            throughput_threaded_rps=thr.get("value"),
            throughput_async_rps=asy.get("value"),
            async_vs_threaded=(
                round(asy["value"] / thr["value"], 3)
                if thr.get("value") else None),
            knee_stage=knee,
            queue_wait_p99_ms=asy.get("queue_wait_p99_ms"),
            compute_p99_ms=asy.get("compute_p99_ms"),
            connections_rejected=int(
                front.get("connections_rejected", 0)),
            steps_threaded=thr.get("steps"),
            steps_async=asy.get("steps"),
        )
        if ext_trace:
            row["trace"] = trace_path
        row["ok"] = bool(
            thr.get("slo_met") and asy.get("slo_met")
            and asy_open >= 10 * max(thr_open, 1)
            and thr.get("value") and asy.get("value")
            and asy["value"] >= 0.8 * thr["value"]
            and knee != "queue_wait")
    finally:
        if ctx is not None:
            ctx.cleanup()
    return row


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import os

    p = argparse.ArgumentParser(prog="python -m dpsvm_tpu.serving")
    p.add_argument("--selfcheck", action="store_true",
                   help="engine/batcher/registry round-trip on a "
                        "synthetic model: asserts zero post-warmup "
                        "compiles and bitwise parity with "
                        "decision_function")
    p.add_argument("--live-drill", action="store_true",
                   help="run the end-to-end live drift-recovery drill "
                        "(docs/SERVING.md 'Continuous learning'): "
                        "seed a shard log, serve from it, append a "
                        "planted distribution shift, and prove the "
                        "drift->refresh->gate->hot-swap loop recovers "
                        "accuracy; prints ONE JSON row "
                        "(live_refresh_latency) and exits 0 iff it "
                        "recovered eject-free")
    p.add_argument("--tenant-drill", action="store_true",
                   help="run the end-to-end noisy-neighbour drill "
                        "(docs/OBSERVABILITY.md 'Per-tenant "
                        "attribution'): serve a multi-model registry, "
                        "drive an 8-tenant mix with t0 sending 80%%, "
                        "and prove the fair-share rule + incident "
                        "bundle name the hog while the cold tenants' "
                        "p99 stays on its own lane; prints ONE JSON "
                        "row (tenant_isolation) and exits 0 iff the "
                        "culprit was identified")
    p.add_argument("--front-door-drill", action="store_true",
                   help="run the threaded-vs-async transport drill "
                        "(docs/SERVING.md 'Front door'): saturate the "
                        "same model behind both front ends, the async "
                        "one holding 10x the open keep-alive "
                        "connections; prints ONE JSON row "
                        "(serving_slo_max_rps) and exits 0 iff async "
                        "sustained the SLO at the connection ratio "
                        "with the latency knee out of queue_wait")
    args = p.parse_args(argv)
    if not (args.selfcheck or args.live_drill or args.tenant_drill
            or args.front_door_drill):
        p.print_help()
        return 2
    if args.front_door_drill:
        import json

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        trace_env = os.environ.get("BENCH_TRACE_OUT")
        row = front_door_drill(trace_path=trace_env or None)
        print(json.dumps(row))
        return 0 if row.get("ok") else 1
    if args.tenant_drill:
        import json

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        trace_env = os.environ.get("BENCH_TRACE_OUT")
        row = tenant_isolation_drill(trace_path=trace_env or None)
        print(json.dumps(row))
        return 0 if row.get("ok") else 1
    if args.live_drill:
        import json
        import tempfile

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from dpsvm_tpu.serving.lifecycle import live_drift_drill
        with tempfile.TemporaryDirectory() as tmp:
            trace_env = os.environ.get("BENCH_TRACE_OUT")
            row = live_drift_drill(
                tmp, trace_path=trace_env or os.path.join(
                    tmp, "drill.jsonl"))
        print(json.dumps(row))
        return 0 if row.get("ok") else 1
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the sharded-decision gate needs >= 2 devices; standalone runs
    # (outside the test suite's conftest) force the virtual-CPU mesh
    # unless the caller pinned their own XLA flags
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    problems = selfcheck()
    if problems:
        print("serving selfcheck FAILED:", file=sys.stderr)
        for pr in problems:
            print(f"  {pr}", file=sys.stderr)
        return 1
    print("serving selfcheck OK (zero post-warmup compiles across "
          "mixed-size traffic; engine bitwise == decision_function; "
          "batcher + hot reload consistent; pool ejects a wedged "
          "replica, 504s its dispatch, rebuilds and recovers with "
          "zero stray retraces; async front door bitwise == threaded; "
          "DRR fair queue serves 8:1 weights at 8:1; over-budget "
          "model serves mesh-sharded bitwise == its unsharded "
          "reference)")
    return 0

"""Online prediction HTTP server: stdlib ``ThreadingHTTPServer``.

Endpoints (docs/SERVING.md):

* ``POST /v1/predict``  — ``{"model": name?, "instances": [[...], ...],
  "return": ["labels","decision","proba"]?}``. Instances ride the
  model's MicroBatcher (coalesced onto the engine's bucket ladder);
  the response carries the requested outputs plus per-request timing.
* ``GET /healthz``      — liveness + model list; 503 while draining
  (load balancers stop routing before the listener closes).
* ``GET /metricsz``     — request/error/reject counters, per-model
  batch-row and bucket histograms, queue depths, p50/p95/p99 request
  latency over a sliding window.
* ``GET /v1/models``    — registry manifests (shape, SV counts,
  compaction, warmup-compile receipt, generation).
* ``POST /v1/reload``   — ``{"model": name}``: explicit hot reload via
  the registry (old engine serves until the new one is warm).

Overload: a full batcher queue fast-rejects with HTTP 429 (+
``Retry-After``) instead of queueing unboundedly — clients learn to
back off while p99 stays bounded.

Shutdown reuses the deferred-signal pattern of ``resilience/preempt``:
``serve_until_signal`` traps SIGTERM/SIGINT, and on delivery performs a
graceful drain — stop admitting (503 + batchers closed), finish every
queued batch, complete in-flight HTTP exchanges (handler threads are
non-daemon and joined), then close the listener. A preempted serving
pod answers everything it accepted.

Threading model: one handler thread per connection (stdlib), all
device work funneled through one MicroBatcher worker per model — the
HTTP layer never calls jit directly.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

import numpy as np

from dpsvm_tpu.serving.batcher import (KNOWN_OUTPUTS, BatcherClosedError,
                                       MicroBatcher, QueueFullError)
from dpsvm_tpu.serving.registry import ModelRegistry

#: request bodies above this are rejected (413) before parsing.
MAX_BODY_BYTES = 64 * 1024 * 1024


def _jsonable(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


class _Server(ThreadingHTTPServer):
    # In-flight exchanges must complete during drain: track handler
    # threads and join them on server_close (the stdlib default daemon
    # threads would be abandoned mid-response).
    daemon_threads = False
    block_on_close = True
    owner: "ServingServer"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "dpsvm-serve"
    # Headers and body go out as separate writes; with Nagle on, the
    # second write stalls behind the client's delayed ACK (~40 ms) —
    # measured p50 went 44 ms -> ~4 ms with it off on both ends.
    disable_nagle_algorithm = True

    # -- plumbing -----------------------------------------------------

    def log_message(self, fmt, *args):       # quiet by default; errors
        if self.server.owner.verbose:        # and metrics tell the story
            super().log_message(fmt, *args)

    def _send(self, code: int, payload: dict,
              headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        body = json.dumps(payload, default=_jsonable).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass                             # client went away; fine

    def _body(self) -> Optional[dict]:
        n = int(self.headers.get("Content-Length") or 0)
        if n > MAX_BODY_BYTES:
            self._send(413, {"error": f"body over {MAX_BODY_BYTES} bytes"})
            return None
        raw = self.rfile.read(n) if n else b"{}"
        try:
            body = json.loads(raw or b"{}")
        except json.JSONDecodeError as e:
            self._send(400, {"error": f"bad JSON body: {e}"})
            return None
        if not isinstance(body, dict):
            self._send(400, {"error": "body must be a JSON object"})
            return None
        return body

    # -- routes -------------------------------------------------------

    def do_GET(self) -> None:                # noqa: N802 (stdlib API)
        owner = self.server.owner
        if self.path == "/healthz":
            if owner.draining:
                self._send(503, {"status": "draining",
                                 "models": owner.registry.names()})
            else:
                self._send(200, {"status": "ok",
                                 "models": owner.registry.names(),
                                 "uptime_s": round(owner.uptime, 3)})
        elif self.path == "/metricsz":
            self._send(200, owner.metrics())
        elif self.path == "/v1/models":
            self._send(200, {"models": owner.registry.manifests()})
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:               # noqa: N802 (stdlib API)
        owner = self.server.owner
        if self.path == "/v1/predict":
            self._predict(owner)
        elif self.path == "/v1/reload":
            self._reload(owner)
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def _reload(self, owner: "ServingServer") -> None:
        body = self._body()
        if body is None:
            return
        name = body.get("model", "default")
        try:
            engine = owner.registry.reload(name)
        except KeyError as e:
            self._send(404, {"error": str(e)})
            return
        except (ValueError, OSError) as e:
            self._send(400, {"error": f"reload failed (old model still "
                                      f"serving): {e}"})
            return
        man = dict(engine.manifest)
        man["generation"] = owner.registry.manifests()[name]["generation"]
        self._send(200, {"reloaded": name, "manifest": man})

    def _predict(self, owner: "ServingServer") -> None:
        t0 = time.perf_counter()
        if owner.draining:
            owner.count("errors")
            self._send(503, {"error": "draining"})
            return
        body = self._body()
        if body is None:
            owner.count("errors")
            return
        name = body.get("model", "default")
        want = tuple(body.get("return") or ("labels", "decision"))
        inst = body.get("instances")
        try:
            engine = owner.registry.engine(name)
        except KeyError as e:
            owner.count("errors")
            self._send(404, {"error": str(e)})
            return
        if inst is None:
            owner.count("errors")
            self._send(400, {"error": "missing 'instances'"})
            return
        try:
            x = np.asarray(inst, dtype=np.float32)
        except (ValueError, TypeError) as e:
            owner.count("errors")
            self._send(400, {"error": f"instances not numeric: {e}"})
            return
        if not np.all(np.isfinite(x)):
            owner.count("errors")
            self._send(400, {"error": "instances contain non-finite "
                                      "values"})
            return
        # Validate HERE, before the batcher: a bad request rejected at
        # admission can never poison the coalesced batch it would have
        # ridden in (the worker publishes one error to every ticket of
        # a failed batch).
        if x.ndim == 1:
            x = x[None, :]
        d = engine.num_attributes
        if x.ndim != 2 or x.shape[0] == 0 or x.shape[1] != d:
            owner.count("errors")
            self._send(400, {"error": f"instances must be a non-empty "
                                      f"(m, {d}) matrix, got shape "
                                      f"{list(x.shape)}"})
            return
        if x.shape[0] > self.server.owner.max_queue:
            owner.count("errors")
            self._send(413, {"error": f"{x.shape[0]} rows in one "
                                      f"request exceeds the queue bound "
                                      f"({owner.max_queue}); split the "
                                      "batch (or use `dpsvm test "
                                      "--batch` for offline eval)"})
            return
        bad = [w for w in want if w not in KNOWN_OUTPUTS]
        if bad:
            owner.count("errors")
            self._send(400, {"error": f"unknown outputs {bad}; pick "
                                      f"from {list(KNOWN_OUTPUTS)}"})
            return
        if "proba" in want and not engine.calibrated:
            owner.count("errors")
            self._send(400, {"error": f"model {name!r} has no "
                                      "probability calibration"})
            return
        try:
            res = owner.batcher(name).infer(x, want,
                                            timeout=owner.predict_timeout)
        except QueueFullError as e:
            owner.count("rejected")
            self._send(429, {"error": str(e)},
                       headers=(("Retry-After", "1"),))
            return
        except BatcherClosedError:
            owner.count("errors")
            self._send(503, {"error": "draining"})
            return
        except (ValueError, TimeoutError) as e:
            # bad width / unknown output / uncalibrated proba / timeout
            owner.count("errors")
            self._send(400, {"error": str(e)})
            return
        ms = (time.perf_counter() - t0) * 1000.0
        owner.observe_latency(ms)
        owner.count("requests")
        out = {k: _jsonable(v) for k, v in res.items()}
        out.update(model=name, n=int(x.shape[0]), ms=round(ms, 3))
        self._send(200, out)


class ServingServer:
    """Registry + per-model batchers + the HTTP front end."""

    def __init__(self, registry: ModelRegistry, host: str = "127.0.0.1",
                 port: int = 0, *, max_batch: int = 256,
                 max_delay_ms: float = 2.0, max_queue: int = 4096,
                 predict_timeout: float = 60.0, verbose: bool = False):
        self.registry = registry
        self.host = host
        self.requested_port = int(port)
        self.max_batch = int(max_batch)
        self.max_delay_ms = float(max_delay_ms)
        self.max_queue = int(max_queue)
        self.predict_timeout = float(predict_timeout)
        self.verbose = verbose
        self.draining = False
        self._batchers: Dict[str, MicroBatcher] = {}
        self._lock = threading.Lock()
        self._lat_ms: deque = deque(maxlen=8192)
        self._counters = {"requests": 0, "errors": 0, "rejected": 0}
        self._t0 = time.monotonic()
        self._httpd: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None

    # -- metrics ------------------------------------------------------

    @property
    def uptime(self) -> float:
        return time.monotonic() - self._t0

    def count(self, key: str) -> None:
        with self._lock:
            self._counters[key] += 1

    def observe_latency(self, ms: float) -> None:
        with self._lock:
            self._lat_ms.append(ms)

    def metrics(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            lat = np.asarray(self._lat_ms, np.float64)
            batchers = dict(self._batchers)
        out = dict(counters)
        out["uptime_s"] = round(self.uptime, 3)
        out["draining"] = self.draining
        if lat.size:
            p50, p95, p99 = np.percentile(lat, [50.0, 95.0, 99.0])
            out["latency_ms"] = {"count": int(lat.size),
                                 "p50": round(float(p50), 3),
                                 "p95": round(float(p95), 3),
                                 "p99": round(float(p99), 3)}
        else:
            out["latency_ms"] = {"count": 0, "p50": None, "p95": None,
                                 "p99": None}
        models = {}
        for name, b in batchers.items():
            st = b.stats()
            try:
                st["bucket_histogram"] = {
                    str(k): v for k, v in sorted(
                        self.registry.engine(name).bucket_counts().items())
                    if v}
            except KeyError:
                pass
            models[name] = st
        out["models"] = models
        return out

    # -- batchers -----------------------------------------------------

    def batcher(self, name: str) -> MicroBatcher:
        with self._lock:
            b = self._batchers.get(name)
            if b is None:
                # Resolve the engine per batch (closure over the
                # registry), so a hot reload swaps under a live batcher.
                def infer_fn(x, want, _name=name):
                    return self.registry.engine(_name).infer(x, want)
                b = MicroBatcher(infer_fn, max_batch=self.max_batch,
                                 max_delay_ms=self.max_delay_ms,
                                 max_queue=self.max_queue)
                self._batchers[name] = b
            return b

    # -- lifecycle ----------------------------------------------------

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("server not started")
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingServer":
        self._httpd = _Server((self.host, self.requested_port), _Handler)
        self._httpd.owner = self
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dpsvm-serve-http",
                                        kwargs={"poll_interval": 0.1},
                                        daemon=True)
        self._thread.start()
        return self

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful shutdown: refuse new work, answer everything
        already accepted, then close the listener."""
        self.draining = True
        with self._lock:
            batchers = list(self._batchers.values())
        for b in batchers:                  # finish every queued batch
            b.close(drain=True, timeout=timeout)
        if self._httpd is not None:
            self._httpd.shutdown()          # stop the accept loop
            self._httpd.server_close()      # join handler threads
        if self._thread is not None:
            self._thread.join(timeout)

    def serve_until_signal(self) -> int:
        """Run until SIGTERM/SIGINT, then drain. Returns the signal
        number (0 if drained for another reason). Reuses the deferred-
        signal trap from ``resilience/preempt``: the handler only sets
        a flag; the drain runs here, on the main thread, at a moment of
        our choosing — never inside a signal frame."""
        from dpsvm_tpu.resilience import preempt

        signum = 0
        with preempt.trap():
            while True:
                pending = preempt.pending()
                if pending is not None:
                    signum = pending
                    break
                time.sleep(0.05)
        self.drain()
        return signum

"""Online prediction HTTP server: stdlib ``ThreadingHTTPServer``.

Endpoints (docs/SERVING.md):

* ``POST /v1/predict``  — ``{"model": name?, "instances": [[...], ...],
  "return": ["labels","decision","proba"]?}``. Instances ride the
  model's MicroBatcher (coalesced onto the engine's bucket ladder);
  the response carries the requested outputs plus per-request timing.
* ``GET /healthz``      — liveness + model list; 503 while draining
  (load balancers stop routing before the listener closes).
* ``GET /metricsz``     — request/error/reject counters, per-model
  batch-row and bucket histograms, queue depths, p50/p95/p99 request
  latency over a sliding window, plus the per-tenant cost ledger
  (``tenants``) and per-model request/latency view (``per_model``).
  Requests carry a tenant label (``X-Tenant`` header / ``tenant``
  body field, defaulting to the model name) and every response bills
  it — docs/OBSERVABILITY.md "Per-tenant attribution".
* ``GET /v1/models``    — registry manifests (shape, SV counts,
  compaction, warmup-compile receipt, generation).
* ``POST /v1/reload``   — ``{"model": name}``: explicit hot reload via
  the registry (old engine serves until the new one is warm).

Overload: a full batcher queue fast-rejects with HTTP 429 (+
``Retry-After``) instead of queueing unboundedly — clients learn to
back off while p99 stays bounded. Before that cliff there is a slope
(docs/SERVING.md "Resilience"): as queue fill crosses the shed
thresholds the server first drops ``proba`` to ``decision``
(``serving/budget.DegradeController`` tier 1), then sheds whole
requests to a registered cheaper sibling model (tier 2, e.g. the
``approx/`` twin), marking degraded responses with a ``degraded``
field.

Resilience: requests carry a deadline budget (``timeout_ms`` in the
body or ``X-Deadline-Ms`` header, capped by ``--deadline-ms``) that
bounds queue wait AND device dispatch; a blown budget is **504** +
``Retry-After`` (never a 400 — the client did nothing wrong). With
``replicas > 1`` each model serves from a ``serving/pool.ReplicaPool``
— wedged/NaN-poisoned replicas are ejected and rebuilt in the
background while the rest keep answering, and hedged re-dispatch
(``--hedge-ms``) converts tail stalls into second chances. /metricsz
carries the robustness counters (504s, ejections, rebuilds, hedges,
shed tiers, expired tickets) and the rolling score-distribution
window the drift detector (``serving/lifecycle.py``) reads.

Observability: with ``--trace-out`` + ``--trace-sample-rate`` each
sampled request threads a span tree through the stack (admission ->
queue wait -> batch formation -> device dispatch -> respond, with
replica-compute and hedge markers below the dispatch) and the tree is
emitted into the serving trace as schema ``span`` records at
request completion — the per-request "where did the time go" that
aggregate /metricsz percentiles cannot answer
(docs/OBSERVABILITY.md "Spans"; observability/spans.py).

Shutdown reuses the deferred-signal pattern of ``resilience/preempt``:
``serve_until_signal`` traps SIGTERM/SIGINT, and on delivery performs a
graceful drain — stop admitting (503 + batchers closed), finish every
queued batch, complete in-flight HTTP exchanges (handler threads are
non-daemon and joined), then close the listener. A preempted serving
pod answers everything it accepted.

Threading model: one handler thread per connection (stdlib), all
device work funneled through one MicroBatcher worker per model — the
HTTP layer never calls jit directly.
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

import numpy as np

from dpsvm_tpu.observability import blackbox, slo
from dpsvm_tpu.observability.metrics import (DEFAULT_LATENCY_BUCKETS_MS,
                                             DEFAULT_TENANT_BUDGET,
                                             PROMETHEUS_CONTENT_TYPE,
                                             TENANT_OTHER,
                                             MetricsRegistry,
                                             TenantLabelBudget,
                                             incidents_counter,
                                             sanitize_tenant,
                                             wants_prometheus)
from dpsvm_tpu.observability.spans import RequestSpans, should_sample
from dpsvm_tpu.serving.batcher import (KNOWN_OUTPUTS, BatcherClosedError,
                                       MicroBatcher, QueueFullError)
from dpsvm_tpu.serving.budget import (TIER_NONE, TIER_SHED_PROBA,
                                      TIER_SHED_SIBLING, Budget,
                                      DeadlineExceededError,
                                      DegradeController)
from dpsvm_tpu.serving.pool import PoolUnavailableError, ReplicaPool
from dpsvm_tpu.serving.registry import ModelRegistry

#: request bodies above this are rejected (413) before parsing.
MAX_BODY_BYTES = 64 * 1024 * 1024

#: response-class counters that also bill the request's tenant
#: (docs/OBSERVABILITY.md "Per-tenant attribution"); the shed counters
#: stay fleet-wide — a shed decision belongs to queue pressure, not to
#: the request that happened to trip it.
_TENANT_COUNT_KEYS = ("requests", "errors", "rejected", "deadline_504")


def _new_tenant_acc() -> Dict[str, float]:
    """One tenant's host-side cost ledger row (exact values for the
    JSON /metricsz; the Prometheus families mirror these)."""
    return {"requests": 0.0, "errors": 0.0, "rejected": 0.0,
            "deadline_504": 0.0, "rows": 0.0, "wall_ms": 0.0,
            "queue_wait_ms": 0.0, "compute_ms": 0.0}


def _jsonable(v):
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


class _Server(ThreadingHTTPServer):
    # In-flight exchanges must complete during drain: track handler
    # threads and join them on server_close (the stdlib default daemon
    # threads would be abandoned mid-response).
    daemon_threads = False
    block_on_close = True
    owner: "ServingServer"


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "dpsvm-serve"
    # Headers and body go out as separate writes; with Nagle on, the
    # second write stalls behind the client's delayed ACK (~40 ms) —
    # measured p50 went 44 ms -> ~4 ms with it off on both ends.
    disable_nagle_algorithm = True

    # -- plumbing -----------------------------------------------------

    def log_message(self, fmt, *args):       # quiet by default; errors
        if self.server.owner.verbose:        # and metrics tell the story
            super().log_message(fmt, *args)

    def _send(self, code: int, payload: dict,
              headers: Tuple[Tuple[str, str], ...] = ()) -> None:
        # Span back-stop: whatever path produced this response, the
        # request's span tree (when one is open) is finished with THIS
        # status — every 4xx/5xx branch gets attribution without each
        # one hand-closing the tree. The success path finishes earlier
        # (with budget/model extras); finish is once-only, so this is
        # then a no-op.
        rs = getattr(self, "_rs", None)
        if rs is not None and not rs.finished:
            self.server.owner.finish_request_spans(rs, status=code)
        body = json.dumps(payload, default=_jsonable).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers:
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass                             # client went away; fine

    def _send_text(self, code: int, text: str,
                   content_type: str = "text/plain") -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _body(self) -> Optional[dict]:
        n = int(self.headers.get("Content-Length") or 0)
        if n > MAX_BODY_BYTES:
            self._send(413, {"error": f"body over {MAX_BODY_BYTES} bytes"})
            return None
        raw = self.rfile.read(n) if n else b"{}"
        try:
            body = json.loads(raw or b"{}")
        except json.JSONDecodeError as e:
            self._send(400, {"error": f"bad JSON body: {e}"})
            return None
        if not isinstance(body, dict):
            self._send(400, {"error": "body must be a JSON object"})
            return None
        return body

    # -- routes -------------------------------------------------------

    def do_GET(self) -> None:                # noqa: N802 (stdlib API)
        owner = self.server.owner
        if self.path == "/healthz":
            if owner.draining:
                self._send(503, {"status": "draining",
                                 "models": owner.registry.names()})
            else:
                self._send(200, {"status": "ok",
                                 "models": owner.registry.names(),
                                 "uptime_s": round(owner.uptime, 3)})
        elif self.path.startswith("/metricsz"):
            # ?format=prometheus = the text exposition of the unified
            # metric registry (observability/metrics.py) — what a
            # scraper consumes; the bare endpoint keeps the JSON blob.
            if wants_prometheus(self.path):
                self._send_text(200, owner.metrics_text(),
                                PROMETHEUS_CONTENT_TYPE)
            else:
                self._send(200, owner.metrics())
        elif self.path == "/v1/models":
            self._send(200, {"models": owner.model_manifests()})
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:               # noqa: N802 (stdlib API)
        owner = self.server.owner
        if self.path == "/v1/predict":
            self._predict(owner)
        elif self.path == "/v1/reload":
            self._reload(owner)
        else:
            self._send(404, {"error": f"no route {self.path}"})

    def _reload(self, owner: "ServingServer") -> None:
        body = self._body()
        if body is None:
            return
        name = body.get("model", "default")
        try:
            engine = owner.registry.reload(name)
        except KeyError as e:
            self._send(404, {"error": str(e)})
            return
        except (ValueError, OSError) as e:
            self._send(400, {"error": f"reload failed (old model still "
                                      f"serving): {e}"})
            return
        owner.refresh_pool(name)        # replicas pick the new gen up
        man = dict(engine.manifest)
        man["generation"] = owner.registry.manifests()[name]["generation"]
        self._send(200, {"reloaded": name, "manifest": man})

    def _predict(self, owner: "ServingServer") -> None:
        t0 = time.perf_counter()
        if owner.draining:
            owner.count("errors")
            self._send(503, {"error": "draining"})
            return
        # Request-scoped span tree (docs/OBSERVABILITY.md "Spans"):
        # opened for sampled requests (--trace-sample-rate against an
        # open serving trace) and for any request that asks via the
        # X-Trace-Spans header (the loadgen breakdown path — forced,
        # so a client probing "where did MY time go" never loses the
        # sampling lottery). None = this request records nothing.
        want_spans_back = (str(self.headers.get("X-Trace-Spans", ""))
                           .lower() in ("1", "true", "yes"))
        rs = owner.start_request_spans(force=want_spans_back)
        self._rs = rs
        body = self._body()
        if body is None:
            owner.count("errors")
            return
        name = body.get("model", "default")
        # Tenant identity, fixed at admission (docs/OBSERVABILITY.md
        # "Per-tenant attribution"): X-Tenant header beats the body's
        # `tenant` field beats the model name. Hostile values are
        # sanitized and the label budget may resolve a long-tail
        # tenant to the `other` aggregate; the span tree carries the
        # resolved label downstream, so no pipeline signature changes
        # and no extra device transfers.
        tenant = owner.admit_tenant(self.headers.get("X-Tenant"),
                                    body.get("tenant"), name)
        if rs is not None:
            rs.tenant = tenant
            rs.model = name
        want = tuple(body.get("return") or ("labels", "decision"))
        inst = body.get("instances")
        # Fleet routing (docs/SERVING.md "Model fleet"): a non-resident
        # registration behind an armed model cache serves through the
        # cache's synchronous cold path — no pool, no batcher; the
        # cache decides transient vs hydrate and does its own width/
        # calibration validation (ValueError -> 400 below).
        engine = None
        try:
            cold = owner.serves_cold(name)
            if not cold:
                engine = owner.registry.engine(name)
        except KeyError as e:
            owner.count("errors", tenant=tenant)
            self._send(404, {"error": str(e)})
            return
        if inst is None:
            owner.count("errors", tenant=tenant)
            self._send(400, {"error": "missing 'instances'"})
            return
        try:
            x = np.asarray(inst, dtype=np.float32)
        except (ValueError, TypeError) as e:
            owner.count("errors", tenant=tenant)
            self._send(400, {"error": f"instances not numeric: {e}"})
            return
        if not np.all(np.isfinite(x)):
            owner.count("errors", tenant=tenant)
            self._send(400, {"error": "instances contain non-finite "
                                      "values"})
            return
        # Validate HERE, before the batcher: a bad request rejected at
        # admission can never poison the coalesced batch it would have
        # ridden in (the worker publishes one error to every ticket of
        # a failed batch).
        if x.ndim == 1:
            x = x[None, :]
        if x.ndim != 2 or x.shape[0] == 0 or (
                engine is not None
                and x.shape[1] != engine.num_attributes):
            d = engine.num_attributes if engine is not None else "d"
            owner.count("errors", tenant=tenant)
            self._send(400, {"error": f"instances must be a non-empty "
                                      f"(m, {d}) matrix, got shape "
                                      f"{list(x.shape)}"})
            return
        if x.shape[0] > self.server.owner.max_queue:
            owner.count("errors", tenant=tenant)
            self._send(413, {"error": f"{x.shape[0]} rows in one "
                                      f"request exceeds the queue bound "
                                      f"({owner.max_queue}); split the "
                                      "batch (or use `dpsvm test "
                                      "--batch` for offline eval)"})
            return
        bad = [w for w in want if w not in KNOWN_OUTPUTS]
        if bad:
            owner.count("errors", tenant=tenant)
            self._send(400, {"error": f"unknown outputs {bad}; pick "
                                      f"from {list(KNOWN_OUTPUTS)}"})
            return
        # Deadline budget: fixed at admission, bounds queue wait AND
        # device dispatch. A blown budget is 504 (see below).
        try:
            budget = owner.budget_for(
                body.get("timeout_ms",
                         self.headers.get("X-Deadline-Ms")),
                tenant=tenant)
        except ValueError as e:
            owner.count("errors", tenant=tenant)
            self._send(400, {"error": str(e)})
            return
        if cold:
            # Synchronous cold dispatch through the model cache: no
            # degrade ladder (there is no queue to protect), no
            # batcher. The measured wall below IS the cold-start
            # latency the fleet drill reports the p99 of.
            try:
                ride = tuple(dict.fromkeys(want + ("decision",)))
                res = owner.model_cache.infer(name, x, want=ride)
            except KeyError as e:
                owner.count("errors", tenant=tenant)
                self._send(404, {"error": str(e)})
                return
            except ValueError as e:
                owner.count("errors", tenant=tenant)
                self._send(400, {"error": str(e)})
                return
            eff_name, eff_want, degraded = name, want, None
            self._respond_predict(owner, t0, rs, budget, tenant, name,
                                  eff_name, eff_want, degraded, x, res,
                                  want_spans_back)
            return
        # Degradation ladder: shed the optional expensive output, then
        # shed the whole request to the registered sibling, BEFORE the
        # queue-full 429 cliff.
        eff_name, eff_want, degraded = owner.degrade(name, want)
        if eff_name != name:
            try:
                engine = owner.registry.engine(eff_name)
            except KeyError:
                eff_name, degraded = name, None    # sibling vanished
        if "proba" in eff_want and not engine.calibrated:
            owner.count("errors")
            self._send(400, {"error": f"model {eff_name!r} has no "
                                      "probability calibration"})
            return
        try:
            # Always ride "decision" along: the engine derives every
            # output from the one decision pass anyway, and the server
            # feeds the values to the drift detector's score window.
            ride = tuple(dict.fromkeys(eff_want + ("decision",)))
            # admission is auto-closed by queue_wait's start inside
            # submit — one timestamp per stage transition, so no time
            # can fall between an explicit end and the next start
            ticket = owner.batcher(eff_name).submit(
                x, ride, deadline=budget.deadline, spans=rs)
            res = ticket.wait(budget.remaining())
        except QueueFullError as e:
            owner.count("rejected", tenant=tenant)
            self._send(429, {"error": str(e)},
                       headers=(("Retry-After", "1"),))
            return
        except BatcherClosedError:
            owner.count("errors", tenant=tenant)
            self._send(503, {"error": "draining"})
            return
        except (DeadlineExceededError, TimeoutError) as e:
            # the satellite bugfix: a timeout is the SERVER's miss —
            # 504 + Retry-After, never the 400 family
            owner.count("deadline_504", tenant=tenant)
            self._send(504, {"error": str(e)},
                       headers=(("Retry-After", "1"),))
            return
        except PoolUnavailableError as e:
            owner.count("errors", tenant=tenant)
            self._send(503, {"error": str(e)},
                       headers=(("Retry-After", "1"),))
            return
        except ValueError as e:
            # bad width / unknown output / uncalibrated proba
            owner.count("errors", tenant=tenant)
            self._send(400, {"error": str(e)})
            return
        self._respond_predict(owner, t0, rs, budget, tenant, name,
                              eff_name, eff_want, degraded, x, res,
                              want_spans_back)

    def _respond_predict(self, owner: "ServingServer", t0, rs, budget,
                         tenant, name, eff_name, eff_want, degraded,
                         x, res, want_spans_back) -> None:
        """The shared 200 tail of both predict paths (batched and
        fleet-cold): score-window feed, span close, latency + tenant
        accounting, counted response."""
        if rs is not None:
            # respond opens IMMEDIATELY on wake (before the score-
            # window feed) — auto-closing the dispatch stage, so the
            # post-compute bookkeeping is attributed, not residual
            rs.start("respond")
        owner.observe_scores(res.get("decision"))
        out = {k: _jsonable(v) for k, v in res.items() if k in eff_want}
        if degraded:
            out["degraded"] = degraded
        # Close the span tree BEFORE measuring ms so the root span and
        # the /metricsz latency observation describe the same wall
        # (the residual left to `respond` is the JSON encode + send).
        breakdown = owner.finish_request_spans(
            rs, status=200, budget=budget, model=eff_name,
            rows=int(x.shape[0]))
        if breakdown is not None and want_spans_back:
            out["spans"] = breakdown
        ms = (time.perf_counter() - t0) * 1000.0
        owner.observe_latency(ms)
        # tenant/model accounting BEFORE the counted response, so the
        # watch sample the count triggers sees this request's lanes
        owner.account_request(tenant, name, rows=int(x.shape[0]),
                              ms=ms, breakdown=breakdown)
        owner.count("requests", tenant=tenant)
        out.update(model=name, n=int(x.shape[0]), ms=round(ms, 3))
        self._send(200, out)


class ServingServer:
    """Registry + per-model replica pools + batchers + the HTTP front
    end (module docstring for the resilience pieces)."""

    def __init__(self, registry: ModelRegistry, host: str = "127.0.0.1",
                 port: int = 0, *, max_batch: int = 256,
                 max_delay_ms: float = 2.0, max_queue: int = 4096,
                 predict_timeout: float = 60.0, replicas: int = 1,
                 hedge="off", degrade: bool = True,
                 shed_proba_fill: float = 0.5,
                 shed_sibling_fill: float = 0.8,
                 siblings: Optional[Dict[str, str]] = None,
                 score_window: int = 4096,
                 trace_out: Optional[str] = None,
                 trace_sample_rate: float = 1.0,
                 metrics_registry: Optional[MetricsRegistry] = None,
                 watch_rules=None, bundle_dir: Optional[str] = None,
                 watch: bool = True,
                 tenant_budget: int = DEFAULT_TENANT_BUDGET,
                 model_cache_budget: Optional[int] = None,
                 verbose: bool = False):
        self.registry = registry
        self.host = host
        self.requested_port = int(port)
        self.max_batch = int(max_batch)
        self.max_delay_ms = float(max_delay_ms)
        self.max_queue = int(max_queue)
        self.predict_timeout = float(predict_timeout)
        self.replicas = int(replicas)
        self.hedge = hedge
        self.verbose = verbose
        self.draining = False
        self._batchers: Dict[str, MicroBatcher] = {}
        self._pools: Dict[str, ReplicaPool] = {}
        self._siblings: Dict[str, str] = {}
        self.degrader = DegradeController(
            enabled=degrade, shed_proba_fill=shed_proba_fill,
            shed_sibling_fill=shed_sibling_fill)
        self._lock = threading.Lock()
        self._pool_create_lock = threading.Lock()
        self._lat_ms: deque = deque(maxlen=8192)
        self._scores: deque = deque(maxlen=int(score_window))
        # The hand-rolled request counters now live in the unified
        # metric registry (observability/metrics.py): the JSON
        # /metricsz keys read the same series the Prometheus
        # exposition renders, so the two surfaces cannot drift. The
        # CLI passes the process-wide default_registry() (one surface
        # per process — training and serving alike); library/test
        # instances default to a private registry so per-instance
        # counter assertions stay exact.
        self.mreg = (metrics_registry if metrics_registry is not None
                     else MetricsRegistry())
        self._counters = {
            key: self.mreg.counter(f"dpsvm_serving_{key}_total", help_)
            .labels()
            for key, help_ in (
                ("requests", "requests answered 200"),
                ("errors", "client/server errors (4xx/5xx except "
                           "429/504)"),
                ("rejected", "fast-rejected on a full queue (429)"),
                ("deadline_504", "deadline budget blown (504)"),
                ("shed_proba", "tier-1 shed: proba dropped to "
                               "decision"),
                ("shed_sibling", "tier-2 shed: served by the sibling "
                                 "model"))}
        self._h_latency = self.mreg.histogram(
            "dpsvm_serving_request_latency_ms",
            "request wall latency (admission to response)",
            buckets=DEFAULT_LATENCY_BUCKETS_MS).labels()
        # Per-stage latency from the sampled span trees: the scrapeable
        # twin of the trace's span records (one histogram series per
        # stage name — queue_wait / device_dispatch / ...). Registered
        # lazily on the first sampled request: a histogram FAMILY with
        # zero series renders a sample-less TYPE line the exposition
        # grammar rejects.
        self._h_span = None
        self._c_spans = self.mreg.counter(
            "dpsvm_serving_spans_sampled_total",
            "requests that recorded a span tree").labels()
        # Per-tenant cost attribution (docs/OBSERVABILITY.md
        # "Per-tenant attribution"): an exact host-side ledger for the
        # JSON /metricsz plus bounded-cardinality Prometheus families
        # — at most ``tenant_budget`` live tenant label values, the
        # long tail aggregated under ``other`` and LRU-evicted series
        # removed from the exposition. Everything here is arithmetic
        # on numbers the request path already produced: zero extra
        # device->host transfers.
        self.tenant_budget = TenantLabelBudget(
            int(tenant_budget), on_evict=self._evict_tenant)
        self._tenants: Dict[str, Dict[str, float]] = {}
        self._per_model: Dict[str, dict] = {}
        self._c_tenant = {
            key: self.mreg.counter(f"dpsvm_tenant_{key}_total", help_,
                                   labels=("tenant",))
            for key, help_ in (
                ("requests", "requests answered 200, per tenant"),
                ("errors", "error responses, per tenant"),
                ("rejected", "queue-full 429s, per tenant"),
                ("deadline_504", "deadline budget blown (504), per "
                                 "tenant"),
                ("rows", "rows predicted, per tenant"),
                ("queue_wait_ms", "queue-wait milliseconds from "
                                  "sampled span trees, per tenant"),
                ("compute_ms", "device-dispatch milliseconds from "
                               "sampled span trees, per tenant"))}
        # lazy, like _h_span: a histogram family with zero series
        # renders a sample-less TYPE line the grammar rejects
        self._h_tenant = None
        self._g_queue = self.mreg.gauge(
            "dpsvm_serving_queue_depth",
            "micro-batcher queue depth in rows", labels=("model",))
        self._g_healthy = self.mreg.gauge(
            "dpsvm_serving_replicas_healthy",
            "replicas with a closed circuit", labels=("model",))
        # Model-fleet cache (dpsvm_tpu/fleet, docs/SERVING.md "Model
        # fleet"): when armed, NON-resident registrations (lazy ones,
        # and anything the cache pages out) are served by the budgeted
        # ModelCache instead of a dedicated pool/batcher — the cold
        # path is synchronous by design, its latency IS the cold-start
        # story. Resident eager engines keep the classic batched path
        # untouched. The fault/eviction counters exist unconditionally
        # so the model-cache-thrash rule always has its lane (zero on
        # a cache-less server).
        self._c_model_faults = self.mreg.counter(
            "dpsvm_fleet_model_faults_total",
            "cold-model hydrations into the fleet cache").labels()
        self._c_model_evictions = self.mreg.counter(
            "dpsvm_fleet_model_evictions_total",
            "resident models paged out of the fleet cache").labels()
        self.model_cache = None
        if model_cache_budget is not None:
            from dpsvm_tpu.fleet.modelcache import ModelCache
            self.model_cache = ModelCache(
                registry, budget=int(model_cache_budget),
                max_batch=self.max_batch,
                on_event=self._fleet_event)
        self._g_uptime = self.mreg.gauge("dpsvm_serving_uptime_seconds",
                                         "seconds since server start")
        self._g_draining = self.mreg.gauge("dpsvm_serving_draining",
                                           "1 while draining")
        self._g_expired = self.mreg.gauge(
            "dpsvm_serving_expired_tickets",
            "tickets dropped at batch formation (deadline passed)")
        self.mreg.add_collector(self._collect_gauges)
        # Continuous watch (observability/slo.py, docs/OBSERVABILITY.md
        # "Watch & alerts"): every server evaluates the serving SLO
        # rules against its OWN counters on every counted response —
        # no scraper in the loop — and feeds a bounded flight recorder
        # (observability/blackbox.py) from the event/span paths it
        # already runs. A rule firing emits `alert` into the events
        # ring + serving trace, bumps dpsvm_incidents_total, and (with
        # ``bundle_dir``) dumps a self-contained incident bundle.
        self.bundle_dir = bundle_dir
        self.watch: Optional[slo.Watchtower] = None
        if watch:
            self.watch = slo.Watchtower(
                slo.load_rules(watch_rules, default="serving"))
        self._c_incidents = incidents_counter(self.mreg)
        self._g_alert = None
        if self.watch is not None:
            self._g_alert = self.mreg.gauge(
                "dpsvm_alert_firing",
                "1 while the named alert rule is firing",
                labels=("rule", "severity"))
            for r in self.watch.ruleset:
                self._g_alert.labels(rule=r.name,
                                     severity=r.severity).set(0)
        self._flight = blackbox.FlightRecorder(blackbox.make_manifest(
            solver="serving",
            config={"models": list(registry.names()),
                    "replicas": int(replicas)}))
        self._events: deque = deque(maxlen=512)
        self._trace = None
        self._trace_out = trace_out
        if not (0.0 <= float(trace_sample_rate) <= 1.0):
            raise ValueError(f"trace_sample_rate must be in [0, 1], "
                             f"got {trace_sample_rate}")
        self.trace_sample_rate = float(trace_sample_rate)
        self._admitted = 0       # sampling stride counter
        self._trace_seq = 0      # request trace_id allocator
        self._t0 = time.monotonic()
        self._httpd: Optional[_Server] = None
        self._thread: Optional[threading.Thread] = None
        # The async front door (serving/frontdoor.AsyncFrontDoor)
        # attaches itself here when it wraps this core with
        # ``start(listen=False)``; /metricsz and the doctor probe read
        # its stats through this handle. None = classic threaded
        # listener.
        self.front_door = None
        for name, sib in (siblings or {}).items():
            self.set_sibling(name, sib)

    # -- metrics ------------------------------------------------------

    @property
    def uptime(self) -> float:
        return time.monotonic() - self._t0

    def count(self, key: str, tenant: Optional[str] = None) -> None:
        self._counters[key].inc()
        if tenant is not None and key in _TENANT_COUNT_KEYS:
            with self._lock:
                acc = self._tenants.setdefault(tenant,
                                               _new_tenant_acc())
                acc[key] += 1.0
            # re-resolve labels() every increment: an LRU eviction may
            # have removed this tenant's series, and a stale child
            # handle would update an orphan (metrics._Metric.remove)
            self._c_tenant[key].labels(tenant=tenant).inc()
        # every counted terminal response is one watch sample: the
        # rules see the burn as it happens, not at the next scrape
        self._watch_note()

    # -- model-fleet cache --------------------------------------------

    def _fleet_event(self, event: str, **extra) -> None:
        """The model cache's event sink: count the fault/evict, ride
        the event into the ring + serving trace, and note a watch
        sample so the model-cache-thrash rule sees the fault rate as
        it happens, not at the next counted response."""
        if event == "model_fault":
            self._c_model_faults.inc()
        elif event == "model_evict":
            self._c_model_evictions.inc()
        self.emit_event(event, **extra)
        self._watch_note()

    def serves_cold(self, name: str) -> bool:
        """Whether ``name`` routes through the model cache's cold path
        right now: the cache is armed and the registry holds no
        hydrated engine for the name. Raises KeyError for an unknown
        name (the 404)."""
        if self.model_cache is None:
            # unknown names surface as the engine lookup's KeyError
            return False
        return not self._registry_resident(name)

    def _registry_resident(self, name: str) -> bool:
        """Residency per the registry; duck-typed test registries
        without a residency surface are all-eager by construction."""
        fn = getattr(self.registry, "resident", None)
        return True if fn is None else bool(fn(name))

    def model_manifests(self) -> Dict[str, dict]:
        """``/v1/models``: the registry's manifests with the fleet
        cache's residency overlaid — a cache-managed model is
        ``resident`` iff its buffers are packed right now, regardless
        of the (never-hydrated) registry entry."""
        out = self.registry.manifests()
        if self.model_cache is not None:
            for name, man in out.items():
                if not man.get("resident"):
                    man["resident"] = bool(
                        self.model_cache.is_resident(name))
        return out

    # -- per-tenant attribution ---------------------------------------

    def admit_tenant(self, header_val, body_val,
                     model_name: str) -> str:
        """Resolve one request's tenant label at admission:
        ``X-Tenant`` header, else the body's ``tenant`` field, else
        the model name (single-tenant deployments get per-model
        attribution for free). Hostile values are sanitized
        (metrics.sanitize_tenant) and the label budget may resolve a
        long-tail tenant to ``other``."""
        raw = sanitize_tenant(header_val)
        if raw is None:
            raw = sanitize_tenant(body_val)
        if raw is None:
            raw = sanitize_tenant(model_name) or "default"
        return self.tenant_budget.resolve(raw)

    def account_request(self, tenant: str, model: str, *, rows: int,
                        ms: float, breakdown: Optional[dict] = None
                        ) -> None:
        """Bill one answered request: rows + wall to the tenant and
        the model; queue-wait and device-compute ms when the request
        recorded a span tree (the sampled-spans caveat the docs pin —
        stage lanes cover the sampled fraction, wall covers all)."""
        qw = comp = 0.0
        if breakdown:
            qw = float(breakdown.get("queue_wait") or 0.0)
            comp = float(breakdown.get("device_dispatch") or 0.0)
        with self._lock:
            acc = self._tenants.setdefault(tenant, _new_tenant_acc())
            acc["rows"] += rows
            acc["wall_ms"] += ms
            acc["queue_wait_ms"] += qw
            acc["compute_ms"] += comp
            pm = self._per_model.setdefault(
                model, {"requests": 0, "lat": deque(maxlen=2048)})
            pm["requests"] += 1
            pm["lat"].append(ms)
        self._c_tenant["rows"].labels(tenant=tenant).inc(rows)
        if qw:
            self._c_tenant["queue_wait_ms"].labels(
                tenant=tenant).inc(qw)
        if comp:
            self._c_tenant["compute_ms"].labels(
                tenant=tenant).inc(comp)
        if self._h_tenant is None:
            self._h_tenant = self.mreg.histogram(
                "dpsvm_tenant_request_latency_ms",
                "request wall latency per tenant",
                labels=("tenant",),
                buckets=DEFAULT_LATENCY_BUCKETS_MS)
        self._h_tenant.labels(tenant=tenant).observe(ms)

    def _evict_tenant(self, tenant: str) -> None:
        """TenantLabelBudget eviction callback: the evicted tenant's
        ledger row folds into ``other`` (totals survive — the tail is
        aggregated, never dropped) and its Prometheus series leave the
        exposition so live cardinality stays within budget. The
        per-tenant histogram series is removed without folding
        (bucketed observations cannot be re-attributed)."""
        with self._lock:
            acc = self._tenants.pop(tenant, None)
            if acc is not None:
                other = self._tenants.setdefault(TENANT_OTHER,
                                                 _new_tenant_acc())
                for k, v in acc.items():
                    other[k] = other.get(k, 0.0) + v
        for fam in self._c_tenant.values():
            fam.remove(tenant=tenant)
        if self._h_tenant is not None:
            self._h_tenant.remove(tenant=tenant)
        if acc is not None:
            for key in ("requests", "errors", "rejected",
                        "deadline_504", "rows", "queue_wait_ms",
                        "compute_ms"):
                v = acc.get(key, 0.0)
                if v:
                    self._c_tenant[key].labels(
                        tenant=TENANT_OTHER).inc(v)

    # -- continuous watch ---------------------------------------------

    def watch_sample(self) -> Dict[str, float]:
        """The canonical sample the serving rules evaluate
        (observability/slo.py's documented vocabulary) — all host-side
        counter reads."""
        sample = {key: float(c.value)
                  for key, c in self._counters.items()}
        with self._lock:
            batchers = dict(self._batchers)
            tenants = {t: dict(a) for t, a in self._tenants.items()}
        depth = sum(b.queue_depth for b in batchers.values())
        sample["queue_depth"] = float(depth)
        sample["queue_fill"] = (depth / self.max_queue
                                if self.max_queue else 0.0)
        # fleet-cache lanes — always present (0.0 without a cache) so
        # the model-cache-thrash rate rule has a continuous series
        sample["model_faults"] = float(self._c_model_faults.value)
        sample["model_evictions"] = float(
            self._c_model_evictions.value)
        # per-tenant lanes — the vocabulary slo.py's per_tenant rule
        # templates expand over (tenant:<name>:<metric>)
        for ten, acc in tenants.items():
            for k in ("requests", "deadline_504", "queue_wait_ms",
                      "compute_ms"):
                sample[f"tenant:{ten}:{k}"] = float(acc.get(k, 0.0))
        return sample

    def _watch_note(self) -> None:
        if self.watch is None:
            return
        try:
            transitions = self.watch.observe(self.watch_sample())
        except Exception:
            return                  # watching must never kill serving
        for tr in transitions:
            self._on_alert(tr)

    def _on_alert(self, tr: dict) -> None:
        """One rule transition: events ring + serving trace + metrics,
        and on a firing, the incident bundle."""
        firing = tr["state"] == "firing"
        # a per-tenant rule's transition names its tenant — ride it on
        # the event/incident records so a bundle can name the culprit
        ten = {"tenant": tr["tenant"]} if tr.get("tenant") else {}
        if self._g_alert is not None:
            self._g_alert.labels(rule=tr["rule"],
                                 severity=tr["severity"]).set(
                                     1 if firing else 0)
        self.emit_event("alert", rule=tr["rule"], window=tr["window"],
                        severity=tr["severity"], state=tr["state"],
                        reason=tr["reason"], **ten)
        if not firing:
            return
        self._c_incidents.inc()
        self._flight.snapshot_metrics(self.mreg)
        if self.bundle_dir:
            extra = {"source": "serving",
                     "counters": {k: int(c.value) for k, c
                                  in self._counters.items()}}
            extra.update(ten)
            path = blackbox.dump_bundle(
                self.bundle_dir, recorder=self._flight,
                rule=tr["rule"], severity=tr["severity"],
                window=tr["window"], reason=tr["reason"],
                registry=self.mreg, extra=extra)
            if path:
                self.emit_event("incident", rule=tr["rule"],
                                window=tr["window"],
                                severity=tr["severity"], bundle=path,
                                **ten)

    def observe_latency(self, ms: float) -> None:
        self._h_latency.observe(ms)      # the Prometheus histogram
        with self._lock:
            self._lat_ms.append(ms)      # exact percentiles for JSON

    def _collect_gauges(self) -> None:
        """Pre-scrape hook (mreg collector): gauges derived from live
        state, refreshed at render/snapshot time."""
        self._g_uptime.set(self.uptime)
        self._g_draining.set(1 if self.draining else 0)
        with self._lock:
            batchers = dict(self._batchers)
            pools = dict(self._pools)
        expired = 0
        for name, b in batchers.items():
            self._g_queue.labels(model=name).set(b.queue_depth)
            expired += b.stats().get("expired", 0)
        self._g_expired.set(expired)
        for name, p in pools.items():
            self._g_healthy.labels(model=name).set(p.n_healthy)

    def metrics_text(self) -> str:
        """`/metricsz?format=prometheus`: the registry's text
        exposition (collectors run first, so derived gauges are
        fresh)."""
        return self.mreg.render_prometheus()

    def observe_scores(self, decision) -> None:
        """Feed decision values into the rolling score-distribution
        window — what the drift detector (serving/lifecycle.py) and
        /metricsz's ``score_window`` read. Multiclass (m, P) pairwise
        matrices are flattened: drift in ANY pair's scores counts."""
        if decision is None:
            return
        vals = np.asarray(decision, np.float64).ravel()
        with self._lock:
            self._scores.extend(float(v) for v in vals)

    def score_window(self) -> np.ndarray:
        with self._lock:
            return np.asarray(self._scores, np.float64)

    # -- resilience policy --------------------------------------------

    def budget_for(self, raw, tenant: Optional[str] = None) -> Budget:
        """The request's deadline budget: ``timeout_ms`` (body) /
        ``X-Deadline-Ms`` (header), capped by the server-wide
        ``predict_timeout``. Invalid values are a 400 (ValueError).
        ``tenant`` rides the budget across threads so deadline
        accounting downstream bills the right tenant."""
        if raw is None:
            return Budget(self.predict_timeout, tenant=tenant)
        try:
            ms = float(raw)
        except (TypeError, ValueError):
            raise ValueError(f"timeout_ms must be a number, got {raw!r}")
        if not (math.isfinite(ms) and ms > 0):
            raise ValueError(f"timeout_ms must be finite and > 0, "
                             f"got {raw!r}")
        return Budget(min(ms / 1000.0, self.predict_timeout),
                      tenant=tenant)

    def set_sibling(self, name: str, sibling: str) -> None:
        """Register ``sibling`` as the tier-2 degradation target for
        ``name`` (typically the approx twin of an exact model). Both
        must be registered and agree on feature width."""
        e, s = self.registry.engine(name), self.registry.engine(sibling)
        if e.num_attributes != s.num_attributes:
            raise ValueError(
                f"sibling {sibling!r} has {s.num_attributes} "
                f"attributes, {name!r} expects {e.num_attributes}")
        with self._lock:
            self._siblings[name] = sibling

    def sibling(self, name: str) -> Optional[str]:
        with self._lock:
            return self._siblings.get(name)

    def degrade(self, name: str, want: tuple
                ) -> "tuple[str, tuple, Optional[str]]":
        """Apply the shed ladder for one request: returns
        (effective model, effective want, degraded marker or None)."""
        tier = self.degrader.tier_for(
            self.batcher(name).queue_depth, self.max_queue)
        if self.degrader.note(tier) and tier != TIER_NONE:
            self.emit_event("shed", model=name, tier=tier)
        if tier == TIER_NONE:
            return name, want, None
        degraded = None
        if tier >= TIER_SHED_PROBA and "proba" in want:
            want = tuple(w for w in want if w != "proba") or ("decision",)
            degraded = "shed_proba"
            self.count("shed_proba")
        if tier >= TIER_SHED_SIBLING:
            sib = self.sibling(name)
            if sib is not None:
                self.count("shed_sibling")
                return sib, want, f"sibling:{sib}"
        return name, want, degraded

    # -- request-scoped spans -----------------------------------------

    def start_request_spans(self, force: bool = False
                            ) -> Optional[RequestSpans]:
        """Open a span tree for an admitted request, or None.

        Sampled requests (deterministic stride at
        ``trace_sample_rate`` — observability/spans.should_sample) are
        recorded only while a serving trace is open (the records need
        somewhere to land); ``force`` (the X-Trace-Spans header)
        records regardless, so the loadgen breakdown works against a
        server with no --trace-out. The unsampled fast path is one
        counter increment."""
        with self._lock:
            i = self._admitted
            self._admitted += 1
            take = force or (self._trace is not None
                             and should_sample(i, self.trace_sample_rate))
            if not take:
                return None
            self._trace_seq += 1
            tid = f"req-{self._trace_seq}"
        # admission opens WITH the root (same timestamp): parse +
        # validate is stage 1 of every request
        return RequestSpans(tid, first_stage="admission")

    def finish_request_spans(self, rs: Optional[RequestSpans],
                             status: Optional[int] = None,
                             budget=None, **extra) -> Optional[dict]:
        """Close a request's span tree: end the root (with the HTTP
        status + deadline accounting), feed the per-stage histograms,
        emit the records into the serving trace when one is open, and
        return the stage breakdown (ms). Once-only (None / already
        finished = no-op), and never raises into the serving path."""
        if rs is None or rs.finished:
            return None
        ex = dict(extra)
        if status is not None:
            ex["status"] = int(status)
        if budget is not None:
            try:
                ex.update(budget.describe())
            except Exception:
                pass
        try:
            rs.finish(**ex)
            bd = rs.breakdown()
            self._c_spans.inc()
            if self._h_span is None:
                self._h_span = self.mreg.histogram(
                    "dpsvm_serving_span_ms",
                    "per-stage request latency from sampled span "
                    "trees",
                    labels=("span",),
                    buckets=DEFAULT_LATENCY_BUCKETS_MS)
            for stage, ms in bd.items():
                if stage != "total_ms":
                    self._h_span.labels(span=stage).observe(ms)
            with self._lock:
                tr = self._trace
            if tr is not None:
                rs.emit_into(tr)
            try:
                rs.emit_into(self._flight)   # the black-box copy
            except Exception:
                pass
            return bd
        except Exception:
            return None                # attribution never kills serving

    # -- events + serving trace ---------------------------------------

    def emit_event(self, event: str, **extra) -> None:
        """Robustness event sink: in-memory ring (for /metricsz and
        tests) + the serving trace when one is open + the black-box
        flight recorder (so a bundle dumped later carries the recent
        eject/rebuild/shed/alert history)."""
        with self._lock:
            self._events.append({"event": event, "t": round(
                self.uptime, 3), **extra})
            tr = self._trace
        if tr is not None:
            try:
                tr.event(event, **extra)
            except Exception:
                pass                   # tracing must not kill serving
        try:
            self._flight.event(event, **extra)
        except Exception:
            pass

    def metrics(self) -> dict:
        counters = {k: int(c.value) for k, c in self._counters.items()}
        with self._lock:
            lat = np.asarray(self._lat_ms, np.float64)
            scores = np.asarray(self._scores, np.float64)
            batchers = dict(self._batchers)
            pools = dict(self._pools)
            events = list(self._events)
            tenants_acc = {t: dict(a)
                           for t, a in self._tenants.items()}
            per_model_acc = {
                m: {"requests": d["requests"],
                    "lat": np.asarray(d["lat"], np.float64)}
                for m, d in self._per_model.items()}
        out = dict(counters)
        out["uptime_s"] = round(self.uptime, 3)
        out["draining"] = self.draining
        out["spans_sampled"] = int(self._c_spans.value)
        # continuous-watch state: the same rule states the Prometheus
        # exposition carries as dpsvm_alert_firing series
        out["alerts"] = (self.watch.states()
                         if self.watch is not None else [])
        out["incidents_total"] = int(self._c_incidents.value)
        if lat.size:
            p50, p95, p99 = np.percentile(lat, [50.0, 95.0, 99.0])
            out["latency_ms"] = {"count": int(lat.size),
                                 "p50": round(float(p50), 3),
                                 "p95": round(float(p95), 3),
                                 "p99": round(float(p99), 3)}
        else:
            out["latency_ms"] = {"count": 0, "p50": None, "p95": None,
                                 "p99": None}
        # the rolling score-distribution window the drift detector
        # reads (summary over HTTP; LifecycleLoop reads score_window())
        if scores.size:
            q5, q50, q95 = np.percentile(scores, [5.0, 50.0, 95.0])
            out["score_window"] = {
                "count": int(scores.size),
                "mean": round(float(np.mean(scores)), 6),
                "std": round(float(np.std(scores)), 6),
                "p5": round(float(q5), 6), "p50": round(float(q50), 6),
                "p95": round(float(q95), 6)}
        else:
            out["score_window"] = {"count": 0, "mean": None,
                                   "std": None, "p5": None, "p50": None,
                                   "p95": None}
        out["degrade"] = self.degrader.stats()
        # pool-level robustness counters, totalled and per model
        totals = {"ejections": 0, "rebuilds": 0, "hedges_fired": 0,
                  "hedges_won": 0, "redispatches": 0, "timeouts": 0,
                  "stray_compiles": 0}
        out["expired"] = 0
        models = {}
        for name, b in batchers.items():
            st = b.stats()
            out["expired"] += st.get("expired", 0)
            try:
                st["bucket_histogram"] = {
                    str(k): v for k, v in sorted(
                        self.registry.engine(name).bucket_counts().items())
                    if v}
            except KeyError:
                pass
            pool = pools.get(name)
            if pool is not None:
                pm = pool.metrics()
                st["pool"] = pm
                for k in totals:
                    totals[k] += pm.get(k, 0)
            models[name] = st
        out.update(totals)
        out["models"] = models
        # per-model request/latency view: registry models that have
        # not served yet still appear, zeroed — a dashboard can tile
        # the fleet without learning the model list elsewhere
        per_model = {}
        for name in self.registry.names():
            d = per_model_acc.get(name)
            lat_m = (d["lat"] if d is not None
                     else np.asarray([], np.float64))
            if lat_m.size:
                p50, p95, p99 = np.percentile(lat_m,
                                              [50.0, 95.0, 99.0])
                lat_out = {"count": int(lat_m.size),
                           "p50": round(float(p50), 3),
                           "p95": round(float(p95), 3),
                           "p99": round(float(p99), 3)}
            else:
                lat_out = {"count": 0, "p50": None, "p95": None,
                           "p99": None}
            st = models.get(name) or {}
            per_model[name] = {
                "requests": int(d["requests"]) if d is not None else 0,
                "latency_ms": lat_out,
                "queue_depth_rows": int(st.get("queue_depth_rows",
                                               0))}
        out["per_model"] = per_model
        # per-tenant cost ledger + label-budget health — the JSON twin
        # of the dpsvm_tenant_* Prometheus families (and the source
        # slo.sample_from_metricsz_json flattens into tenant: lanes)
        tb = self.tenant_budget.stats()
        out["tenants"] = {
            "budget": tb["budget"], "live": tb["live"],
            "evictions": tb["evictions"], "overflow": tb["overflow"],
            "per_tenant": {
                ten: {"requests": int(a["requests"]),
                      "errors": int(a["errors"]),
                      "rejected": int(a["rejected"]),
                      "deadline_504": int(a["deadline_504"]),
                      "rows": int(a["rows"]),
                      "wall_ms": round(a["wall_ms"], 3),
                      "queue_wait_ms": round(a["queue_wait_ms"], 3),
                      "compute_ms": round(a["compute_ms"], 3)}
                for ten, a in sorted(tenants_acc.items())}}
        # fleet model-cache block (docs/SERVING.md "Model fleet") —
        # the JSON twin of dpsvm_fleet_model_*_total, and the source
        # slo.sample_from_metricsz_json + the doctor probe read
        if self.model_cache is not None:
            out["model_cache"] = self.model_cache.stats()
        # front-door block (docs/SERVING.md "Front door"): which
        # transport answers connections, how many are open, and the
        # per-tenant fair-queue lane depths — the source the doctor
        # probe reads. The threaded listener has no connection cap or
        # admission queue, so its block is just the kind marker.
        fd = self.front_door
        out["front_door"] = (fd.stats() if fd is not None
                             else {"kind": "threaded"})
        out["events"] = events[-64:]
        return out

    # -- pools + batchers ---------------------------------------------

    def pool(self, name: str) -> ReplicaPool:
        """The model's replica pool (created on first use; ``start()``
        pre-creates one per registered model so the replica builds are
        paid at boot, not on the first request). The first replica
        shares the registry's already-warm engine; later replicas —
        and every post-ejection rebuild — are fresh engines built from
        the CURRENT registry source."""
        with self._lock:
            p = self._pools.get(name)
        if p is not None:
            return p
        with self._pool_create_lock:   # serialize expensive builds
            with self._lock:
                p = self._pools.get(name)
            if p is not None:
                return p
            shared = {"used": False}

            def build(i, _name=name):
                if not shared["used"]:
                    shared["used"] = True
                    return self.registry.engine(_name)
                return self.registry.build(_name)

            p = ReplicaPool(build, self.replicas, name=name,
                            deadline_s=self.predict_timeout,
                            hedge=self.hedge, watch_compiles=True,
                            on_event=self.emit_event,
                            metrics=self.mreg)
            with self._lock:
                self._pools[name] = p
            return p

    def refresh_pool(self, name: str) -> None:
        """Rolling-rebuild the model's replicas from the current
        registry generation — the pool side of a hot reload/promote."""
        with self._lock:
            p = self._pools.get(name)
        if p is not None:
            p.refresh()

    def batcher(self, name: str) -> MicroBatcher:
        with self._lock:
            b = self._batchers.get(name)
            if b is None:
                # All device work routes through the replica pool; the
                # pool resolves engines per dispatch, so a hot reload
                # (pool refresh) swaps under a live batcher. `spans`
                # rides through so the pool can hang replica_compute /
                # hedge spans under each request's dispatch stage.
                def infer_fn(x, want, deadline=None, spans=(),
                             _name=name):
                    return self.pool(_name).infer(x, want,
                                                  deadline=deadline,
                                                  spans=spans)
                b = MicroBatcher(infer_fn, max_batch=self.max_batch,
                                 max_delay_ms=self.max_delay_ms,
                                 max_queue=self.max_queue)
                self._batchers[name] = b
            return b

    # -- lifecycle ----------------------------------------------------

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("server not started")
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self, listen: bool = True) -> "ServingServer":
        """Open the trace, arm the emergency bundle, pre-build pools —
        and (by default) start the threaded HTTP listener.
        ``listen=False`` does everything EXCEPT the listener: the async
        front door (serving/frontdoor.py) wraps a core started this
        way and brings its own event-loop transport, so the two front
        ends share one request core instead of forking it."""
        if self._trace_out:
            from dpsvm_tpu.observability.record import open_serving_trace
            self._trace = open_serving_trace(
                self._trace_out,
                models={n: {"replicas": self.replicas}
                        for n in self.registry.names()},
                sample_rate=self.trace_sample_rate)
        if self.bundle_dir:
            # hard exits (watchdog stall, crash handlers) still land a
            # bundle: record.flush_open_traces -> blackbox emergency
            blackbox.arm_emergency(self._flight, self.bundle_dir,
                                   self.mreg)
        for name in self.registry.names():
            # replica builds paid at boot — but only for HYDRATED
            # entries: pre-creating a pool for a lazy registration
            # would defeat the whole point of the seconds-not-minutes
            # fleet boot (a lazy model's pool builds on first request,
            # or never, if the model cache serves it cold)
            if self._registry_resident(name):
                self.pool(name)
        if not listen:
            return self
        self._httpd = _Server((self.host, self.requested_port), _Handler)
        self._httpd.owner = self
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dpsvm-serve-http",
                                        kwargs={"poll_interval": 0.1},
                                        daemon=True)
        self._thread.start()
        return self

    def drain(self, timeout: float = 30.0) -> None:
        """Graceful shutdown: refuse new work, answer everything
        already accepted, then close the listener."""
        self.draining = True
        blackbox.disarm_emergency(self._flight)
        with self._lock:
            batchers = list(self._batchers.values())
        for b in batchers:                  # finish every queued batch
            b.close(drain=True, timeout=timeout)
        with self._lock:
            pools = list(self._pools.values())
        for p in pools:
            p.close()
        if self._httpd is not None:
            self._httpd.shutdown()          # stop the accept loop
            self._httpd.server_close()      # join handler threads
        if self._thread is not None:
            self._thread.join(timeout)
        with self._lock:
            tr, self._trace = self._trace, None
        counters = {k: int(c.value) for k, c in self._counters.items()}
        if tr is not None:
            from dpsvm_tpu.observability.record import close_serving_trace
            close_serving_trace(tr, requests=counters["requests"],
                                errors=counters["errors"],
                                seconds=self.uptime,
                                rejected=counters["rejected"],
                                deadline_504=counters["deadline_504"],
                                spans_sampled=int(self._c_spans.value))

    def serve_until_signal(self) -> int:
        """Run until SIGTERM/SIGINT, then drain. Returns the signal
        number (0 if drained for another reason). Reuses the deferred-
        signal trap from ``resilience/preempt``: the handler only sets
        a flag; the drain runs here, on the main thread, at a moment of
        our choosing — never inside a signal frame."""
        from dpsvm_tpu.resilience import preempt

        signum = 0
        with preempt.trap():
            while True:
                pending = preempt.pending()
                if pending is not None:
                    signum = pending
                    break
                time.sleep(0.05)
        self.drain()
        return signum

"""Async serving front door: event-loop admission over the batched core.

The threaded front end (``serving/server.py``) spends one OS thread
per open connection — fine at hundreds of clients, a wall at thousands
(10k idle keep-alive connections would mean 10k stacks before the
device sees a single row). This module replaces only the TRANSPORT:
one asyncio event loop holds every connection, parses and validates on
the loop, and feeds the same ``ServingServer`` core — registry,
MicroBatcher, ReplicaPool, degrade ladder, tenant accounting, watch
rules, spans — through ``start(listen=False)``. The two front ends
share one request core, so responses are bitwise-identical between
them (the serving selfcheck's front-door gate pins this).

Between the loop and the batcher sits the **weighted-fair admission
queue** (``serving/fairqueue.py``): requests are validated, billed to
their resolved tenant label, and parked in that tenant's lane; a
dispatcher task drains lanes in deficit-round-robin order into the
MicroBatcher, keeping only a bounded number of rows in flight
(~2 batches) so the batcher's FIFO stays shallow and the DRR order —
not arrival order — decides who runs. One hot tenant saturating its
lane backs up ITS OWN requests (429 on lane overflow) while other
tenants' requests keep jumping to the device; the PR 16
``tenant-fair-share`` watchtower rule, which fires under skewed load
on the threaded path, stays quiet here (the win detector the burst
drill measures).

Span attribution grows one stage: ``admission`` (parse + validate) ->
``fair_queue`` (DRR wait in the tenant lane) -> ``queue_wait`` (the
batcher FIFO, short by construction) -> ``batch_form`` ->
``device_dispatch`` -> ``respond`` (docs/OBSERVABILITY.md "Spans").

The waiting is free: a parked request is a future on the loop, not a
blocked thread. The batcher's ticket ``on_done`` callback trampolines
completion back to the loop (``call_soon_threadsafe``), so the only
threads in the process stay the batcher workers and the pool — the
HTTP layer never blocks one.

Shutdown mirrors the threaded drain (``resilience/preempt`` deferred
trap): on SIGTERM, healthz turns 503 and the listener closes, every
request already admitted — parked in a lane, riding a batch, or
writing its response — is answered, THEN the core drains (batchers,
pools, trace) and the loop stops. Exit code 0; the subprocess test
pins it like the threaded one.
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
import time
from http.client import responses as _HTTP_REASONS
from typing import Dict, Optional

import numpy as np

from dpsvm_tpu.observability.metrics import (PROMETHEUS_CONTENT_TYPE,
                                             wants_prometheus)
from dpsvm_tpu.serving.batcher import (KNOWN_OUTPUTS, BatcherClosedError,
                                       QueueFullError)
from dpsvm_tpu.serving.budget import DeadlineExceededError
from dpsvm_tpu.serving.fairqueue import (DEFAULT_QUANTUM_ROWS, FairQueue,
                                         LaneFullError)
from dpsvm_tpu.serving.pool import PoolUnavailableError
from dpsvm_tpu.serving.server import MAX_BODY_BYTES, _jsonable

#: default open-connection cap (--max-connections): beyond it new
#: connections get an immediate 503 + close instead of an accept-queue
#: stall nobody can see.
DEFAULT_MAX_CONNECTIONS = 10000


class _Pending:
    """One admitted request parked in a fair-queue lane: everything
    the dispatcher needs to submit it, plus the loop future its
    coroutine awaits."""

    __slots__ = ("x", "ride", "deadline", "rs", "eff_name", "rows",
                 "future", "cancelled", "ticket")

    def __init__(self, x, ride, deadline, rs, eff_name, rows, future):
        self.x = x
        self.ride = ride
        self.deadline = deadline
        self.rs = rs
        self.eff_name = eff_name
        self.rows = rows
        self.future = future
        self.cancelled = False
        self.ticket = None


class AsyncFrontDoor:
    """Event-loop HTTP transport over a ``ServingServer`` core
    (module docstring).

    The core must NOT be started by the caller — ``start()`` runs it
    with ``listen=False`` (trace, emergency bundle, pool pre-builds)
    and brings the asyncio listener in its place. ``tenant_weights``
    maps tenant label -> DRR weight (``--tenant-weight NAME=W``,
    default 1; the ``other`` long-tail bucket shares one lane by
    construction of the tenant label budget)."""

    def __init__(self, core, *, host: Optional[str] = None,
                 port: Optional[int] = None,
                 max_connections: int = DEFAULT_MAX_CONNECTIONS,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 lane_capacity: Optional[int] = None,
                 quantum: int = DEFAULT_QUANTUM_ROWS,
                 inflight_rows: Optional[int] = None):
        if max_connections < 1:
            raise ValueError(f"max_connections must be >= 1, got "
                             f"{max_connections}")
        self.core = core
        self.host = host if host is not None else core.host
        self.requested_port = (int(port) if port is not None
                               else core.requested_port)
        self.max_connections = int(max_connections)
        self._weights = dict(tenant_weights or {})
        self._fq = FairQueue(
            weights=self._weights,
            lane_capacity=(int(lane_capacity) if lane_capacity
                           else core.max_queue),
            quantum=quantum)
        # rows allowed past the fair queue at once: enough to keep the
        # batcher worker forming full buckets (~2 batches), small
        # enough that DRR order — not the batcher FIFO — decides
        # service order under backlog
        self._inflight_limit = (int(inflight_rows) if inflight_rows
                                else max(2 * core.max_batch, 1))
        self._inflight_rows = 0
        self._active_requests = 0
        self._conns: set = set()
        self._accepted = 0
        self._rejected_conns = 0
        self._closing = False
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._dispatcher: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None
        self._g_open = None
        self._g_lane = None

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "AsyncFrontDoor":
        self.core.start(listen=False)
        self.core.front_door = self
        mreg = self.core.mreg
        self._g_open = mreg.gauge(
            "dpsvm_frontdoor_open_connections",
            "open HTTP connections on the async front door")
        self._g_lane = mreg.gauge(
            "dpsvm_frontdoor_queue_lane_rows",
            "rows waiting in the per-tenant fair-queue lane",
            labels=("tenant",))
        mreg.add_collector(self._collect_gauges)
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever,
                                        name="dpsvm-frontdoor",
                                        daemon=True)
        self._thread.start()
        fut = asyncio.run_coroutine_threadsafe(self._start_async(),
                                               self._loop)
        fut.result(timeout=30)
        return self

    async def _start_async(self) -> None:
        self._wake = asyncio.Event()
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.requested_port)
        self._dispatcher = asyncio.get_running_loop().create_task(
            self._dispatch_loop())

    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError("front door not started")
        return int(self._server.sockets[0].getsockname()[1])

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def drain(self, timeout: float = 30.0) -> None:
        """SIGTERM semantics, front-door ordering: stop accepting,
        answer EVERYTHING already admitted (lanes empty, no rows in
        flight, no response mid-write), then drain the core (batchers
        with drain=True find empty queues, pools, trace) and stop the
        loop. The fair queue drains BEFORE the core's batchers close —
        the reverse order would 503 requests this process already
        accepted."""
        self.core.draining = True
        if self._loop is not None:
            try:
                asyncio.run_coroutine_threadsafe(
                    self._drain_async(timeout),
                    self._loop).result(timeout + 10)
            except Exception:
                pass            # bounded: the core drain still runs
        self.core.drain(timeout)
        self._stop_loop()

    async def _drain_async(self, timeout: float) -> None:
        self._closing = True
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        deadline = time.perf_counter() + timeout
        while ((len(self._fq) or self._inflight_rows
                or self._active_requests)
               and time.perf_counter() < deadline):
            if self._wake is not None:
                self._wake.set()
            await asyncio.sleep(0.01)
        if self._dispatcher is not None:
            self._dispatcher.cancel()

    def _stop_loop(self) -> None:
        loop, self._loop = self._loop, None
        if loop is None:
            return

        def _close():
            for w in list(self._conns):
                try:
                    w.close()
                except Exception:
                    pass
            loop.stop()

        loop.call_soon_threadsafe(_close)
        if self._thread is not None:
            self._thread.join(10)
        try:
            loop.close()
        except Exception:
            pass

    def serve_until_signal(self) -> int:
        """Run until SIGTERM/SIGINT, then drain (the threaded server's
        contract, same deferred-signal trap — the handler only sets a
        flag, the drain runs here on the main thread)."""
        from dpsvm_tpu.resilience import preempt

        signum = 0
        with preempt.trap():
            while True:
                pending = preempt.pending()
                if pending is not None:
                    signum = pending
                    break
                time.sleep(0.05)
        self.drain()
        return signum

    # -- facts --------------------------------------------------------

    def _collect_gauges(self) -> None:
        if self._g_open is not None:
            self._g_open.set(len(self._conns))
        if self._g_lane is not None:
            for tenant, rows in self._fq.depths().items():
                self._g_lane.labels(tenant=tenant).set(rows)

    def stats(self) -> dict:
        """The ``front_door`` block of /metricsz (and the doctor
        probe's source)."""
        return {
            "kind": "async",
            "open_connections": len(self._conns),
            "max_connections": self.max_connections,
            "connections_accepted": int(self._accepted),
            "connections_rejected": int(self._rejected_conns),
            "inflight_rows": int(self._inflight_rows),
            "inflight_limit_rows": int(self._inflight_limit),
            "tenant_weights": dict(self._weights),
            "fair_queue": self._fq.stats(),
        }

    # -- dispatcher (fair queue -> batcher) ---------------------------

    async def _dispatch_loop(self) -> None:
        while True:
            await self._wake.wait()
            self._wake.clear()
            while self._inflight_rows < self._inflight_limit:
                got = self._fq.pop()
                if got is None:
                    break
                _lane, item, _rows = got
                if item.cancelled:
                    continue        # waiter already gave up (504)
                self._submit(item)

    def _submit(self, item: _Pending) -> None:
        loop = asyncio.get_running_loop()

        def on_done(ticket, _item=item):
            # worker thread -> loop: resolve the parked future. Must
            # be cheap and never raise (batcher._notify swallows, but
            # a dead loop at shutdown shouldn't even get that far).
            try:
                loop.call_soon_threadsafe(self._ticket_done, _item,
                                          ticket)
            except RuntimeError:
                pass                # loop closed mid-drain

        try:
            item.ticket = self.core.batcher(item.eff_name).submit(
                item.x, item.ride, deadline=item.deadline,
                spans=item.rs, on_done=on_done)
        except Exception as e:      # QueueFull/Closed/ValueError -> the
            if not item.future.done():      # waiter maps it to HTTP
                item.future.set_exception(e)
            else:
                item.future.exception()     # consumed; no loop warning
            return
        self._inflight_rows += item.rows

    def _ticket_done(self, item: _Pending, ticket) -> None:
        self._inflight_rows -= item.rows
        if self._wake is not None:
            self._wake.set()
        if not item.future.done():
            item.future.set_result(ticket)

    # -- connection handling ------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        sock = writer.get_extra_info("socket")
        if sock is not None:
            try:                    # same rationale as the threaded
                sock.setsockopt(socket.IPPROTO_TCP,  # front end: the
                                socket.TCP_NODELAY, 1)  # delayed-ACK
            except OSError:                             # stall
                pass
        if self._closing or len(self._conns) >= self.max_connections:
            self._rejected_conns += 1
            try:
                await self._respond(
                    writer, 503,
                    {"error": f"connection limit "
                              f"({self.max_connections}) reached"},
                    keep=False)
            except Exception:
                pass
            writer.close()
            return
        self._conns.add(writer)
        self._accepted += 1
        try:
            while True:
                keep = await self._one_request(reader, writer)
                if not keep:
                    break
        except (ConnectionResetError, BrokenPipeError,
                asyncio.IncompleteReadError):
            pass
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
            except Exception:
                pass

    async def _one_request(self, reader, writer) -> bool:
        """Parse + answer one HTTP/1.1 exchange; returns keep-alive."""
        try:
            line = await reader.readline()
        except (ValueError, ConnectionError):
            return False
        if not line or not line.strip():
            return False            # EOF / client closed keep-alive
        try:
            method, path, _version = line.decode("latin-1").split()
        except ValueError:
            await self._respond(writer, 400,
                                {"error": "malformed request line"},
                                keep=False)
            return False
        headers: Dict[str, str] = {}
        while True:
            h = await reader.readline()
            if not h or h in (b"\r\n", b"\n"):
                break
            k, sep, v = h.decode("latin-1").partition(":")
            if sep:
                headers[k.strip().lower()] = v.strip()
        try:
            n = int(headers.get("content-length") or 0)
        except ValueError:
            await self._respond(writer, 400,
                                {"error": "malformed Content-Length"},
                                keep=False)
            return False
        if n > MAX_BODY_BYTES:
            await self._respond(
                writer, 413,
                {"error": f"body over {MAX_BODY_BYTES} bytes"},
                keep=False)
            return False
        raw = (await reader.readexactly(n)) if n else b"{}"
        keep = headers.get("connection", "").lower() != "close"
        self._active_requests += 1
        try:
            await self._route(writer, method, path, headers, raw, keep)
        except (ConnectionResetError, BrokenPipeError):
            return False
        except Exception as e:      # a handler bug answers 500, never
            try:                    # kills the connection loop silently
                await self._respond(writer, 500,
                                    {"error": f"internal: "
                                              f"{type(e).__name__}: "
                                              f"{e}"},
                                    keep=False)
            except Exception:
                pass
            return False
        finally:
            self._active_requests -= 1
        return keep

    async def _respond(self, writer, code: int, payload,
                       keep: bool = True, content_type: str =
                       "application/json",
                       extra_headers=()) -> None:
        if isinstance(payload, (bytes, str)):
            body = (payload.encode()
                    if isinstance(payload, str) else payload)
        else:
            body = json.dumps(payload, default=_jsonable).encode()
        reason = _HTTP_REASONS.get(code, "")
        head = [f"HTTP/1.1 {code} {reason}",
                "Server: dpsvm-serve-async",
                f"Content-Type: {content_type}",
                f"Content-Length: {len(body)}"]
        for k, v in extra_headers:
            head.append(f"{k}: {v}")
        if not keep:
            head.append("Connection: close")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
                     + body)
        await writer.drain()

    async def _route(self, writer, method: str, path: str, headers,
                     raw: bytes, keep: bool) -> None:
        core = self.core
        if method == "GET" and path == "/healthz":
            if core.draining:
                await self._respond(writer, 503,
                                    {"status": "draining",
                                     "models": core.registry.names()},
                                    keep)
            else:
                await self._respond(
                    writer, 200,
                    {"status": "ok", "models": core.registry.names(),
                     "uptime_s": round(core.uptime, 3)}, keep)
        elif method == "GET" and path.startswith("/metricsz"):
            if wants_prometheus(path):
                await self._respond(writer, 200, core.metrics_text(),
                                    keep,
                                    content_type=PROMETHEUS_CONTENT_TYPE)
            else:
                await self._respond(writer, 200, core.metrics(), keep)
        elif method == "GET" and path == "/v1/models":
            await self._respond(writer, 200,
                                {"models": core.model_manifests()},
                                keep)
        elif method == "POST" and path == "/v1/predict":
            await self._predict(writer, headers, raw, keep)
        elif method == "POST" and path == "/v1/reload":
            await self._reload(writer, raw, keep)
        else:
            await self._respond(writer, 404,
                                {"error": f"no route {path}"}, keep)

    async def _reload(self, writer, raw: bytes, keep: bool) -> None:
        core = self.core
        try:
            body = json.loads(raw or b"{}")
        except json.JSONDecodeError as e:
            await self._respond(writer, 400,
                                {"error": f"bad JSON body: {e}"}, keep)
            return
        name = (body.get("model", "default")
                if isinstance(body, dict) else "default")
        try:
            # engine build = device packing + warmup: off the loop
            engine = await asyncio.to_thread(core.registry.reload, name)
        except KeyError as e:
            await self._respond(writer, 404, {"error": str(e)}, keep)
            return
        except (ValueError, OSError) as e:
            await self._respond(
                writer, 400,
                {"error": f"reload failed (old model still serving): "
                          f"{e}"}, keep)
            return
        core.refresh_pool(name)
        man = dict(engine.manifest)
        man["generation"] = core.registry.manifests()[name]["generation"]
        await self._respond(writer, 200,
                            {"reloaded": name, "manifest": man}, keep)

    # -- the predict path ---------------------------------------------

    async def _predict(self, writer, headers, raw: bytes,
                       keep: bool) -> None:
        """Mirror of the threaded ``_Handler._predict`` — same
        validation order, same status mapping, same accounting — with
        the direct batcher submit replaced by fair-queue admission +
        the parked-future wait. Kept in lockstep on purpose: the
        selfcheck's front-door gate asserts bitwise-equal responses
        between the two transports."""
        core = self.core
        t0 = time.perf_counter()
        rs = None

        async def send(code, payload, extra_headers=()):
            # span back-stop, as in the threaded _send: whatever path
            # produced this response finishes the tree with its status
            if rs is not None and not rs.finished:
                core.finish_request_spans(rs, status=code)
            await self._respond(writer, code, payload, keep,
                                extra_headers=extra_headers)

        if core.draining:
            core.count("errors")
            await send(503, {"error": "draining"})
            return
        want_spans_back = (str(headers.get("x-trace-spans", ""))
                           .lower() in ("1", "true", "yes"))
        rs = core.start_request_spans(force=want_spans_back)
        try:
            body = json.loads(raw or b"{}")
        except json.JSONDecodeError as e:
            core.count("errors")
            await send(400, {"error": f"bad JSON body: {e}"})
            return
        if not isinstance(body, dict):
            core.count("errors")
            await send(400, {"error": "body must be a JSON object"})
            return
        name = body.get("model", "default")
        tenant = core.admit_tenant(headers.get("x-tenant"),
                                   body.get("tenant"), name)
        if rs is not None:
            rs.tenant = tenant
            rs.model = name
        want = tuple(body.get("return") or ("labels", "decision"))
        inst = body.get("instances")
        engine = None
        try:
            cold = core.serves_cold(name)
            if not cold:
                engine = core.registry.engine(name)
        except KeyError as e:
            core.count("errors", tenant=tenant)
            await send(404, {"error": str(e)})
            return
        if inst is None:
            core.count("errors", tenant=tenant)
            await send(400, {"error": "missing 'instances'"})
            return
        try:
            x = np.asarray(inst, dtype=np.float32)
        except (ValueError, TypeError) as e:
            core.count("errors", tenant=tenant)
            await send(400, {"error": f"instances not numeric: {e}"})
            return
        if not np.all(np.isfinite(x)):
            core.count("errors", tenant=tenant)
            await send(400, {"error": "instances contain non-finite "
                                      "values"})
            return
        if x.ndim == 1:
            x = x[None, :]
        if x.ndim != 2 or x.shape[0] == 0 or (
                engine is not None
                and x.shape[1] != engine.num_attributes):
            d = engine.num_attributes if engine is not None else "d"
            core.count("errors", tenant=tenant)
            await send(400, {"error": f"instances must be a non-empty "
                                      f"(m, {d}) matrix, got shape "
                                      f"{list(x.shape)}"})
            return
        if x.shape[0] > core.max_queue:
            core.count("errors", tenant=tenant)
            await send(413, {"error": f"{x.shape[0]} rows in one "
                                      f"request exceeds the queue "
                                      f"bound ({core.max_queue}); "
                                      "split the batch (or use `dpsvm "
                                      "test --batch` for offline "
                                      "eval)"})
            return
        bad = [w for w in want if w not in KNOWN_OUTPUTS]
        if bad:
            core.count("errors", tenant=tenant)
            await send(400, {"error": f"unknown outputs {bad}; pick "
                                      f"from {list(KNOWN_OUTPUTS)}"})
            return
        try:
            budget = core.budget_for(
                body.get("timeout_ms", headers.get("x-deadline-ms")),
                tenant=tenant)
        except ValueError as e:
            core.count("errors", tenant=tenant)
            await send(400, {"error": str(e)})
            return
        if cold:
            # model-cache cold path: synchronous by design, but not on
            # the loop — a cold hydration is exactly the stall that
            # would freeze every other connection
            try:
                ride = tuple(dict.fromkeys(want + ("decision",)))
                res = await asyncio.to_thread(core.model_cache.infer,
                                              name, x, want=ride)
            except KeyError as e:
                core.count("errors", tenant=tenant)
                await send(404, {"error": str(e)})
                return
            except ValueError as e:
                core.count("errors", tenant=tenant)
                await send(400, {"error": str(e)})
                return
            await self._finish_200(writer, send, t0, rs, budget,
                                   tenant, name, name, want, None, x,
                                   res, want_spans_back, keep)
            return
        eff_name, eff_want, degraded = core.degrade(name, want)
        if eff_name != name:
            try:
                engine = core.registry.engine(eff_name)
            except KeyError:
                eff_name, degraded = name, None
        if "proba" in eff_want and not engine.calibrated:
            core.count("errors", tenant=tenant)
            await send(400, {"error": f"model {eff_name!r} has no "
                                      "probability calibration"})
            return
        ride = tuple(dict.fromkeys(eff_want + ("decision",)))
        if rs is not None:
            # the new stage: DRR wait in the tenant lane (auto-closes
            # admission; batcher submit's queue_wait auto-closes this)
            rs.start("fair_queue", tenant=tenant)
        item = _Pending(x, ride, budget.deadline, rs, eff_name,
                        int(x.shape[0]),
                        asyncio.get_running_loop().create_future())
        try:
            self._fq.push(tenant, item, item.rows)
        except LaneFullError as e:
            core.count("rejected", tenant=tenant)
            await send(429, {"error": str(e)},
                       extra_headers=(("Retry-After", "1"),))
            return
        self._wake.set()
        try:
            try:
                ticket = await asyncio.wait_for(item.future,
                                                budget.remaining())
            except asyncio.TimeoutError:
                item.cancelled = True
                if item.ticket is not None:
                    item.ticket.cancelled = True
                raise DeadlineExceededError(
                    "prediction did not complete in time")
            if ticket.error is not None:
                raise ticket.error
            res = ticket.result
        except QueueFullError as e:
            core.count("rejected", tenant=tenant)
            await send(429, {"error": str(e)},
                       extra_headers=(("Retry-After", "1"),))
            return
        except BatcherClosedError:
            core.count("errors", tenant=tenant)
            await send(503, {"error": "draining"})
            return
        except (DeadlineExceededError, TimeoutError) as e:
            core.count("deadline_504", tenant=tenant)
            await send(504, {"error": str(e)},
                       extra_headers=(("Retry-After", "1"),))
            return
        except PoolUnavailableError as e:
            core.count("errors", tenant=tenant)
            await send(503, {"error": str(e)},
                       extra_headers=(("Retry-After", "1"),))
            return
        except ValueError as e:
            core.count("errors", tenant=tenant)
            await send(400, {"error": str(e)})
            return
        await self._finish_200(writer, send, t0, rs, budget, tenant,
                               name, eff_name, eff_want, degraded, x,
                               res, want_spans_back, keep)

    async def _finish_200(self, writer, send, t0, rs, budget, tenant,
                          name, eff_name, eff_want, degraded, x, res,
                          want_spans_back, keep) -> None:
        """The threaded ``_respond_predict`` tail, verbatim semantics:
        score-window feed, span close, latency + tenant accounting,
        counted response."""
        core = self.core
        if rs is not None:
            rs.start("respond")
        core.observe_scores(res.get("decision"))
        out = {k: _jsonable(v) for k, v in res.items()
               if k in eff_want}
        if degraded:
            out["degraded"] = degraded
        breakdown = core.finish_request_spans(
            rs, status=200, budget=budget, model=eff_name,
            rows=int(x.shape[0]))
        if breakdown is not None and want_spans_back:
            out["spans"] = breakdown
        ms = (time.perf_counter() - t0) * 1000.0
        core.observe_latency(ms)
        core.account_request(tenant, name, rows=int(x.shape[0]),
                             ms=ms, breakdown=breakdown)
        core.count("requests", tenant=tenant)
        out.update(model=name, n=int(x.shape[0]), ms=round(ms, 3))
        await self._respond(writer, 200, out, keep)

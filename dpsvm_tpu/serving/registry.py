"""Named multi-model registry with explicit hot reload.

One server process serves many models: each registered name owns a
warmed ``PredictionEngine``. Reload is EXPLICIT (an operator action —
``POST /v1/reload`` or ``ModelRegistry.reload``), never an mtime
watcher: a model file mid-write must not be half-loaded, and the
operator decides when the new artifact is ready.

Reload builds the replacement engine COMPLETELY (load, compact, pack,
warm every bucket) before the swap, then swaps under the lock — so
traffic never sees a cold or partially-constructed engine, and a load
failure (corrupt file, wrong width) leaves the old engine serving. The
``generation`` counter increments per successful reload so /v1/models
exposes which artifact generation is live.

No jax at module import (engine is imported lazily): the registry and
the HTTP server around it stay importable without touching a backend.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class _Entry:
    __slots__ = ("engine", "source", "model", "kwargs", "generation",
                 "loaded_at")

    def __init__(self, engine, source, model, kwargs):
        self.engine = engine
        self.source = source
        self.model = model            # kept for in-memory rebuilds
        self.kwargs = kwargs
        self.generation = 1
        self.loaded_at = time.time() if engine is not None else None


class ModelRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        # serializes lazy hydrations so a request storm on one cold
        # model builds its engine exactly once (the double-checked
        # pattern serving/server.pool uses for replica builds)
        self._hydrate_lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}

    def register(self, name: str, source: Optional[str] = None, *,
                 model=None, lazy: bool = False, **engine_kwargs):
        """Load + warm a model under ``name``. ``source`` is a model
        file or multiclass directory; alternatively pass an in-memory
        ``model`` (then reload is unavailable, but replica rebuilds
        still are — the model object is retained).

        ``lazy=True`` registers the manifest only: no engine is built,
        no device buffers are packed, no ladder is warmed — the first
        ``engine()`` call hydrates on demand. A 1000-model fleet
        registry boots in seconds instead of paying 1000 warmups up
        front (docs/SERVING.md "Model fleet"); ``/v1/models`` reports
        ``resident: false`` until the first request lands. Returns the
        engine (eager) or None (lazy)."""
        if (source is None) == (model is None):
            raise ValueError("register needs exactly one of source= "
                             "(a path) or model= (an in-memory model)")
        engine_kwargs.setdefault("name", name)
        if lazy:
            with self._lock:
                self._entries[name] = _Entry(None, source, model,
                                             engine_kwargs)
            return None
        from dpsvm_tpu.serving.engine import PredictionEngine

        if source is not None:
            engine = PredictionEngine.load(source, **engine_kwargs)
        else:
            engine = PredictionEngine(model, **engine_kwargs)
        with self._lock:
            self._entries[name] = _Entry(engine, source, model,
                                         engine_kwargs)
        return engine

    def build(self, name: str):
        """Construct a FRESH, fully-warmed engine for ``name`` from its
        current source (or retained in-memory model) WITHOUT touching
        the registered entry — the replica pool's rebuild path
        (serving/pool.py): every pool replica beyond the shared first
        one, and every post-ejection rebuild, is its own engine with
        its own device buffers."""
        from dpsvm_tpu.serving.engine import PredictionEngine

        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise KeyError(f"no model named {name!r} "
                               f"(registered: {list(self._entries)})")
            source, model, kwargs = entry.source, entry.model, entry.kwargs
        if source is not None:
            return PredictionEngine.load(source, **kwargs)
        return PredictionEngine(model, **kwargs)

    def source(self, name: str) -> Optional[str]:
        """The artifact path ``name`` was registered from (None for
        in-memory models) — the lifecycle loop's hot-swap target."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise KeyError(f"no model named {name!r} "
                               f"(registered: {list(self._entries)})")
            return entry.source

    def engine(self, name: str):
        """The model's warmed engine — hydrating a lazy entry on first
        touch (build COMPLETELY outside the registry lock, swap in
        under it: concurrent readers of other models never wait on a
        cold model's warmup)."""
        with self._lock:
            entry = self._entries.get(name)
        if entry is None:
            raise KeyError(f"no model named {name!r} "
                           f"(registered: {self.names()})")
        if entry.engine is not None:
            return entry.engine
        with self._hydrate_lock:
            with self._lock:
                entry = self._entries.get(name)
                if entry is None:
                    raise KeyError(f"model {name!r} was removed "
                                   "mid-hydration")
                if entry.engine is not None:
                    return entry.engine
            fresh = self.build(name)
            with self._lock:
                entry = self._entries.get(name)
                if entry is None:
                    raise KeyError(f"model {name!r} was removed "
                                   "mid-hydration")
                entry.engine = fresh
                entry.loaded_at = time.time()
            return fresh

    def resident(self, name: str) -> bool:
        """Whether ``name`` currently holds a hydrated engine (False
        for a lazy entry nobody has requested yet, and for one the
        fleet model cache paged out — ``evict``)."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise KeyError(f"no model named {name!r} "
                               f"(registered: {list(self._entries)})")
            return entry.engine is not None

    def evict(self, name: str) -> bool:
        """Drop ``name``'s hydrated engine (device buffers free with
        it) while keeping the registration — the fleet model cache's
        page-out hook (dpsvm_tpu/fleet/modelcache.py). The next
        ``engine()`` call re-hydrates from the retained source/model.
        Returns whether an engine was actually resident."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise KeyError(f"no model named {name!r} "
                               f"(registered: {list(self._entries)})")
            was = entry.engine is not None
            entry.engine = None
            entry.loaded_at = None
            return was

    def reload(self, name: str):
        """Re-load ``name`` from its source path and swap atomically.
        The old engine serves until the new one is fully warmed."""
        from dpsvm_tpu.resilience import faultinject
        from dpsvm_tpu.serving.engine import PredictionEngine

        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise KeyError(f"no model named {name!r} "
                               f"(registered: {list(self._entries)})")
            source, kwargs = entry.source, entry.kwargs
        if source is None:
            raise ValueError(f"model {name!r} was registered in-memory; "
                             "there is no source path to reload from")
        faultinject.on_serve_reload()   # DPSVM_FAULT_SERVE_FAIL_RELOAD:
        #                                 raises OSError; old stays live
        fresh = PredictionEngine.load(source, **kwargs)   # may raise —
        with self._lock:                                  # old stays live
            entry = self._entries.get(name)
            if entry is None:
                raise KeyError(f"model {name!r} was removed mid-reload")
            entry.engine = fresh
            entry.generation += 1
            entry.loaded_at = time.time()
        return fresh

    def promote_file(self, name: str, candidate_path: str) -> int:
        """Atomic hot-swap of ``name``'s artifact: move the candidate
        file onto the registered source path (``os.replace`` — readers
        see old bytes or new bytes, never a torn file), then the
        explicit warmed reload. Returns the new generation. The only
        blessed way a candidate becomes the serving artifact — the
        lifecycle loops call this, never raw file ops
        (docs/SERVING.md "Continuous learning")."""
        import os

        source = self.source(name)
        if source is None:
            raise ValueError(
                f"model {name!r} was registered in-memory; there is "
                "no source path to promote onto")
        os.replace(candidate_path, source)
        self.reload(name)
        with self._lock:
            return self._entries[name].generation

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def manifests(self) -> Dict[str, dict]:
        """Per-model manifests for ``/v1/models``. Every entry carries
        ``resident``: a hydrated model reports its full engine manifest,
        a cold (lazy, or fleet-cache-evicted) one reports the light
        registration facts only — reading 1000 cold manifests costs no
        model loads (docs/SERVING.md "Model fleet")."""
        with self._lock:
            entries = dict(self._entries)
        out = {}
        for name, e in entries.items():
            if e.engine is not None:
                m = dict(e.engine.manifest)
                m["resident"] = True
                m["loaded_at_unix"] = round(e.loaded_at, 3)
            else:
                m = {"name": name, "source": e.source,
                     "resident": False, "loaded_at_unix": None}
            m["generation"] = e.generation
            out[name] = m
        return out

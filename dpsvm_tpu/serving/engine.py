"""Online prediction engine: device-resident SV buffers + a shape
ladder that never retraces.

Training already turned the reference's per-iteration GPU launches into
big compiled MXU passes; this module does the same for *serving*. The
reference's tester scored one example at a time on the host
(``seq_test.cpp:187-210``); ``models/svm.py`` beat that with a single
``(m, d) @ (d, n_sv)`` pass per call — but every distinct ``m``
compiles a fresh XLA program, so naive online traffic (every request a
new batch size) would retrace constantly, and compilation is the
dominant wall-clock cost on the tunneled chip (docs/PERF.md).

The engine fixes the shape economy once, at load time:

* **SV packing + compaction** — support vectors, duals and squared
  norms go to the device exactly once per model. Zero-coefficient SVs
  (possible in hand-assembled or imported models; our own writers
  already drop them) are compacted away first, shrinking every
  subsequent ``(m, d) @ (d, n_sv)`` pass; the dropped count is recorded
  in the engine manifest.
* **Bucket ladder** — incoming batches are padded up to a small ladder
  of batch shapes: powers of two, capped by ``max_batch`` (which is
  itself the top rung). A request of 37 rows runs at bucket 64; a
  request of 5000 rows against ``max_batch=256`` streams as full
  256-row passes plus one padded remainder bucket.
* **Compile warmup** — every bucket is compiled at construction, so
  steady-state serving pays ZERO retraces. This is not a hope but an
  observable fact: the jitted programs are wrapped with
  ``observability/compilewatch.instrument``, warmup drains the compile
  log into ``warmup_compiles``, and the serving selfcheck
  (``python -m dpsvm_tpu.serving --selfcheck``) asserts the log stays
  empty across mixed-size post-warmup traffic.

Output parity is bitwise, not approximate: each output row of the
kernel matmul depends only on its own input row, so a row evaluated at
bucket 64 is bit-identical to the same row through a direct
``decision_function`` call — the selfcheck asserts this too. (The
engine reuses the exact jitted programs ``models/svm.py`` evaluates
with, so there is one definition of the decision math in the repo.)
That guarantee holds at the default ``precision="highest"``; the
opt-in bf16 ladder (``serve --precision default`` — bf16 multiplies,
f32 accumulation, docs/SERVING.md) trades it for a pinned float
tolerance against the f32 reference decisions instead.

Model coverage = everything ``models/io.py`` / ``models/multiclass.py``
can persist: binary SVC (with optional Platt sidecar), SVR, one-class,
precomputed-kernel models (pure-NumPy column gather — trivially
zero-compile), and one-vs-one multiclass directories (same-spec pairs
collapse into the one concatenated-SV pass of
``models/multiclass.pairwise_decisions``; mixed-spec directories fall
back to per-pair passes, each with its own warmed ladder).
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from dpsvm_tpu.models.multiclass import (MulticlassModel, load_multiclass,
                                         predict_multiclass,
                                         predict_proba_multiclass)
from dpsvm_tpu.models.svm import SVMModel
from dpsvm_tpu.observability import compilewatch
from dpsvm_tpu.serving.batcher import KNOWN_OUTPUTS

AnyModel = Union[SVMModel, MulticlassModel]


def bucket_ladder(max_batch: int) -> List[int]:
    """Powers of two below ``max_batch``, plus ``max_batch`` itself as
    the top rung (NOT rounded up: padding 10000 to 16384 would waste
    60% of every full pass, so the cap is always an exact shape)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    ladder = []
    b = 1
    while b < max_batch:
        ladder.append(b)
        b *= 2
    ladder.append(int(max_batch))
    return ladder


def compact_model(model: SVMModel) -> Tuple[SVMModel, int]:
    """Drop zero-coefficient support vectors before device packing.

    A zero alpha contributes nothing to the decision sum but still
    costs a column in every kernel matmul. Our writers never persist
    them, but imported LIBSVM files and hand-assembled models can carry
    them. Returns (model, n_dropped); the model is returned unchanged
    (same object) when there is nothing to drop, so the common path
    keeps bitwise parity with ``decision_function`` trivially.

    Approx models have no SV set to compact — returned unchanged."""
    if getattr(model, "is_approx", False):
        return model, 0
    alpha = np.asarray(model.alpha)
    keep = alpha != 0
    dropped = int(keep.size - np.count_nonzero(keep))
    if dropped == 0:
        return model, 0
    model = dataclasses.replace(
        model,
        x_sv=np.ascontiguousarray(np.asarray(model.x_sv)[keep]),
        alpha=np.ascontiguousarray(alpha[keep]),
        y_sv=np.ascontiguousarray(np.asarray(model.y_sv)[keep]),
        sv_idx=(np.asarray(model.sv_idx)[keep]
                if model.sv_idx is not None else None),
    )
    return model, dropped


def _load_binary_platt(path: str) -> Optional[Tuple[float, float]]:
    from dpsvm_tpu.models.calibration import load_platt, sidecar_path
    if os.path.exists(sidecar_path(path)):
        return load_platt(path)
    return None


class SegmentPack:
    """N same-spec binary SV models concatenated into the operands of
    ONE ``models/svm._pairwise_decisions_jit`` segment-sum program:
    a ``(m, d) @ (d, S_total)`` kernel pass over every model's SVs at
    once, then a sorted segment_sum per model -> an ``(m, N)`` decision
    matrix per dispatch.

    This is the one definition of the concatenated-SV decision program
    in the repo: the engine's OvO collapse (``_build_mc_batched``) and
    the fleet's same-spec model groups (``dpsvm_tpu/fleet/packer.py``)
    both build THIS, so the two paths cannot drift. All models must
    share (kernel, gamma, coef0, degree, d) — the caller groups by
    spec; this class only asserts it.
    """

    def __init__(self, models: Sequence[SVMModel], *, tag: str,
                 include_b: bool = True,
                 precision_name: str = "HIGHEST"):
        import jax.numpy as jnp

        from dpsvm_tpu.models.svm import _pairwise_decisions_jit

        if not models:
            raise ValueError("SegmentPack needs at least one model")
        specs = {(m.kernel, float(m.gamma), float(m.coef0),
                  int(m.degree), int(m.num_attributes))
                 for m in models}
        if len(specs) != 1:
            raise ValueError(f"SegmentPack needs one shared kernel "
                             f"spec, got {len(specs)}: {sorted(specs)}")
        if models[0].kernel == "precomputed":
            raise ValueError("precomputed-kernel models have no SV "
                             "feature rows to concatenate")
        self.n_models = len(models)
        self.num_attributes = int(models[0].num_attributes)
        self.n_sv = int(sum(m.n_sv for m in models))
        self.sv_all = jnp.asarray(np.concatenate(
            [np.asarray(m.x_sv, np.float32) for m in models]))
        self.coef = jnp.asarray(np.concatenate(
            [np.asarray(m.alpha, np.float32)
             * np.asarray(m.y_sv, np.float32) for m in models]))
        self.seg_ids = jnp.asarray(np.repeat(
            np.arange(len(models), dtype=np.int32),
            [int(m.n_sv) for m in models]))
        self.b_vec = jnp.asarray(np.asarray([m.b for m in models],
                                            np.float32))
        spec = models[0]
        self.kw = dict(kind=spec.kernel, degree=int(spec.degree),
                       include_b=bool(include_b),
                       num_segments=len(models),
                       precision_name=precision_name)
        self.gamma = jnp.float32(spec.gamma)
        self.coef0 = jnp.float32(spec.coef0)
        self._run = compilewatch.instrument(_pairwise_decisions_jit, tag)

    def decide(self, block: np.ndarray) -> np.ndarray:
        """(bucket, d) padded block -> (bucket, N) decision matrix."""
        import jax.numpy as jnp
        return np.asarray(self._run(
            jnp.asarray(block), self.sv_all, self.coef, self.seg_ids,
            self.b_vec, self.gamma, self.coef0, **self.kw))


class PredictionEngine:
    """One loaded model, packed for serving (see module docstring).

    ``infer``/``predict``/``decision_values`` are safe to call from any
    single thread at a time; the serving stack funnels all calls
    through one MicroBatcher worker per model, and a lock here keeps
    direct concurrent use (tests, ad-hoc scripts) correct too.
    """

    def __init__(self, model: AnyModel, *, name: str = "default",
                 max_batch: int = 256, include_b: bool = True,
                 platt: Optional[Tuple[float, float]] = None,
                 source: Optional[str] = None, warmup: bool = True,
                 precision: str = "highest",
                 hbm_budget_mb: Optional[float] = None):
        if precision not in ("highest", "high", "default"):
            raise ValueError("precision must be 'highest', 'high' or "
                             f"'default', got {precision!r}")
        if hbm_budget_mb is not None and not (float(hbm_budget_mb) > 0):
            raise ValueError(f"hbm_budget_mb must be > 0, got "
                             f"{hbm_budget_mb}")
        self.name = str(name)
        self.include_b = bool(include_b)
        self.source = source
        # MXU mode of the decision ladder ("serve --precision"):
        # "highest" = exact f32, the default and the bitwise-
        # decision_function-parity path; "default" = bf16 multiplies
        # with f32 accumulation (docs/SERVING.md). The precomputed-
        # kernel decider is host NumPy and ignores the knob.
        self.precision = str(precision)
        self._pname = self.precision.upper()
        self.max_batch = int(max_batch)
        self.buckets = bucket_ladder(self.max_batch)
        self.multiclass = isinstance(model, MulticlassModel)
        self.warmup_compiles: List[dict] = []
        self.n_sv_dropped = 0
        # per-device HBM budget ("serve --hbm-budget-mb"): a binary SV
        # or approx model whose packed buffers exceed it is served
        # through the mesh-sharded path (serving/sharded.py) when >= 2
        # devices are visible. None = never shard (the default).
        self.hbm_budget_mb = (float(hbm_budget_mb)
                              if hbm_budget_mb is not None else None)
        self._sharded_deciders: List = []
        self._lock = threading.Lock()
        self._bucket_counts: Dict[int, int] = {b: 0 for b in self.buckets}
        if self.multiclass:
            pairs = []
            for m in model.models:
                m, dropped = compact_model(m)
                self.n_sv_dropped += dropped
                pairs.append(m)
            model = dataclasses.replace(model, models=pairs)
            self.platt = None           # per-pair sigmoids live in model
            self.task = "multiclass"
        else:
            model, self.n_sv_dropped = compact_model(model)
            self.platt = platt
            self.task = model.task
        self.model = model
        self._build()
        if warmup:
            self._warmup()

    # -- construction -------------------------------------------------

    @classmethod
    def load(cls, path: str, **kwargs) -> "PredictionEngine":
        """Load any saved model: a multiclass directory
        (``models/multiclass.py``) or a binary/SVR/one-class model file
        (``models/io.py``, LIBSVM format auto-detected), picking up the
        Platt sidecar when one sits next to a binary model."""
        if os.path.isdir(path):
            model: AnyModel = load_multiclass(path)
            platt = None
        else:
            from dpsvm_tpu.models.io import load_model
            model = load_model(path)
            platt = _load_binary_platt(path)
        kwargs.setdefault("platt", platt)
        kwargs.setdefault("name", os.path.basename(path.rstrip("/"))
                          or "default")
        return cls(model, source=path, **kwargs)

    def _build(self) -> None:
        """Pack device-resident buffers and select the per-block
        decision program."""
        if self.multiclass:
            ms = self.model.models
            specs = {(m.kernel, float(m.gamma), float(m.coef0),
                      int(m.degree)) for m in ms}
            if (len(specs) == 1 and ms[0].kernel != "precomputed"
                    and not any(getattr(m, "is_approx", False)
                                for m in ms)):
                self._build_mc_batched()
            else:
                # mixed kernel specs (hand-assembled directory) — one
                # warmed ladder per pair; still zero steady-state
                # compiles, just P passes per block.
                self._pair_deciders = [self._make_binary_decider(m, i)
                                       for i, m in enumerate(ms)]
                self._decide_block = self._decide_mc_per_pair
            return
        self._decide_block = self._make_binary_decider(self.model, None)

    def _maybe_sharded(self, model, tag: str):
        """The --hbm-budget-mb decision: a ShardedDecider when the
        packed buffers exceed the per-device budget and the mesh can
        host them (>= 2 devices), else None (single-device ladder).
        Precomputed models (host gather, nothing device-resident) and
        the same-spec multiclass SegmentPack collapse never shard —
        only binary SV/approx deciders (including multiclass mixed-spec
        per-pair ones) reach here."""
        if self.hbm_budget_mb is None:
            return None
        from dpsvm_tpu.serving import sharded as _sharded
        if not _sharded.eligible(model):
            return None
        if (_sharded.model_bytes_est(model)
                <= self.hbm_budget_mb * (1 << 20)):
            return None
        import jax
        if len(jax.devices()) < 2:
            return None
        sd = _sharded.ShardedDecider(model, include_b=self.include_b,
                                     precision_name=self._pname,
                                     tag=f"{tag}-sharded-decision")
        self._sharded_deciders.append(sd)
        return sd

    def _make_binary_decider(self, model: SVMModel, pair: Optional[int]):
        tag = f"serve[{self.name}]" + (f"-pair{pair}" if pair is not None
                                       else "")
        sharded = self._maybe_sharded(model, tag)
        if sharded is not None:
            return sharded.decide
        if getattr(model, "is_approx", False):
            # EXPLICIT model-kind dispatch: an approx model has no SV
            # buffers — falling through to the SV path would crash on
            # model.x_sv (or worse, serve garbage). The decider is the
            # featurize-and-dot program ``approx/model.py`` evaluates
            # with, so matched shapes stay bitwise-identical to
            # ``decision_function``, like the SV path.
            import jax.numpy as jnp

            from dpsvm_tpu.approx.model import (_approx_decision_jit,
                                                _decider_args)
            args, kw = _decider_args(model)
            run = compilewatch.instrument(_approx_decision_jit,
                                          f"{tag}-approx-decision")
            include_b, pname = self.include_b, self._pname

            def decide(block: np.ndarray) -> np.ndarray:
                return np.asarray(run(jnp.asarray(block), *args,
                                      include_b=include_b,
                                      precision_name=pname, **kw))

            return decide

        if model.kernel == "precomputed":
            coef = (np.asarray(model.alpha, np.float32)
                    * np.asarray(model.y_sv, np.float32))
            sv_idx = np.asarray(model.sv_idx)
            b = np.float32(model.b)

            def decide(block: np.ndarray) -> np.ndarray:
                # K(test, train) column gather — host NumPy, no XLA
                # program, zero compiles by construction.
                dual = block[:, sv_idx] @ coef
                if self.include_b:
                    dual = dual - b
                return dual.astype(np.float32)

            return decide

        import jax.numpy as jnp

        from dpsvm_tpu.models.svm import _decision_jit
        from dpsvm_tpu.ops.kernels import row_norms_sq

        x_sv = jnp.asarray(np.asarray(model.x_sv, np.float32))
        coef = jnp.asarray(np.asarray(model.alpha, np.float32)
                           * np.asarray(model.y_sv, np.float32))
        sv2 = row_norms_sq(x_sv)
        b = jnp.float32(model.b)
        gamma = jnp.float32(model.gamma)
        coef0 = jnp.float32(model.coef0)
        run = compilewatch.instrument(_decision_jit, f"{tag}-decision")
        kind, degree, include_b = model.kernel, int(model.degree), \
            self.include_b
        pname = self._pname

        def decide(block: np.ndarray) -> np.ndarray:
            return np.asarray(run(jnp.asarray(block), x_sv, coef, sv2,
                                  b, gamma, coef0, kind, degree,
                                  include_b, pname))

        return decide

    def _build_mc_batched(self) -> None:
        # The OvO collapse: all P same-spec pairs as ONE SegmentPack
        # program — the construction the fleet packer generalizes to
        # arbitrary same-spec model groups (fleet/packer.py).
        self._pack = SegmentPack(self.model.models,
                                 tag=f"serve[{self.name}]-pairwise",
                                 include_b=self.include_b,
                                 precision_name=self._pname)
        self._decide_block = self._pack.decide

    def _decide_mc_per_pair(self, block: np.ndarray) -> np.ndarray:
        return np.stack([d(block) for d in self._pair_deciders], axis=1)

    def _warmup(self) -> None:
        """Compile every ladder bucket up front; record what it cost.

        Drains the process-global compile log afterwards — engines are
        constructed at process startup (server boot, eval commands),
        never concurrently with a traced training run."""
        compilewatch.drain()            # foreign observations out first
        d = self.num_attributes
        for bucket in self.buckets:
            self._decide_block(np.zeros((bucket, d), np.float32))
        self.warmup_compiles = compilewatch.drain()

    # -- facts --------------------------------------------------------

    @property
    def num_attributes(self) -> int:
        if self.multiclass:
            return int(self.model.models[0].num_attributes)
        return int(self.model.num_attributes)

    @property
    def n_sv(self) -> int:
        if self.multiclass:
            return int(sum(m.n_sv for m in self.model.models))
        return int(self.model.n_sv)

    @property
    def calibrated(self) -> bool:
        if self.multiclass:
            return self.model.platt is not None
        return self.platt is not None

    @property
    def sharded(self) -> bool:
        """True when any of this engine's deciders runs mesh-sharded
        (the --hbm-budget-mb selection fired)."""
        return bool(self._sharded_deciders)

    @property
    def model_kind(self) -> str:
        """Which decision machinery serves this model: "sv" (device SV
        buffers), "approx-rff"/"approx-nystrom" (featurize + dot, no SV
        buffers), or "multiclass" (per-pair kinds in the manifest)."""
        if self.multiclass:
            return "multiclass"
        return getattr(self.model, "model_kind", "sv")

    @property
    def manifest(self) -> dict:
        """Everything an operator (or /v1/models) needs to know about
        the loaded model — including the compile-warmup receipt and the
        SV-compaction count."""
        out = {
            "name": self.name,
            "task": self.task,
            "model_kind": self.model_kind,
            "source": self.source,
            "num_attributes": self.num_attributes,
            "n_sv": self.n_sv,
            "n_sv_dropped": self.n_sv_dropped,
            "max_batch": self.max_batch,
            "buckets": list(self.buckets),
            "include_b": self.include_b,
            "precision": self.precision,
            "calibrated": self.calibrated,
            "warmup_compiles": len(self.warmup_compiles),
            "warmup_compile_seconds": round(
                sum(c["seconds"] for c in self.warmup_compiles), 3),
        }
        if self.hbm_budget_mb is not None:
            out["hbm_budget_mb"] = self.hbm_budget_mb
        out["sharded"] = self.sharded
        if self._sharded_deciders:
            # binary models have exactly one; mixed-spec multiclass may
            # shard several pairs — report the first (they share mesh
            # geometry) plus the count
            out["sharding"] = dict(self._sharded_deciders[0].facts(),
                                   n_sharded_deciders=len(
                                       self._sharded_deciders))
        if self.multiclass:
            out["classes"] = [int(c) for c in self.model.classes]
            out["n_pairs"] = len(self.model.models)
            out["pair_kinds"] = sorted(
                {getattr(m, "model_kind", "sv")
                 for m in self.model.models})
        else:
            out["kernel"] = self.model.kernel
            if self.model_kind.startswith("approx"):
                out["approx_dim"] = int(self.model.fmap.dim)
                out["approx_seed"] = int(self.model.fmap.seed)
        return out

    def bucket_counts(self) -> Dict[int, int]:
        """How many device passes each ladder rung has served (the
        /metricsz bucket histogram)."""
        with self._lock:
            return dict(self._bucket_counts)

    def _bucket_for(self, m: int) -> int:
        for b in self.buckets:
            if b >= m:
                return b
        return self.max_batch

    # -- evaluation ---------------------------------------------------

    def _check(self, x) -> np.ndarray:
        x = np.asarray(x, np.float32)
        if x.ndim == 1:
            x = x[None, :]
        if x.ndim != 2:
            raise ValueError(f"instances must be (m, {self.num_attributes})"
                             f", got shape {x.shape}")
        if x.shape[1] != self.num_attributes:
            raise ValueError(
                f"instances have {x.shape[1]} attributes, model "
                f"{self.name!r} expects {self.num_attributes}")
        return x

    def _decisions(self, x: np.ndarray) -> np.ndarray:
        """(m,) decision values (binary tasks) or (m, P) pairwise
        decisions (multiclass), streamed through the bucket ladder:
        full ``max_batch`` passes, then one padded remainder bucket."""
        x = self._check(x)
        m = x.shape[0]
        out = None
        lo = 0
        while lo < m:
            take = min(self.max_batch, m - lo)
            bucket = self._bucket_for(take)
            block = np.zeros((bucket, x.shape[1]), np.float32)
            block[:take] = x[lo:lo + take]
            with self._lock:
                vals = self._decide_block(block)
                self._bucket_counts[bucket] += 1
            if out is None:
                out = np.empty((m,) + vals.shape[1:], vals.dtype)
            out[lo:lo + take] = vals[:take]
            lo += take
        return out

    def decision_values(self, x) -> np.ndarray:
        """Binary tasks: the (m,) decision/score/prediction vector.
        Multiclass: the (m, P) pairwise decision matrix."""
        return self._decisions(x)

    def pairwise_list(self, x) -> List[np.ndarray]:
        """Multiclass pairwise decisions in the per-pair-list shape
        ``models/multiclass.pairwise_decisions`` returns (the shape
        ``cmd_test`` and the couplers consume)."""
        if not self.multiclass:
            raise ValueError("pairwise_list applies to multiclass models")
        dec = self._decisions(x)
        return [dec[:, p] for p in range(dec.shape[1])]

    def _with_b(self, dec: np.ndarray):
        """Decision values WITH the intercept folded in, from whatever
        ``include_b`` produced (the Platt sigmoids are defined on
        intercept-included decisions)."""
        if self.include_b:
            return dec
        if self.multiclass:
            bs = np.asarray([m.b for m in self.model.models], np.float32)
            return dec - bs[None, :]
        return dec - np.float32(self.model.b)

    def infer(self, x, want: Sequence[str] = ("labels",)) -> dict:
        """One decision pass, every requested output derived from it.

        Returns a dict with any of: ``labels`` (class labels; floats
        for SVR; +1/-1 inlier for one-class), ``decision`` (decision
        values / scores; (m, P) pairwise matrix for multiclass),
        ``proba`` (Platt probability of +1 for binary; (m, k) coupled
        class probabilities for multiclass). Requesting ``proba`` from
        an uncalibrated model raises ValueError."""
        unknown = [w for w in want if w not in KNOWN_OUTPUTS]
        if unknown:
            raise ValueError(f"unknown outputs {unknown}; "
                             f"pick from {list(KNOWN_OUTPUTS)}")
        if "proba" in want and not self.calibrated:
            raise ValueError(
                f"model {self.name!r} has no probability calibration — "
                "train with --probability (binary models also need the "
                ".platt.json sidecar next to the model file)")
        x = self._check(x)
        dec = self._decisions(x)
        out: dict = {}
        if self.multiclass:
            cols = [dec[:, p] for p in range(dec.shape[1])]
            if "proba" in want:
                cols_b = [c for c in
                          np.moveaxis(self._with_b(dec), 1, 0)]
                proba = predict_proba_multiclass(self.model, x,
                                                 decisions=cols_b)
                out["proba"] = proba
                if "labels" in want:
                    # LIBSVM -b 1 semantics: predict by the coupled
                    # argmax so labels stay consistent with proba
                    # (cmd_test's rule).
                    out["labels"] = self.model.classes[
                        np.argmax(proba, axis=1)]
            if "labels" in want and "labels" not in out:
                out["labels"] = predict_multiclass(
                    self.model, x, include_b=self.include_b,
                    decisions=cols)
            if "decision" in want:
                out["decision"] = dec
            return out
        if "decision" in want:
            out["decision"] = dec
        if "labels" in want:
            if self.task == "svr":
                out["labels"] = dec
            else:
                out["labels"] = np.where(dec < 0, -1, 1).astype(np.int32)
        if "proba" in want:
            from dpsvm_tpu.models.calibration import sigmoid_proba
            pa, pb = self.platt
            out["proba"] = sigmoid_proba(self._with_b(dec), pa, pb)
        return out

    def predict(self, x) -> np.ndarray:
        """Labels (classification), predictions (SVR), +1/-1 inlier
        flags (one-class) — ``infer``'s ``labels`` output."""
        return self.infer(x, want=("labels",))["labels"]

    def predict_proba(self, x) -> np.ndarray:
        return self.infer(x, want=("proba",))["proba"]

"""Dynamic micro-batching: many small requests, few big MXU passes.

The amortization argument the training side already made (one big
compiled pass beats many launches — "Recipe for Fast Large-scale SVM
Training", arXiv:2207.01016) applies unchanged to inference: a single
``(64, d) @ (d, n_sv)`` pass costs barely more than a ``(1, d)`` one,
so concurrent single-row requests should ride the same device pass.

One worker thread owns the engine. Requests enqueue; the worker takes
the oldest request and keeps coalescing until either ``max_batch`` rows
are gathered or ``max_delay_ms`` has passed since the batch opened —
the classic size-or-deadline rule, so an idle server adds at most
``max_delay_ms`` latency and a busy one converges to full buckets.

Admission control is a bounded ROW queue: when ``max_queue`` rows are
already waiting, ``submit`` raises ``QueueFullError`` immediately — a
fast reject the HTTP layer turns into 429, instead of unbounded queue
latency (the failure mode where an overloaded server times every
client out instead of telling any of them to back off).

Correctness does not depend on how traffic happens to coalesce: engine
output rows are independent of their batch-mates (bitwise — see
``engine.py``), so a request answered in a 64-row batch is answered
identically to one served alone. ``tests/test_serving.py`` pins this
by replaying the same requests under forced-coalesced and sequential
scheduling.

Stdlib-only on purpose (no jax import): the module is importable on a
machine with no accelerator, and unit tests can drive it with a stub
engine.
"""

from __future__ import annotations

import inspect
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dpsvm_tpu.serving.budget import DeadlineExceededError

#: outputs the engine's ``infer`` understands; "proba" additionally
#: needs calibration. Lives here (stdlib-only module) so the HTTP
#: layer can validate without importing the jax-backed engine.
KNOWN_OUTPUTS = ("labels", "decision", "proba")


class QueueFullError(RuntimeError):
    """Admission reject: the pending-row queue is at capacity. The
    caller should shed load (HTTP 429), not wait."""


class BatcherClosedError(RuntimeError):
    """Submitted after close() — the server is draining."""


class _Ticket:
    """One request's future: wait() blocks until the worker publishes
    this request's slice of the batch result (or its error).

    A ticket may carry an absolute ``deadline`` (perf_counter). A
    waiter that times out marks the ticket ``cancelled``, and the
    worker drops cancelled/expired tickets at batch-formation time
    instead of computing for nobody — the expired work is counted in
    ``stats()["expired"]``, never silently burned.

    ``spans`` (observability/spans.RequestSpans, None when the request
    is unsampled) rides along so the worker can bracket this ticket's
    queue-wait / batch-formation / dispatch stages — the request-scoped
    latency attribution of docs/OBSERVABILITY.md "Spans".

    ``on_done`` (callable taking the ticket, or None) fires from the
    worker thread right after the ticket's result or error is
    published (``event.set()``). It exists for callers that must NOT
    block a thread in ``wait()`` — the asyncio front door passes a
    ``loop.call_soon_threadsafe`` trampoline here and resolves a
    future on the loop instead. The callback must be fast and never
    raise (exceptions are swallowed so they can't kill the worker)."""

    __slots__ = ("rows", "want", "event", "result", "error", "t_submit",
                 "deadline", "cancelled", "spans", "on_done")

    def __init__(self, rows: np.ndarray, want: Tuple[str, ...],
                 deadline: Optional[float] = None, spans=None,
                 on_done=None):
        self.rows = rows
        self.want = want
        self.event = threading.Event()
        self.result: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        self.deadline = deadline
        self.cancelled = False
        self.spans = spans
        self.on_done = on_done

    def wait(self, timeout: Optional[float] = None) -> dict:
        """Block for the result. The wait is bounded by BOTH the given
        timeout and the ticket's own deadline; on expiry the ticket is
        cancelled (so the worker won't compute it) and
        ``DeadlineExceededError`` — a TimeoutError — is raised (the
        HTTP layer maps it to 504, never a 400)."""
        if self.deadline is not None:
            rem = self.deadline - time.perf_counter()
            timeout = rem if timeout is None else min(timeout, rem)
        if timeout is not None and timeout <= 0:
            self.cancelled = True
            raise DeadlineExceededError(
                "deadline exhausted before the prediction completed")
        if not self.event.wait(timeout):
            # Mark first, then re-check: the worker may have published
            # between the wait timing out and the cancel landing.
            self.cancelled = True
            if not self.event.is_set():
                raise DeadlineExceededError(
                    "prediction did not complete in time")
        # The dispatch stage is NOT ended here: the next stage the
        # caller opens (`respond`) auto-closes it at that exact
        # instant (observability/spans.RequestSpans.start), so the
        # thread-wakeup latency between the worker's publish and the
        # caller resuming is attributed to the dispatch with no gap —
        # and a caller that never gets that far (blown deadline) has
        # it cut at the root end by finish(), which IS the attribution.
        if self.error is not None:
            raise self.error
        return self.result


class MicroBatcher:
    """Size-or-deadline request coalescing in front of an engine.

    ``infer_fn(x, want)`` is the engine call (resolved per batch, so a
    registry hot-reload takes effect without rebuilding the batcher).
    ``start=False`` leaves the worker unstarted — tests use it to stage
    a deterministic queue, then ``start()`` to coalesce it in one batch.
    """

    def __init__(self, infer_fn: Callable[[np.ndarray, Tuple[str, ...]],
                                          dict],
                 *, max_batch: int = 256, max_delay_ms: float = 2.0,
                 max_queue: int = 4096, start: bool = True):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._infer = infer_fn
        # Deadline-aware engines (the replica pool) take the batch's
        # deadline as a keyword; plain engines keep the 2-arg shape.
        # Same opt-in for span contexts: a `spans` keyword means the
        # engine (the pool) records its own sub-spans per request.
        try:
            params = inspect.signature(infer_fn).parameters
            self._pass_deadline = "deadline" in params
            self._pass_spans = "spans" in params
        except (TypeError, ValueError):
            self._pass_deadline = False
            self._pass_spans = False
        self.max_batch = int(max_batch)
        self.max_delay_s = max(float(max_delay_ms), 0.0) / 1000.0
        self.max_queue = int(max_queue)
        self._q: deque = deque()
        self._rows_queued = 0
        self._cond = threading.Condition()
        self._closing = False
        self._drain = True
        self._worker: Optional[threading.Thread] = None
        # batch-size histogram: coalesced rows per engine call
        self._batch_rows: Dict[int, int] = {}
        self._n_batches = 0
        self._n_requests = 0
        self._n_rejected = 0
        self._n_expired = 0
        if start:
            self.start()

    # -- client side --------------------------------------------------

    def submit(self, rows, want: Sequence[str] = ("labels",),
               deadline: Optional[float] = None, spans=None,
               on_done=None) -> _Ticket:
        """Enqueue one request (rows: (k, d) float32). Returns a ticket
        to ``wait()`` on. Raises ``QueueFullError`` (fast, no blocking)
        at capacity, ``BatcherClosedError`` while draining.
        ``deadline`` (absolute perf_counter) bounds the whole journey:
        an expired ticket is dropped at batch formation, not computed.
        ``spans`` (RequestSpans or None) opens its ``queue_wait`` the
        moment the ticket is accepted — rejects never count as queue
        time. ``on_done`` (see ``_Ticket``) is attached ATOMICALLY at
        submit so there is no window where the worker publishes before
        the callback exists."""
        rows = np.asarray(rows, np.float32)
        if rows.ndim == 1:
            rows = rows[None, :]
        n = int(rows.shape[0])
        if n == 0:
            raise ValueError("empty request")
        t = _Ticket(rows, tuple(want), deadline, spans=spans,
                    on_done=on_done)
        with self._cond:
            if self._closing:
                raise BatcherClosedError("server is draining")
            if self._rows_queued + n > self.max_queue:
                self._n_rejected += 1
                raise QueueFullError(
                    f"queue full ({self._rows_queued} rows waiting, "
                    f"max {self.max_queue}) — retry with backoff")
            if spans is not None:
                spans.start("queue_wait")
            self._q.append(t)
            self._rows_queued += n
            self._n_requests += 1
            self._cond.notify()
        return t

    def infer(self, rows, want: Sequence[str] = ("labels",),
              timeout: Optional[float] = 60.0) -> dict:
        """submit + wait — the HTTP handler's one call."""
        return self.submit(rows, want).wait(timeout)

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._worker is not None:
            return
        self._worker = threading.Thread(target=self._run,
                                        name="dpsvm-batcher",
                                        daemon=True)
        self._worker.start()

    def close(self, drain: bool = True,
              timeout: Optional[float] = 30.0) -> None:
        """Stop accepting; with ``drain`` the worker finishes every
        queued request first (the SIGTERM graceful-drain semantics),
        otherwise pending tickets fail with BatcherClosedError."""
        with self._cond:
            self._closing = True
            self._drain = drain
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout)

    # -- stats --------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return self._rows_queued

    def stats(self) -> dict:
        with self._cond:
            return {
                "requests": self._n_requests,
                "rejected": self._n_rejected,
                "expired": self._n_expired,
                "batches": self._n_batches,
                "queue_depth_rows": self._rows_queued,
                "batch_rows_histogram": {str(k): v for k, v in
                                         sorted(self._batch_rows.items())},
            }

    # -- worker -------------------------------------------------------

    @staticmethod
    def _notify(t: _Ticket) -> None:
        """Fire the ticket's ``on_done`` (if any) after its terminal
        publish. Runs on the worker thread; callback errors are
        swallowed — a broken callback must not take the batcher (and
        every other tenant's requests) down with it."""
        cb = t.on_done
        if cb is not None:
            try:
                cb(t)
            except Exception:
                pass

    @staticmethod
    def _note_batched(t: _Ticket) -> None:
        """Span bookkeeping at batch admission: the ticket stops
        waiting in the queue and starts riding an open batch
        (batch_form's start auto-closes queue_wait at the same
        timestamp — stage transitions are gap-free by construction)."""
        if t.spans is not None:
            t.spans.start("batch_form")

    def _prune_head(self) -> None:
        """Drop dead tickets from the queue head (holding the lock).
        Cancelled tickets (their waiter already gave up) and
        deadline-expired ones are dropped here — at batch-formation
        time — instead of being computed for nobody; an expired
        ticket's waiter (if any is still blocked on a caller-supplied
        timeout) is woken with DeadlineExceededError. Both count as
        ``expired`` in stats()."""
        now = time.perf_counter()
        while self._q:
            t = self._q[0]
            expired = (t.deadline is not None and t.deadline <= now)
            if not (t.cancelled or expired):
                return
            self._q.popleft()
            self._rows_queued -= int(t.rows.shape[0])
            self._n_expired += 1
            if not t.cancelled:
                t.error = DeadlineExceededError(
                    "deadline passed while queued")
                t.event.set()
            # on_done fires for cancelled tickets too: the async front
            # door accounts inflight rows at submit and only releases
            # them in on_done, so a silent drop here would leak them
            # until the dispatcher wedges at _inflight_limit.
            self._notify(t)

    def _take_batch(self) -> Optional[List[_Ticket]]:
        """Block for the first request, then coalesce until max_batch
        rows or the deadline. None = closed and (drained or no-drain).
        May return an empty list when every queued ticket had already
        expired — the worker just takes the next batch."""
        with self._cond:
            while True:
                self._prune_head()
                if self._q:
                    break
                if self._closing:
                    return None
                self._cond.wait()
            if self._closing and not self._drain:
                return None
            first = self._q.popleft()
            self._rows_queued -= int(first.rows.shape[0])
            self._note_batched(first)
            batch = [first]
            rows = int(first.rows.shape[0])
            deadline = time.perf_counter() + self.max_delay_s
            while rows < self.max_batch:
                self._prune_head()
                if self._q:
                    nxt = int(self._q[0].rows.shape[0])
                    if rows + nxt > self.max_batch:
                        break
                    t = self._q.popleft()
                    self._rows_queued -= nxt
                    self._note_batched(t)
                    batch.append(t)
                    rows += nxt
                    continue
                remaining = deadline - time.perf_counter()
                if remaining <= 0 or self._closing:
                    break
                self._cond.wait(remaining)
            return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                if not self._drain:
                    with self._cond:
                        leftovers = list(self._q)
                        self._q.clear()
                        self._rows_queued = 0
                    for t in leftovers:
                        t.error = BatcherClosedError("server shut down")
                        t.event.set()
                        self._notify(t)
                return
            if not batch:                  # all queued tickets expired
                continue
            x = (batch[0].rows if len(batch) == 1
                 else np.concatenate([t.rows for t in batch]))
            want = tuple(dict.fromkeys(w for t in batch for w in t.want))
            with self._cond:
                self._n_batches += 1
                self._batch_rows[int(x.shape[0])] = \
                    self._batch_rows.get(int(x.shape[0]), 0) + 1
            span_ctxs = []
            for t in batch:
                if t.spans is not None:
                    # auto-closes batch_form at the same instant
                    t.spans.start("device_dispatch",
                                  batch_rows=int(x.shape[0]))
                    span_ctxs.append(t.spans)
            try:
                kw = {}
                if self._pass_deadline:
                    # the batch stays interesting until its LAST
                    # member's deadline (earlier members 504 on their
                    # own wait; later ones still want the result)
                    ds = [t.deadline for t in batch]
                    kw["deadline"] = (None if any(d is None for d in ds)
                                      else max(ds))
                if self._pass_spans and span_ctxs:
                    kw["spans"] = span_ctxs
                res = (self._infer(x, want, **kw) if kw
                       else self._infer(x, want))
            except BaseException as e:     # noqa: BLE001 — published to
                for t in batch:            # every waiting ticket
                    if t.spans is not None:
                        t.spans.end("device_dispatch",
                                    error=type(e).__name__)
                    t.error = e
                    t.event.set()
                    self._notify(t)
                continue
            lo = 0
            for t in batch:
                hi = lo + int(t.rows.shape[0])
                t.result = {k: v[lo:hi] for k, v in res.items()
                            if k in t.want}
                # device_dispatch is ended by the waiter's NEXT stage
                # bracket (auto-close) so wakeup latency stays
                # attributed with no inter-stage gap
                t.event.set()
                self._notify(t)
                lo = hi

"""Mesh-sharded decision pass: one model's buffers spread over chips.

The engine's single-device ladder (``serving/engine.py``) assumes the
packed model fits one device's HBM. A model that doesn't — a large SV
set, or a wide approx feature map — would either OOM at packing or
evict everything else from the PR 19 model cache. This module serves
such a model by sharding the REDUCTION axis of its decision program
over the ``parallel/mesh`` data axis, exactly the way the distributed
trainers shard training rows:

* **SV (dual) models** — the support-vector axis is sharded: each
  device holds ``S/n`` SV rows (+ their coef and squared norms),
  computes its partial ``(m, S/n) kernel-matmul``, and a ``lax.psum``
  over the ``"shard"`` axis folds the partials into the full (m,)
  decision. Query rows are replicated (they are small; the SV buffers
  are what didn't fit).
* **Approx models** — the FEATURE axis is sharded. RFF: each device
  holds a column block of omega and the matching (cos-half, sin-half)
  weight slices, so its partial is the same ``scale * [cos z | sin z]
  @ w_blk`` program the single-device decider runs, just narrower.
  The cos/sin scale is the GLOBAL ``sqrt(2/dim)`` — a naive per-block
  featurize would rescale by the block width and serve garbage.
  Nystrom: landmarks are replicated (they are ``dim``-sized, small by
  construction), the whitening projection's columns and ``w`` are
  sharded.

Padding makes the shards even: the sharded axis is padded up to a
multiple of the mesh size with zero coefficients / zero weights, whose
contribution to the f32 partial is EXACTLY ``0.0`` (finite kernel or
feature value times a zero coefficient), so padding never perturbs the
decision bits.

**What "parity" means here.** f32 addition does not reassociate: a
single ``(m, S) @ (S,)`` matmul and a fold of per-block partials
differ in final bits (observed ~7e-8 on CPU), so NO sharded execution
can be bitwise-equal to the classic single-pass ladder. What IS exact
— and what the tests and the serving selfcheck pin — is that the mesh
execution (partials + ``psum``) is bitwise-identical to the SAME
blocked program run unsharded on one device with an in-order fold:
``ShardedDecider.reference``. Against the classic ladder the sharded
decisions agree to f32 roundoff (the documented, pinned tolerance).
Determinism still holds: the block layout is fixed at build time, so
every request sees one reduction order, and matched shapes are
bitwise-reproducible call over call with zero steady-state retraces
(the program set is one jitted mesh program per ladder bucket, warmed
like every other decider and watched by ``compilewatch``).

Selection lives in the engine: ``--hbm-budget-mb`` (serve) sets a
per-device budget, ``model_bytes_est`` reuses the fleet model-cache
byte math (``fleet/modelcache.resident_bytes``), and a binary SV or
approx model whose packed buffers exceed the budget is served through
this path when ≥2 devices are visible. Precomputed-kernel models
(host NumPy gather, nothing device-resident) and the multiclass
SegmentPack collapse stay on their existing paths.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import numpy as np

from dpsvm_tpu.observability import compilewatch

__all__ = ["ShardedDecider", "model_bytes_est", "eligible"]


# -- byte estimates (the --hbm-budget-mb decision) ---------------------

def model_bytes_est(model) -> int:
    """Estimated device-resident bytes of the PACKED decision buffers.

    Same arithmetic as ``fleet/modelcache.resident_bytes`` for SV
    models — ``n_sv * (d + 2) * 4`` (SV rows + coef + squared norms,
    f32) — extended to the approx kinds (omega / landmarks+proj + w)
    and summed over pairs for multiclass directories. Query blocks and
    outputs are ladder-bounded and excluded, as in the cache math."""
    if getattr(model, "is_approx", False):
        fmap = model.fmap
        dim = int(fmap.dim)
        if fmap.kind == "rff":
            # omega (d, dim/2) + w (dim,)
            return (int(fmap.d) * (dim // 2) + dim) * 4
        n_land = int(fmap.landmarks.shape[0])
        # landmarks (L, d) + their norms (L,) + proj (L, dim) + w (dim,)
        return (n_land * int(fmap.d) + n_land + n_land * dim + dim) * 4
    if getattr(model, "models", None) is not None:       # multiclass
        return int(sum(model_bytes_est(m) for m in model.models))
    if model.kernel == "precomputed":
        # host-side gather: coef + SV indices, nothing device-resident
        return int(model.n_sv) * (4 + 8)
    d = int(model.x_sv.shape[1])
    return int(model.n_sv) * (d + 2) * 4


def eligible(model) -> bool:
    """Can this model's decision program be mesh-sharded? Binary SV
    models with real (non-precomputed) kernels shard the SV axis;
    approx models shard the feature axis. Precomputed models have no
    device buffers to shard; multiclass directories are handled
    per-pair by the engine."""
    if getattr(model, "models", None) is not None:
        return False
    if getattr(model, "is_approx", False):
        return True
    return model.kernel != "precomputed"


# -- the one definition of each partial program ------------------------
# The mesh-local function and the unsharded reference fold call the
# SAME math at the same block shapes, which is what makes the
# psum-vs-in-order-fold parity gate meaningful.

def _sv_partial_math(x, sv_blk, coef_blk, sv2_blk, gamma, coef0,
                     kind: str, degree: int, precision):
    import jax.numpy as jnp

    from dpsvm_tpu.ops.kernels import (KernelSpec, kernel_rows,
                                       row_norms_sq)
    spec = KernelSpec(kind=kind, gamma=gamma, coef0=coef0, degree=degree)
    t2 = row_norms_sq(x)
    k = kernel_rows(x, t2, sv_blk, sv2_blk, spec, precision=precision)
    return jnp.matmul(k, coef_blk, precision=precision)


def _rff_partial_math(x, omega_blk, w_blk, scale, precision):
    import jax.numpy as jnp
    z = jnp.matmul(x, omega_blk, precision=precision)
    phi = scale * jnp.concatenate([jnp.cos(z), jnp.sin(z)], axis=1)
    return jnp.matmul(phi, w_blk, precision=precision)


def _nystrom_partial_math(x, landmarks, l2, proj_blk, w_blk, gamma,
                          coef0, kind: str, degree: int, precision):
    import jax.numpy as jnp

    from dpsvm_tpu.ops.kernels import (KernelSpec, kernel_rows,
                                       row_norms_sq)
    spec = KernelSpec(kind=kind, gamma=gamma, coef0=coef0, degree=degree)
    x2 = row_norms_sq(x)
    k = kernel_rows(x, x2, landmarks, l2, spec, precision=precision)
    phi = jnp.matmul(k, proj_blk, precision=precision)
    return jnp.matmul(phi, w_blk, precision=precision)


def _pad_rows(a: np.ndarray, n: int) -> np.ndarray:
    """Zero-pad axis 0 up to a multiple of n."""
    rem = (-a.shape[0]) % n
    if rem == 0:
        return a
    pad = [(0, rem)] + [(0, 0)] * (a.ndim - 1)
    return np.pad(a, pad)


def _pad_cols(a: np.ndarray, n: int) -> np.ndarray:
    """Zero-pad axis 1 up to a multiple of n."""
    rem = (-a.shape[1]) % n
    if rem == 0:
        return a
    return np.pad(a, ((0, 0), (0, rem)))


class ShardedDecider:
    """``block -> decisions`` over a device mesh (module docstring).

    Drop-in for the engine's per-block deciders: takes the ladder's
    zero-padded ``(bucket, d)`` float32 block, returns the ``(bucket,)``
    decision values with the intercept applied per ``include_b``.
    ``reference(block)`` runs the SAME blocked program unsharded on the
    default device with an in-order partial fold — the bitwise parity
    target. Build once per model; the jitted mesh program is warmed per
    ladder bucket by the engine like any other decider.
    """

    def __init__(self, model, *, include_b: bool = True,
                 precision_name: str = "HIGHEST",
                 shards: Optional[int] = None, devices=None,
                 tag: str = "sharded"):
        import jax

        n_dev = len(devices if devices is not None else jax.devices())
        self.n_shards = int(shards) if shards else n_dev
        if self.n_shards < 1:
            raise ValueError(f"shards must be >= 1, got {shards}")
        self.include_b = bool(include_b)
        self._pname = str(precision_name)
        self._precision = getattr(jax.lax.Precision, self._pname)
        self._b = np.float32(getattr(model, "b", 0.0))
        self.is_approx = bool(getattr(model, "is_approx", False))
        self.axis = "feature" if self.is_approx else "sv"
        self.resident_bytes_est = model_bytes_est(model)
        if self.is_approx:
            self._build_approx(model, devices)
        else:
            self._build_sv(model, devices)
        self._run = compilewatch.instrument(self._fn, tag)

    # -- builders ------------------------------------------------------

    def _mesh(self, devices):
        from dpsvm_tpu.parallel.mesh import make_data_mesh
        return make_data_mesh(self.n_shards, devices)

    def _build_sv(self, model, devices) -> None:
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from dpsvm_tpu.ops.kernels import row_norms_sq
        from dpsvm_tpu.parallel.mesh import SHARD_AXIS, shard_map_compat

        x_sv = _pad_rows(np.asarray(model.x_sv, np.float32),
                         self.n_shards)
        coef = _pad_rows(np.asarray(model.alpha, np.float32)
                         * np.asarray(model.y_sv, np.float32),
                         self.n_shards)
        self.orig_len = int(model.n_sv)
        self.padded_len = int(x_sv.shape[0])
        # squared norms of the PADDED rows (padding rows are zero, so
        # their norm is exactly 0.0) — per-row math, so each shard's
        # slice equals what it would compute locally
        sv2 = np.asarray(row_norms_sq(jnp.asarray(x_sv)))
        mesh = self._mesh(devices)
        row = NamedSharding(mesh, P(SHARD_AXIS))
        self._operands = (
            jax.device_put(x_sv, row),
            jax.device_put(coef, row),
            jax.device_put(sv2, row),
        )
        # host copies for reference() — test-path only, never shipped
        self._host_operands = (x_sv, coef, sv2)
        kind, degree = model.kernel, int(model.degree)
        gamma = float(model.gamma)
        coef0 = float(model.coef0)
        include_b, b = self.include_b, self._b
        precision = self._precision

        def local(x, sv_blk, coef_blk, sv2_blk):
            partial = _sv_partial_math(x, sv_blk, coef_blk, sv2_blk,
                                       gamma, coef0, kind, degree,
                                       precision)
            dual = lax.psum(partial, SHARD_AXIS)
            return dual - b if include_b else dual

        self._fn = jax.jit(shard_map_compat(
            local, mesh=mesh,
            in_specs=(P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
            out_specs=P()))

        def ref_partial(x, k):
            lo = k * (self.padded_len // self.n_shards)
            hi = lo + self.padded_len // self.n_shards
            return _sv_ref_jit(x, jnp.asarray(x_sv[lo:hi]),
                               jnp.asarray(coef[lo:hi]),
                               jnp.asarray(sv2[lo:hi]),
                               jnp.float32(gamma), jnp.float32(coef0),
                               kind=kind, degree=degree,
                               precision_name=self._pname)

        self._ref_partial = ref_partial

    def _build_approx(self, model, devices) -> None:
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from dpsvm_tpu.ops.kernels import row_norms_sq
        from dpsvm_tpu.parallel.mesh import SHARD_AXIS, shard_map_compat

        fmap = model.fmap
        mesh = self._mesh(devices)
        include_b, b = self.include_b, self._b
        precision = self._precision
        n = self.n_shards
        w = np.asarray(model.w, np.float32)
        self.orig_len = int(fmap.dim)

        if fmap.kind == "rff":
            # shard the dim/2 omega columns; each shard's weight slice
            # is [w_cos block | w_sin block] so its local program IS
            # the single-device featurize-and-dot, just narrower. The
            # scale is the GLOBAL sqrt(2/dim) — fixed at the unpadded
            # feature count (see module docstring).
            d2 = int(fmap.omega.shape[1])
            omega = _pad_cols(np.asarray(fmap.omega, np.float32), n)
            d2p = int(omega.shape[1])
            self.padded_len = 2 * d2p
            w_cos = _pad_rows(w[:d2], n)
            w_sin = _pad_rows(w[d2:], n)
            c = d2p // n
            w_perm = np.concatenate(
                [np.concatenate([w_cos[k * c:(k + 1) * c],
                                 w_sin[k * c:(k + 1) * c]])
                 for k in range(n)])
            scale = np.float32(math.sqrt(2.0 / (2 * d2)))
            col = NamedSharding(mesh, P(None, SHARD_AXIS))
            row = NamedSharding(mesh, P(SHARD_AXIS))
            self._operands = (jax.device_put(omega, col),
                              jax.device_put(w_perm, row))
            self._host_operands = (omega, w_perm)

            def local(x, omega_blk, w_blk):
                partial = _rff_partial_math(x, omega_blk, w_blk, scale,
                                            precision)
                dual = lax.psum(partial, SHARD_AXIS)
                return dual - b if include_b else dual

            self._fn = jax.jit(shard_map_compat(
                local, mesh=mesh,
                in_specs=(P(), P(None, SHARD_AXIS), P(SHARD_AXIS)),
                out_specs=P()))

            def ref_partial(x, k):
                return _rff_ref_jit(
                    x, jnp.asarray(omega[:, k * c:(k + 1) * c]),
                    jnp.asarray(w_perm[k * 2 * c:(k + 1) * 2 * c]),
                    scale, precision_name=self._pname)

            self._ref_partial = ref_partial
            return

        # nystrom: landmarks replicated, projection columns + w sharded
        landmarks = np.asarray(fmap.landmarks, np.float32)
        proj = _pad_cols(np.asarray(fmap.proj, np.float32), n)
        w_pad = _pad_rows(w, n)
        self.padded_len = int(proj.shape[1])
        c = self.padded_len // n
        l2 = np.asarray(row_norms_sq(jnp.asarray(landmarks)))
        rep = NamedSharding(mesh, P())
        col = NamedSharding(mesh, P(None, SHARD_AXIS))
        row = NamedSharding(mesh, P(SHARD_AXIS))
        self._operands = (jax.device_put(landmarks, rep),
                          jax.device_put(l2, rep),
                          jax.device_put(proj, col),
                          jax.device_put(w_pad, row))
        self._host_operands = (landmarks, l2, proj, w_pad)
        kind, degree = fmap.kernel, int(fmap.degree)
        gamma, coef0 = float(fmap.gamma), float(fmap.coef0)

        def local(x, lm, lm2, proj_blk, w_blk):
            partial = _nystrom_partial_math(x, lm, lm2, proj_blk,
                                            w_blk, gamma, coef0, kind,
                                            degree, precision)
            dual = lax.psum(partial, SHARD_AXIS)
            return dual - b if include_b else dual

        self._fn = jax.jit(shard_map_compat(
            local, mesh=mesh,
            in_specs=(P(), P(), P(), P(None, SHARD_AXIS),
                      P(SHARD_AXIS)),
            out_specs=P()))

        def ref_partial(x, k):
            return _nystrom_ref_jit(
                x, jnp.asarray(landmarks), jnp.asarray(l2),
                jnp.asarray(proj[:, k * c:(k + 1) * c]),
                jnp.asarray(w_pad[k * c:(k + 1) * c]),
                jnp.float32(gamma), jnp.float32(coef0),
                kind=kind, degree=degree, precision_name=self._pname)

        self._ref_partial = ref_partial

    # -- evaluation ----------------------------------------------------

    def __call__(self, block: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        return np.asarray(self._run(jnp.asarray(block),
                                    *self._operands))

    decide = __call__

    def reference(self, block: np.ndarray) -> np.ndarray:
        """The SAME blocked decision, unsharded: every shard's partial
        computed in shard-index order on the default device and folded
        with in-order f32 adds — bitwise what ``psum`` produces on the
        mesh (the parity gate of the tests and the serving selfcheck).
        """
        import jax.numpy as jnp
        x = jnp.asarray(np.asarray(block, np.float32))
        acc: Optional[np.ndarray] = None
        for k in range(self.n_shards):
            p = np.asarray(self._ref_partial(x, k))
            acc = p if acc is None else acc + p
        if self.include_b:
            acc = acc - self._b
        return acc

    def facts(self) -> dict:
        """Manifest block (serving/engine.py manifest, /v1/models)."""
        return {
            "sharded": True,
            "shard_axis": self.axis,
            "shards": self.n_shards,
            "padded_len": self.padded_len,
            "orig_len": self.orig_len,
            "resident_bytes_est": int(self.resident_bytes_est),
            "per_device_bytes_est":
                int(self.resident_bytes_est // self.n_shards),
        }


# -- reference-fold jits (test path; one per partial program) ----------

@functools.partial(jax.jit,
                   static_argnames=("kind", "degree", "precision_name"))
def _sv_ref_jit(x, sv_blk, coef_blk, sv2_blk, gamma, coef0, kind: str,
                degree: int, precision_name: str):
    return _sv_partial_math(x, sv_blk, coef_blk, sv2_blk, gamma, coef0,
                            kind, degree,
                            getattr(jax.lax.Precision, precision_name))


@functools.partial(jax.jit, static_argnames=("precision_name",))
def _rff_ref_jit(x, omega_blk, w_blk, scale, precision_name: str):
    return _rff_partial_math(x, omega_blk, w_blk, scale,
                             getattr(jax.lax.Precision, precision_name))


@functools.partial(jax.jit,
                   static_argnames=("kind", "degree", "precision_name"))
def _nystrom_ref_jit(x, landmarks, l2, proj_blk, w_blk, gamma, coef0,
                     kind: str, degree: int, precision_name: str):
    return _nystrom_partial_math(
        x, landmarks, l2, proj_blk, w_blk, gamma, coef0, kind, degree,
        getattr(jax.lax.Precision, precision_name))

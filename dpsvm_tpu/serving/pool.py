"""Replica pool: N prediction engines with failure isolation.

PR 4's serving stack is one engine behind one batcher worker — a
single wedged device call (or a poisoned replica emitting NaN) takes
every request down with it, and the only recovery is a process
restart. "Parallel SVMs in Practice" (arXiv:1404.1066) argues that in
deployed systems availability dominates one-shot training quality;
this module is that argument applied to our serving half
(docs/SERVING.md "Resilience"):

* **Failure isolation** — each replica is its own ``PredictionEngine``
  (own device buffers, own warmed ladder) with its own worker thread.
  A wedged or poisoned replica loses *itself*; the pool keeps
  answering from the others.
* **Health + circuit breaker** — every dispatch feeds the replica's
  ``resilience.health.ReplicaMonitor`` (the training HealthMonitor's
  window shape on serving vitals: latency + non-finite output
  counts). A deadline blown *while computing* marks the replica
  wedged; a single non-finite output marks it poisoned (inputs are
  validated finite at admission, so non-finite out = corrupted
  replica state — the serving analogue of the always-armed NaN-gap
  guard). Either way the replica's circuit opens (``eject`` event),
  it stops receiving traffic, and a background rebuild constructs a
  fresh engine from the model source; the rebuilt replica re-enters
  **half-open** and must answer one probe dispatch before the circuit
  closes.
* **Deadline budgets** — every dispatch carries an absolute deadline
  (serving/budget.py). A reaper thread fails blown dispatches with
  ``DeadlineExceededError`` (HTTP: 504) instead of letting callers
  hang on a dead replica.
* **Hedging** — optionally, a dispatch still unanswered after a
  p99-based delay is re-dispatched to a second replica; first answer
  wins (``hedge`` event, hedges fired/won counted). Output parity
  makes this safe: replicas serve the same artifact and rows are
  batch-mate independent, so either answer is THE answer.

Determinism for CI: every failure mode has an injection point in
``resilience/faultinject.py`` (``DPSVM_FAULT_SERVE_*``), so wedge /
poison / failed-rebuild are exact, reproducible events on CPU.

No jax at module import: engines are built by the caller-supplied
``build_fn`` (the registry's loader); the pool itself is stdlib +
numpy and testable with stub engines.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Set, Tuple

import numpy as np

from dpsvm_tpu.observability.metrics import MetricsRegistry
from dpsvm_tpu.resilience import faultinject
from dpsvm_tpu.resilience.health import ReplicaMonitor
from dpsvm_tpu.serving.budget import DeadlineExceededError, hedge_delay_s

#: circuit-breaker states
CLOSED = "closed"          # healthy, receiving traffic
OPEN = "open"              # ejected, rebuild pending/in-flight
HALF_OPEN = "half-open"    # rebuilt, awaiting its probe dispatch

#: rebuild retry policy (the injected-fault model is transient, so
#: retrying is the point; the cap stops a permanently-broken source
#: from spinning forever)
REBUILD_MAX_ATTEMPTS = 6


class PoolUnavailableError(RuntimeError):
    """No replica can take the dispatch (all circuits open). The HTTP
    layer maps this to 503 — the pool is rebuilding, try again."""


class _Dispatch:
    """One batch's journey through the pool: publish-once future with
    deadline, hedge bookkeeping and a record of who is computing it."""

    __slots__ = ("x", "want", "deadline", "event", "result", "error",
                 "lock", "done", "winner", "t0", "hedge_at",
                 "hedge_fired", "primary_idx", "attempts", "computing",
                 "spans")

    def __init__(self, x: np.ndarray, want: Tuple[str, ...],
                 deadline: float, hedge_at: Optional[float],
                 spans: Sequence = ()):
        # span contexts (observability/spans.RequestSpans) of the
        # sampled requests riding this batch: the pool hangs its
        # replica_compute / hedge / redispatch spans under each one's
        # device_dispatch stage (docs/OBSERVABILITY.md "Spans")
        self.spans = tuple(spans or ())
        self.x = x
        self.want = want
        self.deadline = float(deadline)
        self.event = threading.Event()
        self.lock = threading.Lock()
        self.result: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.done = False
        self.winner: Optional[int] = None
        self.t0 = time.perf_counter()
        self.hedge_at = hedge_at       # absolute; None = hedging off
        self.hedge_fired = False
        self.primary_idx: Optional[int] = None
        self.attempts = 0              # redispatches after failures
        self.computing: List["_Replica"] = []

    def complete(self, result: Optional[dict] = None,
                 error: Optional[BaseException] = None,
                 winner: Optional[int] = None) -> bool:
        """Publish exactly once; False if someone already did."""
        with self.lock:
            if self.done:
                return False
            self.done = True
            self.result = result
            self.error = error
            self.winner = winner
        self.event.set()
        return True


class _Replica:
    """One engine + its worker thread + its health record."""

    def __init__(self, idx: int, engine, generation: int = 1,
                 state: str = CLOSED):
        self.idx = int(idx)
        self.engine = engine
        self.generation = int(generation)
        self.state = state
        self.retired = False           # ejected or refreshed away
        self.probing = False           # half-open probe in flight
        self.busy_since: Optional[float] = None  # compute in flight
        self.monitor = ReplicaMonitor()
        self.queue: deque = deque()
        self.cond = threading.Condition()
        self.thread: Optional[threading.Thread] = None

    def enqueue(self, d: _Dispatch) -> None:
        with self.cond:
            self.queue.append(d)
            self.cond.notify()

    def drain_queue(self) -> List[_Dispatch]:
        with self.cond:
            out = list(self.queue)
            self.queue.clear()
            self.cond.notify_all()
        return out


#: pool robustness counters: one registry counter family each, labeled
#: by model — the hand-rolled dict these replaced lives on only as the
#: keys of `metrics()` (docs/OBSERVABILITY.md "Metrics")
_POOL_COUNTER_HELP = {
    "dispatches": "batches dispatched to a replica",
    "ejections": "replicas ejected by the circuit breaker",
    "rebuilds": "successful background replica rebuilds",
    "rebuild_failures": "failed replica rebuild attempts",
    "hedges_fired": "hedged re-dispatches fired",
    "hedges_won": "hedged re-dispatches that answered first",
    "redispatches": "dispatches retried on another replica",
    "timeouts": "dispatches failed on a blown deadline",
}


class ReplicaPool:
    """N replicas behind one dispatch interface (module docstring).

    ``build_fn(idx)`` constructs a warmed engine for slot ``idx`` —
    called at construction, and again for every background rebuild
    (which is what makes a rebuild pick up the CURRENT registry
    source, i.e. the artifact generation serving now).

    ``hedge``: ``"off"`` (default), ``"auto"`` (p99-based delay from
    the pool's rolling latency window), or a float delay in seconds.

    ``metrics``: the ``observability.metrics.MetricsRegistry`` the
    pool's robustness counters live in (labeled ``model=<name>``) —
    the ServingServer passes its own so `/metricsz?format=prometheus`
    exposes them; a standalone pool gets a private registry and
    behaves exactly as before.
    """

    def __init__(self, build_fn: Callable[[int], object],
                 n_replicas: int = 1, *, name: str = "default",
                 deadline_s: float = 30.0, hedge="off",
                 rebuild: bool = True, rebuild_backoff_s: float = 0.05,
                 reap_interval_s: float = 0.005,
                 watch_compiles: bool = False,
                 on_event: Optional[Callable[..., None]] = None,
                 metrics: Optional[MetricsRegistry] = None):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.name = str(name)
        self.build_fn = build_fn
        self.deadline_s = float(deadline_s)
        self.hedge = hedge
        self.rebuild = bool(rebuild)
        self.rebuild_backoff_s = float(rebuild_backoff_s)
        self.reap_interval_s = float(reap_interval_s)
        self.watch_compiles = bool(watch_compiles)
        self._on_event = on_event
        self.events: deque = deque(maxlen=512)
        self._lock = threading.Lock()
        self._rr = 0                   # round-robin cursor
        self._inflight: Set[_Dispatch] = set()
        self._lat_ms: deque = deque(maxlen=512)
        self._building = 0
        self._stray = 0
        # Robustness counters migrated onto the unified metric
        # registry (observability/metrics.py): one counter family per
        # key, this pool's series labeled by model name. `metrics()`
        # reads the same series back, so the JSON view and the
        # Prometheus exposition can never disagree.
        self._mreg = metrics if metrics is not None else MetricsRegistry()
        self._counters = {
            key: self._mreg.counter(f"dpsvm_pool_{key}_total", help_,
                                    labels=("model",))
            .labels(model=self.name)
            for key, help_ in _POOL_COUNTER_HELP.items()}
        self._stop = threading.Event()
        self._replicas: List[_Replica] = []
        for i in range(int(n_replicas)):
            with self._build_guard():
                engine = build_fn(i)
            self._replicas.append(self._spawn(i, engine, generation=1,
                                              state=CLOSED))
        if self.watch_compiles:
            # post-warmup baseline: anything drained later is a stray
            from dpsvm_tpu.observability import compilewatch
            compilewatch.drain()
        self._reaper = threading.Thread(
            target=self._reap, name=f"dpsvm-pool[{self.name}]-reaper",
            daemon=True)
        self._reaper.start()

    # -- construction helpers -----------------------------------------

    class _BuildGuard:
        def __init__(self, pool):
            self.pool = pool

        def __enter__(self):
            with self.pool._lock:
                self.pool._building += 1

        def __exit__(self, *exc):
            if self.pool.watch_compiles:
                # the build's own warmup compiles are not strays;
                # drained before _building drops so a concurrent
                # stray_compiles() can never misattribute them
                from dpsvm_tpu.observability import compilewatch
                compilewatch.drain()
            with self.pool._lock:
                self.pool._building -= 1

    def _build_guard(self) -> "_BuildGuard":
        return self._BuildGuard(self)

    def _spawn(self, idx: int, engine, *, generation: int,
               state: str) -> _Replica:
        r = _Replica(idx, engine, generation=generation, state=state)
        r.thread = threading.Thread(
            target=self._worker, args=(r,),
            name=f"dpsvm-pool[{self.name}]-r{idx}g{generation}",
            daemon=True)
        r.thread.start()
        return r

    # -- events -------------------------------------------------------

    def _emit(self, event: str, **extra) -> None:
        rec = {"event": event, **extra}
        self.events.append(rec)
        if self._on_event is not None:
            try:
                self._on_event(event, **extra)
            except Exception:
                pass                   # observability must not kill serving

    # -- dispatch -----------------------------------------------------

    def _hedge_at(self, t0: float) -> Optional[float]:
        if self.hedge in (None, "off", False) or len(self._replicas) < 2:
            return None
        if self.hedge == "auto":
            with self._lock:
                window = list(self._lat_ms)
            return t0 + hedge_delay_s(window)
        return t0 + float(self.hedge)

    def _choose(self, exclude: Set[int] = frozenset()
                ) -> Optional[_Replica]:
        """Round-robin over CLOSED replicas; a HALF_OPEN replica with
        no probe in flight is eligible too (and the chosen dispatch IS
        its probe). None = every circuit open."""
        with self._lock:
            n = len(self._replicas)
            for step in range(n):
                r = self._replicas[(self._rr + step) % n]
                if r.idx in exclude or r.retired:
                    continue
                if r.state == CLOSED:
                    self._rr = (self._rr + step + 1) % n
                    return r
                if r.state == HALF_OPEN and not r.probing:
                    # probed in ordinary rotation — a rebuilt replica
                    # re-enters service without waiting for the rest of
                    # the pool to fail first
                    r.probing = True
                    self._rr = (self._rr + step + 1) % n
                    return r
        return None

    def infer(self, x, want: Sequence[str] = ("labels",), *,
              timeout: Optional[float] = None,
              deadline: Optional[float] = None,
              spans: Sequence = ()) -> dict:
        """Dispatch one batch; blocks until a replica answers or the
        deadline passes. Raises DeadlineExceededError (504) on a blown
        budget, PoolUnavailableError (503) when every circuit is open,
        ValueError for client mistakes (width mismatch etc.).
        ``spans``: RequestSpans contexts of the batch's sampled
        requests (the batcher threads them through)."""
        x = np.asarray(x, np.float32)
        if deadline is None:
            deadline = time.perf_counter() + (self.deadline_s
                                              if timeout is None
                                              else float(timeout))
        d = _Dispatch(x, tuple(want), deadline, self._hedge_at(
            time.perf_counter()), spans=spans)
        r = self._choose()
        if r is None:
            raise PoolUnavailableError(
                f"pool {self.name!r}: no healthy replica "
                f"(all {len(self._replicas)} circuits open; rebuilding)")
        d.primary_idx = r.idx
        with self._lock:
            self._counters["dispatches"].inc()
            self._inflight.add(d)
        r.enqueue(d)
        try:
            d.event.wait(max(0.0, deadline - time.perf_counter())
                         + 4 * self.reap_interval_s + 0.05)
            if not d.event.is_set():
                # reaper missed (extreme scheduling); fail it ourselves
                self._fail_deadline(d)
        finally:
            with self._lock:
                self._inflight.discard(d)
        if d.error is not None:
            raise d.error
        return d.result

    @staticmethod
    def _span_mark(d: _Dispatch, name: str, **extra) -> None:
        """Stamp a marker span under every sampled request of the
        batch. Defensive: attribution must never kill serving."""
        for ctx in d.spans:
            try:
                ctx.mark(name, parent="device_dispatch", **extra)
            except Exception:
                pass

    def _redispatch(self, d: _Dispatch, exclude: Set[int]) -> None:
        if d.done:
            return
        self._span_mark(d, "redispatch", excluded=sorted(exclude))
        d.attempts += 1
        if d.attempts >= len(self._replicas) + 1:
            d.complete(error=PoolUnavailableError(
                f"pool {self.name!r}: dispatch failed on "
                f"{d.attempts} replicas"))
            return
        r = self._choose(exclude=exclude)
        if r is None:
            d.complete(error=PoolUnavailableError(
                f"pool {self.name!r}: no healthy replica left for "
                "redispatch"))
            return
        with self._lock:
            self._counters["redispatches"].inc()
        r.enqueue(d)

    # -- worker -------------------------------------------------------

    def _worker(self, replica: _Replica) -> None:
        while True:
            with replica.cond:
                while not replica.queue:
                    if replica.retired or self._stop.is_set():
                        return
                    replica.cond.wait(0.1)
                d = replica.queue.popleft()
            if replica.retired:
                self._redispatch(d, exclude={replica.idx})
                continue
            self._compute(replica, d)
            if replica.retired:        # ejected mid-compute (wedge)
                return

    def _unprobe(self, replica: _Replica) -> None:
        """Half-open probe fell through without a verdict (its dispatch
        was answered elsewhere / was a client error) — make the replica
        eligible for the next probe instead of wedging it half-open."""
        with self._lock:
            if replica.state == HALF_OPEN:
                replica.probing = False

    def _compute(self, replica: _Replica, d: _Dispatch) -> None:
        with d.lock:
            if d.done:
                self._unprobe(replica)
                return
            d.computing.append(replica)
        t0 = time.perf_counter()
        # busy_since is what the reaper watches for wedge detection: a
        # compute older than the pool deadline marks the REPLICA wedged
        # even when the dispatch itself was rescued by a hedge (else a
        # won hedge would mask the wedge and the stuck worker's queue
        # would grow unserved forever).
        replica.busy_since = t0
        # Per-request compute spans: each sampled request riding this
        # batch gets a replica_compute child under its device_dispatch
        # (ended in the finally — a wedged compute keeps its span open
        # until the request's finish() cuts it at the root, which IS
        # the attribution of a wedge).
        comp_spans = []
        for ctx in d.spans:
            try:
                # (model, tenant) on the compute span (schema v4): the
                # identity rides the request's span context, so
                # per-tenant device-compute cost is pure host-side
                # span math — zero extra device transfers.
                ident = {"model": self.name}
                tenant = getattr(ctx, "tenant", None)
                if tenant is not None:
                    ident["tenant"] = tenant
                comp_spans.append(
                    (ctx, ctx.start("replica_compute",
                                    parent="device_dispatch",
                                    replica=replica.idx,
                                    generation=replica.generation,
                                    **ident)))
            except Exception:
                pass
        try:
            plan = faultinject.current()
            if plan is not None and plan.note_serve_compute(
                    replica.idx, replica.generation):
                faultinject.serve_wedge_wait()
                if d.done or replica.retired:
                    self._unprobe(replica)
                    return             # released after ejection
            if plan is not None:
                # slow-replica drill (DPSVM_FAULT_SERVE_SLOW_REPLICA_MS):
                # the compute takes longer than the request deadline ->
                # 504 storm -> the serving burn-rate rule must fire
                slow_s = plan.serve_slow_delay_s()
                if slow_s > 0:
                    time.sleep(slow_s)
            try:
                res = replica.engine.infer(d.x, d.want)
            except ValueError as e:
                d.complete(error=e)    # client mistake, not replica ill
                self._unprobe(replica)
                return
            except Exception as e:     # replica fault: isolate + retry
                replica.monitor.note_nonfinite()
                self._eject(replica, f"compute error: {e}")
                self._redispatch(d, exclude={replica.idx})
                return
        finally:
            replica.busy_since = None
            for ctx, sp in comp_spans:
                try:
                    ctx.end(sp)
                except Exception:
                    pass
        ms = (time.perf_counter() - t0) * 1000.0
        replica.monitor.note_latency(ms)
        with self._lock:
            self._lat_ms.append(ms)
        if plan is not None and plan.serve_poisoned(replica.idx,
                                                    replica.generation):
            res = {k: np.full(np.shape(v), np.nan)
                   for k, v in res.items()}
        if self._nonfinite(res):
            replica.monitor.note_nonfinite()
            self._eject(replica, "nonfinite outputs")
            self._redispatch(d, exclude={replica.idx})
            return
        won = d.complete(result=res, winner=replica.idx)
        if won and d.hedge_fired and replica.idx != d.primary_idx:
            with self._lock:
                self._counters["hedges_won"].inc()
            self._span_mark(d, "hedge_won", replica=replica.idx)
        if replica.state == HALF_OPEN:
            # a finite, timely compute is the probe's verdict whether
            # or not it won the publish race: close the circuit
            with self._lock:
                replica.state = CLOSED
                replica.probing = False

    @staticmethod
    def _nonfinite(res: dict) -> bool:
        for v in res.values():
            a = np.asarray(v)
            if (np.issubdtype(a.dtype, np.floating)
                    and not np.all(np.isfinite(a))):
                return True
        return False

    # -- circuit breaker ----------------------------------------------

    def _eject(self, replica: _Replica, reason: str) -> None:
        with self._lock:
            if replica.retired:
                return
            replica.retired = True
            replica.state = OPEN
            self._counters["ejections"].inc()
        self._emit("eject", replica=replica.idx,
                   generation=replica.generation, reason=reason)
        for d in replica.drain_queue():
            self._redispatch(d, exclude={replica.idx})
        if self.rebuild and not self._stop.is_set():
            threading.Thread(
                target=self._rebuild,
                args=(replica.idx, replica.generation),
                name=f"dpsvm-pool[{self.name}]-rebuild{replica.idx}",
                daemon=True).start()

    def _rebuild(self, idx: int, old_generation: int) -> None:
        attempt = 0
        while not self._stop.is_set():
            attempt += 1
            try:
                with self._build_guard():
                    faultinject.on_serve_reload()
                    engine = self.build_fn(idx)
            except Exception as e:     # noqa: BLE001 — retried/reported
                with self._lock:
                    self._counters["rebuild_failures"].inc()
                self._emit("rebuild", replica=idx, ok=False,
                           attempt=attempt, error=str(e))
                if attempt >= REBUILD_MAX_ATTEMPTS:
                    return             # stays OPEN; operator visible
                self._stop.wait(self.rebuild_backoff_s
                                * (2 ** (attempt - 1)))
                continue
            new = self._spawn(idx, engine,
                              generation=old_generation + 1,
                              state=HALF_OPEN)
            with self._lock:
                self._replicas[idx] = new
                self._counters["rebuilds"].inc()
            self._emit("rebuild", replica=idx, ok=True,
                       generation=new.generation, attempt=attempt)
            return

    def refresh(self) -> None:
        """Rolling rebuild of every replica from the CURRENT source —
        the pool side of a registry hot-swap. One replica at a time,
        each fully built+warmed before its predecessor retires, so the
        pool keeps serving throughout (briefly mixed generations)."""
        for idx in range(len(self._replicas)):
            with self._build_guard():
                engine = self.build_fn(idx)
            with self._lock:
                old = self._replicas[idx]
                new = self._spawn(idx, engine,
                                  generation=old.generation + 1,
                                  state=CLOSED)
                self._replicas[idx] = new
                old.retired = True
            for d in old.drain_queue():
                self._redispatch(d, exclude=set())

    # -- reaper -------------------------------------------------------

    def _fail_deadline(self, d: _Dispatch) -> None:
        with d.lock:
            computing = list(d.computing)
        completed = d.complete(error=DeadlineExceededError(
            "deadline budget exhausted before any replica answered"))
        if not completed:
            return
        with self._lock:
            self._counters["timeouts"].inc()
        for r in computing:
            r.monitor.note_timeout()
            self._eject(r, "wedge (deadline blown while computing)")

    def _reap(self) -> None:
        while not self._stop.is_set():
            now = time.perf_counter()
            with self._lock:
                inflight = list(self._inflight)
                replicas = list(self._replicas)
            for r in replicas:
                busy = r.busy_since
                if (busy is not None and not r.retired
                        and now - busy > self.deadline_s):
                    r.monitor.note_timeout()
                    self._eject(r, "wedge (compute exceeded the pool "
                                   "deadline)")
            for d in inflight:
                if d.done:
                    continue
                if now >= d.deadline:
                    self._fail_deadline(d)
                    continue
                if (d.hedge_at is not None and not d.hedge_fired
                        and now >= d.hedge_at):
                    d.hedge_fired = True
                    with d.lock:
                        busy = {r.idx for r in d.computing}
                    r2 = self._choose(exclude=busy | {d.primary_idx})
                    if r2 is not None:
                        with self._lock:
                            self._counters["hedges_fired"].inc()
                        self._emit("hedge", primary=d.primary_idx,
                                   hedge=r2.idx)
                        self._span_mark(d, "hedge_fired",
                                        primary=d.primary_idx,
                                        hedge=r2.idx)
                        r2.enqueue(d)
            self._stop.wait(self.reap_interval_s)

    # -- facts --------------------------------------------------------

    @property
    def num_attributes(self) -> int:
        return int(self._replicas[0].engine.num_attributes)

    @property
    def n_healthy(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas
                       if not r.retired and r.state == CLOSED)

    def replica_states(self) -> List[str]:
        with self._lock:
            return [r.state for r in self._replicas]

    def stray_compiles(self) -> int:
        """Compile events observed OUTSIDE engine builds since the pool
        warmed — the steady-state-retrace counter the chaos acceptance
        pins at zero. Pull-based (drained on read) and suppressed while
        a build is in flight so a rebuild's own warmup is never
        miscounted as a stray."""
        if not self.watch_compiles:
            return self._stray
        with self._lock:
            if self._building > 0:
                return self._stray
        from dpsvm_tpu.observability import compilewatch
        self._stray += len(compilewatch.drain())
        return self._stray

    def metrics(self) -> dict:
        with self._lock:
            reps = list(self._replicas)
        # the registry series ARE the counters now; the JSON view reads
        # them back so the two surfaces cannot drift
        out = {k: int(c.value) for k, c in self._counters.items()}
        out["n_replicas"] = len(reps)
        out["n_healthy"] = sum(1 for r in reps
                               if not r.retired and r.state == CLOSED)
        out["stray_compiles"] = self.stray_compiles()
        out["replicas"] = [
            {"replica": r.idx, "state": (OPEN if r.retired and
                                         r.state != OPEN else r.state),
             "generation": r.generation, **r.monitor.stats()}
            for r in reps]
        return out

    # -- lifecycle ----------------------------------------------------

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            reps = list(self._replicas)
        for r in reps:
            r.retired = True
            with r.cond:
                r.cond.notify_all()
        for r in reps:
            if r.thread is not None:
                r.thread.join(0.5)     # wedged threads stay abandoned

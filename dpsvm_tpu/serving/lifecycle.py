"""Self-healing model lifecycle: drift -> retrain -> gate -> hot-swap.

A serving model decays silently: the traffic distribution moves and
the frozen decision function keeps scoring it with stale confidence.
"Parallel SVMs in Practice" (arXiv:1404.1066) names model refresh as
the deployment concern that dominates one-shot training; the cheap
retrain that makes an AUTOMATED refresh affordable is exactly the
``approx/`` path ("Recipe for Fast Large-scale SVM Training",
arXiv:2207.01016). This module closes that loop with parts the repo
already has:

1. **Drift detection** — a deterministic two-sample Kolmogorov-
   Smirnov distance between a reference score sample (recorded when
   the serving generation was promoted) and the live rolling
   score-distribution window ``/metricsz`` already keeps. No model
   labels needed: a moved input distribution moves the decision-value
   distribution first.
2. **Supervised retrain** — ``resilience.supervisor.run_with_retries``
   wraps the caller's ``retrain_fn``, so a preempted retrain resumes
   from its checkpoint instead of aborting the refresh.
3. **Eval gate** — the candidate must clear a held-out accuracy floor
   AND (when both runs traced) the ``dpsvm compare`` regression gate
   (``observability.compare.regressions``) against the serving
   generation's training trace. A refresh that fails the gate changes
   NOTHING: the old generation keeps serving, and the failure is a
   trace event, not a page.
4. **Atomic hot-swap** — only a passing candidate is promoted:
   ``os.replace`` onto the registry source path (atomic at the
   filesystem level), then the registry's explicit reload (new engine
   fully warmed before the swap) and the replica pool's rolling
   refresh.

Everything is deterministic and injectable, so the whole loop — drift
in, promote or gate-hold out — runs as a CPU CI test
(tests/test_serving_resilience.py).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np


def ks_distance(a, b) -> float:
    """Two-sample Kolmogorov-Smirnov distance sup_x |F_a(x) - F_b(x)|
    — deterministic, rank-based (scale-free), in [0, 1]."""
    a = np.sort(np.asarray(a, np.float64).ravel())
    b = np.sort(np.asarray(b, np.float64).ravel())
    if a.size == 0 or b.size == 0:
        return 0.0
    allv = np.concatenate([a, b])
    cdf_a = np.searchsorted(a, allv, side="right") / a.size
    cdf_b = np.searchsorted(b, allv, side="right") / b.size
    return float(np.max(np.abs(cdf_a - cdf_b)))


class DriftDetector:
    """KS drift test of the live score window against a reference
    sample (the promoted generation's own score distribution).

    ``threshold`` is the KS distance that counts as drift; with the
    default 0.25 a pure location shift of ~0.7 reference standard
    deviations trips it while sampling noise at ``min_count=64`` stays
    an order of magnitude below (KS noise ~ sqrt(1/n) ~ 0.125 at worst
    for the 99th percentile of the null — the margin is the point:
    this arms a RETRAIN, so false positives cost real compute)."""

    def __init__(self, reference, *, threshold: float = 0.25,
                 min_count: int = 64):
        if not (0.0 < threshold <= 1.0):
            raise ValueError(f"threshold must be in (0, 1], "
                             f"got {threshold}")
        self._lock = threading.Lock()
        self.threshold = float(threshold)
        self.min_count = int(min_count)
        self.rearm(reference)

    def rearm(self, reference) -> None:
        """Swap the reference sample — called at every promotion so
        drift is always measured against the GENERATION NOW SERVING."""
        ref = np.asarray(reference, np.float64).ravel()
        if ref.size < 2:
            raise ValueError("reference sample needs >= 2 scores")
        with self._lock:
            self._ref = np.sort(ref)

    def check(self, window) -> Optional[dict]:
        """None = no drift; else the drift facts (the `drift` event's
        payload)."""
        win = np.asarray(window, np.float64).ravel()
        win = win[np.isfinite(win)]
        if win.size < self.min_count:
            return None
        with self._lock:
            ref = self._ref
        ks = ks_distance(ref, win)
        if ks <= self.threshold:
            return None
        return {"ks": round(ks, 6), "threshold": self.threshold,
                "window_n": int(win.size), "reference_n": int(ref.size)}


@dataclasses.dataclass
class RetrainResult:
    """What ``retrain_fn`` hands back: the candidate artifact (a model
    file the serving engine can load), optionally its training trace
    (enables the compare gate) and a fresh reference score sample
    (re-arms the drift detector at promotion)."""
    model_path: str
    trace_path: Optional[str] = None
    reference_scores: Optional[np.ndarray] = None


@dataclasses.dataclass
class GateResult:
    passed: bool
    accuracy: Optional[float]
    floor: float
    problems: "list[str]"


class LifecycleLoop:
    """One model's refresh loop (module docstring).

    * ``score_source()`` -> the live score window (the server's
      ``score_window()``; any 1-D float sequence works).
    * ``retrain_fn(resume_from, attempt)`` -> ``RetrainResult``. Runs
      under ``run_with_retries`` with ``checkpoint_path``, so a
      preempted attempt resumes.
    * ``eval_fn(model_path)`` -> held-out accuracy in [0, 1].
    * ``baseline_trace`` — the serving generation's training trace;
      with it (and a candidate trace) the ``dpsvm compare`` regression
      gate arms at ``fail_on_regress_pct``.
    * ``on_event(name, **extra)`` — trace/metrics sink (`drift`,
      `retrain`, `promote` with ok True/False).
    * ``on_promote(name)`` — post-swap hook (the server refreshes the
      replica pool here).
    """

    def __init__(self, *, registry, name: str,
                 detector: DriftDetector,
                 score_source: Callable[[], Sequence[float]],
                 retrain_fn: Callable[[Optional[str], int],
                                      RetrainResult],
                 eval_fn: Callable[[str], float],
                 accuracy_floor: float,
                 baseline_trace: Optional[str] = None,
                 fail_on_regress_pct: Optional[float] = None,
                 retries: int = 1, backoff_s: float = 0.0,
                 checkpoint_path: Optional[str] = None,
                 cooldown_s: float = 0.0,
                 on_event: Optional[Callable[..., None]] = None,
                 on_promote: Optional[Callable[[str], None]] = None):
        source = registry.source(name)
        if source is None:
            raise ValueError(
                f"model {name!r} was registered in-memory; the "
                "lifecycle loop needs a source path to hot-swap")
        if os.path.isdir(source):
            raise ValueError(
                "lifecycle hot-swap supports single-file model "
                f"artifacts; {source!r} is a directory (multiclass)")
        self.registry = registry
        self.name = name
        self.detector = detector
        self.score_source = score_source
        self.retrain_fn = retrain_fn
        self.eval_fn = eval_fn
        self.accuracy_floor = float(accuracy_floor)
        self.baseline_trace = baseline_trace
        self.fail_on_regress_pct = fail_on_regress_pct
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.checkpoint_path = checkpoint_path
        self.cooldown_s = float(cooldown_s)
        self._on_event = on_event
        self._on_promote = on_promote
        self._last_action_t = 0.0
        self.history: "list[dict]" = []

    def _emit(self, event: str, **extra) -> None:
        self.history.append({"event": event, **extra})
        if self._on_event is not None:
            try:
                self._on_event(event, **extra)
            except Exception:
                pass

    # -- the loop body ------------------------------------------------

    def step(self) -> str:
        """One poll. Returns the outcome: ``"no-drift"``, ``"cooldown"``,
        ``"promoted"``, ``"gate-held"`` (candidate rejected, old
        generation untouched) or ``"retrain-failed"``."""
        if (self.cooldown_s and
                time.monotonic() - self._last_action_t < self.cooldown_s):
            return "cooldown"
        drift = self.detector.check(self.score_source())
        if drift is None:
            return "no-drift"
        self._emit("drift", model=self.name, **drift)
        self._last_action_t = time.monotonic()
        try:
            result = self._retrain()
        except Exception as e:         # noqa: BLE001 — reported, loop
            self._emit("retrain", model=self.name, ok=False,
                       error=str(e))  # survives to the next poll
            return "retrain-failed"
        self._emit("retrain", model=self.name, ok=True,
                   candidate=result.model_path)
        gate = self.gate(result)
        if not gate.passed:
            self._emit("promote", model=self.name, ok=False,
                       accuracy=gate.accuracy, floor=gate.floor,
                       problems=gate.problems)
            return "gate-held"
        self.promote(result, accuracy=gate.accuracy)
        return "promoted"

    def _retrain(self) -> RetrainResult:
        from dpsvm_tpu.resilience.supervisor import run_with_retries

        result = run_with_retries(
            self.retrain_fn, retries=self.retries,
            backoff_s=self.backoff_s,
            checkpoint_path=self.checkpoint_path)
        if not isinstance(result, RetrainResult):
            raise TypeError("retrain_fn must return a RetrainResult, "
                            f"got {type(result).__name__}")
        if not os.path.exists(result.model_path):
            raise FileNotFoundError(
                f"retrain_fn reported {result.model_path!r} but wrote "
                "no such artifact")
        return result

    # -- gate ---------------------------------------------------------

    def gate(self, result: RetrainResult) -> GateResult:
        """Held-out accuracy floor + (when traces exist on both sides)
        the mechanical ``dpsvm compare`` regression verdicts."""
        problems: "list[str]" = []
        accuracy: Optional[float] = None
        try:
            accuracy = float(self.eval_fn(result.model_path))
        except Exception as e:         # noqa: BLE001 — a gate that
            problems.append(f"eval failed: {e}")   # crashes must HOLD
        if accuracy is not None and accuracy < self.accuracy_floor:
            problems.append(f"held-out accuracy {accuracy:.4f} below "
                            f"floor {self.accuracy_floor:.4f}")
        if (self.baseline_trace and result.trace_path
                and self.fail_on_regress_pct is not None):
            try:
                from dpsvm_tpu.observability.compare import (
                    compare_paths, regressions)
                cmp_, _, _ = compare_paths(self.baseline_trace,
                                           result.trace_path)
                problems.extend(regressions(cmp_,
                                            self.fail_on_regress_pct))
            except Exception as e:     # noqa: BLE001
                problems.append(f"trace compare failed: {e}")
        return GateResult(passed=not problems, accuracy=accuracy,
                          floor=self.accuracy_floor, problems=problems)

    # -- swap ---------------------------------------------------------

    def promote(self, result: RetrainResult,
                accuracy: Optional[float] = None) -> None:
        """Atomically replace the serving artifact and hot-reload: the
        candidate file moves onto the registry source path with
        ``os.replace`` (atomic; readers see old bytes or new bytes,
        never a torn file), then the registry builds + warms the new
        engine and swaps it in, then the pool refreshes. Any failure
        here leaves the OLD artifact bytes gone only after the replace
        — which is why the replace is last-resort-recoverable: the
        reload failing keeps the old ENGINE serving from memory."""
        source = self.registry.source(self.name)
        os.replace(result.model_path, source)
        self.registry.reload(self.name)
        if result.trace_path:
            self.baseline_trace = result.trace_path
        if result.reference_scores is not None:
            self.detector.rearm(result.reference_scores)
        if self._on_promote is not None:
            self._on_promote(self.name)
        gen = self.registry.manifests()[self.name]["generation"]
        self._emit("promote", model=self.name, ok=True,
                   generation=gen, accuracy=accuracy)

    # -- background form ----------------------------------------------

    def run(self, interval_s: float,
            stop: Optional[threading.Event] = None) -> threading.Thread:
        """Poll ``step()`` every ``interval_s`` on a daemon thread
        until ``stop`` is set. Returns the thread."""
        stop = stop or threading.Event()
        self.stop_event = stop

        def loop():
            while not stop.is_set():
                try:
                    self.step()
                except Exception:      # noqa: BLE001 — the loop must
                    pass               # outlive a bad poll
                stop.wait(interval_s)

        t = threading.Thread(target=loop, daemon=True,
                             name=f"dpsvm-lifecycle[{self.name}]")
        t.start()
        return t

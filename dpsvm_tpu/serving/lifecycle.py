"""Self-healing model lifecycle: drift -> retrain -> gate -> hot-swap.

A serving model decays silently: the traffic distribution moves and
the frozen decision function keeps scoring it with stale confidence.
"Parallel SVMs in Practice" (arXiv:1404.1066) names model refresh as
the deployment concern that dominates one-shot training; the cheap
retrain that makes an AUTOMATED refresh affordable is exactly the
``approx/`` path ("Recipe for Fast Large-scale SVM Training",
arXiv:2207.01016). This module closes that loop with parts the repo
already has:

1. **Drift detection** — a deterministic two-sample Kolmogorov-
   Smirnov distance between a reference score sample (recorded when
   the serving generation was promoted) and the live rolling
   score-distribution window ``/metricsz`` already keeps. No model
   labels needed: a moved input distribution moves the decision-value
   distribution first.
2. **Supervised retrain** — ``resilience.supervisor.run_with_retries``
   wraps the caller's ``retrain_fn``, so a preempted retrain resumes
   from its checkpoint instead of aborting the refresh.
3. **Eval gate** — the candidate must clear a held-out accuracy floor
   AND (when both runs traced) the ``dpsvm compare`` regression gate
   (``observability.compare.regressions``) against the serving
   generation's training trace. A refresh that fails the gate changes
   NOTHING: the old generation keeps serving, and the failure is a
   trace event, not a page.
4. **Atomic hot-swap** — only a passing candidate is promoted:
   ``os.replace`` onto the registry source path (atomic at the
   filesystem level), then the registry's explicit reload (new engine
   fully warmed before the swap) and the replica pool's rolling
   refresh.

Everything is deterministic and injectable, so the whole loop — drift
in, promote or gate-hold out — runs as a CPU CI test
(tests/test_serving_resilience.py).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np


def ks_distance(a, b) -> float:
    """Two-sample Kolmogorov-Smirnov distance sup_x |F_a(x) - F_b(x)|
    — deterministic, rank-based (scale-free), in [0, 1]."""
    a = np.sort(np.asarray(a, np.float64).ravel())
    b = np.sort(np.asarray(b, np.float64).ravel())
    if a.size == 0 or b.size == 0:
        return 0.0
    allv = np.concatenate([a, b])
    cdf_a = np.searchsorted(a, allv, side="right") / a.size
    cdf_b = np.searchsorted(b, allv, side="right") / b.size
    return float(np.max(np.abs(cdf_a - cdf_b)))


class DriftDetector:
    """KS drift test of the live score window against a reference
    sample (the promoted generation's own score distribution).

    ``threshold`` is the KS distance that counts as drift; with the
    default 0.25 a pure location shift of ~0.7 reference standard
    deviations trips it while sampling noise at ``min_count=64`` stays
    an order of magnitude below (KS noise ~ sqrt(1/n) ~ 0.125 at worst
    for the 99th percentile of the null — the margin is the point:
    this arms a RETRAIN, so false positives cost real compute)."""

    def __init__(self, reference, *, threshold: float = 0.25,
                 min_count: int = 64):
        if not (0.0 < threshold <= 1.0):
            raise ValueError(f"threshold must be in (0, 1], "
                             f"got {threshold}")
        self._lock = threading.Lock()
        self.threshold = float(threshold)
        self.min_count = int(min_count)
        self.rearm(reference)

    def rearm(self, reference) -> None:
        """Swap the reference sample — called at every promotion so
        drift is always measured against the GENERATION NOW SERVING."""
        ref = np.asarray(reference, np.float64).ravel()
        if ref.size < 2:
            raise ValueError("reference sample needs >= 2 scores")
        with self._lock:
            self._ref = np.sort(ref)

    def check(self, window) -> Optional[dict]:
        """None = no drift; else the drift facts (the `drift` event's
        payload)."""
        win = np.asarray(window, np.float64).ravel()
        win = win[np.isfinite(win)]
        if win.size < self.min_count:
            return None
        with self._lock:
            ref = self._ref
        ks = ks_distance(ref, win)
        if ks <= self.threshold:
            return None
        return {"ks": round(ks, 6), "threshold": self.threshold,
                "window_n": int(win.size), "reference_n": int(ref.size)}


@dataclasses.dataclass
class RetrainResult:
    """What ``retrain_fn`` hands back: the candidate artifact (a model
    file the serving engine can load), optionally its training trace
    (enables the compare gate) and a fresh reference score sample
    (re-arms the drift detector at promotion)."""
    model_path: str
    trace_path: Optional[str] = None
    reference_scores: Optional[np.ndarray] = None


@dataclasses.dataclass
class GateResult:
    passed: bool
    accuracy: Optional[float]
    floor: float
    problems: "list[str]"


class LifecycleLoop:
    """One model's refresh loop (module docstring).

    * ``score_source()`` -> the live score window (the server's
      ``score_window()``; any 1-D float sequence works).
    * ``retrain_fn(resume_from, attempt)`` -> ``RetrainResult``. Runs
      under ``run_with_retries`` with ``checkpoint_path``, so a
      preempted attempt resumes.
    * ``eval_fn(model_path)`` -> held-out accuracy in [0, 1].
    * ``baseline_trace`` — the serving generation's training trace;
      with it (and a candidate trace) the ``dpsvm compare`` regression
      gate arms at ``fail_on_regress_pct``.
    * ``on_event(name, **extra)`` — trace/metrics sink (`drift`,
      `retrain`, `promote` with ok True/False).
    * ``on_promote(name)`` — post-swap hook (the server refreshes the
      replica pool here).
    """

    def __init__(self, *, registry, name: str,
                 detector: DriftDetector,
                 score_source: Callable[[], Sequence[float]],
                 retrain_fn: Callable[[Optional[str], int],
                                      RetrainResult],
                 eval_fn: Callable[[str], float],
                 accuracy_floor: float,
                 baseline_trace: Optional[str] = None,
                 fail_on_regress_pct: Optional[float] = None,
                 retries: int = 1, backoff_s: float = 0.0,
                 checkpoint_path: Optional[str] = None,
                 cooldown_s: float = 0.0,
                 on_event: Optional[Callable[..., None]] = None,
                 on_promote: Optional[Callable[[str], None]] = None):
        source = registry.source(name)
        if source is None:
            raise ValueError(
                f"model {name!r} was registered in-memory; the "
                "lifecycle loop needs a source path to hot-swap")
        if os.path.isdir(source):
            raise ValueError(
                "lifecycle hot-swap supports single-file model "
                f"artifacts; {source!r} is a directory (multiclass)")
        self.registry = registry
        self.name = name
        self.detector = detector
        self.score_source = score_source
        self.retrain_fn = retrain_fn
        self.eval_fn = eval_fn
        self.accuracy_floor = float(accuracy_floor)
        self.baseline_trace = baseline_trace
        self.fail_on_regress_pct = fail_on_regress_pct
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.checkpoint_path = checkpoint_path
        self.cooldown_s = float(cooldown_s)
        self._on_event = on_event
        self._on_promote = on_promote
        self._last_action_t = 0.0
        self.history: "list[dict]" = []

    def _emit(self, event: str, **extra) -> None:
        self.history.append({"event": event, **extra})
        if self._on_event is not None:
            try:
                self._on_event(event, **extra)
            except Exception:
                pass

    # -- the loop body ------------------------------------------------

    def step(self) -> str:
        """One poll. Returns the outcome: ``"no-drift"``, ``"cooldown"``,
        ``"promoted"``, ``"gate-held"`` (candidate rejected, old
        generation untouched) or ``"retrain-failed"``."""
        if (self.cooldown_s and
                time.monotonic() - self._last_action_t < self.cooldown_s):
            return "cooldown"
        drift = self.detector.check(self.score_source())
        if drift is None:
            return "no-drift"
        self._emit("drift", model=self.name, **drift)
        self._last_action_t = time.monotonic()
        try:
            result = self._retrain()
        except Exception as e:         # noqa: BLE001 — reported, loop
            self._emit("retrain", model=self.name, ok=False,
                       error=str(e))  # survives to the next poll
            return "retrain-failed"
        self._emit("retrain", model=self.name, ok=True,
                   candidate=result.model_path)
        gate = self.gate(result)
        if not gate.passed:
            self._emit("promote", model=self.name, ok=False,
                       accuracy=gate.accuracy, floor=gate.floor,
                       problems=gate.problems)
            return "gate-held"
        self.promote(result, accuracy=gate.accuracy)
        return "promoted"

    def _retrain(self, fn: Optional[Callable] = None) -> RetrainResult:
        from dpsvm_tpu.resilience.supervisor import run_with_retries

        result = run_with_retries(
            fn or self.retrain_fn, retries=self.retries,
            backoff_s=self.backoff_s,
            checkpoint_path=self.checkpoint_path)
        if not isinstance(result, RetrainResult):
            raise TypeError("retrain_fn must return a RetrainResult, "
                            f"got {type(result).__name__}")
        if not os.path.exists(result.model_path):
            raise FileNotFoundError(
                f"retrain_fn reported {result.model_path!r} but wrote "
                "no such artifact")
        return result

    # -- gate ---------------------------------------------------------

    def gate(self, result: RetrainResult) -> GateResult:
        """Held-out accuracy floor + (when traces exist on both sides)
        the mechanical ``dpsvm compare`` regression verdicts."""
        problems: "list[str]" = []
        accuracy: Optional[float] = None
        try:
            accuracy = float(self.eval_fn(result.model_path))
        except Exception as e:         # noqa: BLE001 — a gate that
            problems.append(f"eval failed: {e}")   # crashes must HOLD
        if accuracy is not None and accuracy < self.accuracy_floor:
            problems.append(f"held-out accuracy {accuracy:.4f} below "
                            f"floor {self.accuracy_floor:.4f}")
        if (self.baseline_trace and result.trace_path
                and self.fail_on_regress_pct is not None):
            try:
                from dpsvm_tpu.observability.compare import (
                    compare_paths, regressions)
                cmp_, _, _ = compare_paths(self.baseline_trace,
                                           result.trace_path)
                problems.extend(regressions(cmp_,
                                            self.fail_on_regress_pct))
            except Exception as e:     # noqa: BLE001
                problems.append(f"trace compare failed: {e}")
        return GateResult(passed=not problems, accuracy=accuracy,
                          floor=self.accuracy_floor, problems=problems)

    # -- swap ---------------------------------------------------------

    def promote(self, result: RetrainResult,
                accuracy: Optional[float] = None) -> None:
        """Atomically replace the serving artifact and hot-reload: the
        candidate file moves onto the registry source path with
        ``os.replace`` (atomic; readers see old bytes or new bytes,
        never a torn file — ``registry.promote_file``), then the
        registry builds + warms the new engine and swaps it in, then
        the pool refreshes. Any failure here leaves the OLD artifact
        bytes gone only after the replace — which is why the replace
        is last-resort-recoverable: the reload failing keeps the old
        ENGINE serving from memory."""
        gen = self.registry.promote_file(self.name, result.model_path)
        if result.trace_path:
            self.baseline_trace = result.trace_path
        if result.reference_scores is not None:
            self.detector.rearm(result.reference_scores)
        if self._on_promote is not None:
            self._on_promote(self.name)
        self._emit("promote", model=self.name, ok=True,
                   generation=gen, accuracy=accuracy)

    # -- background form ----------------------------------------------

    def run(self, interval_s: float,
            stop: Optional[threading.Event] = None) -> threading.Thread:
        """Poll ``step()`` every ``interval_s`` on a daemon thread
        until ``stop`` is set. Returns the thread."""
        stop = stop or threading.Event()
        self.stop_event = stop

        def loop():
            while not stop.is_set():
                try:
                    self.step()
                except Exception:      # noqa: BLE001 — the loop must
                    pass               # outlive a bad poll
                stop.wait(interval_s)

        t = threading.Thread(target=loop, daemon=True,
                             name=f"dpsvm-lifecycle[{self.name}]")
        t.start()
        return t


# ---------------------------------------------------------------------
# continuous learning on a live shard log
# ---------------------------------------------------------------------

class ContinuousLearningLoop(LifecycleLoop):
    """The drift loop closed over a LIVE shard log (docs/SERVING.md
    "Continuous learning", docs/DATA.md "Live shard logs"): drift can
    now trigger either a CHEAP incremental update — warm-start the
    approx weights on the grown log (``fit_approx_stream(live=True,
    init_w=warm_start_vector(served))``) — or a cadenced FULL retrain
    (every ``full_every``-th refresh; typically the cascade
    warm-started from the incremental weights). Both run under the
    retry supervisor, both must clear the accuracy-floor +
    ``dpsvm compare`` gate, and only a passing candidate reaches the
    atomic hot-swap.

    Robustness contract on top of ``LifecycleLoop``:

    * every stage is individually kill-resumable: the refresh
      functions own their training checkpoints (``checkpoint_path``),
      and once a candidate artifact is durable the loop persists a
      STAGE STATE file (``state_path``, atomic JSON) — a process
      killed between retrain and swap resumes at the GATE with the
      same candidate instead of paying the retrain again;
    * a gate failure dumps a PR 13 incident bundle (``bundle_dir``)
      whose embedded trace carries the loop's drift/refresh/retrain/
      promote event history — the refresh that did NOT happen leaves
      an artifact saying exactly why;
    * a passing swap lands a ``live_refresh_latency`` perf-ledger row
      (drift-fire -> swapped-generation wall seconds, kind="serve")
      so refresh latency is a gateable historical fact.

    ``incremental_fn`` / ``retrain_fn`` share the retrain signature
    ``(resume_from, attempt) -> RetrainResult``.
    """

    def __init__(self, *, incremental_fn: Optional[Callable] = None,
                 full_every: int = 0,
                 bundle_dir: Optional[str] = None,
                 state_path: Optional[str] = None,
                 ledger_path: Optional[str] = None, **kw):
        super().__init__(**kw)
        if incremental_fn is None and not kw.get("retrain_fn"):
            raise ValueError("ContinuousLearningLoop needs "
                             "incremental_fn and/or retrain_fn")
        self.incremental_fn = incremental_fn
        self.full_every = int(full_every)
        self.bundle_dir = bundle_dir
        self.state_path = state_path
        self.ledger_path = ledger_path
        self.refresh_count = 0
        self.last_refresh: Optional[dict] = None
        self._flight = None
        if bundle_dir:
            from dpsvm_tpu.observability import blackbox
            self._flight = blackbox.FlightRecorder(
                blackbox.make_manifest(
                    solver="serving",
                    config={"model": self.name,
                            "loop": "continuous-learning"}))

    def _emit(self, event: str, **extra) -> None:
        if self._flight is not None:
            try:
                self._flight.event(event, n_iter=0, **extra)
            except Exception:
                pass
        super()._emit(event, **extra)

    # -- durable stage state ------------------------------------------

    def _load_stage_state(self) -> Optional[dict]:
        if not self.state_path or not os.path.exists(self.state_path):
            return None
        import json
        try:
            with open(self.state_path) as fh:
                st = json.load(fh)
        except (OSError, ValueError):
            return None
        if not os.path.exists(st.get("model_path", "")):
            self._clear_stage_state()   # candidate gone: restart clean
            return None
        return st

    def _save_stage_state(self, kind: str, result: RetrainResult,
                          fired_unix: float) -> None:
        if not self.state_path:
            return
        import json
        st = {"stage": "gate", "kind": kind,
              "model_path": result.model_path,
              "trace_path": result.trace_path,
              "reference_scores":
                  (np.asarray(result.reference_scores,
                              np.float64).tolist()
                   if result.reference_scores is not None else None),
              "fired_unix": float(fired_unix),
              "refresh_count": int(self.refresh_count)}
        tmp = f"{self.state_path}.tmp{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(st, fh)
        os.replace(tmp, self.state_path)

    def _clear_stage_state(self) -> None:
        if self.state_path:
            try:
                os.unlink(self.state_path)
            except OSError:
                pass

    # -- the loop body ------------------------------------------------

    def step(self) -> str:
        resumed = self._load_stage_state()
        if resumed is not None:
            # Killed between a durable candidate and the swap: resume
            # at the gate — the retrain is not paid twice.
            self._emit("refresh_resume", model=self.name,
                       refresh_kind=resumed["kind"],
                       candidate=resumed["model_path"])
            self.refresh_count = int(resumed.get(
                "refresh_count", self.refresh_count))
            result = RetrainResult(
                model_path=resumed["model_path"],
                trace_path=resumed.get("trace_path"),
                reference_scores=(
                    np.asarray(resumed["reference_scores"], np.float64)
                    if resumed.get("reference_scores") is not None
                    else None))
            return self._gate_and_swap(resumed["kind"], result,
                                       resumed.get("fired_unix"))
        if (self.cooldown_s and
                time.monotonic() - self._last_action_t < self.cooldown_s):
            return "cooldown"
        drift = self.detector.check(self.score_source())
        if drift is None:
            return "no-drift"
        self._emit("drift", model=self.name, **drift)
        self._last_action_t = time.monotonic()
        fired_unix = time.time()
        want_full = (self.incremental_fn is None
                     or (self.full_every > 0
                         and (self.refresh_count + 1) % self.full_every
                         == 0))
        kind = "full" if want_full else "incremental"
        gen = self.registry.manifests()[self.name]["generation"]
        self._emit("refresh", model=self.name, refresh_kind=kind,
                   generation=gen)
        fn = self.retrain_fn if kind == "full" else self.incremental_fn
        try:
            result = self._retrain(fn)
        except Exception as e:         # noqa: BLE001 — reported, loop
            self._emit("retrain", model=self.name, ok=False,
                       refresh_kind=kind, error=str(e))
            return "retrain-failed"
        self._emit("retrain", model=self.name, ok=True,
                   refresh_kind=kind, candidate=result.model_path)
        self.refresh_count += 1
        self._save_stage_state(kind, result, fired_unix)
        return self._gate_and_swap(kind, result, fired_unix)

    def _gate_and_swap(self, kind: str, result: RetrainResult,
                       fired_unix: Optional[float]) -> str:
        gate = self.gate(result)
        if not gate.passed:
            self._emit("promote", model=self.name, ok=False,
                       refresh_kind=kind, accuracy=gate.accuracy,
                       floor=gate.floor, problems=gate.problems)
            self._dump_gate_bundle(kind, gate)
            self._clear_stage_state()
            return "gate-held"
        self.promote(result, accuracy=gate.accuracy)
        self._clear_stage_state()
        latency = (max(time.time() - float(fired_unix), 0.0)
                   if fired_unix else None)
        gen = self.registry.manifests()[self.name]["generation"]
        self.last_refresh = {"kind": kind, "seconds": latency,
                             "generation": gen,
                             "accuracy": gate.accuracy}
        if latency is not None:
            from dpsvm_tpu.observability import ledger
            ledger.append(
                "live_refresh_latency",
                {"metric": "live_refresh_latency", "refresh_kind": kind,
                 "model": self.name, "generation": gen,
                 "accuracy": gate.accuracy},
                kind="serve", value=float(latency), unit="s",
                direction="lower", trace=result.trace_path,
                path=self.ledger_path)
        return "promoted"

    def _dump_gate_bundle(self, kind: str, gate: GateResult) -> None:
        """A held gate is an incident: the refresh the system decided
        NOT to ship leaves a bundle naming why (docs/OBSERVABILITY.md
        "Incident bundles")."""
        if self._flight is None or not self.bundle_dir:
            return
        from dpsvm_tpu.observability import blackbox
        blackbox.dump_bundle(
            self.bundle_dir, recorder=self._flight,
            rule="refresh-gate-held", severity="warn",
            window=f"model={self.name}",
            reason="; ".join(gate.problems) or "gate held",
            extra={"source": "continuous-learning",
                   "refresh_kind": kind,
                   "accuracy": gate.accuracy, "floor": gate.floor})


# ---------------------------------------------------------------------
# the end-to-end drill
# ---------------------------------------------------------------------

def live_drift_drill(base_dir: str, *, seed: int = 0,
                     rows_per_shard: int = 96, seed_shards: int = 3,
                     append_shards: int = 4, shift: float = 3.0,
                     shift_at_shard: int = 1,
                     accuracy_floor: float = 0.85,
                     full_every: int = 0,
                     approx_dim: int = 64, c: float = 10.0,
                     trace_path: Optional[str] = None,
                     ledger_path: Optional[str] = None,
                     bundle_dir: Optional[str] = None) -> dict:
    """The live continuous-learning drill, end to end on one process
    (CPU CI + the ``live_drift_drill`` burst tag): seed a shard log,
    train + serve a model from it, APPEND shards whose distribution
    shifts mid-serve, and prove — with no human in the loop — that
    drift fires, the warm-started refresh retrains on the grown log,
    the gate passes, the hot-swap is atomic, the served model's
    held-out accuracy on the SHIFTED world recovers above the floor,
    and serving stays eject-free throughout. Returns one JSON-able
    row (metric ``live_refresh_latency`` = drift-fire -> swapped
    generation wall seconds), appends it to the perf ledger, and —
    when ``trace_path`` is set — records a schema-valid serving trace
    covering every stage event (append_admitted -> drift -> refresh ->
    retrain -> promote)."""
    import json as _json

    from dpsvm_tpu.approx.primal import (fit_approx_stream,
                                         warm_start_vector)
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data import live as livelib
    from dpsvm_tpu.data import stream as streamlib
    from dpsvm_tpu.data.synthetic import save_csv
    from dpsvm_tpu.models.io import load_model, save_model
    from dpsvm_tpu.models.svm import decision_function
    from dpsvm_tpu.observability.record import (close_serving_trace,
                                                open_serving_trace)
    from dpsvm_tpu.serving.pool import ReplicaPool
    from dpsvm_tpu.serving.registry import ModelRegistry

    t_drill = time.perf_counter()
    rng = np.random.default_rng(seed)
    d = 6

    def make_rows(n, shifted):
        x = rng.standard_normal((n, d)).astype(np.float32)
        if shifted:
            x = x + np.float32(shift)
            y = np.where((x[:, 0] - shift)
                         + 0.25 * (x[:, 1] - shift) > 0, 1, -1)
        else:
            y = np.where(x[:, 0] + 0.25 * x[:, 1] > 0, 1, -1)
        return x, np.asarray(y, np.int32)

    # 1. seed log + holdouts (base AND shifted worlds)
    x0, y0 = make_rows(seed_shards * rows_per_shard, False)
    src = os.path.join(base_dir, "seed.csv")
    save_csv(src, x0, y0)
    log_dir = os.path.join(base_dir, "log")
    streamlib.convert_to_shards(src, log_dir,
                                rows_per_shard=rows_per_shard)
    x_ho_base, y_ho_base = make_rows(256, False)
    x_ho_shift, y_ho_shift = make_rows(256, True)

    trace = (open_serving_trace(trace_path,
                                models={"default": "live-drill"})
             if trace_path else None)

    def t_event(name, **extra):
        if trace is not None:
            trace.event(name, n_iter=0, **extra)

    # 2. initial model trained from the log, registered, pooled
    cfg = dict(solver="approx-rff", approx_dim=approx_dim, c=c,
               epsilon=5e-3, max_iter=600, chunk_iters=64,
               verbose=False)
    ds0 = streamlib.ShardedDataset.open(log_dir)
    model0, _res0 = fit_approx_stream(ds0, SVMConfig(**cfg))
    model_path = os.path.join(base_dir, "serving.npz")
    save_model(model0, model_path)
    registry = ModelRegistry()
    registry.register("default", model_path, max_batch=64)
    pool = ReplicaPool(lambda idx: registry.build("default"),
                       n_replicas=1, name="default",
                       on_event=lambda e, **kw: t_event(e, **kw))

    def served_scores(x):
        return np.asarray(
            pool.infer(x, ("decision",))["decision"], np.float64)

    try:
        base_scores = served_scores(x0[:256])
        detector = DriftDetector(base_scores, threshold=0.25,
                                 min_count=64)

        # 3. the live training view + its watcher (events -> trace)
        ds_live = streamlib.ShardedDataset.open(log_dir)
        watcher = livelib.ShardLogWatcher(
            ds_live,
            on_event=lambda e, **kw: t_event(e, **kw))

        # the serving-side score window: decisions of recently
        # ARRIVED rows, scored through the pool — what /metricsz
        # keeps in production
        window: list = []

        def score_arrivals():
            for k in range(max(0, ds_live.n_shards - 2),
                           ds_live.n_shards):
                got = ds_live.read_shard_checked(k)
                if got is not None:
                    window[:] = served_scores(got[0]).tolist()

        def refresh_fn(kind):
            def run(resume_from, attempt):
                served = load_model(registry.source("default"))
                init = warm_start_vector(served)
                ds_train = streamlib.ShardedDataset.open(log_dir)
                tr_path = os.path.join(
                    base_dir, f"refresh-{kind}.jsonl")
                rcfg = SVMConfig(trace_out=tr_path,
                                 resume_from=resume_from, **cfg)
                if kind == "full":
                    # The cadenced full retrain: the cascade's
                    # warm-started exact polish is the chip-scale
                    # move (solver/cascade.py approx_init_w); at
                    # drill scale the same warm-started stream fit
                    # retrains the full log exactly.
                    model, _ = fit_approx_stream(ds_train, rcfg,
                                                 init_w=init)
                else:
                    model, _ = fit_approx_stream(ds_train, rcfg,
                                                 live=True,
                                                 init_w=init)
                cand = os.path.join(base_dir, "candidate.npz")
                save_model(model, cand)
                xs = ds_train.materialize()[0][-256:]
                return RetrainResult(
                    model_path=cand, trace_path=tr_path,
                    reference_scores=np.asarray(
                        decision_function(model, xs), np.float64))
            return run

        def evaluate(candidate_path):
            cand = load_model(candidate_path)
            pred = np.where(np.asarray(
                decision_function(cand, x_ho_shift)) < 0, -1, 1)
            return float(np.mean(pred == y_ho_shift))

        loop = ContinuousLearningLoop(
            registry=registry, name="default", detector=detector,
            score_source=lambda: np.asarray(window, np.float64),
            retrain_fn=refresh_fn("full"),
            incremental_fn=refresh_fn("incremental"),
            full_every=full_every,
            eval_fn=evaluate, accuracy_floor=accuracy_floor,
            state_path=os.path.join(base_dir, "refresh.state.json"),
            bundle_dir=bundle_dir, ledger_path=ledger_path,
            on_event=t_event,
            on_promote=lambda _name: pool.refresh())

        # 4. pre-shift serving: appends from the BASE world keep the
        # loop quiet (no false drift fire)
        plan = faultinject.current()
        append_rng = np.random.default_rng(seed + 1)
        outcomes = []
        for i in range(append_shards):
            shifted = (plan.live_shift_now(i) if plan is not None
                       else i + 1 >= shift_at_shard)
            xa = append_rng.standard_normal(
                (rows_per_shard, d)).astype(np.float32)
            if shifted:
                xa = xa + np.float32(shift)
                ya = np.where((xa[:, 0] - shift)
                              + 0.25 * (xa[:, 1] - shift) > 0, 1, -1)
            else:
                ya = np.where(xa[:, 0] + 0.25 * xa[:, 1] > 0, 1, -1)
            livelib.append_shard(log_dir, xa,
                                 np.asarray(ya, np.int32))
            watcher.poll()
            score_arrivals()
            outcomes.append(loop.step())

        promoted = "promoted" in outcomes
        accepted = [o for o in outcomes
                    if o in ("promoted", "gate-held")]
        pred = np.where(np.asarray(
            served_scores(x_ho_shift)) < 0, -1, 1)
        acc_shift = float(np.mean(pred == y_ho_shift))
        pred_b = np.where(np.asarray(
            served_scores(x_ho_base)) < 0, -1, 1)
        acc_base_before = float(np.mean(np.where(np.asarray(
            decision_function(model0, x_ho_shift)) < 0, -1, 1)
            == y_ho_shift))
        pool_metrics = pool.metrics()
        row = {
            "metric": "live_refresh_latency",
            "value": (loop.last_refresh or {}).get("seconds"),
            "unit": "s",
            "promoted": promoted,
            "outcomes": outcomes,
            "refresh_kind": (loop.last_refresh or {}).get("kind"),
            "generation": registry.manifests()["default"]["generation"],
            "log_generation": ds_live.generation,
            "admitted_shards": watcher.admitted_shards,
            "accuracy_shifted_before": acc_base_before,
            "accuracy_shifted_after": acc_shift,
            "accuracy_base_after": float(np.mean(pred_b == y_ho_base)),
            "accuracy_floor": accuracy_floor,
            "ejections": int(pool_metrics.get("ejections", 0)),
            "torn_observed": watcher.torn_observed,
            "stale_observed": watcher.stale_observed,
            "drill_seconds": round(time.perf_counter() - t_drill, 3),
        }
        row["ok"] = bool(promoted and acc_shift >= accuracy_floor
                         and row["ejections"] == 0 and accepted)
        if trace is not None:
            close_serving_trace(trace, requests=len(outcomes),
                                errors=0,
                                seconds=row["drill_seconds"])
        return row
    finally:
        if trace is not None and not trace.closed:
            close_serving_trace(trace)
        pool.close()


# keep the drill's lazy imports honest: faultinject is used above
from dpsvm_tpu.resilience import faultinject  # noqa: E402

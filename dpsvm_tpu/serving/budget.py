"""Per-request deadline budgets, hedging delays, and the overload
degradation ladder.

Three small policies the resilient serving layer shares
(docs/SERVING.md "Resilience"):

* **Budget** — one absolute deadline per request, fixed at admission
  and carried through queue -> batch -> device dispatch, so every
  stage bounds its wait by what is *left*, not by a fresh full
  timeout (the classic failure where three 30 s stages turn a 30 s
  SLO into 90 s). A blown budget surfaces as
  ``DeadlineExceededError`` — a ``TimeoutError`` subclass the HTTP
  layer maps to **504**, never the 400 family (a timeout is the
  server's fault, not the client's).
* **hedge_delay_s** — when to fire a duplicate dispatch at a second
  replica: the p99 of the recent latency window times a small
  multiplier (clamped). Hedging at p99 bounds the work overhead at
  ~1% duplicated requests while converting tail stalls into a second
  chance ("The Tail at Scale" rule of thumb).
* **DegradeController** — tiered load shedding keyed on queue fill,
  so overload is a slope instead of a cliff: first drop the optional
  expensive output (``proba`` -> ``decision``), then shed whole
  requests to a registered cheaper sibling model (the ``approx/``
  path exists exactly to make that sibling affordable), and only
  past that reject with 429.

Stdlib + numpy only (no jax): importable anywhere the batcher is.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

import numpy as np


class DeadlineExceededError(TimeoutError):
    """The request's deadline budget ran out. HTTP layer: 504 +
    Retry-After (NOT a 400 — the client did nothing wrong)."""


class Budget:
    """One request's deadline, fixed at admission.

    All times are ``time.perf_counter`` based; ``deadline`` is
    absolute so it can be handed across threads (ticket -> batcher
    worker -> pool dispatch) without re-anchoring."""

    __slots__ = ("t0", "deadline", "total_s", "tenant")

    def __init__(self, total_s: float,
                 t0: Optional[float] = None,
                 tenant: Optional[str] = None):
        if not (total_s > 0):
            raise ValueError(f"budget must be > 0 s, got {total_s}")
        self.t0 = time.perf_counter() if t0 is None else float(t0)
        self.total_s = float(total_s)
        self.deadline = self.t0 + self.total_s
        # Who this deadline is spent for (docs/OBSERVABILITY.md
        # "Per-tenant attribution"): carried with the deadline across
        # threads so the 504 accounting downstream of the ticket wait
        # can bill the right tenant without re-deriving identity.
        self.tenant = tenant

    def remaining(self) -> float:
        """Seconds left (>= 0)."""
        return max(0.0, self.deadline - time.perf_counter())

    def expired(self) -> bool:
        return time.perf_counter() >= self.deadline

    def check(self, where: str = "") -> None:
        if self.expired():
            raise DeadlineExceededError(
                f"deadline budget ({self.total_s:.3g}s) exhausted"
                + (f" at {where}" if where else ""))

    def spent(self) -> float:
        """Seconds consumed since admission."""
        return time.perf_counter() - self.t0

    def describe(self) -> dict:
        """Deadline accounting for the request's root span
        (docs/OBSERVABILITY.md "Spans"): how big the budget was and
        how much was left when described — a 504's root span says not
        just THAT the budget blew but how deep in it the request
        died."""
        out = {"deadline_ms": round(self.total_s * 1000.0, 3),
               "deadline_remaining_ms": round(
                   self.remaining() * 1000.0, 3)}
        if self.tenant is not None:
            out["tenant"] = self.tenant
        return out

    def __repr__(self) -> str:
        return (f"Budget(total={self.total_s:.3g}s, "
                f"remaining={self.remaining():.3g}s)")


#: hedge clamp bounds (seconds) — below the floor a hedge races the
#: primary on noise; above the cap a "hedge" is just a retry.
HEDGE_MIN_S = 0.002
HEDGE_MAX_S = 2.0
HEDGE_MIN_SAMPLES = 16


def hedge_delay_s(lat_ms: Sequence[float], *,
                  multiplier: float = 1.1,
                  min_s: float = HEDGE_MIN_S,
                  max_s: float = HEDGE_MAX_S,
                  min_samples: int = HEDGE_MIN_SAMPLES) -> float:
    """The p99-based hedge delay: fire the duplicate only when the
    primary has taken longer than (nearly) every recent request.
    With a cold window (fewer than ``min_samples`` observations) the
    delay is the conservative cap — hedging arms itself only once
    the latency distribution is actually known."""
    lat = np.asarray(list(lat_ms), np.float64)
    if lat.size < min_samples:
        return float(max_s)
    p99 = float(np.percentile(lat, 99.0)) / 1000.0
    return float(min(max(p99 * multiplier, min_s), max_s))


#: Degradation tiers, mildest first. ``tier >= 1`` sheds ``proba``;
#: ``tier >= 2`` sheds whole requests to the sibling model;
#: tier 3 is the queue-full 429 the batcher already enforces.
TIER_NONE = 0
TIER_SHED_PROBA = 1
TIER_SHED_SIBLING = 2
TIER_NAMES = {TIER_NONE: "none", TIER_SHED_PROBA: "shed_proba",
              TIER_SHED_SIBLING: "shed_sibling"}


class DegradeController:
    """Maps queue fill to a degradation tier and tracks activations.

    ``tier_for(depth, cap)`` is pure; ``note(tier)`` records the
    transition and returns True exactly when the tier ESCALATED —
    the moment worth a ``shed`` trace event (per-request counting
    would spam the trace under sustained overload)."""

    def __init__(self, *, enabled: bool = True,
                 shed_proba_fill: float = 0.5,
                 shed_sibling_fill: float = 0.8):
        if not (0.0 < shed_proba_fill <= shed_sibling_fill <= 1.0):
            raise ValueError(
                "need 0 < shed_proba_fill <= shed_sibling_fill <= 1, "
                f"got {shed_proba_fill} / {shed_sibling_fill}")
        self.enabled = bool(enabled)
        self.shed_proba_fill = float(shed_proba_fill)
        self.shed_sibling_fill = float(shed_sibling_fill)
        self._tier = TIER_NONE
        self._activations = {TIER_SHED_PROBA: 0, TIER_SHED_SIBLING: 0}
        self._lock = threading.Lock()

    def tier_for(self, queue_depth: int, max_queue: int) -> int:
        if not self.enabled or max_queue <= 0:
            return TIER_NONE
        fill = queue_depth / max_queue
        if fill >= self.shed_sibling_fill:
            return TIER_SHED_SIBLING
        if fill >= self.shed_proba_fill:
            return TIER_SHED_PROBA
        return TIER_NONE

    def note(self, tier: int) -> bool:
        """Record the current tier; True on escalation (emit `shed`)."""
        with self._lock:
            escalated = tier > self._tier
            if escalated and tier in self._activations:
                self._activations[tier] += 1
            self._tier = tier
            return escalated

    @property
    def tier(self) -> int:
        with self._lock:
            return self._tier

    def stats(self) -> dict:
        with self._lock:
            return {
                "tier": self._tier,
                "tier_name": TIER_NAMES.get(self._tier, "?"),
                "activations": {TIER_NAMES[k]: v for k, v in
                                self._activations.items()},
            }

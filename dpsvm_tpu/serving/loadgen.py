"""Load generator for the serving stack: open/closed-loop HTTP traffic
against ``dpsvm serve``, reported as one bench-harness JSON row.

Closed loop (default): N workers, each firing its next request the
moment the previous answer lands — throughput is latency-bound, the
classic saturation probe, and the shape that exercises server-side
micro-batching (concurrent in-flight requests coalesce).

Open loop: requests depart on a fixed schedule (``rps``) regardless of
completions — the arrival process real traffic has; latency here
includes any queueing the server builds up, so it surfaces overload
honestly (no coordinated omission: a worker that falls behind schedule
records its lateness inside the measured latency).

``compare_sequential`` re-runs the same request count single-worker
with one row per request — the no-batching baseline. The headline row
then carries both numbers and their ratio, so "coalesced batching
beats batch-1 sequential submission" is a printed fact, not a claim.

Stdlib HTTP (``http.client`` with keep-alive) + numpy percentiles; no
jax — the loadgen runs from any machine that can reach the server.
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import time
from typing import List, Optional, Sequence, Tuple
from urllib.parse import urlsplit

import numpy as np


def synthetic_rows(d: int, n: int = 512, seed: int = 0) -> np.ndarray:
    """Feature rows for a model of width d when no dataset is given.
    Inference cost depends only on shapes, so random rows measure the
    same thing real ones would."""
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, d)).astype(np.float32)


def fetch_models(url: str, timeout: float = 10.0) -> dict:
    """GET /v1/models and return the full name -> manifest map (lazy
    fleet entries report ``resident: false`` and light registration
    facts only — the fleet drill picks its target names from here)."""
    host, port = _host_port(url)
    conn = _Conn(host, port, timeout=timeout)
    try:
        conn.request("GET", "/v1/models")
        resp = conn.getresponse()
        body = json.loads(resp.read() or b"{}")
    finally:
        conn.close()
    if resp.status != 200:
        raise RuntimeError(f"GET /v1/models -> {resp.status}: {body}")
    return body.get("models", {})


def fetch_manifest(url: str, model: str = "default",
                   timeout: float = 10.0) -> dict:
    """GET /v1/models and return the named model's manifest (the
    loadgen needs the feature width to synthesize rows)."""
    models = fetch_models(url, timeout=timeout)
    if model not in models:
        raise RuntimeError(f"server has no model {model!r} "
                           f"(models: {sorted(models)})")
    return models[model]


def _host_port(url: str) -> Tuple[str, int]:
    parts = urlsplit(url if "//" in url else f"http://{url}")
    return parts.hostname or "127.0.0.1", parts.port or 80


class _Conn(http.client.HTTPConnection):
    """Keep-alive connection with Nagle off: headers and body are
    separate writes, and the 40 ms delayed-ACK stall would otherwise
    dominate every latency percentile this tool exists to measure."""

    def connect(self):
        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


def tenant_of(i: int, tenants: int, skew: float) -> Optional[str]:
    """Deterministic tenant assignment for request index ``i`` (the
    multi-tenant traffic mix ``dpsvm loadgen --tenants`` sends).

    With ``skew`` S in (0, 1], tenant ``t0`` is the planted hot tenant
    and receives fraction S of the requests via the same cumulative-
    quota stride the span sampler uses (observability/spans
    .should_sample — evenly interleaved, no RNG, replayable); the
    remainder round-robins over ``t1..t{N-1}``. skew=0 round-robins
    over all N. ``tenants=0`` disables the mix (None: no ``tenant``
    field — the server falls back to per-model attribution)."""
    if tenants < 1:
        return None
    if tenants == 1:
        return "t0"
    s = min(max(float(skew), 0.0), 1.0)
    if s > 0.0 and int((i + 1) * s) > int(i * s):
        return "t0"
    cold = tenants - 1 if s > 0.0 else tenants
    first = 1 if s > 0.0 else 0
    return f"t{first + i % cold}"


def model_of(i: int, n_models: int, skew: float) -> int:
    """Deterministic model-list index for request index ``i`` (the
    model-fleet traffic mix ``dpsvm loadgen --models`` sends).

    Same cumulative-quota stride as ``tenant_of``: with ``skew`` S in
    (0, 1] the FIRST model in the list is the planted hot model and
    receives fraction S of the requests, evenly interleaved; the rest
    round-robins over the remainder. skew=0 round-robins over all N.
    Round-robin over a fleet larger than the server's model-cache
    budget is the cache-thrash worst case; the skewed mix is the
    realistic one the cache exists for."""
    if n_models <= 1:
        return 0
    s = min(max(float(skew), 0.0), 1.0)
    if s > 0.0 and int((i + 1) * s) > int(i * s):
        return 0
    cold = n_models - 1 if s > 0.0 else n_models
    first = 1 if s > 0.0 else 0
    return first + i % cold


def run_loadgen(url: str, rows: np.ndarray, *, model: str = "default",
                requests: int = 200, batch: int = 1,
                concurrency: int = 8, mode: str = "closed",
                rps: float = 100.0, want: Sequence[str] = ("labels",),
                timeout: float = 30.0, spans: bool = False,
                tenants: int = 0,
                hot_tenant_skew: float = 0.0,
                models: Sequence[str] = (),
                model_skew: float = 0.0,
                connections: int = 0) -> dict:
    """Fire ``requests`` requests of ``batch`` rows each; return the
    result row (throughput + latency percentiles + error count).

    ``spans=True`` asks the server for its per-request span breakdown
    (the ``X-Trace-Spans`` header — forced server-side sampling, so it
    works with or without a serving --trace-out) and aggregates the
    stage percentiles into the row: ``queue_wait_p99_ms`` /
    ``compute_p99_ms`` + the full ``span_p99_ms`` table, so a
    saturate-knee row says WHICH stage hit the knee instead of just
    that p99 did (docs/OBSERVABILITY.md "Spans").

    ``tenants=N`` spreads the requests over N tenant labels (body
    ``tenant`` field; ``tenant_of`` above), ``hot_tenant_skew=S``
    concentrates fraction S on the planted hot tenant ``t0`` — the
    tenant-isolation drill. The row then carries per-tenant request/
    latency sub-rows plus ``hot_p99_ms`` / ``others_p99_ms``, so "one
    noisy tenant did not ruin its neighbours' p99" is a printed fact
    (docs/OBSERVABILITY.md "Per-tenant attribution").

    ``models=[names]`` spreads the requests over a model fleet instead
    of one model (``model_of`` above; ``model_skew`` plants the first
    name as the hot model). The row then carries per-model request/
    latency sub-rows plus ``cold_start_p99_ms`` — p99 over each
    model's FIRST-request latency, the number the HBM model cache
    exists to bound (a fault that hydrates from disk shows up here;
    a resident hit does not). All models must share the primary
    model's feature width (the fleet drill is a same-spec fleet).

    ``connections=N`` pre-opens N keep-alive sockets before the clock
    starts and HOLDS them all for the whole run: the first
    ``concurrency`` of them carry the traffic, the rest sit idle-open.
    That is the front-door drill's shape — thousands of mostly-idle
    connections with a modest request rate — which costs an event-loop
    server one registered socket each and a thread-per-connection
    server one stack each. The row gains ``open_connections`` (how
    many actually opened)."""
    if mode not in ("closed", "open"):
        raise ValueError(f"mode must be 'closed' or 'open', got {mode!r}")
    if requests < 1 or batch < 1 or concurrency < 1:
        raise ValueError("requests, batch and concurrency must be >= 1")
    if tenants < 0:
        raise ValueError(f"tenants must be >= 0, got {tenants}")
    if connections < 0:
        raise ValueError(f"connections must be >= 0, got {connections}")
    rows = np.asarray(rows, np.float32)
    host, port = _host_port(url)
    # Pre-serialize every request body: the generator must measure the
    # server, not its own json.dumps.
    n_rows = rows.shape[0]
    models = list(models)
    bodies: List[bytes] = []
    tenant_by_idx: List[Optional[str]] = []
    model_by_idx: List[str] = []
    for i in range(requests):
        take = [(i * batch + j) % n_rows for j in range(batch)]
        mdl = (models[model_of(i, len(models), model_skew)]
               if models else model)
        model_by_idx.append(mdl)
        body = {"model": mdl, "return": list(want),
                "instances": rows[take].tolist()}
        ten = tenant_of(i, tenants, hot_tenant_skew)
        tenant_by_idx.append(ten)
        if ten is not None:
            body["tenant"] = ten
        bodies.append(json.dumps(body).encode())

    held: List[_Conn] = []
    if connections:
        # open the whole fleet up front, outside the measured wall
        # clock; stop quietly at the server's cap (the row reports the
        # achieved count, and a cap-refused connect is the server
        # behaving, not a loadgen failure)
        for _ in range(int(connections)):
            c = _Conn(host, port, timeout=timeout)
            try:
                c.connect()
            except OSError:
                c.close()
                break
            held.append(c)

    next_idx = [0]
    idx_lock = threading.Lock()
    lat_ms: List[float] = []
    statuses: List[int] = []
    stage_ms: dict = {}            # stage name -> [ms, ...] (spans=True)
    by_tenant: dict = {}           # tenant -> {"ms": [...], "errors": n}
    by_model: dict = {}            # model -> {"lat": [(i, ms)], "errors": n}
    out_lock = threading.Lock()
    t_start = [0.0]
    headers = {"Content-Type": "application/json"}
    if spans:
        headers["X-Trace-Spans"] = "1"

    def worker(wid: int) -> None:
        conn = (held[wid] if wid < len(held)
                else _Conn(host, port, timeout=timeout))
        try:
            while True:
                with idx_lock:
                    i = next_idx[0]
                    if i >= requests:
                        return
                    next_idx[0] += 1
                if mode == "open":
                    # fixed departure schedule; lateness is NOT slept
                    # away (that would be coordinated omission)
                    due = t_start[0] + i / rps
                    delay = due - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    t0 = due if due > t_start[0] else time.perf_counter()
                else:
                    t0 = time.perf_counter()
                breakdown = None
                try:
                    conn.request("POST", "/v1/predict", body=bodies[i],
                                 headers=headers)
                    resp = conn.getresponse()
                    data = resp.read()
                    status = resp.status
                    if spans and status == 200:
                        try:
                            breakdown = json.loads(data).get("spans")
                        except (json.JSONDecodeError, AttributeError):
                            breakdown = None
                except (http.client.HTTPException, OSError):
                    status = -1
                    conn.close()
                    conn = _Conn(host, port, timeout=timeout)
                ms = (time.perf_counter() - t0) * 1000.0
                with out_lock:
                    lat_ms.append(ms)
                    statuses.append(status)
                    ten = tenant_by_idx[i]
                    if ten is not None:
                        acc = by_tenant.setdefault(
                            ten, {"ms": [], "errors": 0})
                        acc["ms"].append(ms)
                        if status != 200:
                            acc["errors"] += 1
                    if models:
                        macc = by_model.setdefault(
                            model_by_idx[i], {"lat": [], "errors": 0})
                        macc["lat"].append((i, ms))
                        if status != 200:
                            macc["errors"] += 1
                    if isinstance(breakdown, dict):
                        for k, v in breakdown.items():
                            if isinstance(v, (int, float)):
                                stage_ms.setdefault(k, []).append(
                                    float(v))
        finally:
            conn.close()

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(concurrency)]
    t_start[0] = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start[0]
    for c in held:          # idle holders release only after the run
        try:
            c.close()
        except Exception:
            pass

    lat = np.asarray(lat_ms, np.float64)
    ok = sum(1 for s in statuses if s == 200)
    errors = len(statuses) - ok
    p50, p95, p99 = (np.percentile(lat, [50.0, 95.0, 99.0])
                     if lat.size else (float("nan"),) * 3)
    counts: dict = {}
    for s in statuses:
        counts[str(s)] = counts.get(str(s), 0) + 1
    # availability over ACCEPTED requests: a 429 is the server saying
    # "not now" — explicit backpressure, not a failure; everything else
    # non-200 (504s, 5xx, connection drops) counts against it.
    accepted = len(statuses) - counts.get("429", 0)
    span_row: dict = {}
    if spans and stage_ms:
        # server-side stage percentiles — WHERE the latency lives
        # ("compute" = the device_dispatch stage: pool dispatch through
        # the engine pass; docs/OBSERVABILITY.md "Spans")
        table = {}
        for k, vals in sorted(stage_ms.items()):
            if k in ("total_ms", "unattributed_ms"):
                continue
            p50s, p99s = np.percentile(np.asarray(vals, np.float64),
                                       [50.0, 99.0])
            table[k] = {"p50_ms": round(float(p50s), 3),
                        "p99_ms": round(float(p99s), 3)}
        span_row = {
            "span_requests": len(stage_ms.get("total_ms", ())),
            "span_p99_ms": table,
            "queue_wait_p99_ms": table.get(
                "queue_wait", {}).get("p99_ms"),
            "compute_p99_ms": table.get(
                "device_dispatch", {}).get("p99_ms"),
        }
    tenant_row: dict = {}
    if tenants >= 1:
        per_tenant = {}
        others: List[float] = []
        for ten, acc in sorted(by_tenant.items()):
            tl = np.asarray(acc["ms"], np.float64)
            tp50, tp99 = (np.percentile(tl, [50.0, 99.0])
                          if tl.size else (float("nan"),) * 2)
            per_tenant[ten] = {
                "requests": int(tl.size),
                "errors": int(acc["errors"]),
                "p50_ms": round(float(tp50), 3),
                "p99_ms": round(float(tp99), 3)}
            if ten != "t0":
                others.extend(acc["ms"])
        tenant_row = {
            "tenants": int(tenants),
            "hot_tenant_skew": round(float(hot_tenant_skew), 4),
            "tenant_rows": per_tenant,
        }
        if hot_tenant_skew > 0.0 and tenants > 1:
            hot = per_tenant.get("t0") or {}
            op99 = (np.percentile(np.asarray(others, np.float64),
                                  99.0)
                    if others else float("nan"))
            tenant_row["hot_tenant"] = "t0"
            tenant_row["hot_p99_ms"] = hot.get("p99_ms")
            tenant_row["others_p99_ms"] = round(float(op99), 3)
    model_row: dict = {}
    if models:
        per_model = {}
        firsts: List[float] = []
        for name, macc in sorted(by_model.items()):
            pairs = sorted(macc["lat"])        # by request index
            ml = np.asarray([ms for _, ms in pairs], np.float64)
            mp50, mp99 = (np.percentile(ml, [50.0, 99.0])
                          if ml.size else (float("nan"),) * 2)
            # latency of the model's FIRST request (lowest request
            # index — deterministic even though workers race): the
            # cold-start sample, a cache fault if the model was not
            # resident when the run began
            first_ms = pairs[0][1] if pairs else float("nan")
            firsts.append(first_ms)
            per_model[name] = {
                "requests": int(ml.size),
                "errors": int(macc["errors"]),
                "p50_ms": round(float(mp50), 3),
                "p99_ms": round(float(mp99), 3),
                "first_ms": round(float(first_ms), 3)}
        cold_p99 = (np.percentile(np.asarray(firsts, np.float64), 99.0)
                    if firsts else float("nan"))
        model_row = {
            "models": len(models),
            "model_skew": round(float(model_skew), 4),
            "model_rows": per_model,
            "cold_start_p99_ms": round(float(cold_p99), 3),
        }
        if model_skew > 0.0 and len(models) > 1:
            model_row["hot_model"] = models[0]
    return {
        "mode": mode,
        "requests": requests,
        "batch": batch,
        "concurrency": concurrency,
        "wall_s": round(wall, 4),
        "throughput_rps": round(ok / wall, 2) if wall > 0 else 0.0,
        "examples_per_sec": round(ok * batch / wall, 2) if wall > 0
        else 0.0,
        "p50_ms": round(float(p50), 3),
        "p95_ms": round(float(p95), 3),
        "p99_ms": round(float(p99), 3),
        "errors": errors,
        "status_counts": {k: counts[k] for k in sorted(counts)},
        "accepted": accepted,
        "availability_pct": (round(100.0 * ok / accepted, 3)
                             if accepted else None),
        **({"target_rps": rps} if mode == "open" else {}),
        **({"open_connections": len(held)} if connections else {}),
        **span_row,
        **tenant_row,
        **model_row,
    }


def fetch_metrics(url: str, timeout: float = 10.0) -> dict:
    """GET /metricsz — the chaos report reads the server-side
    robustness counters (ejections, rebuilds, hedges, sheds) before
    and after the run."""
    host, port = _host_port(url)
    conn = _Conn(host, port, timeout=timeout)
    try:
        conn.request("GET", "/metricsz")
        resp = conn.getresponse()
        body = json.loads(resp.read() or b"{}")
    finally:
        conn.close()
    if resp.status != 200:
        raise RuntimeError(f"GET /metricsz -> {resp.status}: {body}")
    return body


#: robustness counters the chaos row deltas out of /metricsz
CHAOS_COUNTERS = ("ejections", "rebuilds", "hedges_fired", "hedges_won",
                  "redispatches", "deadline_504", "shed_proba",
                  "shed_sibling", "expired", "rejected")


def run_saturate(url: str, rows: np.ndarray, *,
                 model: str = "default", p99_target_ms: float = 50.0,
                 start_rps: float = 25.0, rps_factor: float = 2.0,
                 max_steps: int = 8, step_requests: int = 100,
                 batch: int = 1, concurrency: int = 16,
                 want: Sequence[str] = ("labels",),
                 timeout: float = 30.0,
                 trace: Optional[str] = None,
                 connections: int = 0) -> dict:
    """Drive-to-saturation: step open-loop RPS by ``rps_factor`` until
    p99 exceeds the target (or errors appear), and report ONE SLO row —
    the max sustained throughput at p99 < target, with availability.
    The open loop is the honest probe here: a closed loop slows its own
    arrivals under overload and never finds the knee.

    ``trace`` is the provenance pointer the row carries (the serving
    process's ``--trace-out`` artifact or an archived copy) — the same
    field burst-runner rows carry, so an SLO row is ledger- and
    ``compare``-traceable like a training row. A set ``trace`` also
    turns on the per-request span breakdown (``spans``), so each RPS
    step says WHICH stage (queue wait vs device compute) hit the
    knee."""
    steps = []
    best = None
    rps = float(start_rps)
    spans = trace is not None
    achieved_conns = None
    for _ in range(int(max_steps)):
        r = run_loadgen(url, rows, model=model, requests=step_requests,
                        batch=batch, concurrency=concurrency,
                        mode="open", rps=rps, want=want,
                        timeout=timeout, spans=spans,
                        connections=connections)
        if connections:
            achieved_conns = r.get("open_connections")
        met = (r["errors"] == 0
               and np.isfinite(r["p99_ms"])
               and r["p99_ms"] <= p99_target_ms)
        steps.append({"rps": rps, "p99_ms": r["p99_ms"],
                      "throughput_rps": r["throughput_rps"],
                      "availability_pct": r["availability_pct"],
                      "errors": r["errors"], "slo_met": met,
                      **({"queue_wait_p99_ms": r.get("queue_wait_p99_ms"),
                          "compute_p99_ms": r.get("compute_p99_ms")}
                         if spans else {})})
        if not met:
            break
        best = (rps, r)
        rps *= float(rps_factor)
    row = {
        "metric": "serving_slo_max_rps",
        "unit": "req/s",
        "p99_target_ms": float(p99_target_ms),
        "steps": steps,
        "trace": trace,
        **({"open_connections": achieved_conns} if connections else {}),
    }
    if best is None:
        row.update(value=0.0, slo_met=False, availability_pct=None)
    else:
        srps, r = best
        row.update(value=r["throughput_rps"], slo_met=True,
                   sustained_rps=srps, p99_ms=r["p99_ms"],
                   availability_pct=r["availability_pct"])
        if spans:
            row.update(queue_wait_p99_ms=r.get("queue_wait_p99_ms"),
                       compute_p99_ms=r.get("compute_p99_ms"),
                       span_p99_ms=r.get("span_p99_ms"))
    return row


def loadgen_row(url: str, rows: np.ndarray, *, model: str = "default",
                requests: int = 200, batch: int = 1,
                concurrency: int = 8, mode: str = "closed",
                rps: float = 100.0, want: Sequence[str] = ("labels",),
                timeout: float = 30.0, chaos: bool = False,
                compare_sequential: bool = True,
                trace: Optional[str] = None, tenants: int = 0,
                hot_tenant_skew: float = 0.0,
                models: Sequence[str] = (),
                model_skew: float = 0.0,
                connections: int = 0) -> dict:
    """The one-line result row ``dpsvm loadgen`` prints: the main
    measurement, plus (by default) the batch-1 single-worker sequential
    baseline and the coalescing speedup over it.

    ``chaos=True`` is the chaos-drill report: the fault itself is
    armed server-side (``DPSVM_FAULT_SERVE_*`` env on the serve
    process — it fires mid-run, at the configured request count) and
    the row additionally carries the availability of accepted requests
    plus the delta of the server's robustness counters (ejections,
    rebuilds, hedges, sheds) across the run, read from /metricsz.

    A set ``trace`` turns on the per-request span breakdown: every
    request carries ``X-Trace-Spans`` (the serving side records its
    span tree into --trace-out AND returns the stage milliseconds),
    and the row gains ``queue_wait_p99_ms`` / ``compute_p99_ms`` +
    the full ``span_p99_ms`` table."""
    before = fetch_metrics(url, timeout=timeout) if chaos else None
    main = run_loadgen(url, rows, model=model, requests=requests,
                       batch=batch, concurrency=concurrency, mode=mode,
                       rps=rps, want=want, timeout=timeout,
                       spans=trace is not None, tenants=tenants,
                       hot_tenant_skew=hot_tenant_skew,
                       models=models, model_skew=model_skew,
                       connections=connections)
    row = {
        "metric": "serving_examples_per_sec",
        "value": main["examples_per_sec"],
        "unit": "ex/s",
        # provenance pointer (burst-runner row parity): the serving
        # trace this measurement ran against, when one was archived
        "trace": trace,
        **main,
    }
    if chaos:
        after = fetch_metrics(url, timeout=timeout)
        row["chaos"] = {
            k: int(after.get(k, 0)) - int(before.get(k, 0))
            for k in CHAOS_COUNTERS}
        row["chaos"]["stray_compiles"] = int(
            after.get("stray_compiles", 0))
        row["replica_states"] = [
            r.get("state")
            for m in after.get("models", {}).values()
            for r in m.get("pool", {}).get("replicas", [])]
    if compare_sequential:
        seq = run_loadgen(url, rows, model=model, requests=requests,
                          batch=1, concurrency=1, mode="closed",
                          want=want, timeout=timeout)
        row["seq1_examples_per_sec"] = seq["examples_per_sec"]
        row["seq1_p50_ms"] = seq["p50_ms"]
        row["seq1_errors"] = seq["errors"]
        row["coalesce_speedup"] = (
            round(main["examples_per_sec"] / seq["examples_per_sec"], 3)
            if seq["examples_per_sec"] > 0 else None)
    return row

"""Per-tenant weighted-fair admission queue: deficit round-robin.

The micro-batcher's single FIFO is fair only when tenants behave: one
hot tenant that fires faster than the service rate fills the queue and
every other tenant's requests age behind its backlog — exactly the
noisy-neighbour shape the ``tenant-fair-share`` watchtower rule exists
to catch (observability/slo.py). This module puts a scheduling
decision, not just a detector, in front of the batcher: requests are
queued per tenant LANE and served in deficit-round-robin (DRR) order
(Shreedhar & Varghese '95), so the service RATIO between backlogged
lanes follows their configured weights regardless of arrival ratio.

Semantics (docs/SERVING.md "Front door"):

* One lane per RESOLVED tenant label — the same vocabulary the cost
  ledger bills (``metrics.TenantLabelBudget``): the long tail folds
  into the ``other`` lane, so lane cardinality is bounded by the
  tenant budget and an attacker minting labels shares ONE lane.
* The service unit is ROWS (a 64-row request costs 64× a 1-row one —
  weighting requests would let a tenant cheat with huge batches).
* Each backlogged lane in turn earns ``quantum * weight`` rows of
  deficit and dequeues whole requests while its deficit covers them;
  leftover deficit carries to its next turn, so a lane whose requests
  exceed one quantum still gets its share over multiple rounds. An
  emptied lane forfeits its deficit (classic DRR — credit never
  accumulates while idle).
* Admission is bounded PER LANE (``lane_capacity`` rows): a hot
  tenant's overflow rejects the hot tenant (HTTP 429), never a cold
  one — per-tenant backpressure instead of the shared-FIFO cliff.

Starvation-freedom falls out of the round-robin: a backlogged weight-1
lane is visited once per round, and a round serves at most
``quantum * sum(weights of backlogged lanes)`` rows, so the oldest
request in any lane waits a bounded number of service rows —
``tests/test_frontdoor.py`` pins both properties deterministically.

Stdlib-only and event-loop-friendly: O(1) push, O(lanes) worst-case
pop, no threads of its own. A small lock makes push/pop/stats safe
from any thread (the async front door drives it from the loop; metric
collectors read stats from wherever the scrape lands).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

#: default deficit earned per turn per unit weight, in rows. One
#: batcher-bucket's worth is the natural grain: a lane's turn admits
#: about one coalesced device pass of its traffic.
DEFAULT_QUANTUM_ROWS = 32


class LaneFullError(RuntimeError):
    """Per-tenant admission reject: THIS tenant's lane is at capacity.
    The HTTP layer turns it into 429 for the hot tenant while other
    lanes keep admitting."""


class _Lane:
    __slots__ = ("name", "weight", "deficit", "q", "rows", "pushed",
                 "served", "rejected")

    def __init__(self, name: str, weight: float):
        self.name = name
        self.weight = float(weight)
        self.deficit = 0.0
        self.q: deque = deque()          # (rows, t_push, item)
        self.rows = 0                    # rows currently queued
        self.pushed = 0                  # requests admitted, lifetime
        self.served = 0                  # requests dequeued, lifetime
        self.rejected = 0                # requests refused, lifetime


class FairQueue:
    """Deficit-round-robin queue over tenant lanes (module docstring).

    ``weights`` maps tenant label -> weight (default 1.0 for unlisted
    tenants, including ``other``). Weights must be > 0; they are fixed
    at construction — the serving CLI builds one queue per process from
    ``--tenant-weight`` flags.
    """

    def __init__(self, *, weights: Optional[Dict[str, float]] = None,
                 default_weight: float = 1.0,
                 lane_capacity: int = 4096,
                 quantum: int = DEFAULT_QUANTUM_ROWS):
        if lane_capacity < 1:
            raise ValueError(f"lane_capacity must be >= 1, got "
                             f"{lane_capacity}")
        if quantum < 1:
            raise ValueError(f"quantum must be >= 1, got {quantum}")
        if not (default_weight > 0):
            raise ValueError(f"default_weight must be > 0, got "
                             f"{default_weight}")
        for k, w in (weights or {}).items():
            if not (float(w) > 0):
                raise ValueError(f"tenant weight must be > 0, got "
                                 f"{k}={w}")
        self.lane_capacity = int(lane_capacity)
        self.quantum = int(quantum)
        self.default_weight = float(default_weight)
        self._weights = {k: float(v) for k, v in (weights or {}).items()}
        self._lanes: Dict[str, _Lane] = {}
        # round-robin order over BACKLOGGED lanes: lanes enter at the
        # tail when they go non-empty and leave when drained
        self._active: deque = deque()
        # the lane (if any) that already earned its quantum for the
        # CURRENT front-of-round turn — a turn earns exactly once, so
        # a lane whose deficit runs dry yields instead of re-earning
        # (re-earning would serve the front lane to exhaustion and
        # void the weight ratio entirely)
        self._earned: Optional[_Lane] = None
        self._rows = 0
        self._lock = threading.Lock()

    # -- admission ----------------------------------------------------

    def weight_of(self, tenant: str) -> float:
        return self._weights.get(tenant, self.default_weight)

    def push(self, tenant: str, item, rows: int) -> None:
        """Admit one request (``rows`` service units) to the tenant's
        lane. Raises ``LaneFullError`` when THIS lane is at capacity —
        a fast per-tenant reject that leaves every other lane
        untouched. A single request larger than the lane capacity is
        refused outright (it could never be admitted)."""
        rows = int(rows)
        if rows < 1:
            raise ValueError(f"rows must be >= 1, got {rows}")
        with self._lock:
            lane = self._lanes.get(tenant)
            if lane is None:
                lane = self._lanes[tenant] = _Lane(
                    tenant, self.weight_of(tenant))
            if lane.rows + rows > self.lane_capacity:
                lane.rejected += 1
                raise LaneFullError(
                    f"tenant {tenant!r} queue full ({lane.rows} rows "
                    f"waiting, lane capacity {self.lane_capacity}) — "
                    "retry with backoff")
            was_empty = not lane.q
            lane.q.append((rows, time.perf_counter(), item))
            lane.rows += rows
            lane.pushed += 1
            self._rows += rows
            if was_empty:
                lane.deficit = 0.0       # idle credit never accumulates
                self._active.append(lane)

    # -- service ------------------------------------------------------

    def pop(self):
        """Dequeue the next request in DRR order, or None when empty.
        Returns ``(tenant, item, rows)``."""
        with self._lock:
            while self._active:
                lane = self._active[0]
                if not lane.q:           # drained on a previous pop
                    lane.deficit = 0.0
                    self._active.popleft()
                    self._earned = None
                    continue
                if self._earned is not lane:
                    # lane's turn begins: earn ONE quantum. Earning
                    # again before the turn ends would serve the front
                    # lane to exhaustion regardless of weights.
                    lane.deficit += self.quantum * lane.weight
                    self._earned = lane
                rows = lane.q[0][0]
                if lane.deficit < rows:
                    # deficit exhausted (or an oversized head): turn
                    # over, leftover deficit carries to the next round
                    # — DRR's carryover, no starvation of big requests
                    self._active.rotate(-1)
                    self._earned = None
                    continue
                rows, _t, item = lane.q.popleft()
                lane.deficit -= rows
                lane.rows -= rows
                lane.served += 1
                self._rows -= rows
                if not lane.q:
                    lane.deficit = 0.0
                    self._active.popleft()
                    self._earned = None
                return lane.name, item, rows
            return None

    def drop(self, predicate) -> int:
        """Remove queued items for which ``predicate(item)`` is true
        (cancelled/expired requests); returns rows removed. O(total
        queued) — called on the drain path, not per request."""
        removed = 0
        with self._lock:
            for lane in self._lanes.values():
                if not lane.q:
                    continue
                keep = deque()
                for rows, t, item in lane.q:
                    if predicate(item):
                        lane.rows -= rows
                        self._rows -= rows
                        removed += rows
                    else:
                        keep.append((rows, t, item))
                lane.q = keep
                if not keep and lane in self._active:
                    lane.deficit = 0.0
                    self._active.remove(lane)
                    if self._earned is lane:
                        self._earned = None
        return removed

    # -- facts --------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return self._rows

    @property
    def rows_queued(self) -> int:
        with self._lock:
            return self._rows

    def oldest_age_s(self, tenant: Optional[str] = None) -> float:
        """Age (seconds) of the oldest queued request — in one lane, or
        across all lanes. 0.0 when empty. The starvation-freedom bound
        the tests pin is over this number."""
        now = time.perf_counter()
        with self._lock:
            lanes = ([self._lanes[tenant]]
                     if tenant is not None and tenant in self._lanes
                     else self._lanes.values())
            heads = [lane.q[0][1] for lane in lanes if lane.q]
        return (now - min(heads)) if heads else 0.0

    def depths(self) -> Dict[str, int]:
        """rows queued per lane (only lanes that ever admitted) — the
        /metricsz queue-lane gauges and the doctor report."""
        with self._lock:
            return {name: lane.rows
                    for name, lane in sorted(self._lanes.items())}

    def stats(self) -> dict:
        with self._lock:
            return {
                "rows_queued": self._rows,
                "quantum_rows": self.quantum,
                "lane_capacity_rows": self.lane_capacity,
                "lanes": {
                    name: {"weight": lane.weight, "rows": lane.rows,
                           "depth": len(lane.q),
                           "pushed": lane.pushed,
                           "served": lane.served,
                           "rejected": lane.rejected}
                    for name, lane in sorted(self._lanes.items())},
            }


def parse_tenant_weights(specs) -> Dict[str, float]:
    """``--tenant-weight NAME=W`` flag values -> {name: weight}.
    Raises ValueError with a usable message on malformed specs."""
    out: Dict[str, float] = {}
    for spec in specs or ():
        name, sep, w = str(spec).partition("=")
        if not sep or not name:
            raise ValueError(f"--tenant-weight needs NAME=WEIGHT, got "
                             f"{spec!r}")
        try:
            weight = float(w)
        except ValueError:
            raise ValueError(f"--tenant-weight {name}: weight must be "
                             f"a number, got {w!r}")
        if not (weight > 0):
            raise ValueError(f"--tenant-weight {name}: weight must be "
                             f"> 0, got {weight}")
        out[name] = weight
    return out


def drr_schedule(pushes: List[Tuple[str, int]],
                 weights: Dict[str, float],
                 quantum: int = DEFAULT_QUANTUM_ROWS
                 ) -> List[Tuple[str, int]]:
    """The deterministic service order of a STAGED queue: push every
    ``(tenant, rows)`` first, then pop to exhaustion. Pure function of
    its inputs — what the property tests (and the selfcheck's
    fair-queue gate) assert the 8:1 ratio on."""
    fq = FairQueue(weights=weights, quantum=quantum,
                   lane_capacity=1 << 30)
    for i, (tenant, rows) in enumerate(pushes):
        fq.push(tenant, i, rows)
    order: List[Tuple[str, int]] = []
    while True:
        got = fq.pop()
        if got is None:
            return order
        order.append((got[0], got[2]))

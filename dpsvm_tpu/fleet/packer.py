"""Same-spec batched serving: N resident models, one decision program.

A fleet of tenant models is not N unrelated programs. Tenants
overwhelmingly train with the same recipe — same kernel family, same
gamma, same feature width — so their models differ only in WHICH
support vectors they hold, not in the program that evaluates them.
``serving/engine.SegmentPack`` already proves the collapsed shape for
one multiclass model's OvO pairs: concatenate every member's SVs,
evaluate one ``(m, d) @ (d, S_total)`` kernel pass, and segment-sum
per member. This module generalizes that pack to arbitrary same-spec
model GROUPS, so the fleet's cold path costs one warmed ladder per
spec instead of one per model:

* **one compile budget per spec** — the group's bucket ladder is
  warmed once; a request for ANY member runs the shared program at
  zero steady-state retraces (the engine's guarantee, inherited —
  same ``compilewatch`` instrumentation, same selfcheck discipline);
* **one dispatch per request** — a member request pads into a ladder
  bucket and reads its own column of the ``(m, N)`` decision matrix.
  The extra columns are the price of sharing, and they are cheap: the
  kernel pass is dominated by the shared X stream, exactly the
  argument ``solver/batched_ovo.py`` makes for batched training;
* **membership changes repack** — admitting or evicting a member
  changes ``num_segments`` (a static arg), so the next dispatch
  retraces once. Repacks are counted (``repacks`` in ``stats()``) and
  the fleet selfcheck pins that a churn-free steady state stays at
  zero.

Parity: a member's column is evaluated by the exact jitted program
(``models/svm._pairwise_decisions_jit``) the multiclass engine serves
with, at the same ``precision="highest"`` default — bitwise equal to
a dedicated ``PredictionEngine`` for that model (pinned in
tests/test_modelfleet.py).

No jax at module import; the pack builds lazily on first dispatch.
"""

from __future__ import annotations

import threading
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np


class GroupSpec(NamedTuple):
    """The identity a model must share to join a packed group: the
    static/traced knobs of the segment-sum program plus the feature
    width. Two models with equal GroupSpec compile to the same XLA
    program and can concatenate."""
    kernel: str
    gamma: float
    coef0: float
    degree: int
    num_attributes: int

    @classmethod
    def of(cls, model) -> "GroupSpec":
        return cls(kernel=str(model.kernel), gamma=float(model.gamma),
                   coef0=float(model.coef0), degree=int(model.degree),
                   num_attributes=int(model.num_attributes))


def packable(model) -> bool:
    """Whether ``model`` can join a same-spec group: a binary SV model
    with feature rows to concatenate. Approx models have no SV set,
    precomputed kernels no feature rows, multiclass containers pack
    their own pairs already (``engine._build_mc_batched``)."""
    if getattr(model, "is_approx", False):
        return False
    if getattr(model, "models", None) is not None:   # multiclass dir
        return False
    return getattr(model, "kernel", None) not in (None, "precomputed")


class PackedGroup:
    """One spec's members behind one SegmentPack + bucket ladder.

    Members are (name, model) in admission order; ``decisions_for``
    streams a request through the ladder exactly like
    ``PredictionEngine._decisions`` (full top-rung passes + one padded
    remainder bucket) and slices the member's column. The pack is
    rebuilt lazily after a membership change (``dirty``), and the new
    pack's ladder is re-warmed inside the rebuild so steady-state
    traffic never observes the retrace mid-request."""

    def __init__(self, spec: GroupSpec, *, max_batch: int = 64,
                 include_b: bool = True, precision: str = "highest",
                 warmup: bool = True):
        from dpsvm_tpu.serving.engine import bucket_ladder

        self.spec = spec
        self.max_batch = int(max_batch)
        self.buckets = bucket_ladder(self.max_batch)
        self.include_b = bool(include_b)
        self.precision = str(precision)
        self.warmup = bool(warmup)
        self._lock = threading.Lock()
        self._names: List[str] = []
        self._models: List = []
        self._col: Dict[str, int] = {}
        self._pack = None                  # SegmentPack, built lazily
        self.repacks = 0
        self.dispatches = 0

    # -- membership ---------------------------------------------------

    def add(self, name: str, model) -> None:
        with self._lock:
            if name in self._col:
                raise ValueError(f"model {name!r} already packed")
            if GroupSpec.of(model) != self.spec:
                raise ValueError(f"model {name!r} spec "
                                 f"{GroupSpec.of(model)} != group "
                                 f"spec {self.spec}")
            self._names.append(name)
            self._models.append(model)
            self._col[name] = len(self._names) - 1
            self._pack = None              # membership change: repack

    def remove(self, name: str) -> None:
        with self._lock:
            i = self._col.pop(name, None)
            if i is None:
                raise KeyError(f"model {name!r} not in group")
            del self._names[i]
            del self._models[i]
            self._col = {n: j for j, n in enumerate(self._names)}
            self._pack = None

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._col

    def __len__(self) -> int:
        with self._lock:
            return len(self._names)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._names)

    # -- evaluation ---------------------------------------------------

    def _ensure_pack(self):
        """Build (or rebuild) the SegmentPack under the lock; warm the
        ladder so the retrace is paid HERE, at the membership change,
        never spread across later member requests."""
        from dpsvm_tpu.serving.engine import SegmentPack

        if self._pack is not None:
            return self._pack
        if not self._models:
            raise RuntimeError("packed group is empty")
        self._pack = SegmentPack(
            list(self._models),
            tag=f"fleet[{self.spec.kernel}/g{self.spec.gamma:g}"
                f"/d{self.spec.num_attributes}]",
            include_b=self.include_b,
            precision_name=self.precision.upper())
        self.repacks += 1
        if self.warmup:
            d = self.spec.num_attributes
            for bucket in self.buckets:
                self._pack.decide(np.zeros((bucket, d), np.float32))
        return self._pack

    def _bucket_for(self, m: int) -> int:
        for b in self.buckets:
            if b >= m:
                return b
        return self.max_batch

    def decisions_all(self, x: np.ndarray) -> np.ndarray:
        """(m, N) decision matrix for every member at once — the
        fleet's offline sweep shape (score N tenants' models on one
        batch in one dispatch per ladder pass)."""
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[1] != self.spec.num_attributes:
            raise ValueError(
                f"instances must be (m, {self.spec.num_attributes}), "
                f"got shape {x.shape}")
        m = x.shape[0]
        out = None
        lo = 0
        with self._lock:
            pack = self._ensure_pack()
            while lo < m:
                take = min(self.max_batch, m - lo)
                bucket = self._bucket_for(take)
                block = np.zeros((bucket, x.shape[1]), np.float32)
                block[:take] = x[lo:lo + take]
                vals = pack.decide(block)
                self.dispatches += 1
                if out is None:
                    out = np.empty((m, vals.shape[1]), vals.dtype)
                out[lo:lo + take] = vals[:take]
                lo += take
        return out

    def decisions_for(self, name: str, x: np.ndarray) -> np.ndarray:
        """(m,) decision values for one member — a per-model request
        through the shared program."""
        with self._lock:
            i = self._col.get(name)
        if i is None:
            raise KeyError(f"model {name!r} not in group")
        return self.decisions_all(x)[:, i]

    def stats(self) -> dict:
        with self._lock:
            return {"members": len(self._names),
                    "n_sv": int(sum(int(m.n_sv) for m in self._models)),
                    "repacks": self.repacks,
                    "dispatches": self.dispatches,
                    "packed": self._pack is not None}


class GroupPacker:
    """The fleet's spec -> PackedGroup router: every packable resident
    model lands in exactly one group keyed by its GroupSpec. The model
    cache (fleet/modelcache.py) owns membership (admit -> ``add``,
    evict -> ``remove``); this class only keeps the grouping honest
    and answers 'which shared program serves this name'."""

    def __init__(self, *, max_batch: int = 64, include_b: bool = True,
                 precision: str = "highest", warmup: bool = True):
        self.max_batch = int(max_batch)
        self.include_b = bool(include_b)
        self.precision = str(precision)
        self.warmup = bool(warmup)
        self._lock = threading.Lock()
        self._groups: Dict[GroupSpec, PackedGroup] = {}
        self._group_of: Dict[str, GroupSpec] = {}

    def add(self, name: str, model) -> Optional[PackedGroup]:
        """Pack ``name`` into its spec group (created on first member).
        Returns the group, or None for an unpackable model (the caller
        keeps a dedicated engine instead)."""
        if not packable(model):
            return None
        spec = GroupSpec.of(model)
        with self._lock:
            g = self._groups.get(spec)
            if g is None:
                g = PackedGroup(spec, max_batch=self.max_batch,
                                include_b=self.include_b,
                                precision=self.precision,
                                warmup=self.warmup)
                self._groups[spec] = g
            self._group_of[name] = spec
        g.add(name, model)
        return g

    def remove(self, name: str) -> bool:
        with self._lock:
            spec = self._group_of.pop(name, None)
            if spec is None:
                return False
            g = self._groups[spec]
        g.remove(name)
        with self._lock:
            if len(g) == 0 and self._groups.get(spec) is g:
                del self._groups[spec]
        return True

    def group_for(self, name: str) -> Optional[PackedGroup]:
        with self._lock:
            spec = self._group_of.get(name)
            return self._groups.get(spec) if spec is not None else None

    def groups(self) -> List[PackedGroup]:
        with self._lock:
            return list(self._groups.values())

    def stats(self) -> dict:
        gs = self.groups()
        return {"groups": len(gs),
                "packed_models": int(sum(len(g) for g in gs)),
                "repacks": int(sum(g.repacks for g in gs)),
                "dispatches": int(sum(g.dispatches for g in gs))}

from dpsvm_tpu.fleet import main

raise SystemExit(main())

"""HBM-budgeted model cache: thousands of tenants, a fixed buffer pool.

The reference CUDA trainer's ``cache.cu`` keeps hot kernel ROWS in a
fixed slab and pages cold rows out under LRU. A model fleet has the
same economics one level up: device memory holds a fixed number of
models' SV/feature buffers, and the tenant popularity distribution is
heavy-tailed — so the cache unit here is the MODEL, and the admission
discipline is borrowed wholesale from the per-tenant label budget
(``observability/metrics.TenantLabelBudget``):

* **second-touch admission** — while the budget has free slots a
  first touch hydrates immediately (an empty cache should warm fast);
  once it is FULL, the first request for a cold model is served from a
  throwaway engine (a *transient*: correct, but cold) and only a
  second touch hydrates it — evicting the LRU resident. A one-shot
  scan over 10k models therefore costs 10k transients and ZERO
  evictions — the resident working set never churns (pinned in
  tests/test_modelfleet.py);
* **LRU-of-activity eviction** — admission beyond the budget evicts
  the least-recently-touched resident; the budget ledger's monotone
  tick (no wall clock) keeps the resident set deterministic for the
  selfcheck;
* **fault/evict accounting** — every hydration is a ``model_fault``
  (with its measured ``cold_start_ms``), every page-out a
  ``model_evict``; both flow through ``on_event`` into the serving
  trace and the ``dpsvm_fleet_model_*_total`` counters the watchtower's
  ``model-cache-thrash`` rule watches (observability/slo.py).

Resident packable models (binary SV models) live in same-spec
``PackedGroup``s (fleet/packer.py): their device footprint is their
segment of the shared concatenated-SV program, so N resident tenants
of one spec cost one warmed ladder and one dispatch per request.
Unpackable residents (multiclass dirs, approx/precomputed models,
in-memory registrations) hold a dedicated warmed ``PredictionEngine``.

Conservation law (pinned in tests): every ``infer`` is exactly one of
hit / fault / transient, so ``touches == hits + faults + transients``
and ``evictions <= faults`` always.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from dpsvm_tpu.fleet.packer import GroupPacker, packable
from dpsvm_tpu.observability.metrics import TenantLabelBudget


class _Resident:
    """One hydrated model: either a packed-group member (raw model +
    optional Platt sidecar) or a dedicated engine for unpackable
    kinds."""
    __slots__ = ("model", "platt", "engine", "cold_start_ms")

    def __init__(self, model=None, platt=None, engine=None,
                 cold_start_ms=0.0):
        self.model = model
        self.platt = platt
        self.engine = engine
        self.cold_start_ms = float(cold_start_ms)


class ModelCache:
    """Budgeted residency manager over a ``ModelRegistry``.

    The registry holds the manifest of EVERY model (registered lazy —
    serving/registry.py); the cache decides which of them hold device
    buffers right now. ``infer(name, x, want)`` is the single entry
    point: it resolves residency, hydrates or serves transiently as
    the admission policy dictates, and answers from the packed group
    (one shared dispatch) or the resident/transient engine.
    """

    def __init__(self, registry, *, budget: int, max_batch: int = 64,
                 precision: str = "highest", warmup: bool = True,
                 on_event: Optional[Callable[..., None]] = None):
        if budget < 1:
            raise ValueError(f"model cache budget must be >= 1, "
                             f"got {budget}")
        self.registry = registry
        self.budget = int(budget)
        self.max_batch = int(max_batch)
        self.precision = str(precision)
        self.warmup = bool(warmup)
        self.on_event = on_event
        self._lock = threading.RLock()
        # The admission policy IS the tenant label budget, applied to
        # model names: same second-touch + LRU-of-activity ledger,
        # same deterministic tick. on_evict fires inside resolve() —
        # the RLock makes the page-out re-entrant from _admit.
        self._ledger = TenantLabelBudget(self.budget,
                                         on_evict=self._page_out)
        self._packer = GroupPacker(max_batch=self.max_batch,
                                   precision=self.precision,
                                   warmup=self.warmup)
        self._resident: Dict[str, _Resident] = {}
        self.touches = 0
        self.hits = 0
        self.faults = 0
        self.transients = 0
        self.evictions = 0
        self.cold_start_ms: List[float] = []

    # -- events -------------------------------------------------------

    def _emit(self, event: str, **extra) -> None:
        if self.on_event is not None:
            self.on_event(event, **extra)

    # -- residency ----------------------------------------------------

    def resident_names(self) -> List[str]:
        """Resident models, most-recently-touched first (the ledger's
        activity order)."""
        with self._lock:
            return [n for n in self._ledger.residents()
                    if n in self._resident]

    def is_resident(self, name: str) -> bool:
        with self._lock:
            return name in self._resident

    def _page_out(self, name: str) -> None:
        """Ledger eviction hook: free ``name``'s device buffers (its
        packed-group segment or its engine) but keep the registry
        entry — the model re-hydrates from its source on the next
        second touch."""
        with self._lock:
            res = self._resident.pop(name, None)
            if res is None:
                return
            self._packer.remove(name)
            self.evictions += 1
        self._emit("model_evict", model=name)

    def evict(self, name: str) -> bool:
        """Operator page-out (doctor/drills). Returns whether the
        model was resident."""
        with self._lock:
            was = name in self._resident
            self._page_out(name)
            return was

    def _hydrate(self, name: str) -> _Resident:
        """Load ``name`` from its registered source and give it device
        residency: packable binary SV models join their spec's
        PackedGroup (warmed ladder shared across the group), anything
        else gets a dedicated warmed engine. The measured wall time is
        the model's cold start."""
        t0 = time.perf_counter()
        source = self.registry.source(name)
        res = _Resident()
        if source is None or os.path.isdir(source):
            # in-memory registration or multiclass dir: the registry's
            # replica-build path already does the right load + warmup
            from dpsvm_tpu.serving.engine import PredictionEngine

            if source is None:
                res.engine = self.registry.build(name)
            else:
                res.engine = PredictionEngine.load(
                    source, name=name, max_batch=self.max_batch,
                    precision=self.precision, warmup=self.warmup)
        else:
            from dpsvm_tpu.models.io import load_model
            from dpsvm_tpu.serving.engine import (PredictionEngine,
                                                  _load_binary_platt)

            model = load_model(source)
            if packable(model):
                res.model = model
                res.platt = _load_binary_platt(source)
                self._packer.add(name, model)
                # warm the (possibly repacked) group now so the fault
                # pays the whole cold start, not the next request
                g = self._packer.group_for(name)
                if g is not None and self.warmup:
                    g.decisions_all(np.zeros(
                        (1, g.spec.num_attributes), np.float32))
            else:
                res.engine = PredictionEngine(
                    model, name=name, max_batch=self.max_batch,
                    precision=self.precision, warmup=self.warmup)
        res.cold_start_ms = (time.perf_counter() - t0) * 1e3
        self._resident[name] = res
        self.faults += 1
        self.cold_start_ms.append(res.cold_start_ms)
        return res

    def _transient_engine(self, name: str):
        """Serve a non-admitted touch from a throwaway engine: no
        warmup, no residency, dropped after the reply. Correctness is
        identical (same load path, same jitted programs); the cost is
        the cold dispatch — which is the POINT: one-shot churn pays
        its own price instead of evicting the working set."""
        from dpsvm_tpu.serving.engine import PredictionEngine

        source = self.registry.source(name)
        if source is None:
            return self.registry.build(name)
        return PredictionEngine.load(source, name=name,
                                     max_batch=self.max_batch,
                                     precision=self.precision,
                                     warmup=False)

    # -- serving ------------------------------------------------------

    def infer(self, name: str, x, want: Sequence[str] = ("labels",)) -> dict:
        """Serve one request for ``name``: hit (resident), fault
        (second touch — hydrate, then serve warm), or transient (first
        touch — throwaway engine). Raises KeyError for an unregistered
        name, ValueError for bad inputs (same contract as
        ``PredictionEngine.infer``)."""
        self.registry.source(name)          # KeyError for unknown names
        with self._lock:
            self.touches += 1
            resolved = self._ledger.resolve(name)
            res = self._resident.get(name)
            if res is not None:
                self.hits += 1
                return self._serve_resident(name, res, x, want)
            if resolved == name:
                # admitted (second touch): hydration fault — serve
                # under the lock so a concurrent evict can't unseat
                # the model between hydration and its first answer
                res = self._hydrate(name)
                out = self._serve_resident(name, res, x, want)
                cold_ms = res.cold_start_ms
            else:
                out = None
        if out is not None:
            self._emit("model_fault", model=name,
                       cold_start_ms=round(cold_ms, 3))
            return out
        # not admitted: transient serve outside the lock (slow path
        # must not block resident traffic)
        engine = self._transient_engine(name)
        with self._lock:
            self.transients += 1
        return engine.infer(x, want=want)

    def _serve_resident(self, name: str, res: _Resident, x, want) -> dict:
        if res.engine is not None:
            return res.engine.infer(x, want=want)
        from dpsvm_tpu.serving.batcher import KNOWN_OUTPUTS

        unknown = [w for w in want if w not in KNOWN_OUTPUTS]
        if unknown:
            raise ValueError(f"unknown outputs {unknown}; "
                             f"pick from {list(KNOWN_OUTPUTS)}")
        if "proba" in want and res.platt is None:
            raise ValueError(
                f"model {name!r} has no probability calibration — "
                "binary models need the .platt.json sidecar next to "
                "the model file")
        group = self._packer.group_for(name)
        if group is None:                    # pragma: no cover - guard
            raise RuntimeError(f"resident model {name!r} lost its "
                               "packed group")
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[1] != group.spec.num_attributes:
            # engines raise the same ValueError shape; the server maps
            # it to HTTP 400 on the cold path too
            raise ValueError(
                f"model {name!r} expects (n, "
                f"{group.spec.num_attributes}) features, got "
                f"{tuple(x.shape)}")
        dec = group.decisions_for(name, x)
        out: dict = {}
        if "decision" in want:
            out["decision"] = dec
        if "labels" in want:
            if getattr(res.model, "task", "svc") == "svr":
                out["labels"] = dec
            else:
                out["labels"] = np.where(dec < 0, -1, 1).astype(np.int32)
        if "proba" in want:
            from dpsvm_tpu.models.calibration import sigmoid_proba
            pa, pb = res.platt
            # packed groups serve include_b=True decisions, the form
            # the Platt sigmoid is defined on
            out["proba"] = sigmoid_proba(dec, pa, pb)
        return out

    def decisions_group(self, name: str, x) -> np.ndarray:
        """(m, N) decision matrix of the WHOLE spec group ``name``
        belongs to — the fleet sweep shape (score every same-spec
        resident on one batch in one dispatch per ladder pass)."""
        with self._lock:
            group = self._packer.group_for(name)
            if group is None:
                raise KeyError(f"model {name!r} is not resident in a "
                               "packed group")
        return group.decisions_all(x)

    # -- accounting ---------------------------------------------------

    def resident_bytes(self) -> int:
        """Estimated device bytes held by resident models: packed
        groups hold float32 SV rows + coefficients + segment ids +
        intercepts; engine residents are estimated from their SV
        count. The budget is enforced in MODELS (the ledger), this is
        the observability companion for the docs' budget math."""
        with self._lock:
            total = 0
            for g in self._packer.groups():
                s = g.stats()
                total += s["n_sv"] * (g.spec.num_attributes + 2) * 4
                total += s["members"] * 4
            for res in self._resident.values():
                if res.engine is not None:
                    d = int(res.engine.num_attributes)
                    total += int(res.engine.n_sv) * (d + 2) * 4
            return total

    def stats(self) -> dict:
        """Counters + ledger + packer state for /metricsz and the
        doctor probe. Conservation: touches == hits + faults +
        transients."""
        with self._lock:
            ledger = self._ledger.stats()
            return {
                "budget": self.budget,
                "resident": len(self._resident),
                "touches": self.touches,
                "hits": self.hits,
                "faults": self.faults,
                "transients": self.transients,
                "evictions": self.evictions,
                "ledger_overflow": ledger["overflow"],
                "resident_bytes_est": self.resident_bytes(),
                "cold_start_p99_ms": _p99(self.cold_start_ms),
                "packer": self._packer.stats(),
            }


def _p99(vals: List[float]) -> float:
    if not vals:
        return 0.0
    return float(np.percentile(np.asarray(vals, np.float64), 99.0))

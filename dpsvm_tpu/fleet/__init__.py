"""Model-fleet subsystem: thousands of tenant models, one process.

The ROADMAP's north star is millions of users — which means millions
of TENANTS, each with a small model, not one giant model. Until this
package the registry held a handful of always-resident engines and
every model was trained by hand. The fleet layer closes that gap
(docs/SERVING.md "Model fleet"):

* ``modelcache`` — ``ModelCache``: an HBM-budgeted model-granularity
                   generalization of the reference trainer's
                   ``cache.cu`` kernel-row LRU. Second-touch admission
                   + LRU-of-activity (the ``TenantLabelBudget``
                   discipline applied to model names) so one-shot
                   churn never evicts the working set; every hydration
                   is a ``model_fault`` with its measured cold start,
                   every page-out a ``model_evict``.
* ``packer``     — ``GroupPacker``/``PackedGroup``: resident models of
                   identical spec (kernel/γ/coef0/degree/width) share
                   ONE concatenated segment-sum decision program (the
                   engine's OvO collapse generalized — the same
                   ``SegmentPack``), so N same-spec tenants cost one
                   warmed bucket ladder and one dispatch per request,
                   zero steady-state retraces.
* ``grid``       — ``train_grid``: the production line. A whole C×γ
                   grid solved as mesh-partitioned batched sweep
                   programs (``solver/batched_ovo.train_c_sweep``),
                   held-out per-cell scores, cascade polish for the
                   winner, one trace, and atomic promotion through
                   ``ModelRegistry.promote_file``.

CLI: ``dpsvm grid`` (training), ``dpsvm serve --model-cache-budget``
(serving), ``dpsvm loadgen --models/--model-skew`` (drills).

CI gate: ``python -m dpsvm_tpu.fleet --selfcheck`` — registers 64
tiny models lazily under a cache budget of 8, churns them, and
asserts the properties the fleet design rests on: counter
conservation (touches == hits + faults + transients), a deterministic
resident set one-shot scans cannot evict, zero stray retraces across
steady-state packed-group traffic, packed decisions matching a fresh
dedicated engine load, and a schema-valid fault/evict trace. Wired
into tier-1 by ``tests/test_modelfleet.py``; the heavier 1000-model
``--drill`` is the ``fleet_cache_drill`` burst tag and lands the
``fleet_cold_start_p99_ms`` perf-ledger row.

Importing this package initializes no backend: the cache and packer
pull jax lazily, on first hydration/dispatch.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from dpsvm_tpu.fleet.modelcache import ModelCache
from dpsvm_tpu.fleet.packer import (GroupPacker, GroupSpec, PackedGroup,
                                    packable)

__all__ = [
    "ModelCache", "GroupPacker", "GroupSpec", "PackedGroup", "packable",
    "train_grid", "GridResult", "GridCell", "holdout_split",
    "sequential_grid_seconds", "promote_winner", "selfcheck",
    "fleet_cache_drill", "main",
]

_LAZY = {
    "train_grid": ("dpsvm_tpu.fleet.grid", "train_grid"),
    "GridResult": ("dpsvm_tpu.fleet.grid", "GridResult"),
    "GridCell": ("dpsvm_tpu.fleet.grid", "GridCell"),
    "holdout_split": ("dpsvm_tpu.fleet.grid", "holdout_split"),
    "sequential_grid_seconds": ("dpsvm_tpu.fleet.grid",
                                "sequential_grid_seconds"),
    "promote_winner": ("dpsvm_tpu.fleet.grid", "promote_winner"),
}


def __getattr__(name: str):
    """PEP 562 lazy re-exports (the serving package's idiom): the grid
    trainer pulls the solver stack only when something asks for it."""
    try:
        mod, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}") from None
    import importlib
    return getattr(importlib.import_module(mod), attr)


def _tiny_fleet(base: str, n_models: int, *, specs=((0.5, 4),),
                seed: int = 7) -> List[str]:
    """Save ``n_models`` tiny same-width binary SV models under
    ``base``; model i uses spec i % len(specs) ((gamma, d) pairs share
    d). Returns the saved paths in name order."""
    import os

    import numpy as np

    from dpsvm_tpu.models.io import save_model
    from dpsvm_tpu.models.svm import SVMModel

    rng = np.random.default_rng(seed)
    paths = []
    for i in range(n_models):
        gamma, d = specs[i % len(specs)]
        n_sv = int(rng.integers(4, 12))
        model = SVMModel(
            x_sv=rng.standard_normal((n_sv, d)).astype(np.float32),
            alpha=rng.uniform(0.05, 2.0, n_sv).astype(np.float32),
            y_sv=np.where(rng.random(n_sv) < 0.5, -1, 1).astype(np.int32),
            b=float(rng.normal()), gamma=gamma)
        path = os.path.join(base, f"m{i:04d}.svm")
        save_model(model, path)
        paths.append(path)
    return paths


def selfcheck(tmp_dir: Optional[str] = None) -> List[str]:
    """Run the fleet cache end to end on 64 tiny models under a budget
    of 8; return a list of problems (empty = healthy). See module
    docstring for what is asserted and why."""
    import os
    import tempfile
    import time as _time

    import numpy as np

    problems: List[str] = []
    ctx = (tempfile.TemporaryDirectory() if tmp_dir is None else None)
    base = tmp_dir if tmp_dir is not None else ctx.name
    try:
        from dpsvm_tpu.models.io import load_model
        from dpsvm_tpu.models.svm import decision_function
        from dpsvm_tpu.observability import compilewatch
        from dpsvm_tpu.observability.record import (close_serving_trace,
                                                    open_serving_trace)
        from dpsvm_tpu.observability.schema import (read_trace,
                                                    validate_trace)
        from dpsvm_tpu.serving.registry import ModelRegistry

        n_models, budget, d = 64, 8, 4
        paths = _tiny_fleet(base, n_models,
                            specs=((0.5, d), (0.25, d)))
        registry = ModelRegistry()
        t0 = _time.perf_counter()
        for i, path in enumerate(paths):
            registry.register(f"m{i:04d}", path, lazy=True)
        boot_s = _time.perf_counter() - t0
        if boot_s > 2.0:
            problems.append(f"lazy registration of {n_models} models "
                            f"took {boot_s:.2f}s — it is loading "
                            "models eagerly")
        if any(m["resident"] for m in registry.manifests().values()):
            problems.append("lazy registration reported resident "
                            "models before any request")

        trace_path = os.path.join(base, "fleet_selfcheck.jsonl")
        tr = open_serving_trace(trace_path, models={})
        cache = ModelCache(registry, budget=budget, max_batch=16,
                           on_event=tr.event)
        rng = np.random.default_rng(3)
        q = rng.standard_normal((5, d)).astype(np.float32)

        # 1) filling an under-budget cache hydrates on first touch
        # (fault), answers the repeat from residency (hit)
        hot = [f"m{i:04d}" for i in range(budget)]
        for name in hot:
            cache.infer(name, q)            # under budget: fault
            cache.infer(name, q)            # resident: hit
        st = cache.stats()
        if st["faults"] != budget or st["resident"] != budget:
            problems.append(f"expected {budget} faults/{budget} "
                            f"residents after double-touching the hot "
                            f"set, got {st['faults']}/{st['resident']}")
        if st["evictions"] != 0:
            problems.append(f"{st['evictions']} evictions while under "
                            "budget")

        # 2) zero stray retraces across steady-state resident traffic
        compilewatch.drain()
        outs = {}
        for _ in range(3):
            for name in hot:
                outs[name] = cache.infer(
                    name, q, want=("labels", "decision"))
        stray = compilewatch.drain()
        if stray:
            progs = sorted({c["program"] for c in stray})
            problems.append(
                f"{len(stray)} compile event(s) across steady-state "
                f"resident traffic (programs: {progs}) — the packed "
                "groups are leaking retraces")

        # 3) packed decisions match a fresh direct evaluation
        for name in hot:
            i = int(name[1:])
            direct = decision_function(load_model(paths[i]), q)
            got = outs[name]["decision"]
            if not np.allclose(got, direct, atol=1e-5):
                problems.append(
                    f"packed decision for {name} differs from a fresh "
                    f"load (max abs err "
                    f"{np.max(np.abs(got - direct)):.3g})")
                break
            want_labels = np.where(direct < 0, -1, 1).astype(np.int32)
            if not np.array_equal(outs[name]["labels"], want_labels):
                problems.append(f"packed labels differ for {name}")
                break

        # 4) a one-shot scan over the cold tail is all transients:
        # the resident working set must not churn
        before = set(cache.resident_names())
        for i in range(budget, n_models):
            cache.infer(f"m{i:04d}", q)
        st = cache.stats()
        if set(cache.resident_names()) != before:
            problems.append("a one-shot cold scan changed the "
                            "resident set")
        if st["evictions"] != 0:
            problems.append(f"a one-shot cold scan caused "
                            f"{st['evictions']} evictions")
        if st["transients"] != n_models - budget:
            problems.append(
                f"expected {n_models - budget} transient serves "
                f"(one per cold-scan touch of a full cache), got "
                f"{st['transients']}")

        # 5) a genuinely hot newcomer evicts exactly the LRU resident.
        # Pick the LAST-scanned model: the second-touch waiting ledger
        # is bounded by the budget, so only recently-seen one-timers
        # are still admission candidates (by design — a returning
        # model from a long-past scan starts over).
        lru = cache.resident_names()[-1]
        newcomer = f"m{n_models - 1:04d}"
        cache.infer(newcomer, q)            # 2nd-ever touch: admitted
        st = cache.stats()
        if st["evictions"] != 1 or lru in cache.resident_names():
            problems.append(
                f"admission over budget should evict the LRU ({lru}); "
                f"evictions={st['evictions']}, residents="
                f"{cache.resident_names()}")
        if newcomer not in cache.resident_names():
            problems.append(f"admitted newcomer {newcomer} is not "
                            "resident")

        # 6) conservation: every touch is exactly one of hit / fault /
        # transient
        st = cache.stats()
        if st["touches"] != st["hits"] + st["faults"] + st["transients"]:
            problems.append(
                f"counter conservation violated: touches "
                f"{st['touches']} != hits {st['hits']} + faults "
                f"{st['faults']} + transients {st['transients']}")
        if st["faults"] != len(cache.cold_start_ms):
            problems.append("every fault must record a cold start "
                            f"({st['faults']} faults, "
                            f"{len(cache.cold_start_ms)} samples)")

        # 7) the fault/evict story is a schema-valid trace
        close_serving_trace(tr, requests=st["touches"], errors=0,
                            seconds=_time.perf_counter() - t0,
                            model_faults=st["faults"],
                            model_evictions=st["evictions"])
        tprobs = validate_trace(read_trace(trace_path))
        if tprobs:
            problems.append(f"fleet trace failed schema validation: "
                            f"{tprobs[:3]}")
        events = [r["event"] for r in read_trace(trace_path)
                  if r.get("kind") == "event"]
        if events.count("model_fault") != st["faults"]:
            problems.append(
                f"trace carries {events.count('model_fault')} "
                f"model_fault events for {st['faults']} faults")
        if events.count("model_evict") != st["evictions"]:
            problems.append(
                f"trace carries {events.count('model_evict')} "
                f"model_evict events for {st['evictions']} evictions")
    finally:
        if ctx is not None:
            ctx.cleanup()
    return problems


def fleet_cache_drill(tmp_dir: Optional[str] = None,
                      trace_path: Optional[str] = None,
                      n_models: int = 1000, budget: int = 32) -> dict:
    """The 1000-model residency drill (the ``fleet_cache_drill`` burst
    tag): register ``n_models`` lazily, replay a deterministic skewed
    stream (a hot set that fits the budget + a long one-shot tail),
    and prove the fixed budget holds — residents never exceed it, the
    hot set stays resident through the tail scan, counters conserve,
    and every hydration's cold start is measured. Returns ONE
    JSON-able row (``metric: fleet_cold_start_p99_ms``, trace-pointed)
    with the ``ok`` verdict the burst runner gates on; the CLI appends
    it to the perf ledger (kind="fleet")."""
    import os
    import tempfile
    import time as _time

    import numpy as np

    from dpsvm_tpu.observability.record import (close_serving_trace,
                                                open_serving_trace)
    from dpsvm_tpu.observability.schema import read_trace, validate_trace
    from dpsvm_tpu.serving.registry import ModelRegistry

    ctx = (tempfile.TemporaryDirectory() if tmp_dir is None else None)
    base = tmp_dir if tmp_dir is not None else ctx.name
    row: dict = {"metric": "fleet_cold_start_p99_ms", "unit": "ms",
                 "models": int(n_models), "budget": int(budget),
                 "ok": False}
    try:
        d = 4
        # a handful of distinct artifacts shared by many names: the
        # cache is keyed on NAMES (a registration is a tenant), so
        # this exercises 1000-model churn without 1000 file writes
        arts = _tiny_fleet(base, 8, specs=((0.5, d), (0.25, d)),
                           seed=13)
        registry = ModelRegistry()
        t0 = _time.perf_counter()
        for i in range(n_models):
            registry.register(f"t{i:05d}", arts[i % len(arts)],
                              lazy=True)
        row["register_seconds"] = round(_time.perf_counter() - t0, 3)

        if trace_path is None:
            trace_path = os.path.join(base, "fleet_drill.jsonl")
        tr = open_serving_trace(trace_path, models={})
        cache = ModelCache(registry, budget=budget, max_batch=16,
                           on_event=tr.event)
        rng = np.random.default_rng(5)
        q = rng.standard_normal((4, d)).astype(np.float32)

        # hot set: 3/4 of the budget, touched repeatedly -> resident
        hot = [f"t{i:05d}" for i in range(0, n_models,
                                          n_models // (budget * 3 // 4))]
        hot = hot[:budget * 3 // 4]
        peak_resident = 0
        for _ in range(3):
            for name in hot:
                cache.infer(name, q)
                peak_resident = max(peak_resident,
                                    cache.stats()["resident"])
        # one-shot tail: every model once, in name order
        for i in range(n_models):
            cache.infer(f"t{i:05d}", q)
            if i % 250 == 0:
                peak_resident = max(peak_resident,
                                    cache.stats()["resident"])
        st = cache.stats()
        peak_resident = max(peak_resident, st["resident"])
        seconds = _time.perf_counter() - t0
        close_serving_trace(tr, requests=st["touches"], errors=0,
                            seconds=seconds,
                            model_faults=st["faults"],
                            model_evictions=st["evictions"])
        tprobs = validate_trace(read_trace(trace_path))

        row.update({
            "value": round(st["cold_start_p99_ms"], 3),
            "touches": st["touches"], "hits": st["hits"],
            "faults": st["faults"], "transients": st["transients"],
            "evictions": st["evictions"],
            "resident": st["resident"],
            "peak_resident": peak_resident,
            "resident_bytes_est": st["resident_bytes_est"],
            "packer": st["packer"],
            "hot_models": len(hot),
            "seconds": round(seconds, 3),
            "trace": trace_path,
            "trace_valid": not tprobs,
        })
        hot_resident = all(cache.is_resident(n) for n in hot)
        conserved = (st["touches"] ==
                     st["hits"] + st["faults"] + st["transients"])
        row["hot_set_survived_scan"] = hot_resident
        row["ok"] = bool(conserved and hot_resident and not tprobs
                         and peak_resident <= budget
                         and st["faults"] >= len(hot)
                         and row["value"] > 0.0)
    finally:
        if ctx is not None:
            ctx.cleanup()
    return row


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import os

    p = argparse.ArgumentParser(prog="python -m dpsvm_tpu.fleet")
    p.add_argument("--selfcheck", action="store_true",
                   help="64 lazy models under a cache budget of 8: "
                        "asserts counter conservation, a scan-proof "
                        "resident set, zero steady-state retraces "
                        "through the packed groups, parity with fresh "
                        "loads, and a schema-valid fault/evict trace")
    p.add_argument("--drill", action="store_true",
                   help="the 1000-model fleet_cache_drill: lazy-boot a "
                        "1000-name registry, replay a skewed stream "
                        "under a budget of 32, and print ONE JSON row "
                        "(fleet_cold_start_p99_ms, trace-pointed); "
                        "exits 0 iff the budget held and counters "
                        "conserved")
    args = p.parse_args(argv)
    if not (args.selfcheck or args.drill):
        p.print_help()
        return 2
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.drill:
        import json

        trace_env = os.environ.get("BENCH_TRACE_OUT")
        row = fleet_cache_drill(trace_path=trace_env or None)
        print(json.dumps(row))
        return 0 if row.get("ok") else 1
    problems = selfcheck()
    if problems:
        print("fleet selfcheck FAILED:", file=sys.stderr)
        for pr in problems:
            print(f"  {pr}", file=sys.stderr)
        return 1
    print("fleet selfcheck OK (64 lazy models under a budget of 8: "
          "counters conserved, one-shot churn never touched the "
          "working set, zero stray retraces through the packed "
          "groups, packed decisions match fresh loads, fault/evict "
          "trace schema-valid)")
    return 0

"""Mesh-parallel C×γ grid trainer: the fleet's model production line.

Hyperparameter search dominates fleet training cost ("A Recipe for
Fast Large-scale SVM Training", arxiv 2207.01016): every tenant model
is really a C×γ GRID of candidate models, of which one is promoted.
The repo already holds the hard part — ``solver/batched_ovo.
train_c_sweep`` solves a whole C×γ product grid as ONE compiled
batched program (C only moves the box bound, γ only the kernel
epilogue after the shared dot products). This module wraps it into
the production line:

* **mesh parallelism** — the C axis is partitioned contiguously
  across local devices, one batched sweep program per device running
  concurrently (each partition is still a full C-chunk × γ batched
  solve, so the per-device program keeps the shared-kernel-pass
  economics). On one device the partition is the whole grid — same
  numbers, one program;
* **held-out selection** — a deterministic seeded split scores every
  cell on rows the solver never saw; the winner is the row-major-first
  argmax (ties break toward smaller C then smaller γ, the LIBSVM
  grid.py convention of preferring the simpler model);
* **cascade polish** — the winning cell can be refit on ALL rows
  (train + holdout) through the cascade schedule
  (``config.solver="cascade"``), warm-starting from the sweep's
  screening economics — the sweep picks, the polish ships;
* **one trace** — a ``RunTrace(solver="grid")`` carries a
  ``grid_cell`` event per cell (C, γ, held-out accuracy, n_sv) and a
  ``grid_winner`` marker, so ``dpsvm report`` renders a grid run like
  any other solve;
* **atomic promotion** — ``promote_winner`` hands the winner to
  ``ModelRegistry.promote_file``, the repo's only blessed
  artifact-swap path (os.replace + fully-warmed reload).

``grid_vs_sequential`` times the same grid as per-cell sequential
``api.fit`` calls and emits the speedup — the perf-ledger's
``grid_vs_sequential`` row (docs/PERF.md).
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np


@dataclasses.dataclass
class GridCell:
    """One (C, γ) grid point: its compacted model, solver result, and
    held-out score."""
    c: float
    gamma: float
    model: object
    result: object
    holdout_acc: float


@dataclasses.dataclass
class GridResult:
    cells: List[GridCell]               # row-major (C, gamma) order
    winner: int                         # index into cells
    n_train: int
    n_holdout: int
    train_seconds: float                # wall for the whole grid
    devices: int
    polished: bool = False

    @property
    def best(self) -> GridCell:
        return self.cells[self.winner]


def holdout_split(n: int, holdout_frac: float, seed: int):
    """Deterministic shuffled split: (train_idx, holdout_idx). Seeded
    permutation, not a stride — stride splits alias sorted datasets
    (every k-th row one class) and the grid's scores must mean the
    same thing on every run of the same seed."""
    if not 0.0 < holdout_frac < 1.0:
        raise ValueError(f"holdout_frac must be in (0, 1), "
                         f"got {holdout_frac}")
    n_hold = max(1, int(round(n * holdout_frac)))
    if n_hold >= n:
        raise ValueError(f"holdout_frac {holdout_frac} leaves no "
                         f"training rows (n={n})")
    perm = np.random.default_rng(seed).permutation(n)
    return np.sort(perm[n_hold:]), np.sort(perm[:n_hold])


def _partition(items: Sequence, k: int) -> List[List]:
    """Contiguous near-even split of ``items`` into <= k non-empty
    chunks (order preserved — partitioning the C axis keeps row-major
    reassembly trivial)."""
    k = max(1, min(int(k), len(items)))
    base, extra = divmod(len(items), k)
    out, lo = [], 0
    for i in range(k):
        hi = lo + base + (1 if i < extra else 0)
        out.append(list(items[lo:hi]))
        lo = hi
    return out


def train_grid(x, y, *, cs: Sequence[float],
               gammas: Optional[Sequence[float]] = None,
               config=None, holdout_frac: float = 0.2, seed: int = 0,
               polish: bool = False, trace=None,
               max_devices: Optional[int] = None) -> GridResult:
    """Solve the full C×γ grid, score every cell held-out, pick the
    winner. ``trace`` is an open ``RunTrace`` (the caller owns its
    lifecycle — the CLI opens one per run; library callers may pass
    None)."""
    import jax

    from dpsvm_tpu import api
    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.models.svm import evaluate

    config = config or SVMConfig()
    x = np.asarray(x, np.float32)
    y = np.asarray(y)
    cs = [float(c) for c in cs]
    gammas_l = [float(g) for g in gammas] if gammas is not None else None
    if not cs:
        raise ValueError("grid needs at least one C value")

    tr_idx, ho_idx = holdout_split(len(y), holdout_frac, seed)
    x_tr, y_tr = x[tr_idx], y[tr_idx]
    x_ho, y_ho = x[ho_idx], y[ho_idx]

    devices = jax.local_devices()
    if max_devices is not None:
        devices = devices[:max(1, int(max_devices))]
    c_parts = _partition(cs, len(devices))

    t0 = time.perf_counter()
    part_out: List[Optional[list]] = [None] * len(c_parts)
    errors: List[BaseException] = []

    def _solve(i: int, part_cs: List[float], dev) -> None:
        # one batched sweep program per device; jax dispatches the
        # whole partition onto `dev` (computation-follows-data via
        # default_device, so partitions genuinely run side by side on
        # a multi-device host)
        try:
            with jax.default_device(dev):
                part_out[i] = api.sweep_c(x_tr, y_tr, part_cs,
                                          config, gammas=gammas_l)
        except BaseException as e:          # re-raised on the caller
            errors.append(e)

    if len(c_parts) == 1:
        _solve(0, c_parts[0], devices[0])
    else:
        threads = [threading.Thread(target=_solve,
                                    args=(i, p, devices[i % len(devices)]),
                                    name=f"grid-part-{i}")
                   for i, p in enumerate(c_parts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    if errors:
        raise errors[0]
    fitted = [pair for part in part_out for pair in (part or [])]
    grid_seconds = time.perf_counter() - t0

    gs = gammas_l if gammas_l is not None else [None]
    cells: List[GridCell] = []
    for i, (model, result) in enumerate(fitted):
        c_val = cs[i // len(gs)]
        g_val = float(result.gamma)
        acc = float(evaluate(model, x_ho, y_ho))
        cells.append(GridCell(c=c_val, gamma=g_val, model=model,
                              result=result, holdout_acc=acc))
        if trace is not None:
            trace.event("grid_cell", n_iter=int(result.n_iter),
                        c=c_val, gamma=g_val,
                        holdout_acc=round(acc, 6),
                        n_sv=int(result.n_sv),
                        converged=bool(result.converged))
    winner = int(np.argmax([c.holdout_acc for c in cells]))

    polished = False
    if polish:
        # refit the winning cell on ALL rows through the cascade
        # schedule — the shipped artifact sees the holdout too
        best = cells[winner]
        pol_cfg = dataclasses.replace(config, c=best.c,
                                      gamma=best.gamma,
                                      solver="cascade")
        model, result = api.fit(x, y, pol_cfg)
        cells[winner] = GridCell(c=best.c, gamma=best.gamma,
                                 model=model, result=result,
                                 holdout_acc=best.holdout_acc)
        polished = True

    out = GridResult(cells=cells, winner=winner, n_train=len(tr_idx),
                     n_holdout=len(ho_idx),
                     train_seconds=time.perf_counter() - t0,
                     devices=len(c_parts), polished=polished)
    if trace is not None:
        best = out.best
        trace.event("grid_winner", n_iter=int(best.result.n_iter),
                    c=best.c, gamma=best.gamma,
                    holdout_acc=round(best.holdout_acc, 6),
                    polished=polished)
        trace.summary(converged=all(c.result.converged for c in cells),
                      n_iter=max(int(c.result.n_iter) for c in cells),
                      b=float(best.result.b),
                      b_lo=float(best.result.b_lo),
                      b_hi=float(best.result.b_hi),
                      n_sv=int(best.result.n_sv),
                      train_seconds=out.train_seconds,
                      grid_cells=len(cells),
                      grid_devices=out.devices,
                      grid_seconds=round(grid_seconds, 6))
    return out


def sequential_grid_seconds(x, y, *, cs: Sequence[float],
                            gammas: Optional[Sequence[float]] = None,
                            config=None, holdout_frac: float = 0.2,
                            seed: int = 0) -> Tuple[float, List]:
    """The baseline the batched grid is measured against: the same
    cells, one ``api.fit`` each, same train/holdout split. Returns
    (wall_seconds, [(c, gamma, model)] in the grid's row-major
    order)."""
    from dpsvm_tpu import api
    from dpsvm_tpu.config import SVMConfig

    config = config or SVMConfig()
    x = np.asarray(x, np.float32)
    y = np.asarray(y)
    tr_idx, _ = holdout_split(len(y), holdout_frac, seed)
    x_tr, y_tr = x[tr_idx], y[tr_idx]
    gs = [float(g) for g in gammas] if gammas is not None else [config.gamma]
    t0 = time.perf_counter()
    fitted = []
    for c in cs:
        for g in gs:
            cfg = dataclasses.replace(config, c=float(c), gamma=g)
            model, _ = api.fit(x_tr, y_tr, cfg)
            fitted.append((float(c), g, model))
    return time.perf_counter() - t0, fitted


def promote_winner(grid: GridResult, registry, name: str) -> int:
    """Ship the winning cell through the registry's atomic promote
    path: serialize the model to a candidate file next to the
    registered source, then ``promote_file`` (os.replace + warmed
    reload — the ONLY blessed artifact swap, docs/SERVING.md
    "Continuous learning"). Returns the new generation."""
    from dpsvm_tpu.models.io import save_model

    source = registry.source(name)
    if source is None:
        raise ValueError(f"model {name!r} was registered in-memory; "
                         "there is no source path to promote onto")
    d = os.path.dirname(os.path.abspath(source)) or "."
    fd, cand = tempfile.mkstemp(prefix=f".{os.path.basename(source)}.",
                                suffix=".grid-cand", dir=d)
    os.close(fd)
    try:
        save_model(grid.best.model, cand)
        return registry.promote_file(name, cand)
    finally:
        if os.path.exists(cand):        # promote_file moved it on success
            os.unlink(cand)

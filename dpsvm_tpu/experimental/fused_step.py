"""Fused SMO iteration as one Pallas TPU kernel.

The XLA path (solver/smo.py) lowers one SMO iteration to several HLO
ops — working-row gather, (2, d) @ (d, n) matmul, RBF epilogue, f AXPY,
masked argmin/argmax — each making its own pass over HBM. This kernel
fuses everything that touches O(n) data into a SINGLE pass over X per
iteration (the reference's equivalent span is ``train_step2`` +
``train_step1``, svmTrain.cu:485-497/469-483, which launches five device
kernels and crosses the host boundary each iteration):

    grid over row-blocks of X; for block k:
      dots  = rows @ X[k]^T                  (MXU)
      K     = exp(-gamma (x2 + w2 - 2 dots)) (VPU, svmTrain.cu:128-135)
      f[k] += dhi*K_hi + dlo*K_lo            (update_functor semantics)
      block-local Keerthi-masked argmin/argmax of the NEW f
      sequential SMEM scan -> next iteration's working set

so the next selection comes out of the same HBM pass that updates f.
The scalar prologue (eta from the two working rows, alpha updates with
the reference's independent clip, svmTrainMain.cpp:282-295) runs in XLA
before the kernel; for the RBF kernel eta depends only on the two rows,
never on the full K rows, which is what makes the fusion legal.

Padding contract: arrays are padded to a multiple of the block size with
x = 0, y = 0, alpha = 0. Padded rows classify into neither I_up nor
I_low (the ``valid = y != 0`` guard below), so selection can never
return one.

Outside TPU the kernel runs in Pallas interpret mode, which is what the
CPU test-suite exercises.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dpsvm_tpu.ops.selection import masked_scores

# Row-block size: X block (BLOCK_N, d) f32 must fit in VMEM twice
# (double buffering). 512 rows x 784 feats x 4 B = 1.6 MB.
DEFAULT_BLOCK_N = 512


def pad_to_block(n: int, block_n: int) -> int:
    return ((n + block_n - 1) // block_n) * block_n


def _fused_iter_kernel(scal_ref, rows_ref, x_ref, x2_ref, y_ref, alpha_ref,
                       f_ref, fout_ref, sel_i_ref, sel_v_ref,
                       best_i, best_v, *, block_n: int, mxu_precision):
    """One grid step: process rows [k*block_n, (k+1)*block_n) of X."""
    k = pl.program_id(0)

    d_hi = scal_ref[0]      # (alpha_hi' - alpha_hi) * y_hi
    d_lo = scal_ref[1]      # (alpha_lo' - alpha_lo) * y_lo
    gamma = scal_ref[2]
    w2_hi = scal_ref[3]     # |x_hi|^2
    w2_lo = scal_ref[4]
    c = scal_ref[5]

    # (2, block_n) dot products of both working rows against this block.
    dots = lax.dot_general(
        rows_ref[:], x_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=mxu_precision)

    x2b = x2_ref[0]
    k_hi = jnp.exp(-gamma * (x2b + w2_hi - 2.0 * dots[0]))
    k_lo = jnp.exp(-gamma * (x2b + w2_lo - 2.0 * dots[1]))
    fnew = f_ref[0] + d_hi * k_hi + d_lo * k_lo
    fout_ref[0] = fnew

    # Keerthi-masked scores on the POST-update (alpha, f); padding rows
    # (y == 0) belong to neither set. Same helper as the XLA path so the
    # svmTrain.cu:54-91 semantics live in exactly one place.
    yb = y_ref[0]
    f_up, f_low = masked_scores(alpha_ref[0], yb, fnew, c, valid=yb != 0.0)

    bmin = jnp.min(f_up)
    imin = jnp.argmin(f_up).astype(jnp.int32) + k * block_n
    bmax = jnp.max(f_low)
    imax = jnp.argmax(f_low).astype(jnp.int32) + k * block_n

    # Sequential cross-block scan (TPU grid steps run in order). Strict
    # </> keeps the first-index-wins tie-break of jnp.argmin/argmax.
    @pl.when(k == 0)
    def _():
        best_v[0] = bmin
        best_i[0] = imin
        best_v[1] = bmax
        best_i[1] = imax

    @pl.when((k > 0) & (bmin < best_v[0]))
    def _():
        best_v[0] = bmin
        best_i[0] = imin

    @pl.when((k > 0) & (bmax > best_v[1]))
    def _():
        best_v[1] = bmax
        best_i[1] = imax

    @pl.when(k == pl.num_programs(0) - 1)
    def _():
        sel_i_ref[0] = best_i[0]
        sel_i_ref[1] = best_i[1]
        sel_v_ref[0] = best_v[0]
        sel_v_ref[1] = best_v[1]


def fused_update_select(rows, scalars, x, x2, y, alpha, f, *,
                        block_n: int = DEFAULT_BLOCK_N,
                        mxu_precision=lax.Precision.HIGHEST,
                        interpret: bool = False):
    """f update + next working-set selection in one pass over X.

    rows: (2, d) working rows [x_hi, x_lo] (same dtype as x);
    scalars: (8,) f32 [d_hi, d_lo, gamma, w2_hi, w2_lo, c, 0, 0];
    x: (n_pad, d); x2/y/alpha/f: (1, n_pad) f32, padded as per module
    docstring. Returns (f_new (1, n_pad), sel_i (2,) i32, sel_v (2,) f32)
    where sel_i = [i_hi, i_lo] and sel_v = [b_hi, b_lo].
    """
    n_pad, d = x.shape
    assert n_pad % block_n == 0, (n_pad, block_n)
    nb = n_pad // block_n

    vec = lambda: pl.BlockSpec((1, block_n), lambda k: (0, k),
                               memory_space=pltpu.VMEM)
    kernel = functools.partial(_fused_iter_kernel, block_n=block_n,
                               mxu_precision=mxu_precision)
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                 # scalars
            pl.BlockSpec((2, d), lambda k: (0, 0),
                         memory_space=pltpu.VMEM),                 # rows
            pl.BlockSpec((block_n, d), lambda k: (k, 0),
                         memory_space=pltpu.VMEM),                 # x block
            vec(),                                                 # x2
            vec(),                                                 # y
            vec(),                                                 # alpha
            vec(),                                                 # f
        ],
        out_specs=[
            vec(),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
            jax.ShapeDtypeStruct((2,), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.float32),
        ],
        scratch_shapes=[pltpu.SMEM((2,), jnp.int32),
                        pltpu.SMEM((2,), jnp.float32)],
        input_output_aliases={6: 0},
        interpret=interpret,
    )(scalars, rows, x, x2, y, alpha, f)


class FusedCarry(NamedTuple):
    """While-loop carry for the fused path. Selection lives in the carry:
    each body consumes the working set chosen at the tail of the previous
    iteration (the semantics are identical to select-then-update — the
    selection has just moved across the loop back-edge)."""
    alpha: jax.Array   # (1, n_pad) f32
    f: jax.Array       # (1, n_pad) f32
    i_hi: jax.Array    # () i32
    i_lo: jax.Array    # () i32
    b_hi: jax.Array    # () f32
    b_lo: jax.Array    # () f32
    n_iter: jax.Array  # () i32


def fused_smo_body(carry: FusedCarry, x, x2, y, c: float, gamma: float, *,
                   block_n: int = DEFAULT_BLOCK_N,
                   mxu_precision=lax.Precision.HIGHEST,
                   interpret: bool = False) -> FusedCarry:
    """One SMO iteration: scalar prologue in XLA, O(n) work in Pallas.

    Same math as solver/smo.py::smo_step (svmTrainMain.cpp:282-299):
    eta from the two working rows (K(a,a) uses the same exp form as the
    reference's host rbf_kernel, svmTrain.cu:696-714), alpha updates
    independently clipped to [0, C], lo written before hi.
    """
    i_hi, i_lo = carry.i_hi, carry.i_lo
    b_hi, b_lo = carry.b_hi, carry.b_lo
    alpha, f = carry.alpha, carry.f
    d = x.shape[1]

    row_hi = lax.dynamic_slice(x, (i_hi, 0), (1, d))
    row_lo = lax.dynamic_slice(x, (i_lo, 0), (1, d))
    rows = jnp.concatenate([row_hi, row_lo], axis=0)          # (2, d)
    rows32 = rows.astype(jnp.float32)

    x2_hi = x2[0, i_hi]
    x2_lo = x2[0, i_lo]
    pair = jnp.matmul(rows32, rows32.T,
                      precision=lax.Precision.HIGHEST)        # (2, 2)
    k_hh = jnp.exp(-gamma * (2.0 * x2_hi - 2.0 * pair[0, 0]))
    k_ll = jnp.exp(-gamma * (2.0 * x2_lo - 2.0 * pair[1, 1]))
    k_hl = jnp.exp(-gamma * (x2_hi + x2_lo - 2.0 * pair[0, 1]))
    eta = k_hh + k_ll - 2.0 * k_hl

    y_hi = y[0, i_hi]
    y_lo = y[0, i_lo]
    a_hi = alpha[0, i_hi]
    a_lo = alpha[0, i_lo]
    s = y_lo * y_hi
    a_lo_u = a_lo + y_lo * (b_hi - b_lo) / eta
    a_hi_u = a_hi + s * (a_lo - a_lo_u)
    a_lo_n = jnp.clip(a_lo_u, 0.0, c)
    a_hi_n = jnp.clip(a_hi_u, 0.0, c)

    # lo written before hi (svmTrain.cu:491-492); the f-update deltas use
    # the computed values, not a re-read, matching svmTrain.cu:485-497.
    alpha = alpha.at[0, i_lo].set(a_lo_n)
    alpha = alpha.at[0, i_hi].set(a_hi_n)

    scalars = jnp.stack([
        (a_hi_n - a_hi) * y_hi,
        (a_lo_n - a_lo) * y_lo,
        jnp.float32(gamma),
        x2_hi, x2_lo, jnp.float32(c),
        jnp.float32(0.0), jnp.float32(0.0),
    ]).astype(jnp.float32)

    f_new, sel_i, sel_v = fused_update_select(
        rows, scalars, x, x2, y, alpha, f,
        block_n=block_n, mxu_precision=mxu_precision, interpret=interpret)

    return FusedCarry(alpha=alpha, f=f_new,
                      i_hi=sel_i[0], i_lo=sel_i[1],
                      b_hi=sel_v[0], b_lo=sel_v[1],
                      n_iter=carry.n_iter + 1)

"""Single-device SMO with the fused Pallas iteration kernel.

Same algorithm and driver contract as solver/smo.py, but each iteration's
O(n) work — kernel rows, f update, next working-set selection — is one
Pallas pass over X (experimental/fused_step.py) instead of several XLA ops. The
whole loop still lives in one ``lax.while_loop`` under ``jit``; only the
state layout differs (vectors are (1, n_pad) so the kernel can slice them
on the 128-lane axis, and the working set rides in the carry across the
loop back-edge).

When ``matmul_precision == "default"`` X is stored bfloat16, halving the
per-iteration HBM traffic that dominates the iteration; f/alpha/x2 stay
float32 (the accumulators and all scalar math are always float32).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.experimental.fused_step import (DEFAULT_BLOCK_N, FusedCarry,
                                      fused_smo_body, pad_to_block)
from dpsvm_tpu.observability import compilewatch
from dpsvm_tpu.ops.kernels import row_norms_sq
from dpsvm_tpu.ops.selection import masked_extrema
from dpsvm_tpu.solver.driver import (device_sv_count, host_training_loop,
                                     pack_stats, resume_state)


def _should_interpret() -> bool:
    return jax.default_backend() != "tpu"


def use_fused(config: SVMConfig) -> bool:
    """Dispatch policy for api.train.

    'auto' currently resolves to the plain XLA path: measured on a v5e
    chip at the MNIST benchmark shape (60000x784), XLA keeps bf16 X
    pinned in VMEM across while-loop iterations (~64 us/iter) while a
    pallas_call re-stages X from HBM every invocation (~200 us/iter), so
    the hand-fused kernel only matches XLA at f32 and loses at bf16.
    'on' forces the fused kernel (interpret mode off-TPU — how the CPU
    test suite runs it)."""
    if config.use_pallas != "on":
        return False
    return config.fused_incompatibility() is None


@functools.partial(jax.jit, static_argnames=("c", "gamma", "epsilon",
                                             "max_iter", "block_n",
                                             "precision_name", "interpret"),
                   donate_argnums=(0,))
def _run_chunk(carry: FusedCarry, x, x2, y, limit, *, c, gamma, epsilon,
               max_iter, block_n, precision_name, interpret):
    precision = getattr(lax.Precision, precision_name)

    def cond(s: FusedCarry):
        return (s.b_lo > s.b_hi + 2.0 * epsilon) & (s.n_iter < limit)

    def body(s: FusedCarry):
        return fused_smo_body(s, x, x2, y, c, gamma, block_n=block_n,
                              mxu_precision=precision, interpret=interpret)

    final = lax.while_loop(cond, body, carry)

    # Reference do-while parity (svmTrainMain.cpp:235-310): the body whose
    # selection first satisfies the gap still performs its alpha/f update
    # before the loop condition is evaluated. Our while checks the gap
    # before the update, so on a convergence exit apply that one trailing
    # update (keeping the converged b_hi/b_lo, which are what the
    # reference reports and derives b from). Gates: the reference only
    # runs that body while iter < max_iter, and a chunk that made no
    # progress (already-converged carry, e.g. resuming a finished run)
    # must not re-apply it.
    def trailing(s: FusedCarry):
        t = body(s)
        return t._replace(b_hi=s.b_hi, b_lo=s.b_lo)

    # Fire when this call ends converged below the iteration cap AND the
    # trailing body has not already been applied to this carry: either
    # bodies ran in this call (n_iter advanced past the entry value), or
    # this is the program-initial selection (n_iter == 0) that already
    # satisfies the gap — the reference's do-while runs one body in both.
    # The progress gate makes the trailing update idempotent, which the
    # host driver relies on: its pipelined poll speculatively re-enters
    # the runner with a finished carry (a zero-body no-op that must not
    # re-apply the update — trailing itself bumps n_iter, closing the
    # gate after the first application).
    converged = ~(final.b_lo > final.b_hi + 2.0 * epsilon)
    progressed = (final.n_iter > carry.n_iter) | (final.n_iter == 0)
    out = lax.cond(converged & progressed & (final.n_iter < max_iter),
                   trailing, lambda s: s, final)
    return out, pack_stats(out.n_iter, out.b_lo, out.b_hi,
                           n_sv=device_sv_count(out.alpha))


def init_fused_carry(alpha, f, y, c: float) -> FusedCarry:
    """Selection for the first iteration from current (alpha, f); also the
    resume path — the working set is a pure function of solver state."""
    valid = y[0] != 0.0
    i_hi, b_hi, i_lo, b_lo = masked_extrema(alpha[0], y[0], f[0], c,
                                            valid=valid)
    return FusedCarry(alpha=alpha, f=f,
                      i_hi=i_hi.astype(jnp.int32),
                      i_lo=i_lo.astype(jnp.int32),
                      b_hi=b_hi, b_lo=b_lo, n_iter=jnp.int32(0))


def train_single_device_fused(x: np.ndarray, y: np.ndarray,
                              config: SVMConfig,
                              device: Optional[jax.Device] = None,
                              block_n: int = DEFAULT_BLOCK_N) -> TrainResult:
    """Train on one device via the fused Pallas iteration kernel."""
    config.validate()
    n, d = x.shape
    gamma = float(config.resolve_gamma(d))
    interpret = _should_interpret()
    precision_name = config.matmul_precision.upper()

    n_pad = pad_to_block(n, block_n)
    xp = np.zeros((n_pad, d), np.float32)
    xp[:n] = x
    yp = np.zeros((1, n_pad), np.float32)
    yp[0, :n] = y

    x_dtype = (jnp.bfloat16 if config.matmul_precision == "default"
               else jnp.float32)
    xd = jax.device_put(jnp.asarray(xp), device).astype(x_dtype)
    # x2 from the STORED (possibly bf16-cast) X so that K(a, a) computed
    # from bf16 dot products stays ~1 and eta stays positive; in f32 mode
    # this is the plain row-norm.
    x2 = row_norms_sq(xd.astype(jnp.float32))[None, :]       # (1, n_pad) f32
    yd = jax.device_put(jnp.asarray(yp), device)

    alpha = jnp.zeros((1, n_pad), jnp.float32)
    f = -yd                                                  # f = -y, pad 0

    ckpt = resume_state(config, n, d, gamma)
    if ckpt is not None:
        alpha = alpha.at[0, :n].set(jnp.asarray(ckpt.alpha))
        f = f.at[0, :n].set(jnp.asarray(ckpt.f))
    if ckpt is not None and not (ckpt.b_lo >
                                 ckpt.b_hi + 2.0 * float(config.epsilon)):
        # Finished-run checkpoint: return it as-is instead of entering
        # the loop (where the trailing do-while update would be
        # re-applied). Mirrors the smo path, whose first chunk exits
        # immediately on the restored converged gap.
        return TrainResult(
            alpha=np.asarray(ckpt.alpha), b=(ckpt.b_lo + ckpt.b_hi) / 2.0,
            n_iter=ckpt.n_iter, converged=True, b_lo=ckpt.b_lo,
            b_hi=ckpt.b_hi, train_seconds=0.0, gamma=gamma,
            n_sv=int(np.sum(np.asarray(ckpt.alpha) > 0)))

    carry = init_fused_carry(alpha, f, yd, float(config.c))
    if ckpt is not None:
        # Mid-training resume: the freshly recomputed selection is the
        # correct working set — its b's come from the CURRENT (alpha, f),
        # which the fused body feeds into the alpha step (checkpoints
        # written by the smo path record the previous body's selection,
        # which would be stale here).
        carry = carry._replace(n_iter=jnp.int32(ckpt.n_iter))
        if ckpt.n_iter < int(config.max_iter) and not (
                float(carry.b_lo) > float(carry.b_hi)
                + 2.0 * float(config.epsilon)):
            # Budget gate mirrors the smo path: a checkpoint written AT
            # max_iter resumes to zero bodies there (limit == n_iter),
            # so the do-while mirror must not spend an extra update.
            # The recomputed selection already satisfies the gap. The smo
            # path's resumed loop still runs one body here (its cond saw
            # the checkpoint's stale open gap, and the body both computes
            # this selection and applies its update — reference do-while,
            # svmTrainMain.cpp:235-310). Mirror it once, host-side,
            # keeping this selection's b's; the chunk loop then exits on
            # its first poll without re-firing the trailing update
            # (_run_chunk's progress gate sees n_iter already advanced).
            body = jax.jit(functools.partial(
                fused_smo_body, c=float(config.c), gamma=gamma,
                block_n=block_n,
                mxu_precision=getattr(lax.Precision, precision_name),
                interpret=interpret))
            stepped = body(carry, xd, x2, yd)
            carry = stepped._replace(b_hi=carry.b_hi, b_lo=carry.b_lo)
    if device is not None:
        carry = jax.device_put(carry, device)

    # Compile accounting rides the partial: the statics live in its
    # keywords and _run_chunk is the jit whose cache is watched
    # (observability/compilewatch.py).
    run = compilewatch.instrument(
        functools.partial(
            _run_chunk, c=float(config.c), gamma=gamma,
            epsilon=float(config.epsilon), max_iter=int(config.max_iter),
            block_n=block_n, precision_name=precision_name,
            interpret=interpret),
        "fused-chunk", jitted=_run_chunk)

    def carry_from_ckpt(ck):
        # Divergence-rollback hook (docs/ROBUSTNESS.md): rebuild the
        # fused carry from checkpoint (alpha, f) — the working set is a
        # pure function of solver state (init_fused_carry). No budget/
        # converged mirror dance here: mid-run rollback checkpoints were
        # written at polls where the gap was still open and n_iter was
        # under max_iter, so the next dispatched body applies the
        # recomputed selection exactly like the smo path's next body.
        a = jnp.zeros((1, n_pad), jnp.float32).at[0, :n].set(
            jnp.asarray(ck.alpha, jnp.float32))
        ff = (-yd).at[0, :n].set(jnp.asarray(ck.f, jnp.float32))
        c2 = init_fused_carry(a, ff, yd, float(config.c))._replace(
            n_iter=jnp.int32(ck.n_iter))
        return jax.device_put(c2, device) if device is not None else c2

    return host_training_loop(
        config, gamma, n, d, carry,
        step_chunk=lambda s, lim: run(s, xd, x2, yd, np.int32(lim)),
        carry_to_host=lambda s: (np.asarray(s.alpha[0, :n]),
                                 np.asarray(s.f[0, :n])),
        it0=int(ckpt.n_iter) if ckpt is not None else 0,
        carry_from_ckpt=carry_from_ckpt,
    )

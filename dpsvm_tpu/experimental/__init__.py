"""Hand-written Pallas kernels — demoted to experimental, opt-in only.

Status (terminal decision, round 5, pre-registered in docs/ROUND4.md
rules 3/4 and executed per the no-window default): on every chip
measurement to date the hand-fused kernels LOSE to the plain XLA
lowering of the same math —

* fused 2-violator iteration (``fused_step.py`` + ``fused.py``,
  replacing the reference's 5-kernel-launch iteration,
  ``svmTrain.cu:469-497``): at the 60000x784 benchmark shape XLA keeps
  the bf16-cast X VMEM-resident across ``lax.while_loop`` iterations
  (~64 us/iter) while a ``pallas_call`` re-stages X from HBM every
  invocation (~200 us/iter). Measured round 2, `docs/PERF.md`
  ("Per-phase cost" and the selection A/B sections).
* inner-subsolve kernel (``subsolve_kernel.py``): same math as
  ``solver/decomp.inner_subsolve``'s XLA while_loop; never earned a
  chip win (its A/B arm `conv_decomp2048_pal` remains queued in the
  sweep backlog).

Both remain fully functional and tested (``tests/test_fused.py``,
``tests/test_subsolve_kernel.py``) and reachable via
``SVMConfig(use_pallas="on")`` — ``"auto"`` NEVER selects them.
Promotion back out of experimental requires the pre-registered bar:
``pallas_cliff`` beating XLA past the VMEM cliff by >10% (rule 3), or
``conv_decomp2048_pal`` beating its XLA arm by >5% (rule 4); the sweep
arms that decide this stay armed in ``benchmarks/burst_runner.py``.

Why keep them at all: they are the only in-tree demonstrations of
block-pipelined Pallas patterns over this solver's data layout
(manual HBM->VMEM staging, in-kernel while_loops, masked block
reductions), and the cliff regime (n past VMEM capacity, where both
paths must stream from HBM) is measured-undecided — the one place the
fused design could still win.
"""

"""The decomposition inner subsolve as ONE Pallas TPU kernel.

The XLA inner loop (solver/decomp.py inner_subsolve) pays per-step op
dispatch: each WSS2 pair update lowers to several unfusable HLO groups
(reductions, gathers, scatters) costing ~22 us of fixed latency per
step regardless of q. This kernel runs the WHOLE capped subsolve —
up to ``max_cap`` pair updates — inside a single kernel launch: the
(q, q) block, the alphas and the subproblem gradient live in VMEM for
the entire loop, and a step is pure VPU work (masked extrema, one-hot
scalar selects, two dynamic row loads, an AXPY), so the per-step cost
is the arithmetic, not the dispatch.

Design notes:
  * scalar gathers (f[i], y[i], c[i], eta entries) are one-hot
    multiply-reduces over (q,) vectors — no dynamic scalar indexing,
    which TPU vector memory dislikes;
  * the two kernel-block rows are ``pl.ds`` dynamic-start row loads
    from the VMEM-resident block (supported on the sublane dimension);
    the diagonal is extracted once before the loop;
  * the loop is a ``lax.fori_loop`` to the COMPILE-TIME cap with a
    ``live`` flag (a converged or budget-capped subsolve keeps the
    state fixed); the dynamic remaining-budget cap rides in as a
    scalar and folds into ``live``. Entry extrema seed the stopping
    state exactly like the XLA path (an already-optimal block must
    no-op).

Off-TPU the kernel runs in Pallas interpret mode (the CPU test suite's
path — tests/test_subsolve_kernel.py asserts it walks the XLA
inner_subsolve's trajectory).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from dpsvm_tpu.ops.selection import masked_scores_and_masks
from dpsvm_tpu.ops.update import alpha_pair_step


def _subsolve_kernel(scal_ref, cap_ref, kww_ref, y_ref, c_ref, act_ref,
                     a_ref, f_ref, aout_ref, fout_ref, stats_ref, *,
                     q: int, max_cap: int, pairwise: bool):
    eps = scal_ref[0]
    step_cap = cap_ref[0]

    yv = y_ref[0]
    cv = c_ref[0]
    act = act_ref[0] != 0.0
    iota = lax.broadcasted_iota(jnp.int32, (q,), 0)
    # Diagonal K_jj, extracted once (O(q^2), outside the loop).
    ii = lax.broadcasted_iota(jnp.int32, (q, q), 0)
    jj = lax.broadcasted_iota(jnp.int32, (q, q), 1)
    kjj = jnp.sum(jnp.where(ii == jj, kww_ref[...], 0.0), axis=1)

    def row(idx):
        return kww_ref[pl.ds(idx, 1), :][0]

    def pick(vec, idx):
        """vec[idx] without dynamic indexing: one-hot reduce."""
        return jnp.sum(jnp.where(iota == idx, vec, 0.0))

    def body(_, state):
        a, f, bh, bl, t, live = state
        # Gate on the PREVIOUS step's stored gap, exactly like the XLA
        # while_loop's cond (checked before the body): the body whose
        # fresh selection first satisfies the gap still applies its
        # trailing update. Gating on the fresh gap would run one fewer
        # step and diverge from inner_subsolve's trajectory.
        live = live & (bl > bh + 2.0 * eps) & (t < step_cap)
        fu, fl, _, in_low = masked_scores_and_masks(a, yv, f, cv,
                                                    valid=act)
        i_hi = jnp.argmin(fu).astype(jnp.int32)
        bh_t = jnp.min(fu)
        bl_t = jnp.max(fl)

        row_hi = row(i_hi)
        k_hh = pick(kjj, i_hi)
        # WSS2 partner: maximize (fl - bh)^2 / (K_ii + K_jj - 2 K_ij).
        bb = fl - bh_t
        aa = jnp.maximum(k_hh + kjj - 2.0 * row_hi, 1e-12)
        obj = jnp.where(in_low & (bb > 0), bb * bb / aa, -1.0)
        i_lo = jnp.argmax(obj).astype(jnp.int32)
        bl_sel = pick(fl, i_lo)

        row_lo = row(i_lo)
        k_ll = pick(kjj, i_lo)
        k_hl = pick(row_hi, i_lo)
        eta = jnp.maximum(k_hh + k_ll - 2.0 * k_hl, 1e-12)

        y_hi, y_lo = pick(yv, i_hi), pick(yv, i_lo)
        a_hi, a_lo = pick(a, i_hi), pick(a, i_lo)
        c_hi, c_lo = pick(cv, i_hi), pick(cv, i_lo)
        a_hi_n, a_lo_n = alpha_pair_step(a_hi, a_lo, y_hi, y_lo, bh_t,
                                         bl_sel, eta, c_hi, c_lo,
                                         pairwise)
        # lo-then-hi one-hot writes (the i_hi == i_lo corner keeps the
        # hi value, matching the XLA path's .at[] write order).
        a_new = jnp.where(iota == i_lo, a_lo_n, a)
        a_new = jnp.where(iota == i_hi, a_hi_n, a_new)
        f_new = (f + (a_hi_n - a_hi) * y_hi * row_hi
                 + (a_lo_n - a_lo) * y_lo * row_lo)

        a = jnp.where(live, a_new, a)
        f = jnp.where(live, f_new, f)
        bh = jnp.where(live, bh_t, bh)
        bl = jnp.where(live, bl_t, bl)
        t = t + jnp.where(live, 1, 0).astype(jnp.int32)
        return a, f, bh, bl, t, live

    # Entry extrema seed the stopping state (already-optimal block =>
    # the very first `live` is False and the loop is a no-op).
    a0 = a_ref[0]
    f0 = f_ref[0]
    fu0, fl0, _, _ = masked_scores_and_masks(a0, yv, f0, cv, valid=act)
    init = (a0, f0, jnp.min(fu0), jnp.max(fl0), jnp.int32(0), True)
    a, f, bh, bl, t, _ = lax.fori_loop(0, max_cap, body, init)

    aout_ref[0] = a
    fout_ref[0] = f
    stats_ref[0] = bh
    stats_ref[1] = bl
    # Bit pattern, not a cast: an f32 VALUE lane would corrupt counts
    # above 2^24 (the same hazard driver.pack_stats documents), and
    # inner_iters is unbounded.
    stats_ref[2] = lax.bitcast_convert_type(t, jnp.float32)


@functools.partial(jax.jit, static_argnames=("max_cap", "pairwise",
                                             "interpret"))
def pallas_inner_subsolve(k_ww, y_w, c_w, a_w0, f_w0, active, epsilon,
                          step_cap, *, max_cap: int, pairwise: bool,
                          interpret: bool = False):
    """Run the capped WSS2 subsolve in one kernel launch.

    Same contract as solver/decomp.inner_subsolve: returns
    (a, f, b_hi, b_lo, t). ``max_cap`` is the static loop bound (the
    config's inner cap); ``step_cap`` the dynamic remaining budget.
    """
    q = k_ww.shape[0]
    scal = jnp.stack([jnp.float32(epsilon)])
    cap = jnp.reshape(jnp.asarray(step_cap, jnp.int32), (1,))
    out_shapes = (
        jax.ShapeDtypeStruct((1, q), jnp.float32),    # a
        jax.ShapeDtypeStruct((1, q), jnp.float32),    # f
        jax.ShapeDtypeStruct((3,), jnp.float32),      # b_hi, b_lo, t
    )
    a, f, stats = pl.pallas_call(
        functools.partial(_subsolve_kernel, q=q, max_cap=max_cap,
                          pairwise=pairwise),
        out_shape=out_shapes,
        interpret=interpret,
    )(scal, cap, k_ww,
      y_w[None, :], c_w[None, :],
      active.astype(jnp.float32)[None, :],
      a_w0[None, :], f_w0[None, :])
    return (a[0], f[0], stats[0], stats[1],
            lax.bitcast_convert_type(stats[2], jnp.int32))

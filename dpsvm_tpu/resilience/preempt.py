"""Preemption snapshots: deferred SIGTERM/SIGINT handling for training.

On preemptible TPU VMs a SIGTERM mid-run is the COMMON case, not the
exception — the reference simply dies and loses everything (SURVEY §5).
Here the shared host driver (solver/driver.host_training_loop) runs its
poll loop inside ``trap()``: a delivered SIGTERM/SIGINT only sets a
flag, and at the next poll boundary the driver pulls a consistent carry,
writes a final checkpoint, emits a ``preempt`` trace event and raises
``PreemptedError`` — which the CLI converts into ``PREEMPT_EXIT_CODE``
(75, BSD EX_TEMPFAIL), the code the retry supervisor
(resilience/supervisor.py) treats as "resume me".

Pipelining note: the driver keeps pipelined dispatch enabled while
trapped — the speculative chunk's stats are only read (sequentializing
one poll) when a signal is ACTUALLY pending, so the zero-signal hot
path pays nothing (docs/ROBUSTNESS.md "Snapshot semantics").

A second SIGINT escalates to an immediate ``KeyboardInterrupt`` (the
operator hammering Ctrl-C must still win over a hung device call).
Handlers are installed only from the main thread (Python restricts
``signal.signal`` to it); worker-thread training loops run untrapped.
"""

from __future__ import annotations

import contextlib
import signal
import threading
from typing import Iterator, Optional

#: BSD sysexits EX_TEMPFAIL: "temporary failure, retry later" — distinct
#: from error exits AND from the watchdog's 124, but treated the same by
#: the retry supervisor's transient set.
PREEMPT_EXIT_CODE = 75


class PreemptedError(RuntimeError):
    """Training was interrupted by a (possibly simulated) preemption
    signal; the run is RESUMABLE from ``checkpoint_path`` when set."""

    def __init__(self, signum: int, n_iter: int,
                 checkpoint_path: Optional[str] = None):
        self.signum = int(signum)
        self.n_iter = int(n_iter)
        self.checkpoint_path = checkpoint_path
        where = (f"snapshot saved to {checkpoint_path}"
                 if checkpoint_path else
                 "no checkpoint_path configured — state NOT saved")
        super().__init__(
            f"training preempted by signal {signum} at iteration "
            f"{n_iter} ({where})")


_pending: Optional[int] = None       # signum, None = nothing pending
_hits = 0
_depth = 0                           # trap() nesting (polish runs 2 trains)


def pending() -> Optional[int]:
    """The pending preemption signal number, or None."""
    return _pending


def clear() -> None:
    global _pending, _hits
    _pending = None
    _hits = 0


def simulate(signum: int = signal.SIGTERM) -> None:
    """Mark a preemption as pending without a real signal — the fault
    injector's hook (resilience/faultinject.py) and test seam. Works in
    any thread and outside trap()."""
    global _pending, _hits
    _pending = int(signum)
    _hits += 1


def _handler(signum, frame) -> None:
    global _pending, _hits
    _hits += 1
    if signum == signal.SIGINT and _hits > 1:
        # Second Ctrl-C: the operator wants OUT now, snapshot or not.
        raise KeyboardInterrupt
    _pending = int(signum)


@contextlib.contextmanager
def trap(signums=(signal.SIGTERM, signal.SIGINT)) -> Iterator[None]:
    """Install the deferring handlers for the duration of a training
    loop; restore the previous handlers (and clear any leftover flag)
    on exit. No-op off the main thread and re-entrant under nesting."""
    global _depth
    if threading.current_thread() is not threading.main_thread():
        yield
        return
    if _depth:
        _depth += 1
        try:
            yield
        finally:
            _depth -= 1
        return
    clear()
    prev = {}
    for s in signums:
        try:
            prev[s] = signal.signal(s, _handler)
        except (ValueError, OSError):        # unsupported on platform
            pass
    _depth = 1
    try:
        yield
    finally:
        _depth = 0
        for s, h in prev.items():
            signal.signal(s, h)
        # A signal that landed after the final poll was absorbed: the
        # run completed and its artifacts are being written — beating
        # the preemption deadline is the point. Drop the stale flag so
        # the next run in this process starts clean.
        clear()

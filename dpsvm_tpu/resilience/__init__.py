"""Fault-tolerant training: the resilience subsystem.

Four cooperating pieces, all wired through the shared host driver
(solver/driver.host_training_loop) so every solver path — smo / fused /
decomp / dist-smo / dist-decomp — gets them for free
(docs/ROBUSTNESS.md):

* ``preempt``     — SIGTERM/SIGINT -> snapshot checkpoint + resumable
                    exit code 75 at the next poll boundary;
* ``health``      — divergence guards (non-finite gap, stagnation, SV
                    collapse) with a raise/rollback/ignore policy;
* ``supervisor``  — ``dpsvm train --retries N`` / ``run_with_retries``:
                    re-launch from the newest intact checkpoint with
                    exponential backoff;
* ``faultinject`` — deterministic failure injection (env/API driven)
                    that makes all of the above testable in CI on CPU;
* ``elastic``     — the distributed fault model: cross-shard desync
                    detection + shard heartbeats on the packed-stats
                    poll, ``ShardLostError`` + ``run_elastic`` (resume
                    on the surviving mesh from the newest intact
                    shard-aware checkpoint — docs/DISTRIBUTED.md
                    "Elastic training");
* ``doctor``      — ``dpsvm doctor`` preflight: topology, a tiny
                    timed collective probe, checkpoint-dir health.

Checkpoint integrity (CRC32, keep-N rotation, the ``CheckpointError``
hierarchy) lives with the checkpoint format in ``utils/checkpoint.py``.

``python -m dpsvm_tpu.resilience --selfcheck`` exercises the injector +
supervisor end to end on a tiny CPU problem and asserts the resumed
trajectory is bitwise-identical to an uninterrupted run — the CI gate
next to the telemetry selfcheck.
"""

from __future__ import annotations

import os
from typing import List, Optional

from dpsvm_tpu.resilience.health import (DesyncError, DivergenceError,
                                         HealthMonitor, MAX_ROLLBACKS,
                                         POLICIES)
from dpsvm_tpu.resilience.preempt import (PREEMPT_EXIT_CODE,
                                          PreemptedError)

__all__ = [
    "DesyncError", "DivergenceError", "HealthMonitor", "MAX_ROLLBACKS",
    "POLICIES", "PREEMPT_EXIT_CODE", "PreemptedError", "selfcheck",
]


def selfcheck(tmp_dir: Optional[str] = None,
              host_drill: bool = False) -> List[str]:
    """Injector + supervisor round-trip on a tiny CPU problem; returns
    problems (empty = OK). Flow: (1) an uninterrupted reference run,
    (2) the same run preempted mid-flight by an injected fault and
    resumed by the in-process supervisor — final state must be
    bitwise-identical, (3) the newest checkpoint slot corrupted on disk
    — resume must fall back to the rotation slot and still land on the
    identical state, tracing what it skipped, (4) with >= 2 devices:
    the kill-one-shard drill — a shard injected dead mid-run on a
    virtual-device mesh, ``elastic.run_elastic`` resuming on the
    surviving mesh from the newest intact shard-aware checkpoint,
    final model bitwise-identical to an uninterrupted mesh run with
    the ``reshard``/``retry`` events on a schema-valid trace.

    With ``host_drill=True`` (the ``--selfcheck`` CLI gate and the
    burst runner's ``host_loss_drill`` tag; opt-in because it spawns
    real training subprocesses) it additionally runs the kill-one-HOST
    drill: N localhost single-device host processes training dist-smo
    over a cross-process mesh, one SIGKILLed mid-run, survivors
    reformed by the group supervisor (resilience/hostgroup.py) to the
    same model within 1e-4 with a schema-valid ``host_lost`` ->
    ``reform`` trace.

    Tier-1 (tests/test_resilience.py) and ``python -m
    dpsvm_tpu.resilience --selfcheck`` both run this, so a regression in
    any cooperating piece fails loudly in CI."""
    import dataclasses
    import tempfile

    import numpy as np

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.data.synthetic import make_blobs
    from dpsvm_tpu.resilience import faultinject
    from dpsvm_tpu.resilience.supervisor import run_with_retries
    from dpsvm_tpu.solver.smo import train_single_device
    from dpsvm_tpu.telemetry import load_trace

    problems: List[str] = []
    x, y = make_blobs(n=64, d=4, seed=11)

    def base(**kw) -> SVMConfig:
        # epsilon far below float resolution: the run always spends its
        # full max_iter budget, so every attempt's end state is exactly
        # comparable.
        kw.setdefault("c", 1.0)
        kw.setdefault("gamma", 0.5)
        kw.setdefault("epsilon", 1e-12)
        kw.setdefault("max_iter", 300)
        kw.setdefault("chunk_iters", 25)
        return SVMConfig(**kw)

    with tempfile.TemporaryDirectory(dir=tmp_dir) as td:
        ref = train_single_device(x, y, base())
        if ref.n_iter != 300:
            problems.append(f"reference run stopped at {ref.n_iter}, "
                            "expected the full 300-iteration budget")

        # --- injected preemption + supervised resume -----------------
        ck = os.path.join(td, "state.npz")
        trace = os.path.join(td, "trace_preempt.jsonl")
        cfg = base(checkpoint_path=ck, checkpoint_every=50,
                   checkpoint_keep=2)
        faultinject.install(faultinject.FaultPlan(preempt_at_poll=3))
        try:
            def attempt(resume_from, k):
                c = dataclasses.replace(
                    cfg, resume_from=resume_from,
                    trace_out=os.path.join(td, f"trace_a{k}.jsonl"))
                return train_single_device(x, y, c)

            result = run_with_retries(attempt, retries=1, backoff_s=0.0,
                                      checkpoint_path=ck)
        finally:
            faultinject.clear()
        if result.n_iter != ref.n_iter:
            problems.append(f"supervised resume ended at "
                            f"{result.n_iter} != {ref.n_iter}")
        if not np.array_equal(np.asarray(result.alpha),
                              np.asarray(ref.alpha)):
            problems.append("supervised resume alpha is not "
                            "bitwise-identical to the uninterrupted run")
        events = [r["event"] for r in load_trace(
            os.path.join(td, "trace_a0.jsonl")) if r.get("kind") == "event"]
        if "preempt" not in events:
            problems.append(f"attempt 0 trace has no preempt event "
                            f"(events: {events})")
        events1 = [r["event"] for r in load_trace(
            os.path.join(td, "trace_a1.jsonl")) if r.get("kind") == "event"]
        if "retry" not in events1:
            problems.append(f"attempt 1 trace has no retry event "
                            f"(events: {events1})")

        # --- corrupted newest slot -> rotation fallback --------------
        # Bit-flip inside the alpha payload, located by content (a
        # fixed-offset flip can land in dead zip-header bytes).
        from dpsvm_tpu.utils.checkpoint import load_checkpoint
        snap = load_checkpoint(ck)
        raw = bytearray(open(ck, "rb").read())
        payload = np.ascontiguousarray(snap.alpha,
                                       np.float32).tobytes()
        pos = raw.find(payload)
        raw[pos + len(payload) // 2] ^= 0xFF
        with open(ck, "wb") as fh:
            fh.write(bytes(raw))
        trace = os.path.join(td, "trace_fallback.jsonl")
        r2 = train_single_device(x, y, base(resume_from=ck,
                                            trace_out=trace))
        if not np.array_equal(np.asarray(r2.alpha),
                              np.asarray(ref.alpha)):
            problems.append("rotation-slot resume alpha is not "
                            "bitwise-identical to the uninterrupted run")
        ev = [r for r in load_trace(trace) if r.get("kind") == "event"]
        if not any(e["event"] == "rollback" for e in ev):
            problems.append("fallback resume recorded no rollback event")

        # --- kill-one-shard drill: degraded-mesh resume ---------------
        # (needs a multi-device mesh; the __main__ gate forces 4
        # virtual CPU devices, tests/conftest.py forces 8)
        import jax

        from dpsvm_tpu.observability.schema import validate_trace
        from dpsvm_tpu.parallel.dist_smo import train_distributed
        from dpsvm_tpu.resilience import elastic

        p0 = min(4, len(jax.devices()))
        if p0 >= 2:
            ref_mesh = train_distributed(x, y, base(shards=p0))
            ck2 = os.path.join(td, "dist.npz")
            faultinject.install(faultinject.FaultPlan(
                dist_kill_shard=2, dist_kill_poll=3))
            try:
                def dist_attempt(resume_from, shards, k):
                    c = base(shards=shards, checkpoint_path=ck2,
                             checkpoint_every=50, checkpoint_keep=2,
                             resume_from=resume_from,
                             trace_out=os.path.join(
                                 td, f"trace_d{k}.jsonl"))
                    return train_distributed(x, y, c)

                dres = elastic.run_elastic(
                    dist_attempt, shards=p0, retries=1, backoff_s=0.0,
                    checkpoint_path=ck2)
            finally:
                faultinject.clear()
            # Model AGREEMENT across the mesh change is tolerance-
            # pinned (1e-4; observed drift is ulp-class ~1e-6): the
            # survivors' non-power-of-two mesh can tile the kernel
            # d-reduction differently, flipping near-tie selections —
            # the eps-KKT contract of tests/test_dist_decomp.py.
            # Bitwise resume fidelity is pinned by the power-of-two
            # degraded-mesh matrix in tests/test_elastic.py (4 -> 2 ->
            # 1 re-shards land exactly on the uninterrupted run).
            if dres.n_iter != ref_mesh.n_iter:
                problems.append(
                    f"kill-shard drill: resumed run ended at "
                    f"{dres.n_iter} != {ref_mesh.n_iter}")
            if not np.allclose(np.asarray(dres.alpha),
                               np.asarray(ref_mesh.alpha),
                               rtol=0.0, atol=1e-4):
                problems.append(
                    "kill-shard drill: resumed model disagrees with "
                    f"the uninterrupted {p0}-shard run past the 1e-4 "
                    "tolerance")
            d1 = load_trace(os.path.join(td, "trace_d1.jsonl"))
            ev1 = [r["event"] for r in d1 if r.get("kind") == "event"]
            for want in ("retry", "reshard"):
                if want not in ev1:
                    problems.append(f"kill-shard drill: resumed "
                                    f"attempt trace has no {want} "
                                    f"event (events: {ev1})")
            schema_errs = validate_trace(d1)
            if schema_errs:
                problems.append("kill-shard drill: resumed attempt "
                                f"trace fails validation: {schema_errs}")

        # --- kill-one-HOST drill: cross-process reformation ----------
        # (opt-in: real subprocesses, each paying its own jax startup)
        if host_drill:
            from dpsvm_tpu.resilience import hostgroup
            td3 = os.path.join(td, "hostdrill")
            try:
                facts = hostgroup.host_loss_drill(td3)
            except Exception as e:
                problems.append(f"host-loss drill failed: "
                                f"{type(e).__name__}: {e}")
            else:
                if facts.get("host_loss_recovery_s", 0) <= 0:
                    problems.append(
                        "host-loss drill measured no recovery latency "
                        f"(facts: {facts})")
    return problems

"""Host-group supervision: heartbeats, reformation, the admission barrier.

``elastic.py`` owns the IN-process distributed fault model (virtual
device meshes, ``run_elastic``). This module owns the CROSS-process one:
a group of real host processes — one ``dpsvm train --coordinator ...``
per host (parallel/multihost.py) — supervised from outside, because a
host that dies by SIGKILL cannot run any in-process recovery, and its
survivors wedge inside the next gloo/ICI collective waiting for a peer
that will never answer (docs/DISTRIBUTED.md "Multi-host").

Three cooperating pieces:

* **Heartbeat files** — every host appends its liveness fact
  (``host-<id>.json``: n_iter, admitted live generation, pid) to a
  shared directory at each poll boundary, written atomically so a
  reader never sees a torn record. The supervisor and ``dpsvm doctor``
  read ONLY these files — detection never requires a collective on a
  group that may already be wedged.
* **run_host_group** — the reformation supervisor: spawns N localhost
  "hosts" on a fresh coordinator port, watches child exits and
  heartbeat ages, and on a loss kills the wedged survivors, shrinks the
  group to N-1, and relaunches on a NEW port resuming from the newest
  intact checkpoint (the re-shard-on-load path). The resumed attempt's
  trace records ``host_lost`` -> ``reform`` via the env markers below.
* **admission_barrier** — multi-host live ingest (docs/DATA.md "Live
  shard logs"): each host publishes the newest durable manifest
  generation it has OBSERVED, but commits only at the minimum
  generation the whole group has published. A straggler (or a dead
  host) therefore holds everyone at the last common generation — the
  per-host divisor/step-size math (approx/primal.scale_params) can
  never desync across the group.

Env contract (set by the supervisor for its children; absent on a
plain single-host run, where every hook here is a no-op):

* ``DPSVM_HOST_HEARTBEAT_DIR`` — the shared heartbeat directory;
* ``DPSVM_HOST_ID`` / ``DPSVM_HOST_COUNT`` — this host's rank and the
  expected group size (the barrier's membership roll);
* ``DPSVM_HOST_LOST`` / ``DPSVM_REFORM_FROM`` / ``DPSVM_REFORM_TO`` —
  set on a post-loss attempt only; drained into the run trace by
  ``solver/driver.begin_trace`` as the ``host_lost`` and ``reform``
  events.

Fault hooks (resilience/faultinject.py): ``DPSVM_FAULT_HOST_KILL=m``
self-SIGKILLs one host at its m-th poll — the drill's real host death;
``DPSVM_FAULT_HOST_HANG_MS=t`` delays every poll-boundary heartbeat
publish AND every admission poll — the planted straggler. The sleep
sits BEFORE the publish (and before the driver's chunk record, which
follows this hook in the poll loop), so the lag is visible exactly
where a real straggler's would be: a stale heartbeat, a trailing
``host:<k>:n_iter`` lane in the fleet sample, and late chunk records
in the merged trace (observability/merge.py).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

ENV_HEARTBEAT_DIR = "DPSVM_HOST_HEARTBEAT_DIR"
ENV_HOST_ID = "DPSVM_HOST_ID"
ENV_HOST_COUNT = "DPSVM_HOST_COUNT"
ENV_HOST_LOST = "DPSVM_HOST_LOST"
ENV_REFORM_FROM = "DPSVM_REFORM_FROM"
ENV_REFORM_TO = "DPSVM_REFORM_TO"

#: Env markers that must never leak from one attempt (or an enclosing
#: test) into a freshly spawned host — the supervisor owns them.
_MARKER_VARS = (ENV_HOST_LOST, ENV_REFORM_FROM, ENV_REFORM_TO,
                "DPSVM_RETRY_ATTEMPT")
_FAULT_VARS = ("DPSVM_FAULT_HOST_KILL", "DPSVM_FAULT_HOST_HANG_MS")


def _log(msg: str) -> None:
    print(f"hostgroup: {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------
# Heartbeat files.

def heartbeat_path(hb_dir: str, host_id: int) -> str:
    return os.path.join(hb_dir, f"host-{int(host_id)}.json")


def write_heartbeat(hb_dir: str, host_id: int, n_iter: int,
                    generation: int = 0, seq: int = 0) -> None:
    """Atomically publish this host's liveness fact. tmp + rename so a
    concurrent reader (supervisor, doctor, a peer's barrier poll) never
    parses a torn record; the file mtime is the liveness clock, so ages
    work even when writer and reader disagree about wall time.

    ``seq`` is the writer's monotonic publish counter: a reader seeing
    the SAME seq twice knows the host stalled, while a record whose
    wall-clock ``t`` stepped backwards but whose seq advanced is a
    clock adjustment, not a stall — the distinction ``dpsvm doctor
    --hosts-dir`` and the fleet federation layer report
    (docs/OBSERVABILITY.md "Fleet")."""
    os.makedirs(hb_dir, exist_ok=True)
    path = heartbeat_path(hb_dir, host_id)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump({"host_id": int(host_id), "n_iter": int(n_iter),
                   "generation": int(generation), "seq": int(seq),
                   "t": time.time(), "pid": os.getpid()}, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def read_heartbeats(hb_dir: str) -> Dict[int, dict]:
    """All parseable heartbeat records, keyed by host id. Torn or alien
    files are skipped, never raised — reporting must survive exactly
    the failures it reports on."""
    out: Dict[int, dict] = {}
    try:
        names = os.listdir(hb_dir)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("host-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(hb_dir, name)) as fh:
                rec = json.load(fh)
            out[int(rec["host_id"])] = rec
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def heartbeat_ages(hb_dir: str,
                   now: Optional[float] = None) -> Dict[int, float]:
    """Seconds since each host's last heartbeat write (file mtime — see
    ``write_heartbeat``). A host with no file yet has no entry."""
    now = time.time() if now is None else now
    ages: Dict[int, float] = {}
    for hid in read_heartbeats(hb_dir):
        try:
            ages[hid] = max(0.0, now - os.path.getmtime(
                heartbeat_path(hb_dir, hid)))
        except OSError:
            continue
    return ages


# ---------------------------------------------------------------------
# In-host hooks (driver poll loop, live-ingest admission).

#: This host's last published facts — n_iter from the driver poll,
#: generation from the admission barrier, seq counting every publish
#: — merged so either writer emits the full record.
_STATE = {"n_iter": 0, "generation": 0, "seq": 0}


def _fault_hang() -> None:
    """The planted-straggler sleep (``DPSVM_FAULT_HOST_HANG_MS``),
    applied before a heartbeat publish so the lag lands where a real
    straggler's would: stale heartbeat, trailing fleet lane, late
    chunk records."""
    hang_ms = os.environ.get("DPSVM_FAULT_HOST_HANG_MS", "").strip()
    if hang_ms.isdigit() and int(hang_ms):
        time.sleep(int(hang_ms) / 1000.0)


def _group() -> Optional[tuple]:
    """(heartbeat_dir, host_id, host_count) when this process runs
    inside a supervised host group, else None. Read from env on every
    call — the polls are chunk-cadence, the reads are nanoseconds, and
    tests monkeypatch the env."""
    hb_dir = os.environ.get(ENV_HEARTBEAT_DIR, "").strip()
    if not hb_dir:
        return None
    try:
        hid = int(os.environ.get(ENV_HOST_ID, "0") or 0)
        count = int(os.environ.get(ENV_HOST_COUNT, "1") or 1)
    except ValueError:
        return None
    return hb_dir, hid, count


def note_poll_heartbeat(n_iter: int) -> None:
    """Driver poll-boundary hook: publish liveness. No-op outside a
    host group; never raises (a full disk must not kill training —
    the supervisor sees the growing age instead)."""
    grp = _group()
    if grp is None:
        return
    hb_dir, hid, _ = grp
    _fault_hang()
    _STATE["n_iter"] = int(n_iter)
    _STATE["seq"] = _STATE.get("seq", 0) + 1
    try:
        write_heartbeat(hb_dir, hid, _STATE["n_iter"],
                        _STATE["generation"], _STATE["seq"])
    except OSError as e:
        _log(f"heartbeat write failed ({e}); continuing")


def admission_barrier(observed_gen: int, committed_gen: int) -> int:
    """Generation this host may COMMIT, given it has durably OBSERVED
    ``observed_gen`` and already consumed ``committed_gen``.

    Outside a host group: identity (``observed_gen``) — the single-host
    live path is untouched. Inside one: publish ``observed_gen`` in the
    heartbeat, read the whole group's published generations, and return
    the group minimum (floored at ``committed_gen`` so the answer never
    moves backwards). A member with no heartbeat yet — still compiling,
    hung, or dead — holds the group at ``committed_gen``: nobody trains
    on rows a peer has not admitted, which is the invariant the shared
    divisor/step-size math needs (docs/DISTRIBUTED.md "Multi-host").

    The planted straggler (``DPSVM_FAULT_HOST_HANG_MS``) sleeps BEFORE
    publishing, so its lag is visible to the group as a stale
    generation and a growing heartbeat age — a doctor/watch fact, not a
    wedge."""
    grp = _group()
    if grp is None:
        return int(observed_gen)
    hb_dir, hid, count = grp
    _fault_hang()
    _STATE["generation"] = max(_STATE["generation"], int(observed_gen))
    _STATE["seq"] = _STATE.get("seq", 0) + 1
    try:
        write_heartbeat(hb_dir, hid, _STATE["n_iter"],
                        _STATE["generation"], _STATE["seq"])
    except OSError as e:
        _log(f"heartbeat write failed ({e}); holding admission")
        return int(committed_gen)
    beats = read_heartbeats(hb_dir)
    gens: List[int] = []
    for k in range(count):
        rec = beats.get(k)
        if rec is None:
            return int(committed_gen)
        gens.append(int(rec.get("generation", 0)))
    return max(int(committed_gen), min(gens))


# ---------------------------------------------------------------------
# The reformation supervisor.

class HostGroupError(RuntimeError):
    """The group died in a way reformation cannot fix: a non-transient
    child exit, or the retry/min-host budget ran out."""


@dataclass
class HostGroupResult:
    """What a supervised run did: how many attempts, the final group
    size, which hosts were lost (in order), and the measured
    detection -> reformed-and-beating latency of the LAST loss."""
    attempts: int
    hosts: int
    losses: List[int] = field(default_factory=list)
    recovery_s: float = 0.0


def _clean_child_env(base: Dict[str, str]) -> Dict[str, str]:
    env = dict(base)
    for k in _MARKER_VARS + _FAULT_VARS:
        env.pop(k, None)
    return env


def _kill_group(procs: Dict[int, subprocess.Popen],
                grace_s: float) -> None:
    """SIGTERM the still-running children, give them ``grace_s`` to
    die, then SIGKILL the rest. Survivors of a host loss are wedged
    inside a collective — SIGTERM alone often cannot reach them."""
    for p in procs.values():
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.time() + grace_s
    for p in procs.values():
        while p.poll() is None and time.time() < deadline:
            time.sleep(0.05)
        if p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass
            p.wait()


def run_host_group(
    make_argv: Callable[[int, int, str, int], Sequence[str]],
    *,
    num_hosts: int,
    heartbeat_dir: str,
    checkpoint_path: Optional[str] = None,
    retries: int = 1,
    deadline_s: float = 60.0,
    min_hosts: int = 1,
    poll_s: float = 0.2,
    grace_s: float = 5.0,
    env_base: Optional[Dict[str, str]] = None,
    first_attempt_env: Optional[Dict[int, Dict[str, str]]] = None,
) -> HostGroupResult:
    """Spawn and supervise a localhost host group; reform on loss.

    ``make_argv(host_id, hosts, coordinator, attempt)`` builds one
    host's command line (typically ``dpsvm train --coordinator ...``).
    Each attempt gets a FRESH coordinator port — the old coordinator
    died with host 0's process group — and, when ``checkpoint_path``
    has an intact slot, ``--resume`` injected exactly like the retry
    supervisor (resilience/supervisor.py). ``first_attempt_env`` plants
    per-host fault env (the drill's ``DPSVM_FAULT_HOST_KILL``) on
    attempt 0 ONLY, so a reformed group cannot re-inherit its own
    death.

    Loss detection is two-channel and collective-free: a child exiting
    with a transient code/signal (supervisor.TRANSIENT_*), or a
    heartbeat older than ``deadline_s`` (a hang — the SIGKILLed-peer
    wedge looks like this on the survivors). The wedged survivors are
    killed (SIGTERM, ``grace_s``, SIGKILL), the group shrinks by the
    one lost host, and the next attempt's env carries the
    ``host_lost``/``reform`` trace markers. ``recovery_s`` measures
    detection -> every reformed host's first heartbeat."""
    from dpsvm_tpu.parallel import multihost
    from dpsvm_tpu.resilience import supervisor

    if num_hosts < 1:
        raise ValueError("num_hosts must be >= 1")
    hosts = int(num_hosts)
    attempt = 0
    losses: List[int] = []
    recovery_s = 0.0
    detection_t: Optional[float] = None
    base_env = _clean_child_env(
        dict(os.environ) if env_base is None else dict(env_base))

    while True:
        port = multihost.find_free_port()
        coordinator = f"127.0.0.1:{port}"
        os.makedirs(heartbeat_dir, exist_ok=True)
        for name in os.listdir(heartbeat_dir):
            if name.startswith("host-"):
                try:
                    os.unlink(os.path.join(heartbeat_dir, name))
                except OSError:
                    pass
        best, skipped = supervisor.newest_intact(checkpoint_path)
        if skipped and best:
            _log(f"skipping unreadable checkpoint slot(s) {skipped} "
                 f"-> resuming {best}")
        procs: Dict[int, subprocess.Popen] = {}
        spawn_t = time.time()
        for hid in range(hosts):
            env = multihost.local_host_env(hid, base=base_env)
            env[ENV_HEARTBEAT_DIR] = heartbeat_dir
            env[ENV_HOST_COUNT] = str(hosts)
            if attempt:
                env["DPSVM_RETRY_ATTEMPT"] = str(attempt)
                env[ENV_HOST_LOST] = str(losses[-1])
                env[ENV_REFORM_FROM] = str(hosts + 1)
                env[ENV_REFORM_TO] = str(hosts)
            elif first_attempt_env and hid in first_attempt_env:
                env.update(first_attempt_env[hid])
            argv = list(make_argv(hid, hosts, coordinator, attempt))
            if best:
                argv = supervisor.with_resume(argv, best)
            procs[hid] = subprocess.Popen(argv, env=env)
        if attempt:
            _log(f"attempt {attempt}: reformed to {hosts} host(s) on "
                 f"{coordinator}"
                 + (f", resuming {best}" if best else ""))

        lost: Optional[int] = None
        lost_reason = ""
        beating: set = set()
        while True:
            time.sleep(poll_s)
            now = time.time()
            rcs = {hid: p.poll() for hid, p in procs.items()}
            exited_bad = {hid: rc for hid, rc in rcs.items()
                          if rc is not None and rc != 0}
            if exited_bad:
                # A SIGKILLed host's gloo peers die within milliseconds
                # of it (connection reset inside the wedged collective)
                # with ORDINARY error exits, so one poll sample can
                # show several corpses: the TRANSIENT death (signal /
                # preempt code) is the root cause, the rest are
                # collateral. Only an all-non-transient group is a
                # real command failure.
                transient = {h: rc for h, rc in exited_bad.items()
                             if supervisor.is_transient(rc)}
                if not transient:
                    lost, rc = sorted(exited_bad.items())[0]
                    _kill_group(procs, grace_s)
                    raise HostGroupError(
                        f"host {lost} exited {rc} (non-transient) on "
                        f"attempt {attempt}")
                lost, rc = sorted(transient.items())[0]
                lost_reason = f"exit {rc}"
                break
            # recovery_s: the reformed group is "back" when every host
            # has published a heartbeat under the new attempt.
            ages = heartbeat_ages(heartbeat_dir, now=now)
            beating |= set(ages)
            if (detection_t is not None
                    and len(beating) >= len(procs)):
                recovery_s = now - detection_t
                detection_t = None
                _log(f"recovered: all {hosts} host(s) beating "
                     f"{recovery_s:.2f}s after loss detection")
            if all(rc == 0 for rc in rcs.values()):
                return HostGroupResult(attempts=attempt + 1,
                                       hosts=hosts, losses=losses,
                                       recovery_s=recovery_s)
            # Hang channel: a host whose last heartbeat (or spawn, if
            # it never beat) is older than the deadline.
            for hid, p in procs.items():
                if rcs[hid] is not None:
                    continue
                age = ages.get(hid, now - spawn_t)
                if age > deadline_s:
                    lost = hid
                    lost_reason = f"heartbeat {age:.1f}s old"
                    break
            if lost is not None:
                break

        detection_t = time.time()
        _log(f"host {lost} lost ({lost_reason}); killing the wedged "
             f"survivors")
        _kill_group(procs, grace_s)
        if hosts - 1 < min_hosts:
            raise HostGroupError(
                f"host {lost} lost but the group cannot shrink below "
                f"min_hosts={min_hosts}")
        if attempt >= retries:
            raise HostGroupError(
                f"host {lost} lost but the retry budget ({retries}) "
                f"is exhausted")
        losses.append(int(lost))
        hosts -= 1
        attempt += 1


# ---------------------------------------------------------------------
# The kill-one-host drill.

def host_loss_drill(tmp_dir: str, *, num_hosts: int = 3,
                    kill_host: int = 1, kill_poll: int = 3,
                    deadline_s: float = 120.0) -> dict:
    """End-to-end host-loss recovery on localhost CPU: train dist-smo
    across ``num_hosts`` REAL single-device host processes, SIGKILL one
    mid-run (``DPSVM_FAULT_HOST_KILL``), and require the survivors to
    reform and land on the uninterrupted group's model.

    Returns the drill facts (for the perf ledger / burst runner):
    ``host_loss_recovery_s``, the model deltas, attempts, events.
    Raises on any failed expectation — callers (resilience selfcheck,
    ``--host-drill``, tests) get a hard gate, not a report to parse.

    Tolerance contract: the survivors' mesh differs from the reference
    mesh, so agreement is pinned at 1e-4 (the same eps-KKT argument as
    the kill-shard drill); bitwise agreement, when the tilings happen
    to coincide, is reported in the result as ``bitwise``.
    """
    import numpy as np

    from dpsvm_tpu.data.synthetic import make_blobs
    from dpsvm_tpu.models.io import load_model
    from dpsvm_tpu.telemetry import load_trace
    from dpsvm_tpu.observability.schema import validate_trace
    from dpsvm_tpu.parallel import multihost

    tmp = os.path.abspath(tmp_dir)
    os.makedirs(tmp, exist_ok=True)
    x, y = make_blobs(n=64, d=4, seed=11)
    data = os.path.join(tmp, "drill.csv")
    with open(data, "w") as fh:
        for row, label in zip(x, y):
            fh.write(f"{int(label)}," +
                     ",".join(f"{v:.9g}" for v in row) + "\n")

    def train_argv(model: str, shards: int, trace: str,
                   extra: Sequence[str] = ()) -> List[str]:
        return [sys.executable, "-m", "dpsvm_tpu.cli", "train",
                "-f", data, "-m", model, "--shards", str(shards),
                "-c", "1.0", "-g", "0.5", "-e", "1e-12", "-n", "300",
                "--chunk-iters", "25", "--no-tuned", "--quiet",
                "--trace-out", trace, *extra]

    # Uninterrupted reference: the same group size, virtual devices in
    # ONE process (proven bitwise-identical to the real multi-process
    # run by tests/test_multihost.py).
    ref_model = os.path.join(tmp, "model_ref.txt")
    ref_env = multihost.local_host_env(0)
    flags = [f for f in ref_env["XLA_FLAGS"].split()
             if "xla_force_host_platform_device_count" not in f]
    ref_env["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={num_hosts}"])
    ref_env.pop(ENV_HEARTBEAT_DIR, None)
    subprocess.run(train_argv(ref_model, num_hosts,
                              os.path.join(tmp, "trace_ref.jsonl")),
                   env=_clean_child_env(ref_env), check=True,
                   timeout=deadline_s)

    ck = os.path.join(tmp, "group.npz")
    hb_dir = os.path.join(tmp, "heartbeats")

    def make_argv(hid: int, hosts: int, coordinator: str,
                  attempt: int) -> List[str]:
        return train_argv(
            os.path.join(tmp, f"model_h{hid}_a{attempt}.txt"), hosts,
            os.path.join(tmp, f"trace_h{hid}_a{attempt}.jsonl"),
            extra=["--coordinator", coordinator,
                   "--num-hosts", str(hosts), "--host-id", str(hid),
                   "--checkpoint", ck, "--checkpoint-every", "50",
                   "--checkpoint-keep", "2"])

    t0 = time.time()
    res = run_host_group(
        make_argv, num_hosts=num_hosts, heartbeat_dir=hb_dir,
        checkpoint_path=ck, retries=1, deadline_s=30.0,
        first_attempt_env={int(kill_host): {
            "DPSVM_FAULT_HOST_KILL": str(int(kill_poll))}})
    wall_s = time.time() - t0

    if res.losses != [int(kill_host)]:
        raise AssertionError(
            f"drill expected host {kill_host} lost, got {res.losses}")
    if res.hosts != num_hosts - 1:
        raise AssertionError(
            f"drill expected a reformed {num_hosts - 1}-host group, "
            f"got {res.hosts}")

    ref = load_model(ref_model)
    got = load_model(os.path.join(tmp, "model_h0_a1.txt"))
    if ref.alpha.shape != got.alpha.shape:
        raise AssertionError(
            f"drill: recovered SV set differs in size "
            f"({got.alpha.shape} vs {ref.alpha.shape})")
    coef_delta = float(np.max(np.abs(
        np.asarray(ref.alpha) * np.asarray(ref.y_sv)
        - np.asarray(got.alpha) * np.asarray(got.y_sv))))
    b_delta = float(abs(float(ref.b) - float(got.b)))
    if coef_delta > 1e-4 or b_delta > 1e-4:
        raise AssertionError(
            f"drill: recovered model disagrees with the uninterrupted "
            f"{num_hosts}-host run (coef delta {coef_delta:g}, b delta "
            f"{b_delta:g}, tolerance 1e-4)")
    bitwise = bool(coef_delta == 0.0 and b_delta == 0.0
                   and np.array_equal(np.asarray(ref.x_sv),
                                      np.asarray(got.x_sv)))

    # The reformed attempt's trace must carry the recovery story and
    # stay schema-valid: host_lost -> reform -> (reshard) -> resume.
    trace = load_trace(os.path.join(tmp, "trace_h0_a1.jsonl"))
    events = [r["event"] for r in trace if r.get("kind") == "event"]
    for want in ("host_lost", "reform"):
        if want not in events:
            raise AssertionError(
                f"drill: reformed trace missing {want} "
                f"(events: {events})")
    if events.index("host_lost") > events.index("reform"):
        raise AssertionError(
            f"drill: host_lost must precede reform (events: {events})")
    errs = validate_trace(trace)
    if errs:
        raise AssertionError(
            f"drill: reformed trace fails schema validation: {errs}")

    facts = {
        "metric": "host_loss_recovery_s",
        "host_loss_recovery_s": round(res.recovery_s, 3),
        "drill_wall_s": round(wall_s, 3),
        "hosts": num_hosts,
        "surviving_hosts": res.hosts,
        "losses": res.losses,
        "attempts": res.attempts,
        "coef_delta": coef_delta,
        "b_delta": b_delta,
        "bitwise": bitwise,
    }
    # Perf-ledger row (observability/ledger.py; DPSVM_PERF_LEDGER=""
    # disables): recovery latency is a gated robustness metric —
    # regressions in detection or reformation show up in `dpsvm perf`
    # exactly like a throughput drop.
    from dpsvm_tpu.observability import ledger
    ledger.append("host_loss_drill", facts, kind="robust",
                  value=facts["host_loss_recovery_s"], unit="s",
                  direction="lower", host_count=num_hosts)
    return facts


# ---------------------------------------------------------------------
# The planted-straggler drill (the fleet observability acceptance).

def straggler_drill(tmp_dir: str, *, num_hosts: int = 3,
                    slow_host: int = 1, hang_ms: int = 400,
                    deadline_s: float = 240.0) -> dict:
    """End-to-end straggler attribution on localhost CPU: train
    dist-smo across ``num_hosts`` real host processes with
    ``DPSVM_FAULT_HOST_HANG_MS`` planted on ``slow_host``, let the run
    COMPLETE (a straggler is a slow member, not a dead one — the
    supervisor must not reform), then require the whole fleet
    observability plane to name the culprit:

    1. the per-host trace family merges (observability/merge.py) into
       a schema-valid fleet trace whose lane digest attributes the
       straggler to ``slow_host`` and leaves the other lanes clean;
    2. a ``skew`` rule replayed over the merged trace fires
       ``skew[host-K]`` naming ``slow_host`` and CLEARS once progress
       drains to a common front;
    3. the hosts' ``--metrics-out`` sidecars federate (``dpsvm
       fleet``) into an exposition that passes validate_exposition;
    4. a fleet incident bundle carries every host's heartbeat, trace
       tail and doctor line, passes validate_bundle, and its incident
       names the host.

    Raises AssertionError on any failed expectation; returns the drill
    facts (ledger row ``straggler_drill``, kind="robust")."""
    from dpsvm_tpu.data.synthetic import make_blobs
    from dpsvm_tpu.observability import blackbox, fleet, merge
    from dpsvm_tpu.observability.report import (host_lanes,
                                                render_report)
    from dpsvm_tpu.observability.schema import validate_trace
    from dpsvm_tpu.observability.slo import Watchtower

    tmp = os.path.abspath(tmp_dir)
    os.makedirs(tmp, exist_ok=True)
    x, y = make_blobs(n=64, d=4, seed=11)
    data = os.path.join(tmp, "drill.csv")
    with open(data, "w") as fh:
        for row, label in zip(x, y):
            fh.write(f"{int(label)}," +
                     ",".join(f"{v:.9g}" for v in row) + "\n")
    hb_dir = os.path.join(tmp, "heartbeats")
    metrics_paths = {hid: os.path.join(tmp, f"metrics_h{hid}.prom")
                     for hid in range(num_hosts)}

    def make_argv(hid: int, hosts: int, coordinator: str,
                  attempt: int) -> List[str]:
        return [sys.executable, "-m", "dpsvm_tpu.cli", "train",
                "-f", data,
                "-m", os.path.join(tmp, f"model_h{hid}_a{attempt}.txt"),
                "--shards", str(hosts),
                "-c", "1.0", "-g", "0.5", "-e", "1e-12", "-n", "300",
                "--chunk-iters", "25", "--no-tuned", "--quiet",
                "--trace-out",
                os.path.join(tmp, f"trace_h{hid}_a{attempt}.jsonl"),
                "--metrics-out", metrics_paths[hid],
                "--coordinator", coordinator,
                "--num-hosts", str(hosts), "--host-id", str(hid)]

    t0 = time.time()
    res = run_host_group(
        make_argv, num_hosts=num_hosts, heartbeat_dir=hb_dir,
        retries=0, deadline_s=max(30.0, 100.0 * hang_ms / 1000.0),
        first_attempt_env={int(slow_host): {
            "DPSVM_FAULT_HOST_HANG_MS": str(int(hang_ms))}})
    wall_s = time.time() - t0
    if res.losses or res.hosts != num_hosts:
        raise AssertionError(
            f"straggler drill must complete without a reformation, "
            f"got losses={res.losses} hosts={res.hosts}")

    # 1. merge + lane attribution
    merged = merge.merge_dir(tmp)
    errs = validate_trace(merged)
    if errs:
        raise AssertionError(
            f"drill: merged trace fails schema validation: {errs}")
    merged_path = merge.write_merged(
        merged, os.path.join(tmp, "trace_fleet.jsonl"))
    lanes = host_lanes(merged)
    if lanes is None or lanes.get("straggler") != int(slow_host):
        raise AssertionError(
            f"drill: merged lanes did not attribute the straggler to "
            f"host {slow_host}: {lanes and lanes.get('straggler')}")
    by_host = {h["host"]: h for h in lanes["hosts"]}
    slow_behind = float(by_host[int(slow_host)]["behind_s"] or 0.0)
    for h, lane in by_host.items():
        if h == int(slow_host):
            continue
        if float(lane["behind_s"] or 0.0) >= max(0.5 * slow_behind,
                                                 hang_ms / 2000.0):
            raise AssertionError(
                f"drill: host {h}'s lane is not clean "
                f"(behind {lane['behind_s']}s vs straggler "
                f"{slow_behind}s)")
    report_text = render_report(merged)
    if f"straggler: host {slow_host}" not in report_text:
        raise AssertionError(
            f"drill: report does not name host {slow_host}:\n"
            f"{report_text}")

    # 2. skew replay over the merged trace: per-host n_iter lanes fed
    # in fleet-time order, then a synthetic drain (every host at the
    # common final front) to pin the CLEAR transition.
    chunks = [r for r in merged
              if r.get("kind") == "chunk"
              and isinstance(r.get("host"), int)]
    span = max(r["t"] for r in chunks) - min(r["t"] for r in chunks)
    window_s = max(0.5, 0.25 * span)
    tower = Watchtower([
        {"name": "iteration-skew", "kind": "skew", "severity": "warn",
         "metric": "n_iter", "window_s": window_s,
         "lag_above": 10.0, "clear_after_s": window_s / 2}])
    latest: Dict[int, float] = {}
    transitions: List[dict] = []
    for rec in chunks:
        latest[rec["host"]] = float(rec["n_iter"])
        transitions += tower.observe(
            {f"host:{k}:n_iter": v for k, v in latest.items()},
            t=float(rec["t"]))
    t_end = max(r["t"] for r in chunks)
    front = max(latest.values())
    drain = {f"host:{k}:n_iter": front for k in latest}
    step = 0.1
    t_drain = t_end
    while t_drain < t_end + 2.0 * window_s + 1.0:
        t_drain += step
        transitions += tower.observe(drain, t=t_drain)
    fired = [tr for tr in transitions if tr["state"] == "firing"
             and tr["rule"] == "iteration-skew"]
    cleared = [tr for tr in transitions if tr["state"] == "ok"
               and tr["rule"] == "iteration-skew"]
    if not fired or fired[0].get("host") != int(slow_host) \
            or f"skew[host-{slow_host}]" not in fired[0]["reason"]:
        raise AssertionError(
            f"drill: skew[host-{slow_host}] did not fire "
            f"(transitions: {transitions})")
    if not cleared:
        raise AssertionError(
            "drill: skew did not clear on drain "
            f"(transitions: {transitions})")

    # 3. metrics federation from the per-host sidecars
    from dpsvm_tpu.observability.metrics import validate_exposition
    state = fleet.collect({h: p for h, p in metrics_paths.items()
                           if os.path.exists(p)})
    if len(state) != num_hosts:
        raise AssertionError(
            f"drill: expected {num_hosts} metrics sidecars, got "
            f"{sorted(state)}")
    snap = fleet.federate(state,
                          heartbeats=fleet.read_heartbeats(hb_dir))
    expo = fleet.render_exposition(snap)
    expo_errs = validate_exposition(expo)
    if expo_errs:
        raise AssertionError(
            f"drill: federated exposition invalid: {expo_errs}")

    # 4. the fleet incident bundle
    arts = fleet.host_artifacts(tmp, hb_dir)
    if sorted(arts) != list(range(num_hosts)):
        raise AssertionError(
            f"drill: expected artifacts for hosts "
            f"{list(range(num_hosts))}, got {sorted(arts)}")
    recorder = blackbox.FlightRecorder(
        blackbox.make_manifest(solver="dist-smo"))
    recorder.event("skew", n_iter=int(front),
                   host=int(slow_host))
    bundle_dir = os.path.join(tmp, "bundles")
    bundle = blackbox.dump_bundle(
        bundle_dir, recorder=recorder, rule="iteration-skew",
        severity="warn", window=f"{window_s:g}s",
        reason=fired[0]["reason"],
        extra={"extra": {"host": int(slow_host),
                         "merged_trace":
                         os.path.basename(merged_path)}},
        host_artifacts=arts)
    problems = blackbox.validate_bundle(bundle)
    if problems:
        raise AssertionError(
            f"drill: fleet bundle invalid: {problems}")
    with open(os.path.join(bundle, "incident.json")) as fh:
        incident = json.load(fh)
    if incident.get("extra", {}).get("host") != int(slow_host) \
            or f"skew[host-{slow_host}]" not in str(
                incident.get("reason")):
        raise AssertionError(
            f"drill: bundle incident does not name host {slow_host}")

    facts = {
        "metric": "straggler_behind_s",
        "straggler_behind_s": round(slow_behind, 3),
        "drill_wall_s": round(wall_s, 3),
        "hosts": num_hosts,
        "straggler": int(slow_host),
        "hang_ms": int(hang_ms),
        "skew_fired": len(fired),
        "bundle": bundle,
    }
    from dpsvm_tpu.observability import ledger
    ledger.append("straggler_drill", facts, kind="robust",
                  value=facts["straggler_behind_s"], unit="s",
                  direction="lower", host_count=num_hosts)
    return facts

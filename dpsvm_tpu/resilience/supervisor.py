"""Resumable retry supervisor: re-launch training after transient death.

Recovery from a preemption/stall used to be a human re-typing the
command with ``--resume`` — on preemptible fleets that is an operator
pager, not a failure policy. The supervisor automates exactly that
loop, in two forms:

* ``supervise(argv, ...)`` — subprocess mode, what ``dpsvm train
  --retries N --retry-backoff S`` runs. Every attempt is a child
  process, so it recovers from ALL transient deaths including the stall
  watchdog's ``os._exit(124)`` (utils/watchdog.py) and a real SIGTERM
  preemption (exit 75, resilience/preempt.py). Before EVERY attempt —
  including the first — the newest intact rotation slot of
  ``checkpoint_path`` is injected as ``--resume``, which makes the
  supervised command idempotent across repeated preemptions: re-running
  it always continues from the latest surviving state.
* ``run_with_retries(fn, ...)`` — in-process mode for API users and the
  selfcheck: retries ``fn`` on ``PreemptedError`` (a watchdog kill
  cannot be caught in-process — use subprocess mode for that).

Each retry waits ``backoff_s * 2**attempt`` and is recorded as a
``retry`` trace event in the next attempt's run trace (the driver picks
the attempt number up from ``DPSVM_RETRY_ATTEMPT`` / the in-process
event queue), so ``dpsvm report`` shows the full recovery history.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence

from dpsvm_tpu.resilience.preempt import PREEMPT_EXIT_CODE, PreemptedError

#: Exit codes worth retrying: 75 = preemption snapshot (preempt.py),
#: 124 = stall watchdog / timeout(1) kill (utils/watchdog.py). Anything
#: else — config errors, real crashes — fails fast.
TRANSIENT_EXIT_CODES = frozenset({PREEMPT_EXIT_CODE, 124})

#: Negative returncodes subprocess reports for signal deaths that mean
#: "the host was going away", i.e. resumable: SIGTERM(15), SIGKILL(9),
#: SIGHUP(1). A SIGTERM that lands before (or despite) the in-process
#: snapshot handler still counts as transient — the checkpoint rotation
#: slots hold whatever was last saved.
TRANSIENT_SIGNALS = frozenset({-15, -9, -1})


def _log(msg: str) -> None:
    print(f"supervisor: {msg}", file=sys.stderr, flush=True)


def is_transient(rc: int) -> bool:
    return rc in TRANSIENT_EXIT_CODES or rc in TRANSIENT_SIGNALS


def strip_flags(argv: Sequence[str], names: Sequence[str]) -> List[str]:
    """Remove ``--flag value`` / ``--flag=value`` occurrences — used to
    peel the supervisor's own flags off the re-launched command."""
    out: List[str] = []
    skip = False
    for a in argv:
        if skip:
            skip = False
            continue
        if a in names:
            skip = True
            continue
        if any(a.startswith(n + "=") for n in names):
            continue
        out.append(a)
    return out


def with_resume(argv: Sequence[str], resume_path: str) -> List[str]:
    """argv with any existing ``--resume X`` replaced by the given
    checkpoint."""
    return strip_flags(argv, ("--resume",)) + ["--resume", resume_path]


def newest_intact(checkpoint_path: Optional[str]
                  ) -> "tuple[Optional[str], List[str]]":
    """Newest loadable rotation slot (+ the corrupt/missing ones it
    skipped). Thin re-export so callers need only this module.

    Mesh note: a slot recorded under a different mesh size is INTACT —
    never skipped as corrupt or rolled past to an older slot. Resuming
    it on the current mesh is the elastic re-shard path
    (solver/driver.resume_state records the ``reshard`` event); the
    supervisor just logs what the slot was saved under."""
    if not checkpoint_path:
        return None, []
    from dpsvm_tpu.utils.checkpoint import (load_checkpoint,
                                            newest_intact_checkpoint)
    best, skipped = newest_intact_checkpoint(checkpoint_path)
    if best:
        try:
            ck = load_checkpoint(best)
            if int(getattr(ck, "shards", 1)) != 1:
                _log(f"{best} was saved on a {ck.mesh_desc()} "
                     f"(iter {ck.n_iter}); a different current mesh "
                     "re-shards on load")
        except Exception:
            pass                      # the resume path re-reports
    return best, skipped


def supervise(argv: Sequence[str], *, retries: int,
              backoff_s: float = 5.0,
              checkpoint_path: Optional[str] = None,
              env: Optional[dict] = None,
              call: Callable[..., int] = subprocess.call,
              sleep: Callable[[float], None] = time.sleep) -> int:
    """Run ``argv`` as a child process, re-launching from the newest
    intact checkpoint after transient exits. Returns the final exit
    code (0, the last transient code when retries ran out, or the first
    non-transient code)."""
    attempt = 0
    while True:
        cmd = list(argv)
        best, skipped = newest_intact(checkpoint_path)
        if skipped and best:
            _log(f"skipping unreadable checkpoint slot(s) "
                 f"{skipped} -> resuming {best}")
        if best:
            cmd = with_resume(cmd, best)
            _log(f"attempt {attempt + 1}: resuming from {best}")
        elif attempt:
            _log(f"attempt {attempt + 1}: no intact checkpoint — "
                 "restarting from scratch")
        child_env = dict(os.environ if env is None else env)
        if attempt:
            # The next attempt's run trace records this as a `retry`
            # event (solver/driver.begin_trace).
            child_env["DPSVM_RETRY_ATTEMPT"] = str(attempt)
        rc = call(cmd, env=child_env)
        if rc == 0 or not is_transient(rc) or attempt >= retries:
            if rc and is_transient(rc):
                _log(f"transient exit {rc} but retry budget "
                     f"({retries}) exhausted")
            return rc
        delay = backoff_s * (2 ** attempt)
        attempt += 1
        _log(f"transient exit {rc}; retry {attempt}/{retries} "
             f"in {delay:.1f}s")
        if delay > 0:
            sleep(delay)


def run_with_retries(fn: Callable[[Optional[str], int], object], *,
                     retries: int, backoff_s: float = 5.0,
                     checkpoint_path: Optional[str] = None,
                     sleep: Callable[[float], None] = time.sleep):
    """In-process supervisor: ``fn(resume_from, attempt)`` is called
    with the newest intact checkpoint (None on a cold start) and
    retried on ``PreemptedError`` with exponential backoff."""
    attempt = 0
    while True:
        resume, skipped = newest_intact(checkpoint_path)
        if skipped and resume:
            _log(f"skipping unreadable checkpoint slot(s) "
                 f"{skipped} -> resuming {resume}")
        if attempt:
            # Queue the retry marker for the attempt's run trace.
            from dpsvm_tpu.solver import driver
            driver.queue_trace_event("retry", attempt=attempt,
                                     resumed_from=resume)
        try:
            return fn(resume, attempt)
        except PreemptedError as e:
            if attempt >= retries:
                raise
            delay = backoff_s * (2 ** attempt)
            attempt += 1
            _log(f"preempted at iter {e.n_iter}; retry "
                 f"{attempt}/{retries} in {delay:.1f}s")
            if delay > 0:
                sleep(delay)

"""``dpsvm doctor``: is the cluster sane before burning an hour?

"Parallel SVMs in Practice" (arXiv:1404.1066) observes that most
wasted cluster time is spent discovering *environmental* failures —
dead devices, hung interconnects, unwritable storage — an hour into a
job instead of a second before it. The doctor is that second: a
preflight that exercises exactly the three things a distributed
training run depends on, each with a bounded wait, and exits non-zero
with a one-line diagnosis.

1. **Topology** — backend reachable within ``--timeout`` (the
   tunneled-TPU hang is the motivating failure: utils/backend_guard),
   device/mesh/process facts printed (parallel/multihost.topology).
2. **Collective probe** — a tiny ``shard_map`` psum over the requested
   mesh, run in a worker thread with a deadline: a hung ICI/DCN link
   or a wedged device surfaces here in seconds, not after the first
   real chunk. The probe result is also checked for correctness
   (psum of ones == P) — a wrong answer is a worse sign than a hang.
3. **Checkpoint health** — directory writability (create + remove a
   probe file) and newest-slot integrity: the rotation set is scanned
   exactly like a resume would (``newest_intact_checkpoint``), and the
   newest intact slot's recorded mesh/iteration are reported so the
   operator knows what a restart would resume (a mesh different from
   ``--shards`` is reported as a pending re-shard, not an error —
   docs/DISTRIBUTED.md "Elastic training").
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Callable, List, Optional, Tuple


def _collective_probe(shards: int, timeout_s: float
                      ) -> Tuple[bool, str]:
    """psum(ones) over a ``shards``-device mesh with a deadline.
    Returns (ok, detail). Runs in a daemon worker so a hung collective
    cannot wedge the doctor past its budget."""
    result: dict = {}

    def work():
        try:
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P

            from dpsvm_tpu.parallel.mesh import (SHARD_AXIS,
                                                 make_data_mesh,
                                                 shard_map_compat)

            mesh = make_data_mesh(shards)
            probe = shard_map_compat(
                lambda v: lax.psum(jnp.sum(v), SHARD_AXIS),
                mesh=mesh, in_specs=(P(SHARD_AXIS),), out_specs=P())
            got = float(jax.jit(probe)(jnp.ones((shards,))))
            result["got"] = got
        except Exception as e:
            result["err"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=work, daemon=True,
                         name="dpsvm-doctor-collective")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return False, (f"collective probe TIMED OUT after {timeout_s:g}s "
                       f"on a {shards}-device mesh — suspect a hung "
                       "interconnect or wedged device")
    if "err" in result:
        return False, f"collective probe failed: {result['err']}"
    if result.get("got") != float(shards):
        return False, (f"collective probe returned {result.get('got')} "
                       f"!= {float(shards)} — a device is computing "
                       "wrong answers")
    return True, (f"psum over {shards} device"
                  f"{'s' if shards != 1 else ''} OK "
                  f"(= {result['got']:g})")


def _checkpoint_probe(path: str, shards: int) -> Tuple[bool, List[str]]:
    """Writability + newest-slot integrity of a checkpoint path."""
    from dpsvm_tpu.utils.checkpoint import (load_checkpoint,
                                            newest_intact_checkpoint)

    lines: List[str] = []
    directory = os.path.dirname(os.path.abspath(path)) or "."
    try:
        os.makedirs(directory, exist_ok=True)
        fd, probe = tempfile.mkstemp(dir=directory,
                                     suffix=".doctor-probe")
        os.close(fd)
        os.unlink(probe)
        lines.append(f"checkpoint dir writable: {directory}")
    except OSError as e:
        lines.append(f"checkpoint dir NOT writable: {directory} ({e})")
        return False, lines
    if not os.path.exists(path):
        lines.append(f"no checkpoint yet at {path} (a fresh run "
                     "starts from scratch)")
        return True, lines
    best, skipped = newest_intact_checkpoint(path)
    if skipped:
        lines.append(f"corrupt/unreadable slot(s) skipped: {skipped}")
    if best is None:
        lines.append(f"NO intact checkpoint slot at {path} — a "
                     "restart cannot resume")
        return False, lines
    ck = load_checkpoint(best)
    bad = ck.verify_shard_crcs()
    if bad:
        lines.append(f"newest intact slot {best} has damaged shard "
                     f"region(s) {bad}")
        return False, lines
    note = ""
    if ck.needs_reshard(shards):
        note = (f" — saved on a {ck.mesh_desc()}, this mesh is "
                f"{shards}: resume will RE-SHARD (not an error)")
    lines.append(f"newest intact slot: {best} (iter {ck.n_iter}, "
                 f"({ck.n}, {ck.d}) problem, {ck.shards}-shard "
                 f"manifest){note}")
    return True, lines


def run_doctor(shards: int = 0, checkpoint_path: Optional[str] = None,
               timeout_s: float = 60.0,
               out: Callable[[str], None] = print) -> int:
    """The full preflight; returns the process exit code (0 = sane).
    Prints its findings through ``out`` and always ends with one
    DOCTOR line carrying the verdict."""
    from dpsvm_tpu.utils.backend_guard import probe_devices

    devices, reason = probe_devices(timeout_s)
    if devices is None:
        out(f"backend: UNREACHABLE ({reason})")
        out(f"DOCTOR FAIL: backend unreachable — {reason}")
        return 3
    from dpsvm_tpu.parallel.multihost import topology

    topo = topology()
    out(f"backend: {topo.get('platform')} "
        f"({topo.get('global_devices')} device(s), "
        f"{topo.get('local_devices')} local, "
        f"process {topo.get('process_id')}/{topo.get('processes')}, "
        f"kinds {topo.get('device_kinds')})")
    p = int(shards) or len(devices)
    if p > len(devices):
        out(f"DOCTOR FAIL: asked for {p} shards but only "
            f"{len(devices)} devices are visible")
        return 4
    ok, detail = _collective_probe(p, timeout_s)
    out(f"collective: {detail}")
    if not ok:
        out(f"DOCTOR FAIL: {detail}")
        return 5
    if checkpoint_path:
        ck_ok, lines = _checkpoint_probe(checkpoint_path, p)
        for ln in lines:
            out(f"checkpoint: {ln}")
        if not ck_ok:
            out(f"DOCTOR FAIL: {lines[-1]}")
            return 6
    out(f"DOCTOR OK: {p}-shard mesh sane"
        + (", checkpoint path healthy" if checkpoint_path else ""))
    return 0

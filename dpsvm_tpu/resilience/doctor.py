"""``dpsvm doctor``: is the cluster sane before burning an hour?

"Parallel SVMs in Practice" (arXiv:1404.1066) observes that most
wasted cluster time is spent discovering *environmental* failures —
dead devices, hung interconnects, unwritable storage — an hour into a
job instead of a second before it. The doctor is that second: a
preflight that exercises exactly the three things a distributed
training run depends on, each with a bounded wait, and exits non-zero
with a one-line diagnosis.

1. **Topology** — backend reachable within ``--timeout`` (the
   tunneled-TPU hang is the motivating failure: utils/backend_guard),
   device/mesh/process facts printed (parallel/multihost.topology).
2. **Collective probe** — a tiny ``shard_map`` psum over the requested
   mesh, run in a worker thread with a deadline: a hung ICI/DCN link
   or a wedged device surfaces here in seconds, not after the first
   real chunk. The probe result is also checked for correctness
   (psum of ones == P) — a wrong answer is a worse sign than a hang.
3. **Checkpoint health** — directory writability (create + remove a
   probe file), free disk space, and newest-slot integrity: the
   rotation set is scanned exactly like a resume would
   (``newest_intact_checkpoint``), and the newest intact slot's
   recorded mesh/iteration are reported so the operator knows what a
   restart would resume (a mesh different from ``--shards`` is
   reported as a pending re-shard, not an error —
   docs/DISTRIBUTED.md "Elastic training").
4. **Data health** (``--data DIR``, docs/DATA.md) — manifest parse,
   a shard CRC spot-check (first / middle / last, the same verified
   read a training run performs), free disk space for the shard
   directory, and a one-shard TIMED read (a degraded disk or slow
   network filesystem surfaces as MB/s before the run starts, not as
   a mystery stall an hour in). Distinct exit codes: 7 = integrity,
   8 = disk space.
5. **Host group** (``--coordinator`` / ``--hosts-dir``,
   docs/DISTRIBUTED.md "Multi-host") — deadline-bounded TCP
   reachability of the ``jax.distributed`` coordinator (a pure socket
   probe: the doctor NEVER initializes a distributed backend — the
   probing process may still want to) and per-host heartbeat
   freshness/iteration/generation from the group supervisor's shared
   directory (resilience/hostgroup.py). Exit 9 = host group degraded.

The doctor also REPORTS (never gates on) the tuned-knob profile
resolution would consult for this backend — knobs, provenance and the
measured win, or exactly why no entry applies (docs/PERF.md
"Autotuning").
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Callable, List, Optional, Tuple


def _collective_probe(shards: int, timeout_s: float
                      ) -> Tuple[bool, str]:
    """psum(ones) over a ``shards``-device mesh with a deadline.
    Returns (ok, detail). Runs in a daemon worker so a hung collective
    cannot wedge the doctor past its budget."""
    result: dict = {}

    def work():
        try:
            import jax
            import jax.numpy as jnp
            from jax import lax
            from jax.sharding import PartitionSpec as P

            from dpsvm_tpu.parallel.mesh import (SHARD_AXIS,
                                                 make_data_mesh,
                                                 shard_map_compat)

            mesh = make_data_mesh(shards)
            probe = shard_map_compat(
                lambda v: lax.psum(jnp.sum(v), SHARD_AXIS),
                mesh=mesh, in_specs=(P(SHARD_AXIS),), out_specs=P())
            got = float(jax.jit(probe)(jnp.ones((shards,))))
            result["got"] = got
        except Exception as e:
            result["err"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=work, daemon=True,
                         name="dpsvm-doctor-collective")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        return False, (f"collective probe TIMED OUT after {timeout_s:g}s "
                       f"on a {shards}-device mesh — suspect a hung "
                       "interconnect or wedged device")
    if "err" in result:
        return False, f"collective probe failed: {result['err']}"
    if result.get("got") != float(shards):
        return False, (f"collective probe returned {result.get('got')} "
                       f"!= {float(shards)} — a device is computing "
                       "wrong answers")
    return True, (f"psum over {shards} device"
                  f"{'s' if shards != 1 else ''} OK "
                  f"(= {result['got']:g})")


def _checkpoint_probe(path: str, shards: int) -> Tuple[bool, List[str]]:
    """Writability + newest-slot integrity of a checkpoint path."""
    from dpsvm_tpu.utils.checkpoint import (load_checkpoint,
                                            newest_intact_checkpoint)

    lines: List[str] = []
    directory = os.path.dirname(os.path.abspath(path)) or "."
    try:
        os.makedirs(directory, exist_ok=True)
        fd, probe = tempfile.mkstemp(dir=directory,
                                     suffix=".doctor-probe")
        os.close(fd)
        os.unlink(probe)
        lines.append(f"checkpoint dir writable: {directory}")
    except OSError as e:
        lines.append(f"checkpoint dir NOT writable: {directory} ({e})")
        return False, lines
    if not os.path.exists(path):
        lines.append(f"no checkpoint yet at {path} (a fresh run "
                     "starts from scratch)")
        return True, lines
    best, skipped = newest_intact_checkpoint(path)
    if skipped:
        lines.append(f"corrupt/unreadable slot(s) skipped: {skipped}")
    if best is None:
        lines.append(f"NO intact checkpoint slot at {path} — a "
                     "restart cannot resume")
        return False, lines
    ck = load_checkpoint(best)
    bad = ck.verify_shard_crcs()
    if bad:
        lines.append(f"newest intact slot {best} has damaged shard "
                     f"region(s) {bad}")
        return False, lines
    note = ""
    if ck.needs_reshard(shards):
        note = (f" — saved on a {ck.mesh_desc()}, this mesh is "
                f"{shards}: resume will RE-SHARD (not an error)")
    lines.append(f"newest intact slot: {best} (iter {ck.n_iter}, "
                 f"({ck.n}, {ck.d}) problem, {ck.shards}-shard "
                 f"manifest){note}")
    return True, lines


#: free-space floor for the disk probes: below this a checkpoint
#: rotation (or the next shard write) is one bad day from ENOSPC.
MIN_FREE_BYTES = 64 * 1024 * 1024


def _free_disk_probe(directory: str, need_bytes: int
                     ) -> Tuple[bool, str]:
    """Free space on ``directory``'s filesystem vs what the caller is
    about to write (floored at MIN_FREE_BYTES)."""
    try:
        st = os.statvfs(directory)
    except OSError as e:
        return False, f"cannot stat filesystem of {directory}: {e}"
    free = st.f_bavail * st.f_frsize
    need = max(int(need_bytes), MIN_FREE_BYTES)
    mb = 1024.0 * 1024.0
    if free < need:
        return False, (f"{directory}: only {free / mb:.0f} MiB free "
                       f"(< {need / mb:.0f} MiB needed) — the next "
                       "write will ENOSPC")
    return True, f"{directory}: {free / mb:,.0f} MiB free"


def _data_probe(path: str, out: Callable[[str], None]
                ) -> Tuple[bool, int]:
    """Shard-dataset health: manifest + CRC spot-check + free disk +
    one-shard timed read, plus the live-log probes (docs/DATA.md
    "Live shard logs") — manifest generation, a torn in-progress
    publish, and a conversion cursor ahead of the manifest each get a
    distinct one-line verdict under the existing exit-code scheme
    (7 = integrity, 8 = disk). Returns (ok, exit_code)."""
    import glob
    import json

    from dpsvm_tpu.data.live import TornPublishError
    from dpsvm_tpu.data.stream import (CURSOR_NAME, ShardedDataset,
                                       StreamError)

    # Live-log state probes run FIRST: a torn publish makes the
    # manifest unopenable, and the verdict must say "writer crashed
    # mid-publish", not "corrupt dataset". A .prev backup beside an
    # unreadable manifest is the torn-publish signature — a frozen
    # dataset with a rotted manifest has no backup and keeps the
    # ordinary corrupt-manifest verdict.
    from dpsvm_tpu.data.live import (PREV_MANIFEST_NAME,
                                     read_manifest_checked)
    cursor_path = os.path.join(path, CURSOR_NAME)
    if os.path.isdir(path):
        try:
            read_manifest_checked(path)
        except TornPublishError as e:
            if os.path.exists(os.path.join(path, PREV_MANIFEST_NAME)):
                out(f"data: {e}")
                out("DOCTOR FAIL: in-progress (torn) publish — a "
                    "writer crashed mid-publish (or is mid-write on a "
                    "non-atomic filesystem); readers hold their last "
                    "admitted view, the restarted writer repairs from "
                    f"{PREV_MANIFEST_NAME}")
                return False, 7
        except StreamError:
            pass                # open() below owns the verdict
    try:
        ds = ShardedDataset.open(path)
    except (FileNotFoundError, StreamError) as e:
        out(f"data: {e}")
        out(f"DOCTOR FAIL: {e}")
        return False, 7
    if os.path.exists(cursor_path):
        try:
            with open(cursor_path) as fh:
                rows_done = int(json.load(fh).get("rows_done", 0))
        except (OSError, ValueError):
            rows_done = -1
        if rows_done > ds.n or rows_done < 0:
            out(f"data: conversion cursor claims {rows_done} row(s) "
                f"done but the manifest holds {ds.n}")
            out("DOCTOR FAIL: cursor ahead of the manifest — a "
                "conversion wrote past the published dataset (foreign "
                "cursor, or a manifest rolled back under it); delete "
                f"{CURSOR_NAME} only after confirming the shards")
            return False, 7
        out(f"data: stale conversion cursor present ({rows_done} "
            f"rows done <= manifest n={ds.n}; harmless leftover)")
    tmps = glob.glob(os.path.join(path, "manifest.json.tmp*"))
    if tmps:
        out(f"data: {len(tmps)} manifest tmp file(s) present — a "
            "publish may be in flight (or a writer died pre-rename); "
            "harmless to readers")
    gen = int(ds.manifest.get("generation", 0))
    out(f"data: {path}: {ds.n} rows x {ds.d} features in "
        f"{ds.n_shards} shard(s) of {ds.rows_per_shard} "
        f"({ds.manifest.get('label_dtype')} labels, "
        f"log generation {gen}"
        + (", live-append manifest" if "manifest_crc" in ds.manifest
           else ", frozen conversion") + ")")
    ok, detail = _free_disk_probe(path, MIN_FREE_BYTES)
    out(f"data: disk: {detail}")
    if not ok:
        out(f"DOCTOR FAIL: {detail}")
        return False, 8
    problems = ds.verify(spot=3)
    if problems:
        for p in problems:
            out(f"data: INTEGRITY: {p}")
        out(f"DOCTOR FAIL: {problems[0]} — a training run would "
            "raise (or quarantine) here")
        return False, 7
    import time
    t0 = time.perf_counter()
    x, _y = ds.read_shard(0)
    dt = max(time.perf_counter() - t0, 1e-9)
    mb = x.nbytes / (1024.0 * 1024.0)
    out(f"data: timed read: shard 0 ({mb:.1f} MiB) in {dt * 1e3:.1f} "
        f"ms ({mb / dt:,.0f} MB/s) — CRC spot-check OK on "
        f"{min(3, ds.n_shards)} shard(s)")
    return True, 0


def _serving_tenant_probe(url: str, out: Callable[[str], None]) -> None:
    """Reporting-only probe of a live serve process's tenant label
    budget (docs/OBSERVABILITY.md "Per-tenant attribution"): live
    series vs budget, evictions, overflow folded into 'other' — with a
    WARNING near saturation (>= 80% of budget), since a saturated
    budget means NEW tenants stop getting their own cost rows. Never
    changes the doctor verdict: a down server is not a broken mesh."""
    import json
    import urllib.error
    import urllib.request

    full = url.rstrip("/")
    if not full.endswith("/metricsz"):
        full += "/metricsz"
    try:
        with urllib.request.urlopen(full, timeout=10) as r:
            obj = json.loads(r.read())
    except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
        out(f"serving: UNREACHABLE ({e}) — reporting only, not a "
            "doctor failure")
        return
    tn = obj.get("tenants") if isinstance(obj, dict) else None
    if not isinstance(tn, dict):
        out("serving: no tenant block in /metricsz (pre-attribution "
            "server, or not a `dpsvm serve` endpoint)")
        return
    budget = int(tn.get("budget") or 0)
    live = int(tn.get("live") or 0)
    out(f"serving: tenant labels: {live}/{budget} budget slots live, "
        f"{int(tn.get('evictions') or 0)} evictions, "
        f"{int(tn.get('overflow') or 0)} requests folded into "
        "'other'")
    if budget and live >= 0.8 * budget:
        out(f"serving: WARNING tenant label budget near saturation "
            f"({live}/{budget} live) — new tenants will fold into "
            "'other'; raise `serve --tenant-budget` if per-tenant "
            "attribution matters for the tail")
    # Model-cache saturation (docs/SERVING.md "Model fleet") — same
    # reporting-only contract: a thrashing cache is a capacity-planning
    # fact, not a broken mesh.
    mc = obj.get("model_cache") if isinstance(obj, dict) else None
    if isinstance(mc, dict):
        mbudget = int(mc.get("budget") or 0)
        resident = int(mc.get("resident") or 0)
        faults = int(mc.get("faults") or 0)
        evictions = int(mc.get("evictions") or 0)
        transients = int(mc.get("transients") or 0)
        out(f"serving: model cache: {resident}/{mbudget} residents, "
            f"{faults} faults, {evictions} evictions, "
            f"{transients} transient serves, cold-start p99 "
            f"{float(mc.get('cold_start_p99_ms') or 0.0):.1f} ms, "
            f"~{int(mc.get('resident_bytes_est') or 0) // (1 << 20)} "
            "MiB resident")
        if mbudget and resident >= 0.8 * mbudget:
            out(f"serving: WARNING model cache near saturation "
                f"({resident}/{mbudget} resident) — cold models serve "
                "transiently until a second touch evicts the LRU; "
                "raise `serve --model-cache-budget` if the working "
                "set outgrew the budget (watch the model-cache-thrash "
                "rule)")
    # Front-door transport (docs/SERVING.md "Front door") — same
    # reporting-only contract: connection-cap pressure and queue-lane
    # depth are capacity facts, not a broken mesh.
    fd = obj.get("front_door") if isinstance(obj, dict) else None
    if isinstance(fd, dict):
        kind = fd.get("kind", "threaded")
        if kind != "async":
            out("serving: front end: threaded (thread-per-connection; "
                "`serve --front-end async` holds 10k+ connections on "
                "one event loop)")
        else:
            open_c = int(fd.get("open_connections") or 0)
            max_c = int(fd.get("max_connections") or 0)
            out(f"serving: front end: async ({open_c}/{max_c} "
                "connections open, "
                f"{int(fd.get('connections_rejected') or 0)} rejected "
                f"at the cap, {int(fd.get('inflight_rows') or 0)} "
                "rows in flight)")
            fq = fd.get("fair_queue") or {}
            lanes = fq.get("lanes") or {}
            if lanes:
                depth = ", ".join(
                    f"{t}: {int(v.get('rows') or 0)} rows (w="
                    f"{v.get('weight')})"
                    for t, v in sorted(lanes.items()))
                out(f"serving: fair-queue lanes: {depth}; "
                    f"{int(fq.get('rows_queued') or 0)} rows queued "
                    f"of {int(fq.get('lane_capacity_rows') or 0)} "
                    "per-lane capacity")
            if max_c and open_c >= 0.8 * max_c:
                out(f"serving: WARNING open connections near the cap "
                    f"({open_c}/{max_c}) — new connections will get "
                    "an immediate 503; raise `serve "
                    "--max-connections` if this is organic load")


def _hostgroup_probe(coordinator: Optional[str],
                     hosts_dir: Optional[str],
                     num_hosts: int, max_age_s: float,
                     timeout_s: float,
                     out: Callable[[str], None]) -> Tuple[bool, str]:
    """Multi-host preflight (docs/DISTRIBUTED.md "Multi-host").
    Reporting-only and collective-free by design: the coordinator
    check is a pure TCP connect with a deadline (it must be usable
    from a process that will LATER distributed-initialize — touching
    jax here would forfeit that), and group liveness is read from the
    heartbeat files the supervisor itself watches. The cross-host
    psum agreement check runs ONLY when this process is already
    inside an initialized group — the doctor never forms one.
    Returns (ok, reason-if-degraded)."""
    from dpsvm_tpu.parallel import multihost
    from dpsvm_tpu.resilience import hostgroup

    degraded: List[str] = []
    if coordinator:
        why = multihost.coordinator_reachable(
            coordinator, timeout_s=min(timeout_s, 10.0))
        if why is None:
            out(f"hostgroup: coordinator {coordinator} reachable")
        else:
            out(f"hostgroup: {why}")
            degraded.append(why)
    if hosts_dir:
        beats = hostgroup.read_heartbeats(hosts_dir)
        ages = hostgroup.heartbeat_ages(hosts_dir)
        expected = (set(range(int(num_hosts))) if num_hosts
                    else set(beats))
        if not beats:
            msg = f"no heartbeats in {hosts_dir}"
            out(f"hostgroup: {msg}")
            degraded.append(msg)
        for hid in sorted(expected | set(beats)):
            rec = beats.get(hid)
            if rec is None:
                msg = f"host {hid} has NO heartbeat (expected one)"
                out(f"hostgroup: {msg}")
                degraded.append(msg)
                continue
            age = ages.get(hid, float("inf"))
            stale = age > max_age_s
            # seq vs wall-clock disagreement (docs/OBSERVABILITY.md
            # "Fleet"): the heartbeat's own wall stamp `t` older than
            # the file mtime says by more than the staleness budget
            # means the writer's clock stepped BACKWARD mid-run — the
            # record is fresh (seq advanced, mtime young) but its
            # timestamp lies. A stalled host is the opposite shape:
            # old mtime AND old t, seq frozen.
            seq = rec.get("seq")
            clock_note = ""
            t_rec = rec.get("t")
            if isinstance(t_rec, (int, float)):
                try:
                    mtime = os.path.getmtime(
                        hostgroup.heartbeat_path(hosts_dir, hid))
                    drift = mtime - float(t_rec)
                    if not stale and drift > max_age_s:
                        clock_note = (f" — wall clock stepped back "
                                      f"{drift:.0f}s (seq {seq} is "
                                      "fresh; trust seq, not t)")
                except OSError:
                    pass
            out(f"hostgroup: host {hid}: beat {age:.1f}s ago, "
                f"iter {rec.get('n_iter')}, "
                f"seq {seq if seq is not None else '-'}, "
                f"generation {rec.get('generation')}, "
                f"pid {rec.get('pid')}"
                + (f" — STALE (> {max_age_s:g}s, seq frozen at "
                   f"{seq})" if stale else "")
                + clock_note)
            if stale:
                degraded.append(f"host {hid} heartbeat {age:.1f}s old "
                                f"(> {max_age_s:g}s)")
            elif clock_note:
                degraded.append(f"host {hid} wall clock stepped "
                                "backward (heartbeat t older than "
                                "file mtime)")
    if multihost.is_initialized():
        import numpy as np
        got = multihost.host_allgather(multihost.host_id())
        want = list(range(multihost.host_count()))
        if sorted(int(v) for v in np.asarray(got).ravel()) == want:
            out(f"hostgroup: cross-host allgather agrees "
                f"({multihost.host_count()} host(s))")
        else:
            msg = (f"cross-host allgather disagrees: {got!r} vs "
                   f"hosts {want}")
            out(f"hostgroup: {msg}")
            degraded.append(msg)
    else:
        out("hostgroup: not inside an initialized host group — "
            "cross-host collective check skipped (reporting-only: "
            "the doctor never initializes one)")
    return (not degraded,
            degraded[0] if degraded else "")


def run_doctor(shards: int = 0, checkpoint_path: Optional[str] = None,
               data_path: Optional[str] = None,
               timeout_s: float = 60.0,
               serving_url: Optional[str] = None,
               coordinator: Optional[str] = None,
               hosts_dir: Optional[str] = None,
               num_hosts: int = 0,
               heartbeat_max_age_s: float = 60.0,
               out: Callable[[str], None] = print) -> int:
    """The full preflight; returns the process exit code (0 = sane).
    Prints its findings through ``out`` and always ends with one
    DOCTOR line carrying the verdict."""
    from dpsvm_tpu.utils.backend_guard import probe_devices

    devices, reason = probe_devices(timeout_s)
    if devices is None:
        out(f"backend: UNREACHABLE ({reason})")
        out(f"DOCTOR FAIL: backend unreachable — {reason}")
        return 3
    from dpsvm_tpu.parallel.multihost import topology

    topo = topology()
    out(f"backend: {topo.get('platform')} "
        f"({topo.get('global_devices')} device(s), "
        f"{topo.get('local_devices')} local, "
        f"process {topo.get('process_id')}/{topo.get('processes')}, "
        f"kinds {topo.get('device_kinds')})")
    # Roofline denominators (observability/roofline.py): the peak
    # FLOP/s + HBM-bandwidth table `dpsvm report` divides by. Printed
    # HERE — with an honest `unknown` for unrecognized hardware —
    # instead of failing silently later as an n/a in report.
    from dpsvm_tpu.observability import roofline

    kinds = topo.get("device_kinds") or [
        getattr(devices[0], "device_kind", None)]
    for line in roofline.doctor_lines(kinds):
        out(f"roofline: {line}")
    # Tuned-profile resolution (docs/PERF.md "Autotuning"): which
    # per-backend knob profile train/serve would consult right now —
    # or exactly why none applies (missing, opted out, wrong backend,
    # provenance-invalid).
    from dpsvm_tpu.tuning import profile as tuned_profile

    for line in tuned_profile.doctor_lines(kinds[0] if kinds else None):
        out(f"tuned: {line}")
    p = int(shards) or len(devices)
    if p > len(devices):
        out(f"DOCTOR FAIL: asked for {p} shards but only "
            f"{len(devices)} devices are visible")
        return 4
    ok, detail = _collective_probe(p, timeout_s)
    out(f"collective: {detail}")
    if not ok:
        out(f"DOCTOR FAIL: {detail}")
        return 5
    if checkpoint_path:
        ck_ok, lines = _checkpoint_probe(checkpoint_path, p)
        for ln in lines:
            out(f"checkpoint: {ln}")
        if not ck_ok:
            out(f"DOCTOR FAIL: {lines[-1]}")
            return 6
        directory = (os.path.dirname(os.path.abspath(checkpoint_path))
                     or ".")
        disk_ok, detail = _free_disk_probe(directory, MIN_FREE_BYTES)
        out(f"checkpoint: disk: {detail}")
        if not disk_ok:
            out(f"DOCTOR FAIL: {detail}")
            return 8
    if data_path:
        data_ok, code = _data_probe(data_path, out)
        if not data_ok:
            return code
    if coordinator or hosts_dir:
        hg_ok, why = _hostgroup_probe(coordinator, hosts_dir,
                                      num_hosts, heartbeat_max_age_s,
                                      timeout_s, out)
        if not hg_ok:
            out(f"DOCTOR FAIL: host group degraded — {why}")
            return 9
    if serving_url:
        _serving_tenant_probe(serving_url, out)
    out(f"DOCTOR OK: {p}-shard mesh sane"
        + (", checkpoint path healthy" if checkpoint_path else "")
        + (", shard data healthy" if data_path else "")
        + (", host group healthy" if coordinator or hosts_dir else ""))
    return 0

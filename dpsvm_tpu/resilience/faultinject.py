"""Deterministic fault injection for the resilience subsystem.

Every failure mode the resilience stack handles — preemption signals,
corrupted/failed checkpoint writes, non-finite solver state — is rare
and timing-dependent in the wild, so each one has a deterministic
injection point that fires at an exact, configured moment. That makes
the whole subsystem testable in CI on CPU (tests/test_resilience.py,
``python -m dpsvm_tpu.resilience --selfcheck``) and soakable on real
hardware (``BENCH_FAULT_*`` through bench.py / benchmarks/
burst_runner.py).

Knobs (env: ``DPSVM_FAULT_*``, with ``BENCH_FAULT_*`` accepted as
aliases so benchmark harness configs stay in the BENCH_ namespace; API:
``install(FaultPlan(...))``):

* ``DPSVM_FAULT_CHECKPOINT_WRITE=k`` — the k-th (1-based)
  ``save_checkpoint`` call in this process fails after the tmp write,
  before the rename (exercises atomicity + rotation fallback);
* ``DPSVM_FAULT_NAN_ITER=j`` — the first stats poll observing
  ``n_iter >= j`` reports a NaN gap (exercises the HealthMonitor's
  non-finite detection and the rollback policy);
* ``DPSVM_FAULT_PREEMPT_POLL=m`` — the m-th (1-based) host poll raises
  a simulated preemption (exercises snapshot + resumable exit + retry
  supervisor without OS signal timing races).

Each fault fires exactly ONCE per process: counters live on the
process-global plan, so a supervisor retry inside the same process (or
a resumed attempt) runs clean after the injected failure — which is
exactly the transient-fault model the subsystem exists for.
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Optional


class InjectedFaultError(OSError):
    """Raised by the checkpoint-write injection point (an OSError, like
    the real failures it stands in for)."""


def _log(msg: str) -> None:
    print(f"FAULTINJECT: {msg}", file=sys.stderr, flush=True)


@dataclasses.dataclass
class FaultPlan:
    fail_checkpoint_write: int = 0   # 1-based save counter; 0 = off
    nan_at_iter: int = 0             # poison first poll with n_iter >= j
    preempt_at_poll: int = 0         # 1-based host-poll counter

    # process-lifetime counters (fire-once semantics)
    _writes: int = 0
    _polls: int = 0
    _nan_fired: bool = False

    def any(self) -> bool:
        return bool(self.fail_checkpoint_write or self.nan_at_iter
                    or self.preempt_at_poll)

    def note_checkpoint_write(self, path: str) -> None:
        self._writes += 1
        if (self.fail_checkpoint_write
                and self._writes == self.fail_checkpoint_write):
            _log(f"failing checkpoint write #{self._writes} -> {path}")
            raise InjectedFaultError(
                f"injected checkpoint-write failure #{self._writes}")

    def note_poll(self) -> bool:
        """True exactly at the configured poll — the driver then
        simulates a preemption signal."""
        self._polls += 1
        if self.preempt_at_poll and self._polls == self.preempt_at_poll:
            _log(f"simulating preemption at poll #{self._polls}")
            return True
        return False

    def poison_stats(self, st):
        """Replace b_lo with NaN on the first qualifying poll (a stand-in
        for device-state corruption observed at the poll boundary)."""
        if (self.nan_at_iter and not self._nan_fired
                and st.n_iter >= self.nan_at_iter):
            self._nan_fired = True
            _log(f"poisoning stats with NaN gap at iter {st.n_iter}")
            return st._replace(b_lo=float("nan"))
        return st


_plan: Optional[FaultPlan] = None
_env_checked = False


def _env_int(name: str) -> int:
    for prefix in ("DPSVM_FAULT_", "BENCH_FAULT_"):
        v = os.environ.get(prefix + name, "").strip()
        if v:
            try:
                return int(v)
            except ValueError:
                _log(f"ignoring non-integer {prefix}{name}={v!r}")
    return 0


def plan_from_env() -> Optional[FaultPlan]:
    p = FaultPlan(
        fail_checkpoint_write=_env_int("CHECKPOINT_WRITE"),
        nan_at_iter=_env_int("NAN_ITER"),
        preempt_at_poll=_env_int("PREEMPT_POLL"))
    return p if p.any() else None


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Explicitly set (or with None, clear) the process fault plan —
    the API-level seam tests use instead of env vars."""
    global _plan, _env_checked
    _plan = plan
    _env_checked = True
    return plan


def clear() -> None:
    global _plan, _env_checked
    _plan = None
    _env_checked = False


def current() -> Optional[FaultPlan]:
    """The active plan: an installed one, else env-configured (resolved
    once per process), else None. The no-fault path costs one global
    read."""
    global _plan, _env_checked
    if not _env_checked:
        _env_checked = True
        _plan = plan_from_env()
        if _plan is not None:
            _log(f"active plan: {_plan}")
    return _plan


def on_checkpoint_write(path: str) -> None:
    """save_checkpoint's injection point (utils/checkpoint.py)."""
    p = current()
    if p is not None:
        p.note_checkpoint_write(path)

"""Deterministic fault injection for the resilience subsystem.

Every failure mode the resilience stack handles — preemption signals,
corrupted/failed checkpoint writes, non-finite solver state — is rare
and timing-dependent in the wild, so each one has a deterministic
injection point that fires at an exact, configured moment. That makes
the whole subsystem testable in CI on CPU (tests/test_resilience.py,
``python -m dpsvm_tpu.resilience --selfcheck``) and soakable on real
hardware (``BENCH_FAULT_*`` through bench.py / benchmarks/
burst_runner.py).

Knobs (env: ``DPSVM_FAULT_*``, with ``BENCH_FAULT_*`` accepted as
aliases so benchmark harness configs stay in the BENCH_ namespace; API:
``install(FaultPlan(...))``):

* ``DPSVM_FAULT_CHECKPOINT_WRITE=k`` — the k-th (1-based)
  ``save_checkpoint`` call in this process fails after the tmp write,
  before the rename (exercises atomicity + rotation fallback);
* ``DPSVM_FAULT_NAN_ITER=j`` — the first stats poll observing
  ``n_iter >= j`` reports a NaN gap (exercises the HealthMonitor's
  non-finite detection and the rollback policy);
* ``DPSVM_FAULT_PREEMPT_POLL=m`` — the m-th (1-based) host poll raises
  a simulated preemption (exercises snapshot + resumable exit + retry
  supervisor without OS signal timing races).

Distributed knobs (``DPSVM_FAULT_DIST_*``, consumed by the shared
driver on multi-shard runs — docs/DISTRIBUTED.md "Elastic training"):

* ``DPSVM_FAULT_DIST_KILL_SHARD=k`` — shard **#k** (1-based) "dies" at
  a distributed host poll (``DPSVM_FAULT_DIST_KILL_POLL=m`` picks the
  poll; default the 2nd): the driver raises ``ShardLostError``, the
  transient signal ``elastic.run_elastic`` answers by resuming on the
  surviving mesh from the newest intact shard-aware checkpoint — the
  kill-one-shard drill;
* ``DPSVM_FAULT_DIST_DESYNC_AT=j`` — the first poll observing
  ``n_iter >= j`` reports one shard's probe (``DESYNC_SHARD``,
  default the last shard) disagreeing with the rest (exercises
  cross-shard desync detection -> ``desync`` event -> the
  ``on_divergence`` policy);
* ``DPSVM_FAULT_DIST_SLOW_SHARD=k`` — shard #k's probe stops advancing
  (every poll replays its first-seen value): the straggler model —
  its heartbeat age grows in the chunk records and the stall
  watchdog's dist verdict fingers it.

Multi-host knobs (``DPSVM_FAULT_HOST_*``, consumed by the shared driver
and the live-ingest barrier — docs/DISTRIBUTED.md "Multi-host"; the
host-group drill plants them in ONE host subprocess's environment, so
the blast radius is per-host, exactly like the real failures):

* ``DPSVM_FAULT_HOST_KILL=m`` — THIS process SIGKILLs itself at its
  m-th (1-based) host poll: a real, uncatchable host death mid-run
  (no snapshot, no cleanup — the heartbeat file simply stops). The
  host-group supervisor (resilience/hostgroup.py) detects the dead
  member, reforms the group on the survivors and resumes from the
  newest intact checkpoint — the kill-one-host drill;
* ``DPSVM_FAULT_HOST_HANG_MS=t`` — THIS process sleeps ``t``
  milliseconds at every live-ingest admission poll (the straggler-host
  model): its published generation lags, the cross-host min-generation
  barrier holds every host at the straggler's boundary (no desync),
  and the hang surfaces as heartbeat age in doctor/watch — never as a
  silent wedge.

Data-pipeline knobs (``DPSVM_FAULT_IO_*``, consumed by the shard
reader in ``data/stream.py`` — docs/DATA.md "Failure playbook"):

* ``DPSVM_FAULT_IO_READ_FAIL_ONCE=k`` — the k-th (1-based) shard read
  in this process raises a TRANSIENT ``OSError`` exactly once
  (exercises the bounded retry-with-backoff path; the retry re-read
  succeeds);
* ``DPSVM_FAULT_IO_CORRUPT_SHARD=k`` — shard **#k** (1-based) reads
  with a flipped payload byte on EVERY read (persistent corruption —
  a rotted file stays rotted), so the manifest CRC check fails and the
  ``on_bad_shard`` policy fires (quarantine event / raise);
* ``DPSVM_FAULT_IO_TRUNCATE_SHARD=k`` — shard #k reads as a file cut
  to half its bytes on every read (the killed-writer / torn-copy
  model; surfaces as an unreadable-npz corruption);
* ``DPSVM_FAULT_IO_SLOW_READ_MS=t`` — every shard read sleeps ``t``
  milliseconds first (the degraded-disk / network-filesystem model;
  exercises the doctor's timed-read probe and ingest-seconds
  accounting).

Serving-side knobs (``DPSVM_FAULT_SERVE_*``, consumed by
``serving/pool.py`` / ``serving/registry.py`` — docs/SERVING.md
"Resilience"):

* ``DPSVM_FAULT_SERVE_WEDGE_REPLICA=k`` — replica **#k** (1-based)
  wedges: its worker blocks forever at the next compute (release with
  ``release_serve_wedge()`` in in-process tests; a chaos subprocess
  just abandons the daemon thread). Combine with
  ``DPSVM_FAULT_SERVE_WEDGE_AFTER=m`` to delay the wedge until the
  pool has served ``m`` computes (fault mid-loadgen, after warmup);
* ``DPSVM_FAULT_SERVE_NAN_AFTER=m`` — the replica that serves the
  m-th pool compute becomes NaN-poisoned: every output it produces
  from then on is non-finite, until the pool rebuilds it (the poison
  is pinned to the replica *generation*, so the rebuilt replica is
  clean — the transient device-buffer-corruption model);
* ``DPSVM_FAULT_SERVE_FAIL_RELOAD=j`` — the j-th (1-based) engine
  reload/rebuild in this process fails (exercises
  failed-reload-keeps-serving and the rebuild retry loop);
* ``DPSVM_FAULT_SERVE_SLOW_REPLICA_MS=t`` — EVERY replica compute
  sleeps ``t`` milliseconds first (the degraded-device / saturated-
  interconnect model): with request deadlines under ``t`` this is the
  deterministic 504 storm that must fire the serving burn-rate alert
  and dump an incident bundle (docs/OBSERVABILITY.md "Watch &
  alerts"). Combine with ``DPSVM_FAULT_SERVE_SLOW_FOR=m`` to LIFT the
  fault after the first ``m`` slowed computes — the alert must then
  clear, which is the recovery half of the drill.

Live shard-log knobs (``DPSVM_FAULT_LIVE_*``, consumed by the append
writer / the drift drill in ``data/live.py`` + ``serving/lifecycle.py``
— docs/DATA.md "Live shard logs"):

* ``DPSVM_FAULT_LIVE_TORN_PUBLISH=k`` — the k-th (1-based) manifest
  publish in this process writes only the FIRST HALF of the manifest
  bytes directly onto ``manifest.json`` (the non-atomic-filesystem /
  kill-9-mid-write model) and raises ``WriterCrashError``: readers
  must hold their last-admitted view (the torn file fails the
  manifest CRC) and the restarted writer must repair on its next
  publish;
* ``DPSVM_FAULT_LIVE_STALE_GENERATION=k`` — the k-th publish lands a
  CRC-VALID manifest whose ``generation`` did NOT increase (a replayed
  or split-brain writer): readers must refuse to advance on it;
* ``DPSVM_FAULT_LIVE_WRITER_CRASH_AFTER=k`` — the writer "crashes"
  (raises ``WriterCrashError``) right after the k-th appended shard
  file is durable but BEFORE its manifest publish: the orphan shard is
  invisible to readers and the next append must overwrite it;
* ``DPSVM_FAULT_LIVE_SHIFT_AT_SHARD=k`` — the drill's append source
  plants the distribution shift from appended shard #k (1-based) on
  (``live_shift_now``): the deterministic drift trigger of the
  ``live_drift_drill``.

Cascade / bench-infra knobs (``solver/cascade.py``, ``bench_common.py``
— docs/APPROX.md "Cascade"):

* ``DPSVM_FAULT_CASCADE_STOP_STAGE=k`` — the cascade raises
  ``CascadeInterrupted`` right after its stage-#k boundary state is
  durable on disk (1 = approx warm-start, 2 = screening, 3 = the
  first polish round): the kill->resume drill's deterministic kill
  point — re-running the same command must land a bitwise-identical
  model;
* ``DPSVM_FAULT_PREFLIGHT_WEDGE_S=t`` — the bench doctor preflight's
  device probe hangs ``t`` seconds (the dead-TPU-tunnel model): with
  ``t`` past the doctor deadline, bench.py / the burst runner must
  exit with a clear ``"degraded": true`` verdict row instead of
  burning the round.

Each fault fires exactly ONCE per process: counters live on the
process-global plan, so a supervisor retry inside the same process (or
a resumed attempt) runs clean after the injected failure — which is
exactly the transient-fault model the subsystem exists for.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
from typing import Optional, Tuple


class InjectedFaultError(OSError):
    """Raised by the checkpoint-write injection point (an OSError, like
    the real failures it stands in for)."""


#: serve hooks are hit from concurrent replica workers (the training
#: hooks are single-threaded and stay lock-free)
_SERVE_LOCK = threading.Lock()


def _log(msg: str) -> None:
    print(f"FAULTINJECT: {msg}", file=sys.stderr, flush=True)


@dataclasses.dataclass
class FaultPlan:
    fail_checkpoint_write: int = 0   # 1-based save counter; 0 = off
    nan_at_iter: int = 0             # poison first poll with n_iter >= j
    preempt_at_poll: int = 0         # 1-based host-poll counter
    # serving-side (docstring above): replica NUMBERS are 1-based,
    # matching the other knobs' "the k-th" convention; 0 = off.
    serve_wedge_replica: int = 0     # replica #k wedges at a compute
    serve_wedge_after: int = 0       # ...once pool computes >= m
    serve_nan_after: int = 0         # poison the replica serving
    #                                  compute #m until it is rebuilt
    serve_fail_reload: int = 0       # 1-based reload/rebuild counter
    serve_slow_replica_ms: int = 0   # every compute sleeps this first
    serve_slow_for: int = 0          # ...only the first m computes
    #                                  (0 = for the process lifetime);
    #                                  past m the fault LIFTS — the
    #                                  504-storm recovery drill
    # distributed-mesh knobs (docstring above): shard NUMBERS 1-based
    dist_kill_shard: int = 0         # shard #k lost at a dist poll
    dist_kill_poll: int = 0          # ...the m-th dist poll (default 2)
    dist_desync_at: int = 0          # poison a probe at n_iter >= j
    dist_desync_shard: int = 0       # which shard lies (default last)
    dist_slow_shard: int = 0         # shard #k's probe stops advancing
    # multi-host knobs (docstring above): planted PER-HOST by the
    # host-group drill, so "this process" is one member of the group
    host_kill: int = 0               # SIGKILL self at the m-th host poll
    host_hang_ms: int = 0            # sleep at every live admission poll
    # data-pipeline knobs (docstring above): shard NUMBERS 1-based
    io_read_fail_once: int = 0       # the k-th shard read fails once
    io_corrupt_shard: int = 0        # shard #k payload bit-flipped
    #                                  (every read — persistent rot)
    io_truncate_shard: int = 0       # shard #k reads half its bytes
    io_slow_read_ms: int = 0         # every shard read sleeps this
    # live shard-log knobs (data/live.py — docstring above): publish /
    # append counters are 1-based like every other "the k-th" knob
    live_torn_publish: int = 0       # the k-th publish tears mid-write
    live_stale_generation: int = 0   # the k-th publish replays its old
    #                                  generation (CRC-valid, stale)
    live_writer_crash_after: int = 0  # crash after shard #k is durable,
    #                                  before its manifest publish
    live_shift_at_shard: int = 0     # drill: appended shard #k on is
    #                                  drawn from the shifted
    #                                  distribution
    # cascade / bench-infra knobs (solver/cascade.py, bench_common.py)
    cascade_stop_stage: int = 0      # kill the cascade right after the
    #                                  stage-#k boundary state is
    #                                  durable (1=approx, 2=screen,
    #                                  3=first polish round): the
    #                                  kill->resume drill's
    #                                  deterministic kill point
    preflight_wedge_s: int = 0       # the bench doctor preflight's
    #                                  device probe hangs this many
    #                                  seconds (simulated dead TPU
    #                                  tunnel; > the doctor deadline =
    #                                  a degraded verdict row)

    # process-lifetime counters (fire-once semantics)
    _writes: int = 0
    _polls: int = 0
    _nan_fired: bool = False
    _serve_computes: int = 0
    _serve_reloads: int = 0
    _wedge_fired: bool = False
    _poisoned: Optional[Tuple[int, int]] = None  # (replica, generation)
    _dist_polls: int = 0
    _kill_fired: bool = False
    _host_polls: int = 0
    _desync_fired: bool = False
    _slow_probe: Optional[tuple] = None   # frozen probe row replayed
    _io_reads: int = 0
    _io_fail_fired: bool = False
    _live_publishes: int = 0
    _live_appends: int = 0
    _torn_fired: bool = False
    _stale_fired: bool = False
    _writer_crash_fired: bool = False
    _cascade_fired: bool = False
    _slow_computes: int = 0
    _slow_lifted_logged: bool = False

    def any(self) -> bool:
        return bool(self.fail_checkpoint_write or self.nan_at_iter
                    or self.preempt_at_poll or self.serve_wedge_replica
                    or self.serve_nan_after or self.serve_fail_reload
                    or self.serve_slow_replica_ms
                    or self.dist_kill_shard or self.dist_desync_at
                    or self.dist_slow_shard or self.host_kill
                    or self.host_hang_ms or self.io_read_fail_once
                    or self.io_corrupt_shard or self.io_truncate_shard
                    or self.io_slow_read_ms or self.cascade_stop_stage
                    or self.preflight_wedge_s or self.live_torn_publish
                    or self.live_stale_generation
                    or self.live_writer_crash_after
                    or self.live_shift_at_shard)

    def cascade_stop_now(self, stage: int) -> bool:
        """True exactly once, when the cascade has made the stage-#k
        boundary state durable (k = ``cascade_stop_stage``) — the
        orchestrator then raises ``CascadeInterrupted``, and the
        kill->resume drill re-runs the same command to prove the
        resumed model is bitwise-identical (solver/cascade.py)."""
        if (self.cascade_stop_stage and not self._cascade_fired
                and stage >= self.cascade_stop_stage):
            self._cascade_fired = True
            _log(f"stopping cascade after stage-{stage} boundary")
            return True
        return False

    def note_checkpoint_write(self, path: str) -> None:
        self._writes += 1
        if (self.fail_checkpoint_write
                and self._writes == self.fail_checkpoint_write):
            _log(f"failing checkpoint write #{self._writes} -> {path}")
            raise InjectedFaultError(
                f"injected checkpoint-write failure #{self._writes}")

    def note_poll(self) -> bool:
        """True exactly at the configured poll — the driver then
        simulates a preemption signal."""
        self._polls += 1
        if self.preempt_at_poll and self._polls == self.preempt_at_poll:
            _log(f"simulating preemption at poll #{self._polls}")
            return True
        return False

    def poison_stats(self, st):
        """Replace b_lo with NaN on the first qualifying poll (a stand-in
        for device-state corruption observed at the poll boundary), and
        apply the dist probe faults (desync / slow shard) to the
        per-shard probe tail when one rides the stats."""
        if (self.nan_at_iter and not self._nan_fired
                and st.n_iter >= self.nan_at_iter):
            self._nan_fired = True
            _log(f"poisoning stats with NaN gap at iter {st.n_iter}")
            st = st._replace(b_lo=float("nan"))
        probes = getattr(st, "shard_probes", None)
        if probes is not None and (self.dist_desync_at
                                   or self.dist_slow_shard):
            st = st._replace(
                shard_probes=self.poison_probes(probes, st.n_iter))
        return st

    def poison_probes(self, probes, n_iter: int):
        """Dist probe faults, applied host-side to the (P, 3) probe
        block exactly where real mesh corruption would surface (the one
        poll read): desync flips one shard's n_iter lane once; the slow
        shard replays its first-seen row every poll so its reported
        progress freezes (heartbeat age grows)."""
        probes = probes.copy()
        p = len(probes)
        if (self.dist_desync_at and not self._desync_fired
                and n_iter >= self.dist_desync_at and p > 1):
            self._desync_fired = True
            k = ((self.dist_desync_shard - 1) % p
                 if self.dist_desync_shard else p - 1)
            # One-ulp disagreement on the replicated gap bound at the
            # SAME iteration — the smallest possible desync (flipping
            # n_iter instead would read as a straggler, which is the
            # heartbeat path's signal, not the desync guard's).
            probes[k, 1] ^= 1
            _log(f"desyncing shard {k} probe at iter {n_iter}")
        if self.dist_slow_shard and p >= self.dist_slow_shard:
            k = self.dist_slow_shard - 1
            if self._slow_probe is None:
                self._slow_probe = tuple(int(v) for v in probes[k])
                _log(f"freezing shard {k} probe (straggler model)")
            probes[k] = self._slow_probe
        return probes

    def dist_kill_now(self) -> int:
        """Counted per DISTRIBUTED host poll; returns the 1-based shard
        to lose exactly once (0 = keep running). The driver raises
        ``elastic.ShardLostError`` — no snapshot, like a real host
        death: recovery starts from the newest PERIODIC checkpoint."""
        if not self.dist_kill_shard:
            return 0
        self._dist_polls += 1
        at = self.dist_kill_poll or 2
        if not self._kill_fired and self._dist_polls >= at:
            self._kill_fired = True
            _log(f"killing shard #{self.dist_kill_shard} at dist poll "
                 f"#{self._dist_polls}")
            return self.dist_kill_shard
        return 0

    def host_kill_now(self) -> bool:
        """Counted per host poll; True exactly once, at the configured
        poll — the driver then SIGKILLs its own process. Unlike
        ``dist_kill_now`` (which raises a catchable ShardLostError in a
        single supervising process) this is a REAL uncatchable death of
        one member of a multi-process host group: no snapshot, no trace
        close, heartbeat file frozen mid-run."""
        if not self.host_kill:
            return False
        self._host_polls += 1
        if self._host_polls >= self.host_kill:
            _log(f"SIGKILLing this host at host poll "
                 f"#{self._host_polls}")
            return True
        return False

    def host_hang_delay_s(self) -> float:
        """Seconds the live-ingest admission poll must sleep (0.0 =
        run clean) — the straggler-host model for the cross-host
        min-generation barrier."""
        return self.host_hang_ms / 1000.0

    # -- data-pipeline injection points (data/stream.py). Like the
    # training hooks these are single-threaded (one reader loop).

    def io_read_begin(self, shard_idx: int) -> None:
        """Called as a shard read starts: applies the slow-read latency
        and raises the one transient read failure (an OSError, so the
        reader's bounded retry recovers it — the transient model)."""
        if self.io_slow_read_ms:
            import time
            time.sleep(self.io_slow_read_ms / 1000.0)
        self._io_reads += 1
        if (self.io_read_fail_once and not self._io_fail_fired
                and self._io_reads >= self.io_read_fail_once):
            self._io_fail_fired = True
            _log(f"failing shard read #{self._io_reads} "
                 f"(shard {shard_idx}) once")
            raise InjectedFaultError(
                f"injected transient read failure at shard read "
                f"#{self._io_reads}")

    def io_corrupt_now(self, shard_idx: int) -> bool:
        """True when shard #(idx+1) should read with a flipped payload
        byte — EVERY read (a rotted file stays rotted), unlike the
        fire-once transient knobs."""
        return bool(self.io_corrupt_shard
                    and shard_idx + 1 == self.io_corrupt_shard)

    def io_truncate_now(self, shard_idx: int) -> bool:
        """True when shard #(idx+1) should read as a half-length file
        (torn copy / killed writer) — every read, like corruption."""
        return bool(self.io_truncate_shard
                    and shard_idx + 1 == self.io_truncate_shard)

    # -- live shard-log injection points (data/live.py). Single-
    # threaded like the other data-pipeline hooks (one writer loop).

    def live_append_begin(self) -> bool:
        """Counted per durable appended shard, BEFORE its publish.
        True exactly once, when the writer should crash with the shard
        on disk but un-published (the orphan-shard model)."""
        self._live_appends += 1
        if (self.live_writer_crash_after and not self._writer_crash_fired
                and self._live_appends >= self.live_writer_crash_after):
            self._writer_crash_fired = True
            _log(f"crashing writer after appended shard "
                 f"#{self._live_appends} (pre-publish)")
            return True
        return False

    def live_publish_mode(self) -> str:
        """Counted per manifest publish. Returns "clean", "torn" (write
        half the bytes non-atomically onto the real manifest path, then
        crash) or "stale" (publish CRC-valid bytes whose generation did
        not advance). Each fires once."""
        self._live_publishes += 1
        if (self.live_torn_publish and not self._torn_fired
                and self._live_publishes >= self.live_torn_publish):
            self._torn_fired = True
            _log(f"tearing manifest publish #{self._live_publishes}")
            return "torn"
        if (self.live_stale_generation and not self._stale_fired
                and self._live_publishes >= self.live_stale_generation):
            self._stale_fired = True
            _log(f"replaying stale generation at publish "
                 f"#{self._live_publishes}")
            return "stale"
        return "clean"

    def live_shift_now(self, append_idx: int) -> bool:
        """True when appended shard #(idx+1) — and every later one —
        should be drawn from the drill's shifted distribution
        (persistent, like real drift: the world does not shift back)."""
        return bool(self.live_shift_at_shard
                    and append_idx + 1 >= self.live_shift_at_shard)

    # -- serving-side injection points (serving/pool.py). Unlike the
    # single-threaded training hooks, these are hit from concurrent
    # replica workers — counters advance under the module serve lock.

    def note_serve_compute(self, replica_idx: int,
                           generation: int) -> bool:
        """Called by a replica worker as a compute begins. Returns True
        exactly when THIS compute should wedge (the worker then blocks
        on the module wedge event). Also arms the NaN poison: the
        replica serving the m-th pool compute becomes poisoned for its
        current generation."""
        with _SERVE_LOCK:
            self._serve_computes += 1
            if (self.serve_wedge_replica and not self._wedge_fired
                    and replica_idx == self.serve_wedge_replica - 1
                    and self._serve_computes >= self.serve_wedge_after):
                self._wedge_fired = True
                _log(f"wedging replica #{self.serve_wedge_replica} at "
                     f"pool compute #{self._serve_computes}")
                return True
            if (self.serve_nan_after and self._poisoned is None
                    and self._serve_computes >= self.serve_nan_after):
                self._poisoned = (int(replica_idx), int(generation))
                _log(f"NaN-poisoning replica {replica_idx} "
                     f"(generation {generation}) from pool compute "
                     f"#{self._serve_computes}")
            return False

    def serve_slow_delay_s(self) -> float:
        """Seconds THIS replica compute must sleep (0.0 = run clean).
        With ``serve_slow_for`` set, only the first m computes are
        slowed — the deterministic lift point of the 504-storm drill;
        without it the slowness persists for the process."""
        if not self.serve_slow_replica_ms:
            return 0.0
        with _SERVE_LOCK:
            self._slow_computes += 1
            if (self.serve_slow_for
                    and self._slow_computes > self.serve_slow_for):
                if not self._slow_lifted_logged:
                    self._slow_lifted_logged = True
                    _log(f"slow-replica fault lifted after "
                         f"{self.serve_slow_for} computes")
                return 0.0
            return self.serve_slow_replica_ms / 1000.0

    def serve_poisoned(self, replica_idx: int, generation: int) -> bool:
        """True while (replica, generation) is the poisoned one — a
        rebuilt replica (new generation) runs clean, which is the
        transient corrupted-buffer model."""
        with _SERVE_LOCK:
            return self._poisoned == (int(replica_idx), int(generation))

    def note_serve_reload(self) -> None:
        """Reload/rebuild injection point (registry.reload + pool
        rebuild). The j-th call in this process fails."""
        with _SERVE_LOCK:
            self._serve_reloads += 1
            fire = (self.serve_fail_reload
                    and self._serve_reloads == self.serve_fail_reload)
            n = self._serve_reloads
        if fire:
            _log(f"failing serve reload #{n}")
            raise InjectedFaultError(f"injected reload failure #{n}")


_plan: Optional[FaultPlan] = None
_env_checked = False


def _env_int(name: str) -> int:
    for prefix in ("DPSVM_FAULT_", "BENCH_FAULT_"):
        v = os.environ.get(prefix + name, "").strip()
        if v:
            try:
                return int(v)
            except ValueError:
                _log(f"ignoring non-integer {prefix}{name}={v!r}")
    return 0


def plan_from_env() -> Optional[FaultPlan]:
    p = FaultPlan(
        fail_checkpoint_write=_env_int("CHECKPOINT_WRITE"),
        nan_at_iter=_env_int("NAN_ITER"),
        preempt_at_poll=_env_int("PREEMPT_POLL"),
        serve_wedge_replica=_env_int("SERVE_WEDGE_REPLICA"),
        serve_wedge_after=_env_int("SERVE_WEDGE_AFTER"),
        serve_nan_after=_env_int("SERVE_NAN_AFTER"),
        serve_fail_reload=_env_int("SERVE_FAIL_RELOAD"),
        serve_slow_replica_ms=_env_int("SERVE_SLOW_REPLICA_MS"),
        serve_slow_for=_env_int("SERVE_SLOW_FOR"),
        dist_kill_shard=_env_int("DIST_KILL_SHARD"),
        dist_kill_poll=_env_int("DIST_KILL_POLL"),
        dist_desync_at=_env_int("DIST_DESYNC_AT"),
        dist_desync_shard=_env_int("DIST_DESYNC_SHARD"),
        dist_slow_shard=_env_int("DIST_SLOW_SHARD"),
        host_kill=_env_int("HOST_KILL"),
        host_hang_ms=_env_int("HOST_HANG_MS"),
        io_read_fail_once=_env_int("IO_READ_FAIL_ONCE"),
        io_corrupt_shard=_env_int("IO_CORRUPT_SHARD"),
        io_truncate_shard=_env_int("IO_TRUNCATE_SHARD"),
        io_slow_read_ms=_env_int("IO_SLOW_READ_MS"),
        live_torn_publish=_env_int("LIVE_TORN_PUBLISH"),
        live_stale_generation=_env_int("LIVE_STALE_GENERATION"),
        live_writer_crash_after=_env_int("LIVE_WRITER_CRASH_AFTER"),
        live_shift_at_shard=_env_int("LIVE_SHIFT_AT_SHARD"),
        cascade_stop_stage=_env_int("CASCADE_STOP_STAGE"),
        preflight_wedge_s=_env_int("PREFLIGHT_WEDGE_S"))
    return p if p.any() else None


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Explicitly set (or with None, clear) the process fault plan —
    the API-level seam tests use instead of env vars."""
    global _plan, _env_checked
    _plan = plan
    _env_checked = True
    return plan


def clear() -> None:
    global _plan, _env_checked
    _plan = None
    _env_checked = False


def current() -> Optional[FaultPlan]:
    """The active plan: an installed one, else env-configured (resolved
    once per process), else None. The no-fault path costs one global
    read."""
    global _plan, _env_checked
    if not _env_checked:
        _env_checked = True
        _plan = plan_from_env()
        if _plan is not None:
            _log(f"active plan: {_plan}")
    return _plan


def on_checkpoint_write(path: str) -> None:
    """save_checkpoint's injection point (utils/checkpoint.py)."""
    p = current()
    if p is not None:
        p.note_checkpoint_write(path)


# Wedged replica workers block here. In-process tests release them at
# teardown; a chaos subprocess just exits around the daemon thread.
_WEDGE_EVENT = threading.Event()


def serve_wedge_wait(timeout: Optional[float] = None) -> None:
    """Block the calling replica worker until released (the wedge)."""
    _WEDGE_EVENT.wait(timeout)


def release_serve_wedge() -> None:
    """Unstick every wedged worker (test teardown)."""
    _WEDGE_EVENT.set()


def reset_serve_wedge() -> None:
    """Re-arm the wedge barrier (paired with ``clear()`` in tests)."""
    global _WEDGE_EVENT
    _WEDGE_EVENT = threading.Event()


def on_serve_reload() -> None:
    """registry.reload / pool-rebuild injection point."""
    p = current()
    if p is not None:
        p.note_serve_reload()

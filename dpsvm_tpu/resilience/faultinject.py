"""Deterministic fault injection for the resilience subsystem.

Every failure mode the resilience stack handles — preemption signals,
corrupted/failed checkpoint writes, non-finite solver state — is rare
and timing-dependent in the wild, so each one has a deterministic
injection point that fires at an exact, configured moment. That makes
the whole subsystem testable in CI on CPU (tests/test_resilience.py,
``python -m dpsvm_tpu.resilience --selfcheck``) and soakable on real
hardware (``BENCH_FAULT_*`` through bench.py / benchmarks/
burst_runner.py).

Knobs (env: ``DPSVM_FAULT_*``, with ``BENCH_FAULT_*`` accepted as
aliases so benchmark harness configs stay in the BENCH_ namespace; API:
``install(FaultPlan(...))``):

* ``DPSVM_FAULT_CHECKPOINT_WRITE=k`` — the k-th (1-based)
  ``save_checkpoint`` call in this process fails after the tmp write,
  before the rename (exercises atomicity + rotation fallback);
* ``DPSVM_FAULT_NAN_ITER=j`` — the first stats poll observing
  ``n_iter >= j`` reports a NaN gap (exercises the HealthMonitor's
  non-finite detection and the rollback policy);
* ``DPSVM_FAULT_PREEMPT_POLL=m`` — the m-th (1-based) host poll raises
  a simulated preemption (exercises snapshot + resumable exit + retry
  supervisor without OS signal timing races).

Serving-side knobs (``DPSVM_FAULT_SERVE_*``, consumed by
``serving/pool.py`` / ``serving/registry.py`` — docs/SERVING.md
"Resilience"):

* ``DPSVM_FAULT_SERVE_WEDGE_REPLICA=k`` — replica **#k** (1-based)
  wedges: its worker blocks forever at the next compute (release with
  ``release_serve_wedge()`` in in-process tests; a chaos subprocess
  just abandons the daemon thread). Combine with
  ``DPSVM_FAULT_SERVE_WEDGE_AFTER=m`` to delay the wedge until the
  pool has served ``m`` computes (fault mid-loadgen, after warmup);
* ``DPSVM_FAULT_SERVE_NAN_AFTER=m`` — the replica that serves the
  m-th pool compute becomes NaN-poisoned: every output it produces
  from then on is non-finite, until the pool rebuilds it (the poison
  is pinned to the replica *generation*, so the rebuilt replica is
  clean — the transient device-buffer-corruption model);
* ``DPSVM_FAULT_SERVE_FAIL_RELOAD=j`` — the j-th (1-based) engine
  reload/rebuild in this process fails (exercises
  failed-reload-keeps-serving and the rebuild retry loop).

Each fault fires exactly ONCE per process: counters live on the
process-global plan, so a supervisor retry inside the same process (or
a resumed attempt) runs clean after the injected failure — which is
exactly the transient-fault model the subsystem exists for.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
from typing import Optional, Tuple


class InjectedFaultError(OSError):
    """Raised by the checkpoint-write injection point (an OSError, like
    the real failures it stands in for)."""


#: serve hooks are hit from concurrent replica workers (the training
#: hooks are single-threaded and stay lock-free)
_SERVE_LOCK = threading.Lock()


def _log(msg: str) -> None:
    print(f"FAULTINJECT: {msg}", file=sys.stderr, flush=True)


@dataclasses.dataclass
class FaultPlan:
    fail_checkpoint_write: int = 0   # 1-based save counter; 0 = off
    nan_at_iter: int = 0             # poison first poll with n_iter >= j
    preempt_at_poll: int = 0         # 1-based host-poll counter
    # serving-side (docstring above): replica NUMBERS are 1-based,
    # matching the other knobs' "the k-th" convention; 0 = off.
    serve_wedge_replica: int = 0     # replica #k wedges at a compute
    serve_wedge_after: int = 0       # ...once pool computes >= m
    serve_nan_after: int = 0         # poison the replica serving
    #                                  compute #m until it is rebuilt
    serve_fail_reload: int = 0       # 1-based reload/rebuild counter

    # process-lifetime counters (fire-once semantics)
    _writes: int = 0
    _polls: int = 0
    _nan_fired: bool = False
    _serve_computes: int = 0
    _serve_reloads: int = 0
    _wedge_fired: bool = False
    _poisoned: Optional[Tuple[int, int]] = None  # (replica, generation)

    def any(self) -> bool:
        return bool(self.fail_checkpoint_write or self.nan_at_iter
                    or self.preempt_at_poll or self.serve_wedge_replica
                    or self.serve_nan_after or self.serve_fail_reload)

    def note_checkpoint_write(self, path: str) -> None:
        self._writes += 1
        if (self.fail_checkpoint_write
                and self._writes == self.fail_checkpoint_write):
            _log(f"failing checkpoint write #{self._writes} -> {path}")
            raise InjectedFaultError(
                f"injected checkpoint-write failure #{self._writes}")

    def note_poll(self) -> bool:
        """True exactly at the configured poll — the driver then
        simulates a preemption signal."""
        self._polls += 1
        if self.preempt_at_poll and self._polls == self.preempt_at_poll:
            _log(f"simulating preemption at poll #{self._polls}")
            return True
        return False

    def poison_stats(self, st):
        """Replace b_lo with NaN on the first qualifying poll (a stand-in
        for device-state corruption observed at the poll boundary)."""
        if (self.nan_at_iter and not self._nan_fired
                and st.n_iter >= self.nan_at_iter):
            self._nan_fired = True
            _log(f"poisoning stats with NaN gap at iter {st.n_iter}")
            return st._replace(b_lo=float("nan"))
        return st

    # -- serving-side injection points (serving/pool.py). Unlike the
    # single-threaded training hooks, these are hit from concurrent
    # replica workers — counters advance under the module serve lock.

    def note_serve_compute(self, replica_idx: int,
                           generation: int) -> bool:
        """Called by a replica worker as a compute begins. Returns True
        exactly when THIS compute should wedge (the worker then blocks
        on the module wedge event). Also arms the NaN poison: the
        replica serving the m-th pool compute becomes poisoned for its
        current generation."""
        with _SERVE_LOCK:
            self._serve_computes += 1
            if (self.serve_wedge_replica and not self._wedge_fired
                    and replica_idx == self.serve_wedge_replica - 1
                    and self._serve_computes >= self.serve_wedge_after):
                self._wedge_fired = True
                _log(f"wedging replica #{self.serve_wedge_replica} at "
                     f"pool compute #{self._serve_computes}")
                return True
            if (self.serve_nan_after and self._poisoned is None
                    and self._serve_computes >= self.serve_nan_after):
                self._poisoned = (int(replica_idx), int(generation))
                _log(f"NaN-poisoning replica {replica_idx} "
                     f"(generation {generation}) from pool compute "
                     f"#{self._serve_computes}")
            return False

    def serve_poisoned(self, replica_idx: int, generation: int) -> bool:
        """True while (replica, generation) is the poisoned one — a
        rebuilt replica (new generation) runs clean, which is the
        transient corrupted-buffer model."""
        with _SERVE_LOCK:
            return self._poisoned == (int(replica_idx), int(generation))

    def note_serve_reload(self) -> None:
        """Reload/rebuild injection point (registry.reload + pool
        rebuild). The j-th call in this process fails."""
        with _SERVE_LOCK:
            self._serve_reloads += 1
            fire = (self.serve_fail_reload
                    and self._serve_reloads == self.serve_fail_reload)
            n = self._serve_reloads
        if fire:
            _log(f"failing serve reload #{n}")
            raise InjectedFaultError(f"injected reload failure #{n}")


_plan: Optional[FaultPlan] = None
_env_checked = False


def _env_int(name: str) -> int:
    for prefix in ("DPSVM_FAULT_", "BENCH_FAULT_"):
        v = os.environ.get(prefix + name, "").strip()
        if v:
            try:
                return int(v)
            except ValueError:
                _log(f"ignoring non-integer {prefix}{name}={v!r}")
    return 0


def plan_from_env() -> Optional[FaultPlan]:
    p = FaultPlan(
        fail_checkpoint_write=_env_int("CHECKPOINT_WRITE"),
        nan_at_iter=_env_int("NAN_ITER"),
        preempt_at_poll=_env_int("PREEMPT_POLL"),
        serve_wedge_replica=_env_int("SERVE_WEDGE_REPLICA"),
        serve_wedge_after=_env_int("SERVE_WEDGE_AFTER"),
        serve_nan_after=_env_int("SERVE_NAN_AFTER"),
        serve_fail_reload=_env_int("SERVE_FAIL_RELOAD"))
    return p if p.any() else None


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Explicitly set (or with None, clear) the process fault plan —
    the API-level seam tests use instead of env vars."""
    global _plan, _env_checked
    _plan = plan
    _env_checked = True
    return plan


def clear() -> None:
    global _plan, _env_checked
    _plan = None
    _env_checked = False


def current() -> Optional[FaultPlan]:
    """The active plan: an installed one, else env-configured (resolved
    once per process), else None. The no-fault path costs one global
    read."""
    global _plan, _env_checked
    if not _env_checked:
        _env_checked = True
        _plan = plan_from_env()
        if _plan is not None:
            _log(f"active plan: {_plan}")
    return _plan


def on_checkpoint_write(path: str) -> None:
    """save_checkpoint's injection point (utils/checkpoint.py)."""
    p = current()
    if p is not None:
        p.note_checkpoint_write(path)


# Wedged replica workers block here. In-process tests release them at
# teardown; a chaos subprocess just exits around the daemon thread.
_WEDGE_EVENT = threading.Event()


def serve_wedge_wait(timeout: Optional[float] = None) -> None:
    """Block the calling replica worker until released (the wedge)."""
    _WEDGE_EVENT.wait(timeout)


def release_serve_wedge() -> None:
    """Unstick every wedged worker (test teardown)."""
    _WEDGE_EVENT.set()


def reset_serve_wedge() -> None:
    """Re-arm the wedge barrier (paired with ``clear()`` in tests)."""
    global _WEDGE_EVENT
    _WEDGE_EVENT = threading.Event()


def on_serve_reload() -> None:
    """registry.reload / pool-rebuild injection point."""
    p = current()
    if p is not None:
        p.note_serve_reload()

"""Divergence guards: solver-state health checks at the poll boundary.

Every signal the monitor reads — n_iter, b_lo, b_hi, SV count — already
rides the solvers' packed-stats transfer (solver/driver.py "Poll
economics"), so monitoring costs ZERO extra device->host traffic; the
"adaptive shrinking" line of work (arXiv:1406.5161) motivates treating
solver-state health as a first-class monitored signal rather than
letting a sick run burn its whole iteration budget.

Detections:

* **non-finite gap** — a NaN/inf b_lo or b_hi. Without the guard a NaN
  gap is WORSE than a hang: every float comparison with NaN is False,
  so the driver's ``not (b_lo > b_hi + 2 eps)`` reads as *converged*
  and the run returns garbage marked success;
* **gap stagnation** — no strict improvement of the best-seen gap for
  ``health_window`` iterations (convergence is non-monotone per-chunk,
  so the window should span many chunks);
* **SV-count collapse** — the support set shrinking to under 1/8 of
  its peak (peak >= 64) while the gap is still open: alpha mass
  draining to zero mid-run is a classic symptom of corrupted state.

The non-finite guard is always armed — a NaN gap is never legitimate
(and without it the run would *return converged* — see the driver's
finite-aware verdict). Stagnation and collapse are trajectory-shape
HEURISTICS: they arm only when ``health_window > 0`` (explicit
opt-in), because a heuristic wired to the default ``raise`` policy
must not be able to kill a legitimate run (e.g. the nu/one-class
wrappers seed alpha densely and legitimately shed SVs).

Policy (``SVMConfig.on_divergence``): ``"raise"`` fails fast with
``DivergenceError``; ``"rollback"`` has the driver restore the newest
intact checkpoint and continue with a halved ``chunk_iters`` (bounded
by MAX_ROLLBACKS — a deterministic divergence would otherwise loop
forever); ``"ignore"`` records a trace event and keeps going.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Optional

POLICIES = ("raise", "rollback", "ignore")

#: Rollbacks allowed per run before the monitor escalates to raise.
MAX_ROLLBACKS = 3

#: Collapse = n_sv * COLLAPSE_FACTOR < peak, once peak >= COLLAPSE_MIN_PEAK.
COLLAPSE_FACTOR = 8
COLLAPSE_MIN_PEAK = 64


class DivergenceError(RuntimeError):
    """The HealthMonitor detected an unhealthy run and the policy says
    fail fast (or rollback options were exhausted/unavailable)."""

    def __init__(self, reason: str, n_iter: int):
        self.reason = reason
        self.n_iter = int(n_iter)
        super().__init__(
            f"training diverged at iteration {n_iter}: {reason}")


class DesyncError(DivergenceError):
    """Cross-shard desync (resilience/elastic.py): shards disagree on
    replicated-by-construction poll state. Subclasses DivergenceError
    because it rides the same ``on_divergence`` policy — callers that
    catch divergence handle desync too, and ones that care WHICH guard
    tripped can still tell."""


class HealthMonitor:
    """Per-run divergence detector, fed one ChunkStats-shaped poll at a
    time by host_training_loop. check() returns a reason string on the
    first detection of each kind (None = healthy)."""

    def __init__(self, policy: str = "raise", window: int = 0):
        if policy not in POLICIES:
            raise ValueError(f"on_divergence must be one of {POLICIES}, "
                             f"got {policy!r}")
        self.policy = policy
        self.window = int(window)
        self.rollbacks = 0
        self._best_gap = math.inf
        self._best_iter: Optional[int] = None
        self._peak_sv = 0
        self._reported: set = set()

    @property
    def exhausted(self) -> bool:
        return self.rollbacks >= MAX_ROLLBACKS

    def note_rollback(self, n_iter: int) -> None:
        """Reset progress tracking after the driver restored a
        checkpoint — the rolled-back trajectory re-earns its window."""
        self.rollbacks += 1
        self._best_gap = math.inf
        self._best_iter = int(n_iter)
        self._peak_sv = 0
        self._reported.clear()

    def _once(self, key: str, reason: str) -> Optional[str]:
        if key in self._reported:
            return None
        self._reported.add(key)
        return reason

    def check(self, *, n_iter: int, b_lo: float, b_hi: float,
              n_sv: int = 0) -> Optional[str]:
        if not (math.isfinite(b_lo) and math.isfinite(b_hi)):
            return self._once(
                "nonfinite",
                f"non-finite optimality gap (b_lo={b_lo}, b_hi={b_hi})")
        if not self.window:         # heuristic guards are opt-in
            return None
        gap = b_lo - b_hi
        self._peak_sv = max(self._peak_sv, int(n_sv))
        if (self._peak_sv >= COLLAPSE_MIN_PEAK
                and int(n_sv) * COLLAPSE_FACTOR < self._peak_sv):
            return self._once(
                "collapse",
                f"SV count collapsed to {n_sv} from a peak of "
                f"{self._peak_sv} with the gap still open ({gap:.4g})")
        if self._best_iter is None:
            self._best_iter = int(n_iter)
        if gap < self._best_gap - 1e-12:
            self._best_gap = gap
            self._best_iter = int(n_iter)
        elif int(n_iter) - self._best_iter >= self.window:
            return self._once(
                "stagnation",
                f"gap stagnant at {self._best_gap:.6g} for "
                f"{int(n_iter) - self._best_iter} iterations "
                f"(window {self.window})")
        return None


class ReplicaMonitor:
    """Serving-side health: the HealthMonitor's window shape applied
    to a prediction replica's two observable vitals (serving/pool.py).

    * **non-finite outputs** — like the training-side NaN-gap guard,
      always armed and never legitimate: the HTTP layer rejects
      non-finite *inputs* at admission and model parameters are
      finite, so a NaN/inf decision value means corrupted replica
      state (a poisoned device buffer). One occurrence is grounds for
      ejection.
    * **latency** — a rolling window of per-dispatch wall times. A
      dispatch that blows the pool deadline while *running* on the
      replica marks it wedged (the pool decides that; the monitor
      records it). The window also feeds the p99-based hedge delay
      (serving/budget.hedge_delay_s).

    Thread-safe: workers record, the reaper and /metricsz read."""

    def __init__(self, window: int = 256):
        self._lat_ms: deque = deque(maxlen=int(window))
        self._lock = threading.Lock()
        self._dispatches = 0
        self._nonfinite = 0
        self._timeouts = 0

    def note_latency(self, ms: float) -> None:
        with self._lock:
            self._dispatches += 1
            self._lat_ms.append(float(ms))

    def note_nonfinite(self) -> None:
        """One compute returned non-finite values — never legitimate
        (see class docstring); the pool ejects on the first report."""
        with self._lock:
            self._nonfinite += 1

    def note_timeout(self) -> None:
        with self._lock:
            self._timeouts += 1

    @property
    def nonfinite(self) -> int:
        with self._lock:
            return self._nonfinite

    def latencies_ms(self) -> "list[float]":
        with self._lock:
            return list(self._lat_ms)

    def stats(self) -> dict:
        with self._lock:
            lat = list(self._lat_ms)
            out = {"dispatches": self._dispatches,
                   "nonfinite": self._nonfinite,
                   "timeouts": self._timeouts}
        if lat:
            s = sorted(lat)
            out["p50_ms"] = round(s[len(s) // 2], 3)
            out["p99_ms"] = round(s[min(len(s) - 1,
                                        int(len(s) * 0.99))], 3)
        return out

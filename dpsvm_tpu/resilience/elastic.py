"""Elastic distributed training: the fault model for the mesh.

The reference's cluster story dies with its weakest rank: a lost MPI
process kills the whole ``mpirun`` job (``svmTrainMain.cpp:153``), and
at cluster scale node loss and stragglers are the DOMINANT failure
modes (arXiv:1406.5161 §6, arXiv:1404.1066 §4). This module gives the
SPMD trainers (parallel/dist_smo.py, dist_decomp.py) the pieces the
single-process resilience stack (preempt/health/supervisor) cannot
provide on its own:

* **shard probes** — each shard appends its own view of the
  replicated-by-construction poll scalars (n_iter, b_lo, b_hi) to the
  packed-stats transfer (one extra ``(3P,)`` i32 tail on the SAME
  device array — still ONE D2H transfer per chunk). Disagreement
  between shards on values that are replicated by construction means a
  desynchronized mesh (corrupted collective, flaky interconnect):
  ``DesyncDetector`` reports it once, the driver emits a ``desync``
  trace event and feeds the existing ``on_divergence`` policy
  (raise → ``DesyncError``; rollback → restore the newest intact
  checkpoint, exactly the recovery a desync needs);
* **shard heartbeats** — host-side per-shard freshness derived from the
  probes: a shard whose reported progress stops advancing while the
  others move is a straggler. Ages ride every chunk record
  (``shard_ages``) and feed the stall watchdog's dist-aware verdict
  (host stall vs collective hang vs straggler — ``stall_extras``);
* **shard loss + degraded-mesh resume** — ``ShardLostError`` is the
  transient "a host died" signal (injectable via
  ``DPSVM_FAULT_DIST_KILL_SHARD``); ``run_elastic`` is the supervisor
  loop that catches it, shrinks the mesh to the survivors, and resumes
  from the newest intact shard-aware checkpoint (utils/checkpoint.py
  records the save-time mesh + per-shard CRCs; the state is the global
  unpadded (alpha, f), so ``prepare_distributed_inputs`` re-pads it
  onto ANY device count — ``reshard`` + ``retry`` trace events, final
  model bit-compatible with an uninterrupted run).

Everything here is CPU-testable: the fault injector
(resilience/faultinject.py ``DPSVM_FAULT_DIST_*``) makes each behavior
a deterministic drill on virtual devices
(``XLA_FLAGS=--xla_force_host_platform_device_count``), wired into
``python -m dpsvm_tpu.resilience --selfcheck`` and
tests/test_elastic.py.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

import numpy as np

#: Per-shard probe lanes appended to the packed stats by the SPMD chunk
#: runners: [n_iter, b_lo bits, b_hi bits] as i32 (floats ride as exact
#: bit patterns, like the replicated lanes — solver/driver.pack_stats).
PROBE_WIDTH = 3


class ShardLostError(RuntimeError):
    """A mesh shard (host/device) was lost mid-run. TRANSIENT: the run
    is resumable on the surviving mesh from the newest intact
    checkpoint (``run_elastic`` automates exactly that loop)."""

    def __init__(self, shard: int, shards: int, n_iter: int):
        self.shard = int(shard)          # 0-based lost shard
        self.shards = int(shards)        # mesh size at loss
        self.n_iter = int(n_iter)
        super().__init__(
            f"shard {shard}/{shards} lost at iteration {n_iter}; "
            f"resume on the surviving mesh from the newest intact "
            f"checkpoint (run_elastic / dpsvm train --retries)")


def probe_values(probes: np.ndarray) -> List[dict]:
    """Decode a (P, 3) i32 probe block into per-shard host values."""
    probes = np.asarray(probes, np.int32).reshape(-1, PROBE_WIDTH)
    out = []
    for row in probes:
        b = row[1:3].view(np.float32)
        out.append({"n_iter": int(row[0]), "b_lo": float(b[0]),
                    "b_hi": float(b[1])})
    return out


def desync_reason(probes: np.ndarray) -> Optional[str]:
    """Reason string when shards disagree on replicated-by-construction
    values (None = consistent). Bit-level comparison: the loop's
    all_gather makes every shard's (b_lo, b_hi) at a given n_iter
    identical down to the bit pattern, so shards reporting the SAME
    iteration with different gap bits are a desynchronized mesh, not
    numerical noise. Shards at DIFFERENT iterations are lag, not
    desync — that is the straggler signal, owned by the heartbeat ages
    (``ShardHeartbeats``), so a slow shard never false-positives the
    desync guard."""
    probes = np.asarray(probes, np.int32).reshape(-1, PROBE_WIDTH)
    if len(probes) < 2:
        return None
    lead = int(probes[:, 0].max())
    lead_mask = probes[:, 0] == lead
    ref_idx = int(np.argmax(lead_mask))
    ref = probes[ref_idx]
    bad = [k for k in range(len(probes))
           if lead_mask[k] and not bool((probes[k] == ref).all())]
    if not bad:
        return None
    vals = probe_values(probes)
    return (f"cross-shard desync on replicated poll state at iteration "
            f"{lead}: shard(s) {bad} disagree with shard {ref_idx} "
            f"(shard {ref_idx}: {vals[ref_idx]}; "
            f"shard {bad[0]}: {vals[bad[0]]})")


class DesyncDetector:
    """Once-per-incident desync reporter fed by the driver at each
    poll; ``reset()`` after a rollback re-arms it (the restored state
    must re-earn a clean bill)."""

    def __init__(self):
        self._reported = False

    def check(self, probes) -> Optional[str]:
        if probes is None or self._reported:
            return None
        reason = desync_reason(probes)
        if reason is not None:
            self._reported = True
        return reason

    def reset(self) -> None:
        self._reported = False


class ShardHeartbeats:
    """Host-side per-shard freshness from the poll probes.

    A shard's heartbeat is the wall-clock time since its reported
    n_iter last ADVANCED. Under healthy SPMD every shard advances at
    every poll, so ages hover near zero; a shard whose probe stops
    moving while others advance (straggler, wedged host — simulated by
    ``DPSVM_FAULT_DIST_SLOW_SHARD``) ages visibly. The ages ride every
    chunk record and back the stall watchdog's dist verdict."""

    def __init__(self, shards: int):
        self.shards = int(shards)
        self._last_iter = np.full((self.shards,), -1, np.int64)
        self._last_seen = np.full((self.shards,), time.monotonic())
        self._last_poll = time.monotonic()

    def note_poll(self, probes) -> List[float]:
        """Record one poll's probes; return per-shard ages (seconds,
        rounded) for the chunk record."""
        now = time.monotonic()
        self._last_poll = now
        if probes is not None:
            probes = np.asarray(probes, np.int32).reshape(
                -1, PROBE_WIDTH)
            for k in range(min(self.shards, len(probes))):
                if int(probes[k, 0]) > self._last_iter[k]:
                    self._last_iter[k] = int(probes[k, 0])
                    self._last_seen[k] = now
        return [round(now - t, 3) for t in self._last_seen]

    def ages(self) -> List[float]:
        now = time.monotonic()
        return [round(now - t, 3) for t in self._last_seen]

    def poll_age(self) -> float:
        return time.monotonic() - self._last_poll


# The one active dist run's heartbeats, consulted by the stall
# watchdog's emergency exit (utils/watchdog.py) from its own thread —
# microseconds before os._exit, while the training thread is wedged in
# a device call, so a lock suffices for the handoff.
_ACTIVE_LOCK = threading.Lock()
_ACTIVE: Optional[ShardHeartbeats] = None


def register_heartbeats(hb: Optional[ShardHeartbeats]) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = hb


def stall_extras() -> dict:
    """Dist-aware facts for the watchdog's ``stall`` event: a verdict
    separating *host stall / collective hang* (the whole mesh stopped
    answering — every shard exactly as stale as the last poll) from a
    *straggler* (one shard's progress lags the rest). Empty for
    single-device runs — the stall event stays exactly what it was."""
    with _ACTIVE_LOCK:
        hb = _ACTIVE
    if hb is None:
        return {}
    ages = hb.ages()
    poll_age = round(hb.poll_age(), 3)
    spread = max(ages) - min(ages)
    if spread > max(1.0, 0.5 * poll_age):
        verdict = (f"straggler-shard-"
                   f"{int(np.argmax(np.asarray(ages)))}")
    else:
        verdict = "collective-hang"
    return {"dist_verdict": verdict, "shards": hb.shards,
            "shard_ages": ages, "poll_age": poll_age}


def surviving_shards(shards: int, min_shards: int = 1) -> int:
    """Mesh size after losing one shard: the survivors. Any size works
    — the checkpoint state is global and re-pads to any mesh — so the
    policy is simply P-1, floored at ``min_shards``."""
    return max(int(shards) - 1, int(min_shards), 1)


def run_elastic(fn: Callable[[Optional[str], int, int], object], *,
                shards: int, retries: int,
                checkpoint_path: Optional[str] = None,
                min_shards: int = 1, backoff_s: float = 0.0,
                sleep: Callable[[float], None] = time.sleep):
    """Elastic supervisor: ``fn(resume_from, shards, attempt)`` runs
    the training; a ``ShardLostError`` shrinks the mesh to the
    survivors and resumes from the newest intact checkpoint (with
    ``reshard`` + ``retry`` queued into the next attempt's trace); a
    ``PreemptedError`` retries on the SAME mesh (the in-process
    supervisor's behavior). Anything else — including a
    ``DivergenceError`` the rollback budget could not absorb — fails
    fast. The sibling of ``supervisor.run_with_retries`` for meshes."""
    from dpsvm_tpu.resilience.preempt import PreemptedError
    from dpsvm_tpu.resilience.supervisor import _log, newest_intact

    attempt = 0
    p = int(shards)
    while True:
        resume, skipped = newest_intact(checkpoint_path)
        if skipped and resume:
            _log(f"skipping unreadable checkpoint slot(s) "
                 f"{skipped} -> resuming {resume}")
        try:
            return fn(resume, p, attempt)
        except (ShardLostError, PreemptedError) as e:
            if attempt >= retries:
                raise
            delay = backoff_s * (2 ** attempt)
            attempt += 1
            from dpsvm_tpu.solver import driver
            if isinstance(e, ShardLostError):
                # The `reshard` trace event itself comes from
                # resume_state when the next attempt loads the
                # checkpoint (it knows the recorded vs current mesh);
                # the supervisor only shrinks and retries.
                survivors = surviving_shards(p, min_shards)
                _log(f"shard {e.shard}/{p} lost at iter {e.n_iter}; "
                     f"retry {attempt}/{retries} on the surviving "
                     f"{survivors}-shard mesh in {delay:.1f}s")
                p = survivors
            else:
                _log(f"preempted at iter {e.n_iter}; retry "
                     f"{attempt}/{retries} in {delay:.1f}s")
            driver.queue_trace_event("retry", attempt=attempt,
                                     resumed_from=resume)
            if delay > 0:
                sleep(delay)

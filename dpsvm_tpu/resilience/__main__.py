"""CLI gate: ``python -m dpsvm_tpu.resilience --selfcheck``.

Runs on CPU without any accelerator (forces JAX_PLATFORMS=cpu when the
ambient env doesn't pin a platform) — the CI twin of
``python -m dpsvm_tpu.telemetry --selfcheck``. ``--selfcheck`` includes
the kill-one-HOST drill (real subprocesses; resilience/hostgroup.py);
``--host-drill`` runs ONLY that drill and prints its facts as a final
JSON line — the burst runner's ``host_loss_drill`` tag harvests the
``host_loss_recovery_s`` metric from it (benchmarks/burst_runner.py).
``--straggler-drill`` runs the fleet-observability acceptance drill
(planted per-poll hang on one host; merged trace + skew rule + metrics
federation + incident bundle must all name it) the same way — the
burst runner's ``straggler_drill`` tag harvests ``straggler_behind_s``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="python -m dpsvm_tpu.resilience")
    p.add_argument("--selfcheck", action="store_true",
                   help="injector + supervisor round-trip on a tiny "
                        "problem; asserts the resumed trajectory is "
                        "bitwise-identical to an uninterrupted run "
                        "(incl. the kill-one-shard degraded-mesh "
                        "drill on a virtual-device mesh AND the "
                        "kill-one-host reformation drill on real "
                        "localhost host processes)")
    p.add_argument("--host-drill", action="store_true",
                   help="run only the kill-one-host drill: 3 "
                        "single-device localhost hosts training over "
                        "a cross-process mesh, one SIGKILLed mid-run, "
                        "survivors reformed from the newest intact "
                        "checkpoint; prints the drill facts "
                        "(host_loss_recovery_s, model deltas) as a "
                        "final JSON line")
    p.add_argument("--straggler-drill", action="store_true",
                   help="run the fleet-observability straggler drill: "
                        "3 localhost hosts with a planted per-poll "
                        "hang on host 1; asserts the merged trace, "
                        "skew rule, metrics federation, and fleet "
                        "incident bundle all name the straggler; "
                        "prints the drill facts as a final JSON line")
    args = p.parse_args(argv)
    if not (args.selfcheck or args.host_drill or args.straggler_drill):
        p.print_help()
        return 2
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.straggler_drill:
        # Pure supervisor process, same as --host-drill: hosts are
        # subprocesses; this process never initialises jax.
        from dpsvm_tpu.resilience import hostgroup

        with tempfile.TemporaryDirectory() as td:
            facts = hostgroup.straggler_drill(td)
        print("straggler drill OK: "
              f"host {facts['straggler']} behind "
              f"{facts['straggler_behind_s']:.2f}s over "
              f"{facts['hosts']} hosts, skew fired "
              f"{facts['skew_fired']}x, bundle validated",
              file=sys.stderr)
        print(json.dumps(facts))
        return 0
    if args.host_drill:
        # Pure supervisor process: the hosts are subprocesses with
        # their own (single-device) jax; this process touches none.
        from dpsvm_tpu.resilience import hostgroup

        with tempfile.TemporaryDirectory() as td:
            facts = hostgroup.host_loss_drill(td)
        print("host-loss drill OK: "
              f"recovered in {facts['host_loss_recovery_s']:.2f}s, "
              f"{facts['hosts']} -> {facts['surviving_hosts']} hosts, "
              f"coef delta {facts['coef_delta']:g}"
              + (" (bitwise)" if facts.get("bitwise") else ""),
              file=sys.stderr)
        print(json.dumps(facts))
        return 0
    if os.environ["JAX_PLATFORMS"] == "cpu":
        # The kill-shard drill needs a mesh: force virtual CPU devices
        # unless the caller already pinned a device count (same pattern
        # as tests/conftest.py).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4"
            ).strip()
    from dpsvm_tpu.resilience import selfcheck

    problems = selfcheck(host_drill=True)
    if problems:
        print("resilience selfcheck FAILED:", file=sys.stderr)
        for pr in problems:
            print(f"  {pr}", file=sys.stderr)
        return 1
    print("resilience selfcheck OK (preempt + retry + rotation "
          "fallback + kill-shard degraded-mesh drill + kill-host "
          "reformation drill, bitwise-identical resume)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""CLI gate: ``python -m dpsvm_tpu.resilience --selfcheck``.

Runs on CPU without any accelerator (forces JAX_PLATFORMS=cpu when the
ambient env doesn't pin a platform) — the CI twin of
``python -m dpsvm_tpu.telemetry --selfcheck``.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="python -m dpsvm_tpu.resilience")
    p.add_argument("--selfcheck", action="store_true",
                   help="injector + supervisor round-trip on a tiny "
                        "problem; asserts the resumed trajectory is "
                        "bitwise-identical to an uninterrupted run "
                        "(incl. the kill-one-shard degraded-mesh "
                        "drill on a virtual-device mesh)")
    args = p.parse_args(argv)
    if not args.selfcheck:
        p.print_help()
        return 2
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if os.environ["JAX_PLATFORMS"] == "cpu":
        # The kill-shard drill needs a mesh: force virtual CPU devices
        # unless the caller already pinned a device count (same pattern
        # as tests/conftest.py).
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4"
            ).strip()
    from dpsvm_tpu.resilience import selfcheck

    problems = selfcheck()
    if problems:
        print("resilience selfcheck FAILED:", file=sys.stderr)
        for pr in problems:
            print(f"  {pr}", file=sys.stderr)
        return 1
    print("resilience selfcheck OK (preempt + retry + rotation "
          "fallback + kill-shard degraded-mesh drill, "
          "bitwise-identical resume)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

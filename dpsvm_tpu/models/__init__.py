"""Model objects and serialization."""

from dpsvm_tpu.models.svm import SVMModel, decision_function, predict, evaluate
from dpsvm_tpu.models.io import save_model, load_model

__all__ = [
    "SVMModel",
    "decision_function",
    "predict",
    "evaluate",
    "save_model",
    "load_model",
]

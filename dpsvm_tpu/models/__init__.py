"""Model objects and serialization."""

from dpsvm_tpu.models.svm import SVMModel, decision_function, predict, evaluate
from dpsvm_tpu.models.io import save_model, load_model
from dpsvm_tpu.models.calibration import (fit_platt, predict_proba,
                                          save_platt, load_platt)

__all__ = [
    "SVMModel",
    "decision_function",
    "predict",
    "evaluate",
    "save_model",
    "load_model",
    "fit_platt",
    "predict_proba",
    "save_platt",
    "load_platt",
]

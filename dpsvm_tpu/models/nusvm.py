"""nu-SVM family: nu-SVC (LIBSVM -s 1) and nu-SVR (-s 4).

The nu formulations replace C's per-example cost with a single nu in
(0, 1] that lower-bounds the SV fraction and upper-bounds the margin-
error fraction. Their duals carry TWO equality constraints (one per
class), which the solver honors with ``nu_selection``: working pairs
share a label and the class with the larger KKT gap is optimized first
(LIBSVM's Solver_NU, svm.cpp). Everything else — the compiled loop, the
masks, the pair update — is the unmodified solver, reached through the
same ``alpha_init``/``f_init`` seeding hooks SVR and one-class use:

  * nu-SVC (solve_nu_svc): box [0, 1], sum of each class's alphas
    = nu*n/2, zero linear term (f0 = K (alpha0 y), no -y), pairwise
    clip (the class sums are invariants). Post-solve, the per-class
    thresholds r1/r2 (from the final gradient's free SVs) give
    r = (r1+r2)/2 and rho = (r1-r2)/2; the stored model rescales
    alpha/r with intercept rho/r so the decision function matches
    C-SVC's form (and sklearn.svm.NuSVC's values).
  * nu-SVR (solve_nu_svr): the 2n doubled variables of epsilon-SVR
    (models/svr.py) but with alpha = alpha* = min(C, remaining) seeding
    (sum C*nu*n/2 per half), linear term -+z instead of the epsilon
    tube (the tube width is a RESULT here: epsilon_eff = (r1+r2)/2,
    intercept b = -(r1-r2)/2).

Quality bar: decision/prediction parity against sklearn's NuSVC/NuSVR
(libsvm) at matched hyperparameters — tests/test_nusvm.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.models.svm import SVMModel


def _solve_nu(x, y_pm, alpha0, f0, config: SVMConfig) -> TrainResult:
    """Run the nu_selection solver (single device; the nu family's
    two-constraint selection has no distributed/decomp variant yet)."""
    from dpsvm_tpu.solver.smo import train_single_device

    # The nu family supports neither shrinking nor decomposition, so
    # "auto" sentinels always concretize to the classic path here.
    if config.shrinking == "auto" or config.working_set == 0:
        config = dataclasses.replace(
            config,
            shrinking=(False if config.shrinking == "auto"
                       else config.shrinking),
            working_set=(2 if config.working_set == 0
                         else config.working_set))
    for field, bad in (("shards", config.shards > 1),
                       ("working_set", config.working_set > 2),
                       ("shrinking", config.shrinking is True),
                       ("cache_size", config.cache_size > 0),
                       ("selection", config.selection != "first-order"),
                       ("select_impl",
                        config.select_impl != "argminmax"),
                       ("backend", config.backend == "numpy"),
                       ("use_pallas", config.use_pallas == "on"),
                       # Checkpoints carry no task tag, and a shape-
                       # compatible C-SVC checkpoint resuming here would
                       # silently replace the nu seeding with alphas
                       # violating both equality constraints.
                       ("resume_from", bool(config.resume_from)),
                       ("checkpoint_path", bool(config.checkpoint_path)),
                       ("weight_pos/weight_neg",
                        config.weight_pos != 1.0
                        or config.weight_neg != 1.0)):
        if bad:
            raise ValueError(f"nu-SVM training does not support {field} "
                             "(the two-constraint Solver_NU selection "
                             "runs on the single-device first-order "
                             "path; class weights and checkpoints do "
                             "not compose with the nu constraints)")
    return train_single_device(x, y_pm, config, f_init=f0,
                               alpha_init=alpha0, guard_eta=True,
                               nu_selection=True)


def _class_thresholds(f, y_pm, alpha, c_box):
    """LIBSVM Solver_NU::calculate_rho's (r1, r2) from the final state.

    G_i = y_i f_i (f maintains K(alpha y); the nu duals have no linear
    term). Per class: the average G over free SVs, else the midpoint of
    the active-bound extremes."""
    g = y_pm * f
    out = []
    for sign in (1.0, -1.0):
        cls = y_pm == sign
        free = cls & (alpha > 0) & (alpha < c_box)
        if free.any():
            out.append(float(g[free].mean()))
            continue
        at0 = cls & (alpha == 0)
        atc = cls & (alpha == c_box)
        # alpha=0 can only increase (G too low is a violation): upper
        # candidate; alpha=C can only decrease: lower candidate.
        ub = float(g[at0].min()) if at0.any() else np.inf
        lb = float(g[atc].max()) if atc.any() else -np.inf
        out.append((ub + lb) / 2.0)
    return out[0], out[1]


def _nu_head_seed(total: float, cap: float, n: int) -> np.ndarray:
    """LIBSVM's prefix seeding — min(cap, remaining) in data order — in
    closed form (a_i = clip(total - i*cap, 0, cap)); the sequential loop
    would cost O(n) Python steps at covtype-scale n."""
    a = np.clip(total - cap * np.arange(n, dtype=np.float64), 0.0, cap)
    return a.astype(np.float32)


def train_nusvc(x: np.ndarray, y: np.ndarray, nu: float = 0.5,
                config: Optional[SVMConfig] = None
                ) -> Tuple[SVMModel, TrainResult]:
    """Fit a nu-SVC (LIBSVM -s 1). ``config.c`` is ignored (the nu-SVC
    box is 1 by construction); labels are +/-1."""
    from dpsvm_tpu.ops.diagnostics import _stream_kv

    from dpsvm_tpu.utils import densify
    x = densify(x)
    config = config or SVMConfig()
    precomp = config.kernel == "precomputed"
    if not 0.0 < nu <= 1.0:
        raise ValueError(f"nu must be in (0, 1], got {nu}")
    if config.weight_pos != 1.0 or config.weight_neg != 1.0:
        raise ValueError("class weights do not apply to nu-SVC (the nu "
                         "constraint fixes each class's alpha mass)")
    x = np.asarray(x, np.float32)
    y = np.asarray(y)
    if x.ndim != 2 or y.shape != (x.shape[0],):
        raise ValueError(f"x must be (n, d) with y (n,), got {x.shape} "
                         f"and {y.shape}")
    if not np.all(np.isin(np.unique(y), (-1, 1))):
        raise ValueError("nu-SVC labels must be +/-1 (binary); for "
                         "multiclass data use models.multiclass")
    if precomp and x.shape[0] != x.shape[1]:
        raise ValueError(
            "precomputed nu-SVC training needs the square (n, n) "
            f"kernel matrix K(train, train); got {x.shape}")
    n, d = x.shape
    pos = y > 0
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    # Feasibility (LIBSVM svm_check_parameter): nu*n/2 alphas of size
    # <= 1 must fit in each class.
    if nu * n / 2.0 > min(n_pos, n_neg) + 1e-9:
        raise ValueError(
            f"nu={nu} is infeasible: nu*n/2 = {nu * n / 2:.1f} exceeds "
            f"the smaller class ({min(n_pos, n_neg)} examples)")

    half = nu * n / 2.0
    alpha0 = np.zeros(n, np.float32)
    for cls in (pos, ~pos):
        idx = np.nonzero(cls)[0]
        alpha0[idx] = _nu_head_seed(half, 1.0, len(idx))

    yf = np.where(pos, 1.0, -1.0).astype(np.float32)
    if precomp:
        # x IS K: seed/threshold gradients are matvecs, no kernel pass
        f0 = (x @ (alpha0 * yf)).astype(np.float32)
    else:
        spec = config.kernel_spec(d)
        f0 = _stream_kv(x, alpha0 * yf, spec, block=4096)

    config = dataclasses.replace(config, c=1.0, clip="pairwise")
    result = _solve_nu(x, yf, alpha0, f0, config)

    alpha = np.asarray(result.alpha, np.float32)
    if precomp:
        f = (x @ (alpha * yf)).astype(np.float32)
    else:
        f = _stream_kv(x, alpha * yf, spec, block=4096)
    r1, r2 = _class_thresholds(f, yf, alpha, 1.0)
    r = (r1 + r2) / 2.0
    if not np.isfinite(r) or r <= 0:
        raise RuntimeError(f"degenerate nu-SVC solution (r={r}); the "
                           "problem may be unseparated at this nu/gamma")
    rho = (r1 - r2) / 2.0

    keep = alpha > 0
    extra = {}
    if precomp:
        extra = dict(sv_idx=np.flatnonzero(keep).astype(np.int64),
                     n_train=n)
    model = SVMModel(
        x_sv=(np.zeros((int(keep.sum()), 0), np.float32) if precomp
              else np.ascontiguousarray(x[keep])),
        alpha=(alpha[keep] / np.float32(r)),
        y_sv=np.where(pos[keep], 1, -1).astype(np.int32),
        b=float(rho / r),
        gamma=float(config.resolve_gamma(d)),
        kernel=config.kernel, coef0=float(config.coef0),
        degree=int(config.degree), **extra)
    result.b = float(rho / r)
    result.n_sv = int(keep.sum())
    return model, result


def train_nusvr(x: np.ndarray, z: np.ndarray, nu: float = 0.5,
                config: Optional[SVMConfig] = None
                ) -> Tuple[SVMModel, TrainResult]:
    """Fit a nu-SVR (LIBSVM -s 4): the tube width is learned, nu bounds
    the fraction of points outside it. ``config.c`` is the usual cost;
    ``config.svr_epsilon`` is ignored (epsilon is a result)."""
    from dpsvm_tpu.ops.diagnostics import _stream_kv

    from dpsvm_tpu.utils import densify
    x = densify(x)
    config = config or SVMConfig()
    precomp = config.kernel == "precomputed"
    if not 0.0 < nu <= 1.0:
        raise ValueError(f"nu must be in (0, 1], got {nu}")
    x = np.asarray(x, np.float32)
    z = np.asarray(z, np.float32)
    if precomp and (x.ndim != 2 or x.shape[0] != x.shape[1]):
        raise ValueError(
            "precomputed nu-SVR training needs the square (n, n) "
            f"kernel matrix K(train, train); got {x.shape}")
    n, d = x.shape
    if z.shape != (n,):
        raise ValueError(f"targets must be ({n},), got {z.shape}")
    C = float(config.c)

    # LIBSVM solve_nu_svr seeding: alpha_j = alpha*_j = min(C, rem),
    # rem from C*nu*n/2.
    seed = _nu_head_seed(C * nu * n / 2.0, C, n)
    alpha0 = np.concatenate([seed, seed]).astype(np.float32)

    # Doubled problem (see models/svr.py): rows [x; x], pseudo-labels
    # [+1; -1]. f = y_i G_i with G = Qa + p, p = [-z; +z]:
    # f_i = K(a y)_i + y_i p_i = K(a y)_i - z_i  (both halves).
    if precomp:
        # the 2n pseudo-examples duplicate the original rows: their
        # kernel matrix is K tiled 2x2 (see models/svr.py)
        x2n = np.tile(x, (2, 2))
    else:
        x2n = np.concatenate([x, x], axis=0)
        spec = config.kernel_spec(d)
    y_pm = np.concatenate([np.ones(n), -np.ones(n)]).astype(np.float32)
    # The seed's kernel term vanishes identically: alpha_j == alpha*_j
    # with opposite pseudo-labels gives coef = seed - seed = 0, so
    # f0 = K@0 - z = -z on both halves — no O(n^2 d) kernel pass needed
    # (round-3 review: _stream_kv here burned minutes at covtype scale
    # computing a zero vector).
    f0 = np.concatenate([-z, -z]).astype(np.float32)

    config = dataclasses.replace(config, clip="pairwise")
    result = _solve_nu(x2n, y_pm, alpha0, f0, config)

    a2 = np.asarray(result.alpha, np.float32)
    delta = a2[:n] - a2[n:]
    if precomp:
        kv = (x @ delta).astype(np.float32)
    else:
        kv = _stream_kv(x, delta, spec, block=4096)
    f = np.concatenate([kv - z, kv - z]).astype(np.float32)
    r1, r2 = _class_thresholds(f, y_pm, a2, np.float32(C))
    # The learned tube half-width -(r1+r2)/2 (LIBSVM's "epsilon = -r",
    # svm.cpp svm_train for NU_SVR); intercept b = -(r1-r2)/2.
    eps_eff = -(r1 + r2) / 2.0
    b = -(r1 - r2) / 2.0

    keep = delta != 0
    extra = {}
    if precomp:
        extra = dict(sv_idx=np.flatnonzero(keep).astype(np.int64),
                     n_train=n)
    model = SVMModel(
        x_sv=(np.zeros((int(keep.sum()), 0), np.float32) if precomp
              else np.ascontiguousarray(x[keep])),
        alpha=np.abs(delta[keep]).astype(np.float32),
        y_sv=np.sign(delta[keep]).astype(np.int32),
        b=float(-b),      # stored so that sum - b == sum + b_intercept
        gamma=float(config.resolve_gamma(d)),
        kernel=config.kernel, coef0=float(config.coef0),
        degree=int(config.degree), task="svr", **extra)
    result.b = float(b)
    result.n_sv = int(keep.sum())
    result.learned_epsilon = float(eps_eff)
    return model, result

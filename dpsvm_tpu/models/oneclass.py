"""One-class SVM (novelty detection) on the classification solver.

LIBSVM's one-class formulation (``svm-train -s 2``, Schoelkopf et al.):

    min  1/2 a' K a
    s.t. 0 <= a_i <= 1,  sum(a) = nu * n

All pseudo-labels are +1, so the Keerthi machinery applies verbatim:
the dual gradient is f = K a (no linear term), the pair update moves
mass between two alphas (s = +1 conserves the sum), and the box is
C = 1. Like SVR (models/svr.py), the whole thing runs on the UNMODIFIED
compiled solver paths — here via the ``alpha_init`` + ``f_init`` hooks,
seeded with LIBSVM's own initialization: a_i = 1 for the first
floor(nu*n) points, the fractional remainder on the next one, 0 after,
and f0 = K a0 computed in one streamed kernel pass.

Decision: f(x) = sum_i a_i K(x_i, x) - rho with rho = (b_lo + b_hi)/2 —
again the existing batched decision function (y_sv all +1), task
"oneclass"; sign >= 0 means inlier.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.models.svm import SVMModel, decision_function


def train_oneclass(x: np.ndarray, nu: float = 0.5,
                   config: Optional[SVMConfig] = None
                   ) -> Tuple[SVMModel, TrainResult]:
    """Fit a one-class SVM on unlabeled rows. 0 < nu < 1 bounds the
    outlier fraction (LIBSVM -n). ``config.c`` is ignored (the one-class
    box is 1 by construction)."""
    from dpsvm_tpu.api import train
    from dpsvm_tpu.ops.diagnostics import _stream_kv

    from dpsvm_tpu.utils import densify
    x = densify(x)
    config = config or SVMConfig()
    precomp = config.kernel == "precomputed"
    if not 0.0 < nu < 1.0:
        raise ValueError(f"nu must be in (0, 1), got {nu}")
    if config.weight_pos != 1.0 or config.weight_neg != 1.0:
        raise ValueError("class weights do not apply to one-class "
                         "training (there is one pseudo-class)")
    x = np.asarray(x, np.float32)
    if x.ndim != 2:
        raise ValueError(f"x must be (n, d), got shape {x.shape}")
    if precomp and x.shape[0] != x.shape[1]:
        raise ValueError(
            "precomputed one-class training needs the square (n, n) "
            f"kernel matrix K(train, train); got {x.shape}")
    n, d = x.shape

    # LIBSVM's init (svm.cpp solve_one_class): sum(alpha0) = nu * n.
    target = nu * n
    n_full = int(target)
    alpha0 = np.zeros(n, np.float32)
    alpha0[:n_full] = 1.0
    if n_full < n:
        alpha0[n_full] = np.float32(target - n_full)
    if not np.any(alpha0 > 0):
        raise ValueError(f"nu={nu} with n={n} initializes no support "
                         "vectors; increase nu or the dataset size")

    if precomp:
        # x IS K: the seed gradient is one matvec, no kernel pass
        f0 = (x @ alpha0).astype(np.float32)
    else:
        spec = config.kernel_spec(d)
        f0 = _stream_kv(x, alpha0, spec, block=4096)

    z = np.ones(n, np.int32)
    # c=1 by construction; pairwise clip because the constraint VALUE
    # (sum alpha = nu*n) is part of the model — the reference's
    # independent clip lets it drift ~1%, which shifts rho visibly
    # (measured: rho 6.67 vs libsvm's 6.57 on a 300-point fixture).
    config = SVMConfig(**{**config.__dict__, "c": 1.0, "clip": "pairwise"})
    # guard_eta: duplicate rows in unlabeled data make eta == 0
    # reachable; clamp like LIBSVM's TAU (see solver/smo.py).
    result = train(x, z, config, f_init=f0, alpha_init=alpha0,
                   guard_eta=True)

    alpha = np.asarray(result.alpha, np.float32)
    keep = alpha > 0
    extra = {}
    if precomp:
        # keep SV indices; prediction gathers the user's K(test, train)
        extra = dict(sv_idx=np.flatnonzero(keep).astype(np.int64),
                     n_train=n)
    model = SVMModel(
        x_sv=(np.zeros((int(keep.sum()), 0), np.float32) if precomp
              else np.ascontiguousarray(x[keep])),
        alpha=alpha[keep],
        y_sv=np.ones(int(keep.sum()), np.int32),
        b=float(result.b),                    # rho
        gamma=float(result.gamma),
        kernel=result.kernel,
        coef0=float(result.coef0),
        degree=int(result.degree),
        task="oneclass",
        **extra,
    )
    return model, result


def score_oneclass(model: SVMModel, x_test: np.ndarray) -> np.ndarray:
    """Signed decision values sum_i a_i K(x_i, x) - rho (>= 0: inlier)."""
    if model.task != "oneclass":
        raise ValueError("score_oneclass needs a task='oneclass' model")
    return decision_function(model, x_test, include_b=True)


def predict_oneclass(model: SVMModel, x_test: np.ndarray) -> np.ndarray:
    """+1 inlier / -1 outlier (sklearn OneClassSVM convention)."""
    dec = score_oneclass(model, x_test)
    return np.where(dec < 0, -1, 1).astype(np.int32)

"""LIBSVM ``.model``-format interoperability.

The reference's model file is its own CSV-ish layout
(``svmTrainMain.cpp:386-416`` — handled by ``models/io.py``); users
switching from LIBSVM/sklearn bring files in LIBSVM's standard text
format instead::

    svm_type c_svc
    kernel_type rbf
    gamma 0.25
    nr_class 2
    total_sv 253
    rho -0.087
    label 1 -1
    nr_sv 130 123
    SV
    <sv_coef> <idx>:<val> <idx>:<val> ...

Mapping onto ``SVMModel`` (decision f(x) = sum_i alpha_i y_i K(x_i,x)
- b, positive => +1 — the reference's convention, which is LIBSVM's
too):

* ``sv_coef_i = alpha_i * y_i`` and ``rho = b``, directly — true for
  binary c_svc, for epsilon_svr (where our alpha/y_sv encode
  delta = a - a*), and for one_class (y_sv all +1, b = rho).
* LIBSVM's decision is positive for ``label[0]``; when a c_svc file
  says ``label -1 1`` the stored coefficients are the negatives of
  ours, so loading flips them (and rho) to keep our positive==+1
  convention. Writing always emits ``label 1 -1``.
* SV feature lines are 1-based sparse ``idx:val``; absent indices are
  zero. Writing emits non-zero features only (LIBSVM's own tools do
  the same for dense data).

Only the binary tasks this framework trains are supported: ``c_svc``,
``epsilon_svr``, ``one_class`` (multiclass LIBSVM files hold k>2
classes and pairwise rho blocks — out of scope, rejected loudly).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from dpsvm_tpu.models.svm import SVMModel

_TASK_TO_SVMTYPE = {"svc": "c_svc", "svr": "epsilon_svr",
                    "oneclass": "one_class"}
_SVMTYPE_TO_TASK = {v: k for k, v in _TASK_TO_SVMTYPE.items()}
_SVMTYPE_TO_TASK["nu_svc"] = "svc"    # a fitted nu model's decision
_SVMTYPE_TO_TASK["nu_svr"] = "svr"    # function is the same functional
                                      # form; only training differed
_KERNEL_TO_LIBSVM = {"linear": "linear", "poly": "polynomial",
                     "rbf": "rbf", "sigmoid": "sigmoid",
                     "precomputed": "precomputed"}
_LIBSVM_TO_KERNEL = {v: k for k, v in _KERNEL_TO_LIBSVM.items()}


def save_libsvm_model(model: SVMModel, path: str) -> int:
    """Write ``model`` in LIBSVM's text format; returns SV lines written.

    SVs are grouped +1-class first to match the ``label 1 -1`` /
    ``nr_sv`` segmentation LIBSVM's own readers assume.
    """
    if model.task not in _TASK_TO_SVMTYPE:
        raise ValueError(f"cannot export task {model.task!r} as a "
                         "LIBSVM model (supported: svc, svr, oneclass)")
    if model.kernel == "precomputed" and model.sv_idx is None:
        # Validate before opening the file: failing mid-write would
        # leave a truncated .model behind.
        raise ValueError("precomputed model has no sv_idx (training "
                         "serials) — cannot write LIBSVM '0:serial' "
                         "SV lines")
    coef = np.asarray(model.alpha, np.float64) * np.asarray(
        model.y_sv, np.float64)
    x = np.asarray(model.x_sv)
    order = np.argsort(-np.asarray(model.y_sv))   # +1 block, then -1
    lines: List[str] = [
        f"svm_type {_TASK_TO_SVMTYPE[model.task]}",
        f"kernel_type {_KERNEL_TO_LIBSVM[model.kernel]}",
    ]
    if model.kernel == "poly":
        lines.append(f"degree {int(model.degree)}")
    if model.kernel != "linear":
        lines.append(f"gamma {model.gamma:.17g}")
    if model.kernel in ("poly", "sigmoid"):
        lines.append(f"coef0 {model.coef0:.17g}")
    if model.task == "svc":
        n_pos = int(np.sum(model.y_sv > 0))
        lines += ["nr_class 2", f"total_sv {model.n_sv}",
                  f"rho {model.b:.17g}", "label 1 -1",
                  f"nr_sv {n_pos} {model.n_sv - n_pos}"]
    else:
        lines += ["nr_class 2", f"total_sv {model.n_sv}",
                  f"rho {model.b:.17g}"]
    lines.append("SV")
    for i in order:
        if model.kernel == "precomputed":
            # LIBSVM stores the SV as its 1-based training serial
            feats = f"0:{int(model.sv_idx[i]) + 1}"
        else:
            feats = " ".join(f"{j + 1}:{v:.9g}"
                             for j, v in enumerate(x[i]) if v != 0)
        lines.append(f"{coef[i]:.17g} {feats}")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    return model.n_sv


def load_libsvm_model(path: str,
                      n_features: Optional[int] = None) -> SVMModel:
    """Read a LIBSVM ``.model`` file into an ``SVMModel``.

    ``n_features`` widens the SV matrix when the file's largest feature
    index undershoots the data's dimensionality (trailing all-zero
    columns are unrepresented in the sparse format).
    """
    with open(path) as fh:
        raw = [ln.strip() for ln in fh]
    header: Dict[str, str] = {}
    sv_lines: List[str] = []
    in_sv = False
    for ln in raw:
        if not ln:
            continue
        if in_sv:
            sv_lines.append(ln)
        elif ln == "SV":
            in_sv = True
        else:
            key, _, val = ln.partition(" ")
            header[key] = val.strip()
    if not in_sv:
        raise ValueError(f"{path}: no 'SV' section — not a LIBSVM "
                         "model file")

    svm_type = header.get("svm_type", "c_svc")
    if svm_type not in _SVMTYPE_TO_TASK:
        raise ValueError(f"{path}: unsupported svm_type {svm_type!r}")
    task = _SVMTYPE_TO_TASK[svm_type]
    ltype = header.get("kernel_type", "rbf")
    if ltype not in _LIBSVM_TO_KERNEL:
        raise ValueError(f"{path}: unsupported kernel_type {ltype!r}")
    kernel = _LIBSVM_TO_KERNEL[ltype]
    nr_class = int(header.get("nr_class", 2))
    if task == "svc" and nr_class != 2:
        raise ValueError(f"{path}: {nr_class}-class LIBSVM models hold "
                         "pairwise coef/rho blocks; import binary "
                         "models (train --multiclass keeps per-pair "
                         "model files instead)")
    rho_vals = [float(v) for v in header.get("rho", "0").split()]
    if len(rho_vals) != 1:
        raise ValueError(f"{path}: expected one rho for a binary model, "
                         f"got {len(rho_vals)}")
    rho = rho_vals[0]

    def _svc_label_flip(coefs, rho):
        """LIBSVM's decision is positive for label[0]; ours for +1 —
        a 'label -1 1' file stores negated coefficients."""
        labels = [int(v) for v in header.get("label", "1 -1").split()]
        if sorted(labels) != [-1, 1]:
            raise ValueError(f"{path}: binary import needs labels "
                             f"{{-1, 1}}, got {labels} — remap labels "
                             "at conversion time (cli convert)")
        if labels[0] == -1:
            return -coefs, -rho
        return coefs, rho

    coefs = np.empty(len(sv_lines), np.float64)
    if kernel == "precomputed":
        if task != "svc":
            raise ValueError(f"{path}: precomputed import supports "
                             "c_svc models only")
        # SV lines are "coef 0:serial" — the SV's 1-based position in
        # the training set. n_train is not stored by LIBSVM; use
        # n_features (K(test, train) width) when given, else the
        # largest serial seen.
        sv_idx = np.empty(len(sv_lines), np.int64)
        for i, ln in enumerate(sv_lines):
            parts = ln.split()
            if len(parts) != 2 or not parts[1].startswith("0:"):
                raise ValueError(f"{path}: precomputed SV line {i} must "
                                 f"be '<coef> 0:<serial>', got {ln!r}")
            coefs[i] = float(parts[0])
            serial = int(parts[1][2:])
            if serial < 1:
                raise ValueError(f"{path}: SV serial {serial} (LIBSVM "
                                 "serials are 1-based)")
            sv_idx[i] = serial - 1
        coefs, rho_pc = _svc_label_flip(coefs, rho)
        # LIBSVM stores no n_train: the largest serial only bounds it
        # from below. Pass n_features (the K(test, train) width) to get
        # the true width — cli test does.
        n_train = max(int(sv_idx.max()) + 1, n_features or 0)
        return SVMModel(
            x_sv=np.zeros((len(sv_lines), 0), np.float32),
            alpha=np.abs(coefs).astype(np.float32),
            y_sv=np.where(coefs >= 0, 1, -1).astype(np.int32),
            b=rho_pc, gamma=float(header.get("gamma", 1.0)),
            kernel="precomputed", task="svc",
            sv_idx=sv_idx, n_train=n_train,
            n_train_exact=n_features is not None)
    feats: List[Dict[int, float]] = []
    max_idx = 0
    for i, ln in enumerate(sv_lines):
        parts = ln.split()
        coefs[i] = float(parts[0])
        row: Dict[int, float] = {}
        for tok in parts[1:]:
            idx_s, _, val_s = tok.partition(":")
            idx = int(idx_s)
            if idx < 1:
                raise ValueError(f"{path}: SV feature index {idx} "
                                 "(LIBSVM indices are 1-based)")
            row[idx] = float(val_s)
            max_idx = max(max_idx, idx)
        feats.append(row)
    d = max(max_idx, n_features or 0)
    if d == 0:
        raise ValueError(f"{path}: SVs carry no features")
    x = np.zeros((len(sv_lines), d), np.float32)
    for i, row in enumerate(feats):
        for idx, val in row.items():
            x[i, idx - 1] = val

    if task == "svc":
        coefs, rho = _svc_label_flip(coefs, rho)
    if task == "oneclass":
        y_sv = np.ones(len(sv_lines), np.int32)
        alpha = coefs.astype(np.float32)
        if (coefs < 0).any():
            raise ValueError(f"{path}: one_class sv_coef must be >= 0")
    else:
        y_sv = np.where(coefs >= 0, 1, -1).astype(np.int32)
        alpha = np.abs(coefs).astype(np.float32)

    gamma = float(header.get("gamma", 1.0 / d))
    return SVMModel(
        x_sv=x, alpha=alpha, y_sv=y_sv, b=rho, gamma=gamma,
        kernel=kernel, coef0=float(header.get("coef0", 0.0)),
        degree=int(header.get("degree", 3)), task=task)

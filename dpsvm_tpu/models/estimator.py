"""scikit-learn-style estimator facade: SVC-shaped fit/predict/score.

The reference is driven only through its CLI binaries; this framework is
library-first, and the natural Python idiom for an SVM trainer is the
sklearn estimator protocol — so `DPSVMClassifier` adapts `api.fit` to
it (duck-typed: no sklearn import or dependency; it simply follows the
fit/predict/score conventions, get_params/set_params included, so it
drops into sklearn pipelines and CV utilities when sklearn is present).

Labels may be ANY two values (sklearn-style), not just +/-1: classes_
is the sorted unique pair, mapped internally onto the solver's -1/+1.
More than two classes dispatches to the one-vs-one trainer.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Union

import numpy as np

from dpsvm_tpu.config import SVMConfig

try:
    # Optional: inheriting sklearn's mixins provides the estimator-tag
    # protocol its meta-utilities (clone, cross_val_score, pipelines,
    # is_classifier/is_regressor) check for. Everything else here is
    # self-contained, so without sklearn the classes are plain objects
    # with the same duck-typed API.
    from sklearn.base import BaseEstimator as _SkBase
    from sklearn.base import ClassifierMixin as _SkClassifier
    from sklearn.base import RegressorMixin as _SkRegressor
    _CLF_BASES = (_SkClassifier, _SkBase)
    _REG_BASES = (_SkRegressor, _SkBase)
except ImportError:                                   # pragma: no cover
    _CLF_BASES = (object,)
    _REG_BASES = (object,)


class _ParamsMixin:
    """get_params/set_params/_check_fitted derived from one per-class
    ``_PARAM_NAMES`` tuple, so each hyperparameter is declared exactly
    twice (init signature + tuple) instead of four times."""

    _PARAM_NAMES: tuple = ()
    _FITTED_ATTR: str = "_model"

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._PARAM_NAMES}

    def set_params(self, **params):
        for k, v in params.items():
            if k not in self._PARAM_NAMES:
                raise ValueError(f"invalid parameter {k!r}")
            setattr(self, k, v)
        return self

    def _check_fitted(self) -> None:
        if not hasattr(self, self._FITTED_ATTR):
            raise RuntimeError(f"this {type(self).__name__} is not "
                               "fitted yet; call fit(X, y) first")

    def _common_config_kwargs(self) -> Dict[str, Any]:
        """The SVMConfig fields shared by both estimators (sklearn's
        explicit-constructor convention forces the __init__ duplication;
        the config mapping need exist only once)."""
        return dict(c=self.C, kernel=self.kernel, degree=self.degree,
                    gamma=self.gamma, coef0=self.coef0, epsilon=self.tol,
                    max_iter=self.max_iter, selection=self.selection,
                    shards=self.shards, working_set=self.working_set,
                    shrinking=self.shrinking,
                    matmul_precision=self.matmul_precision,
                    solver=self.solver, approx_dim=self.approx_dim,
                    approx_seed=self.approx_seed)


class DPSVMClassifier(_ParamsMixin, *_CLF_BASES):
    """SVM classifier on the modified-SMO TPU solver (LIBSVM kernel family).

    Parameters mirror ``sklearn.svm.SVC`` where they overlap (C, kernel,
    degree, gamma, coef0, tol, max_iter) plus this framework's execution
    knobs. ``gamma=None``
    means 1/n_features (the reference's intended default, SURVEY §2d).
    ``probability`` takes True (Platt fit on training decisions, the
    cheap default) or "cv" (5-fold held-out fit — LIBSVM's actual -b 1
    procedure, 5 extra trainings, better calibrated).
    """

    def __init__(self, C: float = 1.0, kernel: str = "rbf",
                 degree: int = 3, gamma: Optional[float] = None,
                 coef0: float = 0.0,
                 tol: float = 1e-3, max_iter: int = 150_000,
                 selection: str = "first-order", shards: int = 1,
                 matmul_precision: str = "highest",
                 working_set: int = 2, shrinking: bool = False,
                 polish: bool = False,
                 probability: "Union[bool, str]" = False,
                 batched: bool = False,
                 class_weight: "Optional[dict]" = None,
                 solver: str = "exact", approx_dim: int = 1024,
                 approx_seed: int = 0):
        self.C = C
        self.kernel = kernel
        self.degree = degree
        self.gamma = gamma
        self.coef0 = coef0
        self.tol = tol
        self.max_iter = max_iter
        self.selection = selection
        self.shards = shards
        self.matmul_precision = matmul_precision
        self.working_set = working_set
        self.shrinking = shrinking
        self.polish = polish
        self.probability = probability
        # Multiclass-only: train all OvO pairs in one compiled batched
        # program (solver/batched_ovo.py); ignored for binary fits
        # (there is nothing to batch).
        self.batched = batched
        # sklearn's class_weight dict (LIBSVM -wi): original label ->
        # cost multiplier. Binary fits map the two classes' weights to
        # weight_neg/weight_pos; multiclass passes per-label weights
        # through to every OvO pair (sequential path only).
        self.class_weight = class_weight
        # Kernel-approximation path (docs/APPROX.md): "approx-rff" /
        # "approx-nystrom" fit a primal linear model over an explicit
        # feature map — no SV set, so n_support_ is None after fit.
        self.solver = solver
        self.approx_dim = approx_dim
        self.approx_seed = approx_seed

    _PARAM_NAMES = ("C", "kernel", "degree", "gamma", "coef0", "tol",
                    "max_iter", "selection", "shards", "matmul_precision",
                    "working_set", "shrinking", "polish", "probability",
                    "batched", "class_weight", "solver", "approx_dim",
                    "approx_seed")
    _FITTED_ATTR = "classes_"

    def _config(self) -> SVMConfig:
        # polish is classification-only (the SVR wrapper seeds f), so it
        # lives here rather than in the shared kwargs.
        return SVMConfig(polish=self.polish,
                         **self._common_config_kwargs())

    # --- sklearn protocol: fit/predict/score ---

    def fit(self, X, y) -> "DPSVMClassifier":
        """Train; fitted state is assigned only after training succeeds,
        so a failed refit leaves the previous fit fully intact (and every
        optional attribute — _platt, intercept_, n_support_ — is reset,
        never stale from an earlier fit with different params)."""
        from dpsvm_tpu.api import fit as _fit
        from dpsvm_tpu.utils import densify

        X = np.asarray(densify(X), np.float32)
        y = np.asarray(y)
        classes = np.unique(y)
        if len(classes) < 2:
            raise ValueError(f"need at least 2 classes, got {classes}")
        state: Dict[str, Any] = {
            "classes_": classes, "_model": None, "_multi": None,
            "_platt": None, "intercept_": None, "n_support_": None,
        }
        if len(classes) == 2:
            cfg = self._config()
            if self.class_weight:
                from dpsvm_tpu.models.multiclass import (
                    resolve_class_weight, weighted_binary_config)
                cw = resolve_class_weight(classes, self.class_weight)
                # classes[1] maps to +1 below; the shared helper forces
                # the pairwise clip (LIBSVM -wi semantics — the
                # independent clip drifts sum(alpha*y) at asymmetric
                # bounds).
                cfg = weighted_binary_config(cfg,
                                             cw.get(classes[1], 1.0),
                                             cw.get(classes[0], 1.0))
            ypm = np.where(y == classes[1], 1, -1).astype(np.int32)
            model, result = _fit(X, ypm, cfg)
            state.update(
                _model=model,
                n_iter_=result.n_iter,
                converged_=result.converged,
                intercept_=np.array([-result.b]),
                n_support_=(None if getattr(model, "is_approx", False)
                            else np.array([int(np.sum(model.y_sv < 0)),
                                           int(np.sum(model.y_sv > 0))])))
            if self.probability:
                from dpsvm_tpu.models.calibration import (fit_platt,
                                                          fit_platt_cv)
                from dpsvm_tpu.models.svm import decision_function
                if self.probability == "cv":
                    # LIBSVM's actual -b 1 procedure (k extra trainings)
                    state["_platt"] = fit_platt_cv(X, ypm, cfg)
                else:
                    dec = np.asarray(decision_function(model, X))
                    state["_platt"] = fit_platt(dec, ypm)
        else:
            from dpsvm_tpu.models.multiclass import train_multiclass
            multi, results = train_multiclass(
                X, y, self._config(), probability=self.probability,
                batched=self.batched, class_weight=self.class_weight)
            state.update(
                _multi=multi,
                n_iter_=int(sum(r.n_iter for r in results)),
                converged_=all(r.converged for r in results))
        for k, v in state.items():
            setattr(self, k, v)
        return self

    def decision_function(self, X) -> np.ndarray:
        self._check_fitted()
        if self._model is None:
            raise ValueError("decision_function is binary-only; use "
                             "predict for multiclass models")
        from dpsvm_tpu.models.svm import decision_function as _dec
        from dpsvm_tpu.utils import densify
        return np.asarray(_dec(self._model,
                               np.asarray(densify(X), np.float32)))

    def predict(self, X) -> np.ndarray:
        self._check_fitted()
        from dpsvm_tpu.utils import densify
        X = np.asarray(densify(X), np.float32)
        if self._model is not None:
            dec = self.decision_function(X)
            return np.where(dec < 0, self.classes_[0], self.classes_[1])
        from dpsvm_tpu.models.multiclass import predict_multiclass
        return predict_multiclass(self._multi, X)

    def predict_proba(self, X) -> np.ndarray:
        """(n, n_classes) probabilities in classes_ order; needs
        probability=True. Binary: the Platt sigmoid; multiclass:
        per-pair Platt + pairwise coupling (LIBSVM -b 1)."""
        self._check_fitted()
        if self._multi is not None:
            if self._multi.platt is None:
                raise RuntimeError("fit with probability=True to enable "
                                   "predict_proba")
            from dpsvm_tpu.models.multiclass import (
                predict_proba_multiclass)
            from dpsvm_tpu.utils import densify
            return predict_proba_multiclass(
                self._multi, np.asarray(densify(X), np.float32))
        if getattr(self, "_platt", None) is None:
            raise RuntimeError("fit with probability=True to enable "
                               "predict_proba")
        from dpsvm_tpu.models.calibration import sigmoid_proba
        p1 = sigmoid_proba(self.decision_function(X), *self._platt)
        return np.stack([1.0 - p1, p1], axis=1)

    def score(self, X, y) -> float:
        return float(np.mean(self.predict(X) == np.asarray(y)))


class DPSVMRegressor(_ParamsMixin, *_REG_BASES):
    """epsilon-SVR on the modified-SMO TPU solver, sklearn-SVR-shaped.

    Parameters mirror ``sklearn.svm.SVR`` where they overlap (C, kernel,
    degree, gamma, coef0, epsilon = tube half-width, tol, max_iter) plus
    this framework's execution knobs. See models/svr.py for the
    2n-variable mapping onto the classification solver.
    """

    def __init__(self, C: float = 1.0, kernel: str = "rbf",
                 degree: int = 3, gamma: Optional[float] = None,
                 coef0: float = 0.0, epsilon: float = 0.1,
                 tol: float = 1e-3, max_iter: int = 150_000,
                 selection: str = "first-order", shards: int = 1,
                 matmul_precision: str = "highest",
                 working_set: int = 2, shrinking: bool = False,
                 solver: str = "exact", approx_dim: int = 1024,
                 approx_seed: int = 0):
        self.C = C
        self.kernel = kernel
        self.degree = degree
        self.gamma = gamma
        self.coef0 = coef0
        self.epsilon = epsilon
        self.tol = tol
        self.max_iter = max_iter
        self.selection = selection
        self.shards = shards
        self.matmul_precision = matmul_precision
        self.working_set = working_set
        self.shrinking = shrinking
        self.solver = solver
        self.approx_dim = approx_dim
        self.approx_seed = approx_seed

    _PARAM_NAMES = ("C", "kernel", "degree", "gamma", "coef0", "epsilon",
                    "tol", "max_iter", "selection", "shards",
                    "matmul_precision", "working_set", "shrinking",
                    "solver", "approx_dim", "approx_seed")

    def _config(self) -> SVMConfig:
        return SVMConfig(svr_epsilon=self.epsilon,
                         **self._common_config_kwargs())

    def fit(self, X, y) -> "DPSVMRegressor":
        from dpsvm_tpu.models.svr import train_svr
        from dpsvm_tpu.utils import densify

        X = np.asarray(densify(X), np.float32)
        y = np.asarray(y, np.float32)
        model, result = train_svr(X, y, self._config())
        self._model = model
        self.n_iter_ = result.n_iter
        self.converged_ = result.converged
        self.intercept_ = np.array([-result.b])
        self.n_support_ = np.array([model.n_sv])
        return self

    def predict(self, X) -> np.ndarray:
        from dpsvm_tpu.models.svr import predict_svr

        self._check_fitted()
        from dpsvm_tpu.utils import densify
        return np.asarray(predict_svr(
            self._model, np.asarray(densify(X), np.float32)))

    def score(self, X, y) -> float:
        """R^2, the sklearn regressor convention."""
        from dpsvm_tpu.models.svr import evaluate_svr

        self._check_fitted()
        return float(evaluate_svr(self._model, np.asarray(X, np.float32),
                                  np.asarray(y, np.float32))["r2"])

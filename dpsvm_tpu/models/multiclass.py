"""Multi-class classification: one-vs-one on the binary SMO trainer.

Beyond-reference capability (the reference is strictly binary): the
LIBSVM construction — K(K-1)/2 pairwise binary problems, each trained on
the examples of its two classes with labels remapped to +/-1 (first
class of the pair = +1), prediction by majority vote with ties going to
the earlier class in sorted order.

Persistence is a directory: ``index.json`` (classes + pair file names)
plus one reference-format model file per pair, so every sub-model stays
individually loadable by the binary tooling.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional, Tuple

import numpy as np

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.models.io import load_model, save_model
from dpsvm_tpu.models.svm import SVMModel, decision_function


@dataclasses.dataclass
class MulticlassModel:
    classes: np.ndarray                    # (k,) sorted original labels
    pairs: List[Tuple[int, int]]           # index pairs into classes
    models: List[SVMModel]                 # one per pair

    @property
    def n_classes(self) -> int:
        return len(self.classes)


def train_multiclass(x: np.ndarray, y: np.ndarray,
                     config: Optional[SVMConfig] = None,
                     ) -> Tuple[MulticlassModel, List[TrainResult]]:
    """Train OvO; y may hold any integer labels (2 classes work too)."""
    from dpsvm_tpu.api import fit

    from dpsvm_tpu.utils import densify
    x = densify(x)
    config = config or SVMConfig()
    if config.kernel == "precomputed":
        raise ValueError(
            "one-vs-one multiclass does not support the precomputed kernel: each pair trains on a ROW subset, which needs the matching column subset of K; slice K per pair and train binary models instead")
    if config.checkpoint_path or config.resume_from:
        # Every pairwise fit would share the one checkpoint file —
        # overwriting each other or failing shape validation mid-run.
        raise ValueError(
            "checkpoint_path/resume_from are single-model options; "
            "they cannot be shared across the pairwise multiclass "
            "subproblems")
    y = np.asarray(y)
    classes = np.unique(y)
    if len(classes) < 2:
        raise ValueError(f"need at least 2 classes, got {classes}")
    pairs, models, results = [], [], []
    for ai in range(len(classes)):
        for bi in range(ai + 1, len(classes)):
            sel = (y == classes[ai]) | (y == classes[bi])
            xs = np.ascontiguousarray(x[sel])
            ys = np.where(y[sel] == classes[ai], 1, -1).astype(np.int32)
            model, result = fit(xs, ys, config)
            pairs.append((ai, bi))
            models.append(model)
            results.append(result)
    return MulticlassModel(classes=classes, pairs=pairs,
                           models=models), results


def predict_multiclass(model: MulticlassModel, x: np.ndarray,
                       include_b: bool = True) -> np.ndarray:
    """Majority vote over pairwise decisions; ties -> earlier class.

    include_b=False drops the intercept like seq_test.cpp:197, matching
    the binary evaluator's --no-b."""
    n = x.shape[0]
    votes = np.zeros((n, model.n_classes), dtype=np.int32)
    for (ai, bi), m in zip(model.pairs, model.models):
        dec = decision_function(m, x, include_b=include_b)
        votes[:, ai] += dec >= 0
        votes[:, bi] += dec < 0
    return model.classes[np.argmax(votes, axis=1)]


def evaluate_multiclass(model: MulticlassModel, x: np.ndarray,
                        y: np.ndarray, include_b: bool = True) -> float:
    return float(np.mean(predict_multiclass(model, x, include_b)
                         == np.asarray(y)))


def save_multiclass(model: MulticlassModel, dirpath: str) -> None:
    os.makedirs(dirpath, exist_ok=True)
    entries = []
    for (ai, bi), m in zip(model.pairs, model.models):
        name = f"pair_{int(model.classes[ai])}_{int(model.classes[bi])}.svm"
        save_model(m, os.path.join(dirpath, name))
        entries.append({"a": int(ai), "b": int(bi), "file": name})
    with open(os.path.join(dirpath, "index.json"), "w") as f:
        json.dump({"format": "dpsvm_tpu-ovo-v1",
                   "classes": [int(c) for c in model.classes],
                   "pairs": entries}, f, indent=1)


def load_multiclass(dirpath: str) -> MulticlassModel:
    index_path = os.path.join(dirpath, "index.json")
    if not os.path.exists(index_path):
        raise FileNotFoundError(index_path)
    with open(index_path) as f:
        index = json.load(f)
    if index.get("format") != "dpsvm_tpu-ovo-v1":
        raise ValueError(f"{index_path}: unknown format "
                         f"{index.get('format')!r}")
    classes = np.asarray(index["classes"])
    pairs, models = [], []
    for e in index["pairs"]:
        pairs.append((int(e["a"]), int(e["b"])))
        models.append(load_model(os.path.join(dirpath, e["file"])))
    return MulticlassModel(classes=classes, pairs=pairs, models=models)

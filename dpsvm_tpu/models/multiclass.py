"""Multi-class classification: one-vs-one on the binary SMO trainer.

Beyond-reference capability (the reference is strictly binary): the
LIBSVM construction — K(K-1)/2 pairwise binary problems, each trained on
the examples of its two classes with labels remapped to +/-1 (first
class of the pair = +1), prediction by majority vote with ties going to
the earlier class in sorted order.

Persistence is a directory: ``index.json`` (classes + pair file names)
plus one reference-format model file per pair, so every sub-model stays
individually loadable by the binary tooling.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import List, Optional, Tuple, Union

import numpy as np

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.models.io import load_model, save_model
from dpsvm_tpu.models.svm import SVMModel, decision_function


@dataclasses.dataclass
class MulticlassModel:
    classes: np.ndarray                    # (k,) sorted original labels
    pairs: List[Tuple[int, int]]           # index pairs into classes
    models: List[SVMModel]                 # one per pair
    platt: "Optional[List[Tuple[float, float]]]" = None
                                           # per-pair Platt (A, B) when
                                           # trained with probability

    @property
    def n_classes(self) -> int:
        return len(self.classes)


def resolve_class_weight(classes, class_weight) -> dict:
    """Validate a user class_weight mapping against the label set.

    ONE copy of the rules for every entry point (train_multiclass, the
    sklearn estimator): must be a dict-like label -> weight mapping
    (sklearn's "balanced" string is NOT supported — compute the weights
    explicitly), and every key must be a label present in y."""
    if isinstance(class_weight, str) or not hasattr(class_weight, "get"):
        raise ValueError(
            f"class_weight must be a dict mapping label -> cost weight; "
            f"got {class_weight!r} ('balanced' is not supported — "
            "compute the weights explicitly, e.g. n/(k*bincount))")
    unknown = {k for k in class_weight if not np.any(classes == k)}
    if unknown:
        raise ValueError(
            f"class_weight has labels not present in y: "
            f"{sorted(unknown)} (classes: {classes.tolist()})")
    return dict(class_weight)


def weighted_binary_config(config: SVMConfig, w_pos: float,
                           w_neg: float) -> SVMConfig:
    """The weighted subproblem's config: C*w_pos on the +1 side,
    C*w_neg on the -1 side, and ALWAYS the pairwise clip.

    class_weight is DEFINED as LIBSVM's -wi, whose solver does the
    joint (pairwise) alpha update — semantic, not stylistic: under the
    reference's independent clip, asymmetric box bounds let
    sum(alpha*y) drift arbitrarily far (measured on the wine 0-vs-1
    pair at w=(0.3, 2.0): drift -252.9, intercept -226.9 vs libsvm's
    2.0 — a converged-but-wrong model), while the pairwise rule
    conserves the constraint and matches libsvm's b to 1e-3."""
    cfg = dataclasses.replace(config, clip="pairwise",
                              weight_pos=float(w_pos),
                              weight_neg=float(w_neg))
    cfg.validate()
    return cfg


def train_multiclass(x: np.ndarray, y: np.ndarray,
                     config: Optional[SVMConfig] = None,
                     probability: "Union[bool, str]" = False,
                     batched: bool = False,
                     class_weight: "Optional[dict]" = None,
                     nu: Optional[float] = None,
                     ) -> Tuple[MulticlassModel, List[TrainResult]]:
    """Train OvO; y may hold any integer labels (2 classes work too).

    ``nu``: train every pair as a nu-SVC instead of C-SVC (LIBSVM
    ``-s 1``, which is OvO for >2 classes — sklearn's NuSVC). nu
    bounds each pair's margin-error fraction; per-pair feasibility
    (nu <= 2*min(n_a, n_b)/(n_a+n_b)) is checked by the binary
    trainer and reported with the failing pair named. Sequential path
    only; composes with probability=True (sigmoid on training
    decisions) but not probability="cv" (its held-out refits are
    C-SVC) and not class_weight (the nu constraint fixes each class's
    alpha mass).

    ``class_weight``: LIBSVM's ``-wi`` generalized to any label set
    (sklearn's ``class_weight`` dict): maps original label -> cost
    multiplier; a pair (a, b) trains with C*w[a] on a's examples and
    C*w[b] on b's. Labels absent from the mapping weigh 1.0. Sequential
    path only (the batched program shares one weight pair across all
    subproblems — rejected loudly, not ignored).

    ``probability=True`` fits a per-pair Platt sigmoid on the pair's
    training decision values (the binary --probability simplification,
    see models/calibration.py) so ``predict_proba_multiclass`` can
    couple them — LIBSVM's ``-b 1`` for multiclass. ``probability="cv"``
    fits each pair's sigmoid on k-fold held-out decisions instead
    (LIBSVM's actual procedure, at k extra trainings per pair).

    ``batched=True`` trains ALL pairs in one compiled batched program
    (solver/batched_ovo.py): per-pair trajectories are exactly the
    sequential solver's, but the X stream and the per-step latency
    floor are paid once per batched step for every pair instead of per
    pair. Restricted to the plain first-order single-device path (the
    guard below); the sequential loop remains the general one."""
    from dpsvm_tpu.api import fit

    from dpsvm_tpu.utils import densify
    x = densify(x)
    config = config or SVMConfig()
    precomp = config.kernel == "precomputed"
    if precomp:
        # LIBSVM -t 4 with >2 classes: each pair trains on the
        # (rows, COLUMNS) sub-kernel K[sel][:, sel], and the pair
        # model's SV indices are remapped to GLOBAL training indices
        # afterwards so prediction consumes the user's full
        # K(test, train) like any precomputed binary model.
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[0] != x.shape[1]:
            raise ValueError(
                "precomputed multiclass training needs the square "
                f"(n, n) kernel matrix K(train, train); got {x.shape}")
        if len(np.asarray(y)) != x.shape[0]:
            # the flatnonzero+fancy-indexing pair slicing below would
            # silently train on a row subset for a short y (the
            # vector-kernel path's boolean mask fails loudly instead)
            raise ValueError(
                f"y has {len(np.asarray(y))} labels for a "
                f"{x.shape[0]}-row kernel matrix")
        if nu is not None:
            # reject the GLOBAL incompatibility here, not as a
            # misleading per-pair error from the first pair's trainer
            raise ValueError(
                "nu-SVC does not support the precomputed kernel: use "
                "a vector kernel (or C-SVC, which supports "
                "precomputed)")
        if batched:
            raise ValueError(
                "the batched program streams a feature matrix; "
                "precomputed multiclass runs the sequential per-pair "
                "path — train with batched=False")
        if probability == "cv":
            raise ValueError(
                "probability='cv' refits on row subsets, which needs "
                "matching kernel column subsets per fold; use "
                "probability=True with the precomputed kernel")
    if config.checkpoint_path or config.resume_from:
        # Every pairwise fit would share the one checkpoint file —
        # overwriting each other or failing shape validation mid-run.
        raise ValueError(
            "checkpoint_path/resume_from are single-model options; "
            "they cannot be shared across the pairwise multiclass "
            "subproblems")
    y = np.asarray(y)
    classes = np.unique(y)
    if len(classes) < 2:
        raise ValueError(f"need at least 2 classes, got {classes}")
    if nu is not None:
        if batched:
            raise ValueError(
                "nu-SVC multiclass runs the sequential per-pair path "
                "(the batched program solves the C-SVC iteration); "
                "train with batched=False")
        if class_weight is not None:
            raise ValueError("class weights do not apply to nu-SVC "
                             "(the nu constraint fixes each class's "
                             "alpha mass)")
        if probability == "cv":
            raise ValueError(
                "probability='cv' refits held-out C-SVC models, which "
                "would calibrate a different model class than the "
                "nu-SVC pairs; use probability=True (sigmoid on "
                "training decisions)")
    if class_weight is not None:
        if batched:
            raise ValueError(
                "class_weight needs per-pair box bounds; the batched "
                "program shares one weight pair across all subproblems "
                "— train with batched=False")
        if config.weight_pos != 1.0 or config.weight_neg != 1.0:
            raise ValueError(
                "pass either class_weight (per original label) or "
                "config weight_pos/weight_neg (per pair side), not "
                "both — ambiguous which applies to a pair")
        class_weight = resolve_class_weight(classes, class_weight)

    def pair_config(ai: int, bi: int) -> SVMConfig:
        """The pair's config: C*w[a] on the +1 side, C*w[b] on the -1
        side, pairwise clip (see weighted_binary_config; numpy label
        scalars hash-equal their python values, so the user's dict
        keys look up directly)."""
        if class_weight is None:
            return config
        return weighted_binary_config(
            config, class_weight.get(classes[ai], 1.0),
            class_weight.get(classes[bi], 1.0))

    if batched:
        if config.solver != "exact":
            raise ValueError(
                "the batched OvO program solves the dual iteration; "
                "approx pairs train sequentially (each is one primal "
                "solve) — train with batched=False")
        from dpsvm_tpu.solver.batched_ovo import (batched_guard,
                                                  ovo_pair_shapes)
        batched_guard(config, "OvO",
                      ovo_pair_shapes(y, classes, x.shape[1]))
    pairs, models, results = [], [], []
    platt: Optional[List[Tuple[float, float]]] = [] if probability else None
    if batched:
        from dpsvm_tpu.solver.batched_ovo import (build_pair_targets,
                                                  compact_submodel,
                                                  train_ovo_batched)

        yb, valid, pairs = build_pair_targets(y, classes)
        batch_results = train_ovo_batched(x, yb, valid, config)
        for p, (ai, bi) in enumerate(pairs):
            sel = valid[p]
            ys = np.where(y[sel] == classes[ai], 1, -1).astype(np.int32)
            model, r = compact_submodel(x, sel, ys, batch_results[p])
            models.append(model)
            results.append(r)
            if probability:
                from dpsvm_tpu.models.calibration import (fit_platt,
                                                          fit_platt_cv)
                xs = np.ascontiguousarray(x[sel])
                if probability == "cv":
                    platt.append(fit_platt_cv(xs, ys, config))
                else:
                    dec = np.asarray(decision_function(models[-1], xs))
                    platt.append(fit_platt(dec, ys))
        return MulticlassModel(classes=classes, pairs=pairs,
                               models=models, platt=platt), results
    for ai in range(len(classes)):
        for bi in range(ai + 1, len(classes)):
            sel = (y == classes[ai]) | (y == classes[bi])
            sel_idx = np.flatnonzero(sel)
            if precomp:
                # the pair's SQUARE sub-kernel (rows AND columns)
                xs = np.ascontiguousarray(x[np.ix_(sel_idx, sel_idx)])
            else:
                xs = np.ascontiguousarray(x[sel])
            ys = np.where(y[sel] == classes[ai], 1, -1).astype(np.int32)
            cfg = pair_config(ai, bi)
            if nu is not None:
                from dpsvm_tpu.models.nusvm import train_nusvc
                try:
                    model, result = train_nusvc(xs, ys, nu, cfg)
                except (ValueError, RuntimeError) as e:
                    # name the failing pair: infeasible nu raises
                    # ValueError, a degenerate solution (unseparated
                    # pair at this nu/gamma) raises RuntimeError —
                    # both re-raise as ValueError so the CLI's error
                    # contract (clean message, exit 2) holds
                    raise ValueError(
                        f"pair ({classes[ai]}, {classes[bi]}): {e}"
                    ) from e
            else:
                model, result = fit(xs, ys, cfg)
            if precomp:
                # remap the pair-local SV indices to the full training
                # set and widen n_train, so this model evaluates
                # against the user's (m, n) K(test, train) directly
                model = dataclasses.replace(
                    model, sv_idx=sel_idx[model.sv_idx],
                    n_train=x.shape[0])
            pairs.append((ai, bi))
            models.append(model)
            results.append(result)
            if probability:
                from dpsvm_tpu.models.calibration import (fit_platt,
                                                          fit_platt_cv)
                if probability == "cv":
                    platt.append(fit_platt_cv(xs, ys, cfg))
                else:
                    # precomputed: the remapped model consumes the
                    # n-wide rows K[sel] (not the square slice)
                    xdec = x[sel] if precomp else xs
                    dec = np.asarray(decision_function(model, xdec))
                    platt.append(fit_platt(dec, ys))
    return MulticlassModel(classes=classes, pairs=pairs,
                           models=models, platt=platt), results


def pairwise_decisions(model: MulticlassModel, x: np.ndarray,
                       include_b: bool = True) -> List[np.ndarray]:
    """One decision vector per pair — computed once and shared by the
    vote and the probability coupling (each pass is a full kernel
    inference; callers evaluating both must not pay it twice).

    When every pair shares one kernel spec (always true for models this
    package trains; checked, not assumed — a hand-assembled directory
    may mix kernels), all P inferences collapse into ONE pass: a single
    ``(m, d) @ (d, sum n_sv)`` MXU matmul over the concatenated SV
    rows, then a per-pair segment sum — instead of P dispatches each
    streaming x_test again."""
    ms = model.models
    specs = {(m.kernel, float(m.gamma), float(m.coef0), int(m.degree))
             for m in ms}
    if (len(specs) == 1 and ms[0].kernel != "precomputed"
            and len(ms) > 1
            # approx pairs have no SV rows to concatenate; their
            # per-pair decision is already one dense matmul
            and not any(getattr(m, "is_approx", False) for m in ms)):
        return _pairwise_decisions_batched(model, x, include_b)
    return [np.asarray(decision_function(m, x, include_b=include_b))
            for m in ms]


def _pairwise_decisions_batched(model: MulticlassModel, x: np.ndarray,
                                include_b: bool,
                                batch_size: int = 8192
                                ) -> List[np.ndarray]:
    import jax.numpy as jnp

    from dpsvm_tpu.models.svm import _pairwise_decisions_jit

    ms = model.models
    x = np.asarray(x, np.float32)
    # Loop-invariant operands go to the device ONCE (the whole point of
    # the batched path is removing redundant transfers).
    sv_all = jnp.asarray(np.concatenate([m.x_sv for m in ms]))
    coef = jnp.asarray(np.concatenate(
        [m.alpha * m.y_sv.astype(np.float32) for m in ms]))
    seg_ids = jnp.asarray(np.repeat(np.arange(len(ms), dtype=np.int32),
                                    [len(m.alpha) for m in ms]))
    b_vec = jnp.asarray(np.array([m.b for m in ms], np.float32))
    spec = ms[0]
    m_rows = x.shape[0]
    P = len(ms)
    args = (sv_all, coef, seg_ids, b_vec, jnp.float32(spec.gamma),
            jnp.float32(spec.coef0))
    kw = dict(kind=spec.kernel, degree=int(spec.degree),
              include_b=include_b, num_segments=P)
    if m_rows <= batch_size:
        out = np.asarray(_pairwise_decisions_jit(jnp.asarray(x), *args,
                                                 **kw))
        return [out[:, p] for p in range(P)]
    # Pad to a full batch grid so jit compiles exactly once
    # (decision_function's scheme).
    out = np.empty((m_rows, P), np.float32)
    for lo in range(0, m_rows, batch_size):
        hi = min(lo + batch_size, m_rows)
        block = np.zeros((batch_size, x.shape[1]), np.float32)
        block[: hi - lo] = x[lo:hi]
        vals = np.asarray(_pairwise_decisions_jit(jnp.asarray(block),
                                                  *args, **kw))
        out[lo:hi] = vals[: hi - lo]
    return [out[:, p] for p in range(P)]


def predict_multiclass(model: MulticlassModel, x: np.ndarray,
                       include_b: bool = True,
                       decisions: Optional[List[np.ndarray]] = None,
                       ) -> np.ndarray:
    """Majority vote over pairwise decisions; ties -> earlier class.

    include_b=False drops the intercept like seq_test.cpp:197, matching
    the binary evaluator's --no-b. ``decisions`` reuses a
    ``pairwise_decisions`` result (include_b must match)."""
    if decisions is None:
        decisions = pairwise_decisions(model, x, include_b=include_b)
    n = x.shape[0]
    votes = np.zeros((n, model.n_classes), dtype=np.int32)
    for (ai, bi), dec in zip(model.pairs, decisions):
        votes[:, ai] += dec >= 0
        votes[:, bi] += dec < 0
    return model.classes[np.argmax(votes, axis=1)]


def _couple_pairwise(r: np.ndarray, max_iter: int = 100,
                     eps: float = 1e-12) -> np.ndarray:
    """Class probabilities from pairwise ones (Wu, Lin & Weng 2004,
    their second method — the one LIBSVM's multiclass -b 1 uses).

    r: (n, k, k) with r[t, i, j] = P(class i | i or j, x_t) and
    r[t, j, i] = 1 - r[t, i, j]. Minimizes
    sum_i sum_{j != i} (r[j,i] p_i - r[i,j] p_j)^2 subject to
    p >= 0, sum p = 1, by the paper's Gauss-Seidel iteration —
    implemented from the published equations, vectorized over the n
    samples (every sample runs the same component update in lockstep;
    convergence is per the max over samples)."""
    n, k, _ = r.shape
    if k == 2:
        p = np.empty((n, 2))
        p[:, 0] = r[:, 0, 1]
        p[:, 1] = r[:, 1, 0]
        return p
    q = np.zeros((n, k, k))
    for i in range(k):
        for j in range(k):
            if i == j:
                mask = np.ones(k, bool)
                mask[i] = False
                q[:, i, i] = np.sum(r[:, mask, i] ** 2, axis=1)
            else:
                q[:, i, j] = -r[:, j, i] * r[:, i, j]
    p = np.full((n, k), 1.0 / k)
    for _ in range(max_iter):
        qp = np.einsum("nij,nj->ni", q, p)
        pqp = np.einsum("ni,ni->n", p, qp)
        if np.max(np.abs(qp - pqp[:, None])) < 0.005 / k:
            break
        for t in range(k):
            diff = (-qp[:, t] + pqp) / q[:, t, t]
            p[:, t] += diff
            pqp = ((pqp + diff * (diff * q[:, t, t] + 2.0 * qp[:, t]))
                   / (1.0 + diff) ** 2)
            qp = (qp + diff[:, None] * q[:, t, :]) / (1.0 + diff)[:, None]
            p /= (1.0 + diff)[:, None]
    return np.clip(p, eps, None) / np.sum(
        np.clip(p, eps, None), axis=1, keepdims=True)


def predict_proba_multiclass(model: MulticlassModel, x: np.ndarray,
                             decisions: Optional[List[np.ndarray]]
                             = None) -> np.ndarray:
    """(n, k) class probabilities in ``model.classes`` order via
    per-pair Platt sigmoids + pairwise coupling (LIBSVM -b 1).
    ``decisions`` reuses a ``pairwise_decisions`` result (the sigmoids
    were fit on intercept-included decisions, so it must be one
    computed with include_b=True)."""
    from dpsvm_tpu.models.calibration import sigmoid_proba

    if model.platt is None:
        raise ValueError("this multiclass model was trained without "
                         "probability calibration — retrain with "
                         "probability=True (CLI: --multiclass "
                         "--probability)")
    if decisions is None:
        decisions = pairwise_decisions(model, x, include_b=True)
    n = x.shape[0]
    k = model.n_classes
    r = np.zeros((n, k, k))
    for (ai, bi), dec, (pa, pb) in zip(model.pairs, decisions,
                                       model.platt):
        # pair label +1 == class ai (train_multiclass's orientation);
        # LIBSVM clips coupled inputs away from exact 0/1
        pr = np.clip(sigmoid_proba(dec, pa, pb), 1e-7, 1.0 - 1e-7)
        r[:, ai, bi] = pr
        r[:, bi, ai] = 1.0 - pr
    return _couple_pairwise(r)


def evaluate_multiclass(model: MulticlassModel, x: np.ndarray,
                        y: np.ndarray, include_b: bool = True) -> float:
    return float(np.mean(predict_multiclass(model, x, include_b)
                         == np.asarray(y)))


def save_multiclass(model: MulticlassModel, dirpath: str) -> None:
    os.makedirs(dirpath, exist_ok=True)
    entries = []
    for i, ((ai, bi), m) in enumerate(zip(model.pairs, model.models)):
        name = f"pair_{int(model.classes[ai])}_{int(model.classes[bi])}.svm"
        save_model(m, os.path.join(dirpath, name))
        entry = {"a": int(ai), "b": int(bi), "file": name}
        if model.platt is not None:
            pa, pb = model.platt[i]
            entry["platt"] = [float(pa), float(pb)]
        entries.append(entry)
    with open(os.path.join(dirpath, "index.json"), "w") as f:
        json.dump({"format": "dpsvm_tpu-ovo-v1",
                   "classes": [int(c) for c in model.classes],
                   "pairs": entries}, f, indent=1)


def load_multiclass(dirpath: str) -> MulticlassModel:
    index_path = os.path.join(dirpath, "index.json")
    if not os.path.exists(index_path):
        raise FileNotFoundError(index_path)
    with open(index_path) as f:
        index = json.load(f)
    if index.get("format") != "dpsvm_tpu-ovo-v1":
        raise ValueError(f"{index_path}: unknown format "
                         f"{index.get('format')!r}")
    classes = np.asarray(index["classes"])
    pairs, models, platt = [], [], []
    for e in index["pairs"]:
        pairs.append((int(e["a"]), int(e["b"])))
        models.append(load_model(os.path.join(dirpath, e["file"])))
        if "platt" in e:
            platt.append((float(e["platt"][0]), float(e["platt"][1])))
    if platt and len(platt) != len(pairs):
        raise ValueError(f"{index_path}: {len(platt)} platt entries for "
                         f"{len(pairs)} pairs — corrupt index")
    return MulticlassModel(classes=classes, pairs=pairs, models=models,
                           platt=platt or None)

"""Platt scaling: calibrated probabilities from SVM decision values.

LIBSVM's ``-b 1`` analog (the reference has no probability outputs).
Fits P(y=+1 | dec) = 1 / (1 + exp(A*dec + B)) by regularized maximum
likelihood with Newton's method (Platt 1999, with the Lin/Weng/Lin 2007
numerical fixes: target smoothing and a stable log-sum formulation).

Two fit procedures: ``fit_platt`` on the training decision values
(the cheap default — one extra inference pass; overestimates
confidence slightly on well-separated data) and ``fit_platt_cv``,
LIBSVM's actual -b 1 procedure (pool 5-fold held-out decisions at the
cost of five extra trainings; CLI ``--probability-cv``, estimator
``probability="cv"`` — measured 8x closer to sklearn's calibrated
probabilities, tests/test_calibration.py).

Persisted as a ``<model>.platt.json`` sidecar so the reference-format
model file stays byte-compatible with the reference tooling.
"""

from __future__ import annotations

import json
import os
from typing import Tuple

import numpy as np

from dpsvm_tpu.models.svm import SVMModel, decision_function


def fit_platt(dec: np.ndarray, y: np.ndarray,
              max_iter: int = 100) -> Tuple[float, float]:
    """Fit (A, B) of the sigmoid on decision values dec with labels y."""
    dec = np.asarray(dec, np.float64)
    y = np.asarray(y)
    n_pos = int(np.sum(y > 0))
    n_neg = len(y) - n_pos
    if n_pos == 0 or n_neg == 0:
        raise ValueError("Platt fit needs both classes present")
    # Smoothed targets (Platt 1999 eq. for prior-correct regularization).
    t = np.where(y > 0, (n_pos + 1.0) / (n_pos + 2.0),
                 1.0 / (n_neg + 2.0))

    a, b = 0.0, float(np.log((n_neg + 1.0) / (n_pos + 1.0)))
    sigma = 1e-12
    for _ in range(max_iter):
        p = sigmoid_proba(dec, a, b)
        # gradient of the negative log-likelihood wrt (a, b)
        d1 = t - p
        g1 = float(np.dot(dec, d1))
        g2 = float(np.sum(d1))
        if abs(g1) < 1e-5 and abs(g2) < 1e-5:
            break
        w = p * (1.0 - p)
        h11 = float(np.dot(dec * dec, w)) + sigma
        h22 = float(np.sum(w)) + sigma
        h21 = float(np.dot(dec, w))
        det = h11 * h22 - h21 * h21
        da = -(h22 * g1 - h21 * g2) / det
        db = -(-h21 * g1 + h11 * g2) / det
        # Backtracking line search on the NLL. With p = 1/(1+e^z):
        # NLL = -sum[t log p + (1-t) log(1-p)]
        #     =  sum[logaddexp(0, z) - (1-t) z]   (stable for any z)
        def nll(aa, bb):
            zz = aa * dec + bb
            return float(np.sum(np.logaddexp(0.0, zz) - (1.0 - t) * zz))
        base = nll(a, b)
        step = 1.0
        while step >= 1e-10:
            na, nb = a + step * da, b + step * db
            if nll(na, nb) < base + 1e-4 * step * (g1 * da + g2 * db):
                a, b = na, nb
                break
            step *= 0.5
        else:
            break
    return float(a), float(b)


def fit_platt_cv(x: np.ndarray, y: np.ndarray, config,
                 k: int = 5, seed: int = 0) -> Tuple[float, float]:
    """LIBSVM-faithful sigmoid fit: pool decision values of k-fold
    HELD-OUT models, then fit (A, B) on the pooled values.

    This is exactly what svm-train -b 1 does (libsvm's
    svm_binary_svc_probability): the extra k trainings buy decision
    values that are not optimistically separated by the very model
    being calibrated. The plain ``fit_platt`` on training decisions is
    the documented cheap default; this is the quality option
    (CLI: --probability-cv).
    """
    import dataclasses

    from dpsvm_tpu.api import fit as _fit
    from dpsvm_tpu.models.cv import kfold_assignment

    # The fold fits are internal: checkpoint/resume/profiling belong to
    # the caller's MAIN fit. Sharing them here would re-resume a
    # full-n checkpoint into fold-sized problems (shape error) or let
    # five fold fits overwrite the real run's checkpoint file.
    config = dataclasses.replace(config, checkpoint_path=None,
                                 checkpoint_every=0, resume_from=None,
                                 profile_dir=None)
    y = np.asarray(y)
    fold = kfold_assignment(y, k, seed=seed)
    dec = np.empty(len(y), np.float64)
    for f in range(k):
        tr = fold != f
        te = ~tr
        if len(np.unique(y[tr])) < 2:
            raise ValueError(f"CV-fit calibration: fold {f} leaves a "
                             "single training class — use fewer folds "
                             "or plain --probability")
        model, _ = _fit(np.ascontiguousarray(x[tr]), y[tr], config)
        dec[te] = np.asarray(decision_function(model, x[te]))
    return fit_platt(dec, y)


def sigmoid_proba(dec: np.ndarray, a: float, b: float) -> np.ndarray:
    """P(y = +1 | dec) = 1/(1 + exp(a*dec + b)), computed stably on
    either side of z = 0."""
    z = a * np.asarray(dec, np.float64) + b
    ez = np.exp(-np.abs(z))
    return np.where(z >= 0, ez / (1.0 + ez), 1.0 / (1.0 + ez))


def predict_proba(model: SVMModel, x: np.ndarray, a: float, b: float,
                  include_b: bool = True) -> np.ndarray:
    """P(y = +1 | x) under the fitted sigmoid."""
    return sigmoid_proba(decision_function(model, x, include_b=include_b),
                         a, b)


def sidecar_path(model_path: str) -> str:
    return model_path + ".platt.json"


def save_platt(model_path: str, a: float, b: float) -> None:
    with open(sidecar_path(model_path), "w") as f:
        json.dump({"format": "dpsvm_tpu-platt-v1", "A": a, "B": b}, f)


def load_platt(model_path: str) -> Tuple[float, float]:
    p = sidecar_path(model_path)
    if not os.path.exists(p):
        raise FileNotFoundError(p)
    with open(p) as f:
        d = json.load(f)
    if d.get("format") != "dpsvm_tpu-platt-v1":
        raise ValueError(f"{p}: unknown format {d.get('format')!r}")
    return float(d["A"]), float(d["B"])

"""k-fold cross-validation (LIBSVM's ``svm-train -v n`` mode).

The reference has no model-selection tooling; LIBSVM's CLI does (one of
its most-used flags), so the train CLI here grows ``--cv K``: train on
k-1 folds, predict the held-out fold, pool the held-out predictions
over all folds, and report pooled accuracy (classification) or
MSE/MAE/R^2 (regression) — exactly LIBSVM's protocol (svm.cpp
``svm_cross_validation``), including per-class stratification of the
fold assignment for classification.

Fold assignment is deterministic per ``seed`` so CV numbers are
reproducible run to run.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig


def kfold_assignment(y: np.ndarray, k: int, seed: int = 0,
                     stratify: bool = True) -> np.ndarray:
    """fold id in [0, k) per example; stratified round-robin per class
    when ``stratify`` (classification), plain shuffle otherwise."""
    n = len(y)
    if not 2 <= k <= n:
        raise ValueError(f"cv folds must be in [2, n={n}], got {k}")
    rng = np.random.default_rng(seed)
    fold = np.empty(n, np.int64)
    if stratify:
        for cls in np.unique(y):
            idx = np.flatnonzero(y == cls)
            rng.shuffle(idx)
            fold[idx] = np.arange(len(idx)) % k
    else:
        perm = rng.permutation(n)
        fold[perm] = np.arange(n) % k
    return fold


def cross_validate(x: np.ndarray, y: np.ndarray, k: int,
                   config: Optional[SVMConfig] = None,
                   task: str = "svc", seed: int = 0) -> dict:
    """Pooled held-out predictions over k folds.

    task: "svc" (binary or multiclass by label count) or "svr".
    Returns {"predictions", "folds", plus task metrics}.
    """
    from dpsvm_tpu.utils import densify
    x = densify(x)
    config = config or SVMConfig()
    if config.kernel == "precomputed":
        raise ValueError(
            "cross-validation does not support the precomputed kernel: folds subset rows, which needs matching column subsets of K; slice K per fold and train binary models instead")
    x = np.asarray(x, np.float32)
    y = np.asarray(y)
    if task not in ("svc", "svr"):
        raise ValueError(f"task must be 'svc' or 'svr', got {task!r}")
    if config.checkpoint_path or config.resume_from:
        raise ValueError("checkpoint/resume are single-run options; they "
                         "cannot be shared across CV folds")

    fold = kfold_assignment(y, k, seed, stratify=task == "svc")
    pred = np.empty(len(y), np.float32 if task == "svr" else y.dtype)
    for f in range(k):
        tr = fold != f
        te = ~tr
        if task == "svr":
            from dpsvm_tpu.models.svr import predict_svr, train_svr
            model, _ = train_svr(x[tr], y[tr], config)
            pred[te] = predict_svr(model, x[te])
        elif len(np.unique(y[tr])) > 2:
            from dpsvm_tpu.models.multiclass import (predict_multiclass,
                                                     train_multiclass)
            mc, _ = train_multiclass(x[tr], y[tr], config)
            pred[te] = predict_multiclass(mc, x[te])
        else:
            from dpsvm_tpu.api import fit
            from dpsvm_tpu.models.svm import predict
            classes = np.unique(y[tr])
            if len(classes) < 2:
                # A fold whose train split holds one class would pass
                # _check_xy (all-+1 is a subset of {-1,+1}) and train a
                # degenerate model; fail loudly instead.
                raise ValueError(
                    f"CV fold {f}: training split has a single class "
                    f"({classes!r}) — a class has fewer than {k} members; "
                    "reduce k or rebalance the data")
            ypm = np.where(y[tr] == classes[-1], 1, -1).astype(np.int32)
            model, _ = fit(x[tr], ypm, config)
            p = predict(model, x[te])
            pred[te] = np.where(p > 0, classes[-1], classes[0])

    out = {"predictions": pred, "folds": fold, "k": k}
    if task == "svr":
        from dpsvm_tpu.models.svr import regression_metrics
        out.update(regression_metrics(pred, y))
    else:
        out["accuracy"] = float(np.mean(pred == y))
    return out

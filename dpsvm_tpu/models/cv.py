"""k-fold cross-validation (LIBSVM's ``svm-train -v n`` mode).

The reference has no model-selection tooling; LIBSVM's CLI does (one of
its most-used flags), so the train CLI here grows ``--cv K``: train on
k-1 folds, predict the held-out fold, pool the held-out predictions
over all folds, and report pooled accuracy (classification) or
MSE/MAE/R^2 (regression) — exactly LIBSVM's protocol (svm.cpp
``svm_cross_validation``), including per-class stratification of the
fold assignment for classification.

Fold assignment is deterministic per ``seed`` so CV numbers are
reproducible run to run.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig


def kfold_assignment(y: np.ndarray, k: int, seed: int = 0,
                     stratify: bool = True) -> np.ndarray:
    """fold id in [0, k) per example; stratified round-robin per class
    when ``stratify`` (classification), plain shuffle otherwise."""
    n = len(y)
    if not 2 <= k <= n:
        raise ValueError(f"cv folds must be in [2, n={n}], got {k}")
    rng = np.random.default_rng(seed)
    fold = np.empty(n, np.int64)
    if stratify:
        for cls in np.unique(y):
            idx = np.flatnonzero(y == cls)
            rng.shuffle(idx)
            fold[idx] = np.arange(len(idx)) % k
    else:
        perm = rng.permutation(n)
        fold[perm] = np.arange(n) % k
    return fold


def cross_validate(x: np.ndarray, y: np.ndarray, k: int,
                   config: Optional[SVMConfig] = None,
                   task: str = "svc", seed: int = 0,
                   batched: bool = False,
                   class_weight: "Optional[dict]" = None) -> dict:
    """Pooled held-out predictions over k folds.

    task: "svc" (binary or multiclass by label count) or "svr".
    Returns {"predictions", "folds", plus task metrics}. With
    ``kernel="precomputed"`` x is the (n, n) K(train, train); folds
    slice (rows, columns) sub-kernels. This works for BOTH tasks —
    classification and SVR (the SVR wrapper consumes the fold's
    sub-kernel like any other precomputed problem; locked in by
    tests/test_cv.py::test_cv_svr_precomputed_kernel) — but only on
    the sequential per-fold path: the batched program streams a
    feature matrix and rejects precomputed below.

    ``class_weight``: per-label costs (LIBSVM -wi; see
    models/multiclass.train_multiclass) applied to every fold's
    training — classification only, sequential only (the batched
    program shares one weight pair; SVR has no classes).

    ``batched=True`` (classification only) trains every fold's
    subproblems in ONE compiled batched program (solver/batched_ovo.py
    — the machinery is a general masked-subproblem batch, and CV folds
    are just K more masks): K subproblems for binary, K * K(K-1)/2 for
    multiclass OvO, instead of k sequential trainings. Same scope guard
    as ``train_multiclass(batched=True)``; SVR is rejected (its 2n
    pseudo-example construction doesn't share X across folds).
    """
    from dpsvm_tpu.utils import densify
    x = densify(x)
    config = config or SVMConfig()
    precomp = config.kernel == "precomputed"
    if precomp:
        # LIBSVM -v with -t 4: each fold trains on the (rows, COLUMNS)
        # sub-kernel K[tr][:, tr] and scores held-out rows against
        # K[te][:, tr] — the same slicing train_multiclass uses per
        # OvO pair (its models then handle pair slicing themselves).
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[0] != x.shape[1]:
            raise ValueError(
                "precomputed CV needs the square (n, n) kernel matrix "
                f"K(train, train); got {x.shape}")
        if len(np.asarray(y)) != x.shape[0]:
            raise ValueError(
                f"y has {len(np.asarray(y))} labels for a "
                f"{x.shape[0]}-row kernel matrix")
        if batched:
            raise ValueError(
                "the batched program streams a feature matrix; "
                "precomputed CV runs the sequential per-fold path — "
                "run --cv without batching")
    x = np.asarray(x, np.float32)
    y = np.asarray(y)
    if task not in ("svc", "svr"):
        raise ValueError(f"task must be 'svc' or 'svr', got {task!r}")
    if config.checkpoint_path or config.resume_from:
        raise ValueError("checkpoint/resume are single-run options; they "
                         "cannot be shared across CV folds")
    if config.trace_out:
        raise ValueError("trace_out records ONE training run; CV folds "
                         "would each overwrite it — trace a single fit "
                         "instead")

    if class_weight is not None:
        if task == "svr":
            raise ValueError("class_weight is classification-only "
                             "(SVR has no classes)")
        if batched:
            raise ValueError(
                "class_weight needs per-pair box bounds; the batched "
                "program shares one weight pair across all subproblems "
                "— run --cv without batching")
        from dpsvm_tpu.models.multiclass import resolve_class_weight
        class_weight = resolve_class_weight(np.unique(y), class_weight)
    if batched and task == "svr":
        raise ValueError(
            "batched CV is classification-only: SVR folds train on "
            "2m pseudo-examples built per fold (models/svr.py), so "
            "they do not share one X the way classification folds "
            "do; run --cv without batching for SVR")

    fold = kfold_assignment(y, k, seed, stratify=task == "svc")
    if batched:
        from dpsvm_tpu.solver.batched_ovo import (batched_guard,
                                                  ovo_pair_shapes)
        # Sentinel resolution is per subproblem on the sequential path:
        # per-fold for binary, per fold x pair for multiclass.
        shapes = []
        d = x.shape[1]
        for f in range(k):
            ytr = y[fold != f]
            cls = np.unique(ytr)
            if len(cls) > 2:
                shapes += ovo_pair_shapes(ytr, cls, d)
            else:
                shapes.append((len(ytr), d))
        batched_guard(config, "CV", shapes)
        pred = _cross_validate_batched(x, y, k, fold, config)
        return {"predictions": pred, "folds": fold, "k": k,
                "accuracy": float(np.mean(pred == y))}
    pred = np.empty(len(y), np.float32 if task == "svr" else y.dtype)
    for f in range(k):
        tr = fold != f
        te = ~tr
        if precomp:
            tr_idx = np.flatnonzero(tr)
            x_tr = np.ascontiguousarray(x[np.ix_(tr_idx, tr_idx)])
            x_te = np.ascontiguousarray(x[np.ix_(np.flatnonzero(te),
                                                 tr_idx)])
        else:
            x_tr, x_te = x[tr], x[te]
        if task == "svr":
            from dpsvm_tpu.models.svr import predict_svr, train_svr
            model, _ = train_svr(x_tr, y[tr], config)
            pred[te] = predict_svr(model, x_te)
        elif len(np.unique(y[tr])) > 2:
            from dpsvm_tpu.models.multiclass import (predict_multiclass,
                                                     train_multiclass)
            mc, _ = train_multiclass(x_tr, y[tr], config,
                                     class_weight=class_weight)
            pred[te] = predict_multiclass(mc, x_te)
        else:
            from dpsvm_tpu.api import fit
            from dpsvm_tpu.models.svm import predict
            classes = np.unique(y[tr])
            if len(classes) < 2:
                # A fold whose train split holds one class would pass
                # _check_xy (all-+1 is a subset of {-1,+1}) and train a
                # degenerate model; fail loudly instead.
                raise ValueError(
                    f"CV fold {f}: training split has a single class "
                    f"({classes!r}) — a class has fewer than {k} members; "
                    "reduce k or rebalance the data")
            ypm = np.where(y[tr] == classes[-1], 1, -1).astype(np.int32)
            cfg = config
            if class_weight is not None:
                from dpsvm_tpu.models.multiclass import (
                    weighted_binary_config)
                cfg = weighted_binary_config(
                    config, class_weight.get(classes[-1], 1.0),
                    class_weight.get(classes[0], 1.0))
            model, _ = fit(x_tr, ypm, cfg)
            p = predict(model, x_te)
            pred[te] = np.where(p > 0, classes[-1], classes[0])

    out = {"predictions": pred, "folds": fold, "k": k}
    if task == "svr":
        from dpsvm_tpu.models.svr import regression_metrics
        out.update(regression_metrics(pred, y))
    else:
        out["accuracy"] = float(np.mean(pred == y))
    return out


def _cross_validate_batched(x: np.ndarray, y: np.ndarray, k: int,
                            fold: np.ndarray, config: SVMConfig
                            ) -> np.ndarray:
    """All folds' classification subproblems in one batched program.

    Binary: K subproblems, subproblem f = the +/-1 problem on rows with
    fold != f. Multiclass: K * P subproblems (every fold x every OvO
    pair), then each fold's slice of results votes on its held-out rows
    exactly like the sequential path's per-fold MulticlassModel.
    """
    from dpsvm_tpu.models.svm import predict
    from dpsvm_tpu.solver.batched_ovo import (build_pair_targets,
                                              compact_submodel,
                                              train_ovo_batched)

    classes = np.unique(y)
    if len(classes) < 2:
        # Same fail-loudly contract as the sequential per-fold guard:
        # a P=0 pair batch would otherwise "train" nothing and vote
        # classes[0] everywhere with a perfect-looking accuracy.
        raise ValueError(f"need at least 2 classes, got {classes}")
    n = len(y)
    pred = np.empty(n, y.dtype)
    # Fold f's training split must hold every class (the sequential
    # path's per-fold guard, checked up front here since training is
    # one shot).
    for f in range(k):
        tr_classes = np.unique(y[fold != f])
        if len(tr_classes) < len(classes):
            raise ValueError(
                f"CV fold {f}: training split is missing classes "
                f"(has {tr_classes!r}) — a class has fewer than {k} "
                "members; reduce k or rebalance the data")

    if len(classes) == 2:
        ypm = np.where(y == classes[-1], 1, -1).astype(np.float32)
        yb = np.tile(ypm, (k, 1))
        valid = np.stack([fold != f for f in range(k)])
        yb[~valid] = 0.0
        results = train_ovo_batched(x, yb, valid, config)
        for f, r in enumerate(results):
            sel = valid[f]
            ys = np.where(ypm[sel] > 0, 1, -1).astype(np.int32)
            model, _ = compact_submodel(x, sel, ys, r)
            te = fold == f
            p = predict(model, x[te])
            pred[te] = np.where(p > 0, classes[-1], classes[0])
        return pred

    # Multiclass: K folds x P pairs in one batch. Subproblem (f, p)
    # is pair p's +/-1 problem masked to fold f's training rows.
    pair_yb, pair_valid, pairs = build_pair_targets(y, classes)
    P = len(pairs)
    yb = np.repeat(pair_yb[None, :, :], k, axis=0).reshape(k * P, n)
    valid = (np.repeat(pair_valid[None, :, :], k, axis=0)
             & np.stack([fold != f for f in range(k)])[:, None, :]
             ).reshape(k * P, n)
    yb[~valid] = 0.0
    results = train_ovo_batched(x, yb, valid, config)
    from dpsvm_tpu.models.multiclass import (MulticlassModel,
                                             predict_multiclass)
    for f in range(k):
        models = []
        for p, (ai, bi) in enumerate(pairs):
            sel = valid[f * P + p]
            ys = np.where(y[sel] == classes[ai], 1, -1).astype(np.int32)
            model, _ = compact_submodel(x, sel, ys, results[f * P + p])
            models.append(model)
        mc = MulticlassModel(classes=classes, pairs=pairs, models=models)
        te = fold == f
        pred[te] = predict_multiclass(mc, x[te])
    return pred


def cross_validate_c_sweep(x: np.ndarray, y: np.ndarray, k: int, cs,
                           config: Optional[SVMConfig] = None,
                           seed: int = 0, gammas=None) -> dict:
    """CV accuracy at every point of a C (x gamma) grid — ALL folds x
    grid points in one compiled batched program (binary
    classification).

    This is LIBSVM grid.py (one k-fold CV per grid point, each fold a
    full training) collapsed into a single batch of k * len(cs) [*
    len(gammas)] masked subproblems. Returns {"cs", "accuracies",
    "best_c", "best_accuracy", "folds"}; with ``gammas`` also
    {"gammas", "best_gamma"}, and "accuracies" becomes a
    (len(cs), len(gammas)) matrix. Ties prefer the SMALLER C (more
    regularization at equal held-out accuracy), then the smaller gamma
    (smoother kernel).
    """
    from dpsvm_tpu.models.svm import predict
    from dpsvm_tpu.solver.batched_ovo import (batched_guard,
                                              compact_submodel,
                                              train_ovo_batched,
                                              validate_c_grid)
    from dpsvm_tpu.utils import densify

    config = config or SVMConfig()
    batched_guard(config, "CV C-sweep")
    if config.checkpoint_path or config.resume_from:
        raise ValueError("checkpoint/resume are single-run options; "
                         "they cannot be shared across the sweep's "
                         "fold x C subproblems")
    # capture the caller's ORIGINAL values before the f32 training cast
    # (reported best_c/best_gamma must compare equal to the input grid)
    cs_in = [float(c) for c in np.asarray(cs).ravel()]
    gammas_in = (None if gammas is None
                 else [float(g) for g in np.asarray(gammas).ravel()])
    cs, gammas = validate_c_grid(cs, config, gammas)
    x = np.asarray(densify(x), np.float32)
    y = np.asarray(y)
    classes = np.unique(y)
    if len(classes) != 2:
        raise ValueError("the CV C-sweep is binary-only; run "
                         "cross_validate per C for multiclass")

    fold = kfold_assignment(y, k, seed, stratify=True)
    for f in range(k):
        if len(np.unique(y[fold != f])) < 2:
            raise ValueError(
                f"CV fold {f}: training split has a single class — a "
                f"class has fewer than {k} members; reduce k")
    batched_guard(config, "CV C-sweep",
                  [(int(np.sum(fold != f)), x.shape[1])
                   for f in range(k)])
    ypm = np.where(y == classes[-1], 1, -1).astype(np.float32)
    n = len(y)
    # The per-fold grid column: (C, gamma) pairs in row-major order
    # (plain C list when no gamma axis).
    if gammas_in is None:
        grid_c, grid_g = list(cs), None
    else:
        grid_c = [c for c in cs for _ in gammas_in]
        grid_g = np.array(gammas_in * len(cs), np.float32)
    J = len(grid_c)
    # Subproblem (f, j) -> row f*J + j: fold f's mask, grid point j.
    yb = np.tile(ypm, (k * J, 1))
    valid = np.repeat(np.stack([fold != f for f in range(k)]), J, axis=0)
    yb[~valid] = 0.0
    c_values = np.tile(np.asarray(grid_c, np.float32), k)
    gamma_values = None if grid_g is None else np.tile(grid_g, k)
    results = train_ovo_batched(x, yb, valid, config, c_values=c_values,
                                gamma_values=gamma_values)

    correct = np.zeros(J, np.int64)
    for f in range(k):
        te = fold == f
        sel = valid[f * J]              # same training mask for all C
        # the fold's training slice and labels are shared by its whole
        # C column — copy once, not J times
        xs = np.ascontiguousarray(x[sel])
        ys = np.where(ypm[sel] > 0, 1, -1).astype(np.int32)
        for j in range(J):
            model, _ = compact_submodel(x, sel, ys, results[f * J + j],
                                        xs=xs)
            p = predict(model, x[te])
            pred = np.where(p > 0, classes[-1], classes[0])
            correct[j] += int(np.sum(pred == y[te]))
    accs = correct / float(n)
    # report the caller's ORIGINAL values (the f32 cast is a training
    # detail; best_c must compare equal to the input grid point)
    if gammas_in is None:
        best = int(max(range(J), key=lambda j: (accs[j], -cs_in[j])))
        return {"cs": cs_in, "accuracies": accs, "best_c": cs_in[best],
                "best_accuracy": float(accs[best]), "folds": fold,
                "k": k}
    G = len(gammas_in)
    best = int(max(range(J), key=lambda j: (
        accs[j], -cs_in[j // G], -gammas_in[j % G])))
    return {"cs": cs_in, "gammas": gammas_in,
            "accuracies": accs.reshape(len(cs_in), G),
            "best_c": cs_in[best // G], "best_gamma": gammas_in[best % G],
            "best_accuracy": float(accs[best]), "folds": fold, "k": k}

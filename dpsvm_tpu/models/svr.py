"""epsilon-SVR (support vector regression) on the classification solver.

The reference is a binary classifier only; this framework also offers
LIBSVM's epsilon-SVR (``svm-train -s 3``) — and it costs almost no new
solver code, because the SVR dual IS a classification-shaped SMO problem
over 2n variables (LIBSVM solves it with the same Solver class):

    min  1/2 (a - a*)' K (a - a*) + p sum(a + a*) - y'(a - a*)
    s.t. sum(a - a*) = 0,  0 <= a, a* <= C

Stack beta = [a; a*] with pseudo-labels z = [+1...; -1...]: the dual
gradient in Keerthi form is exactly the solver's f vector with
initialization f0 = [p - y; -p - y] (classification's f0 = -z is the
special case p=0, y=z), kernel rows taken at base indices, and the very
same I_up/I_low masks, first/second-order selection, eta and
independent-clip alpha step. So ``train_svr`` duplicates the rows,
seeds f via the solvers' ``f_init`` hook, and runs the unmodified
compiled paths — single-device, distributed, oracle, any kernel.

The fitted regressor is an ``SVMModel`` with task="svr" whose
coefficients encode delta_i = a_i - a*_i as (alpha=|delta|,
y=sign(delta)): the existing batched decision function then computes
the regression prediction  y(x) = sum_i delta_i K(x_i, x) - b  with no
changes. (Sign check: an interior a_i has f_i = w.x_i + p - y_i = b at
KKT, so the tube center is w.x - b.)
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.models.svm import SVMModel, decision_function


def train_svr(x: np.ndarray, y: np.ndarray,
              config: Optional[SVMConfig] = None
              ) -> Tuple[SVMModel, TrainResult]:
    """Fit an epsilon-SVR. y: (n,) float targets; tube half-width =
    ``config.svr_epsilon`` (LIBSVM -p, default 0.1).

    ``config.clip`` is ALWAYS the conserving pairwise rule here — the
    SVR dual's equality constraint is part of the model, and the
    reference's independent clip drifts it (round-2 advisory). The
    config default ('independent') cannot be distinguished from an
    explicit request, so the flag is deliberately not honored on this
    path; there is no SVR mode with the drifting clip."""
    from dpsvm_tpu.api import train

    from dpsvm_tpu.utils import densify
    x = densify(x)
    config = config or SVMConfig()
    if config.solver != "exact":
        # Approx SVR solves the epsilon-insensitive loss directly in
        # the primal — no 2n dual stacking (docs/APPROX.md).
        from dpsvm_tpu.approx.primal import fit_approx
        return fit_approx(x, y, config, task="svr")
    precomp = config.kernel == "precomputed"
    config.validate()
    if config.weight_pos != 1.0 or config.weight_neg != 1.0:
        raise ValueError("class weights are a classification concept; "
                         "they would weight the two SVR dual halves "
                         "asymmetrically (use a per-sample-weight "
                         "formulation instead)")
    x = np.asarray(x, np.float32)
    y = np.asarray(y, np.float32)
    if x.ndim != 2:
        raise ValueError(f"x must be (n, d), got shape {x.shape}")
    if precomp and x.shape[0] != x.shape[1]:
        raise ValueError(
            "precomputed SVR training needs the square (n, n) kernel "
            f"matrix K(train, train); got {x.shape}")
    if y.shape != (x.shape[0],):
        raise ValueError(f"y must be ({x.shape[0]},), got {y.shape}")
    n = x.shape[0]
    p = np.float32(config.svr_epsilon)

    # The SVR dual carries the equality constraint sum(a - a*) = 0; the
    # reference's independent clip lets it drift, shifting the intercept
    # off the true optimum in long runs (one-class forces pairwise for
    # the same reason — the constraint is part of the model). Default to
    # the conserving clip; an explicit clip='pairwise' is a no-op, and
    # the classification parity path is unaffected.
    if config.clip == "independent":
        config = dataclasses.replace(config, clip="pairwise")

    if precomp:
        # the 2n pseudo-examples duplicate the original rows, so their
        # kernel matrix is K tiled 2x2 (4x the K memory — CI/model-
        # selection scale; vector kernels stream X instead at scale)
        x2n = np.tile(x, (2, 2))
    else:
        x2n = np.vstack([x, x])
    z = np.concatenate([np.ones(n, np.int32), -np.ones(n, np.int32)])
    f0 = np.concatenate([p - y, -p - y]).astype(np.float32)

    # guard_eta: the stacked twin rows make eta == 0 reachable if a
    # twin pair is ever selected; clamp like LIBSVM's TAU (ADVICE r2).
    result = train(x2n, z, config, f_init=f0, guard_eta=True)

    beta = np.asarray(result.alpha, np.float32)
    delta = beta[:n] - beta[n:]
    keep = delta != 0
    extra = {}
    if precomp:
        # SV indices into the ORIGINAL n rows: prediction gathers the
        # user's K(test, train) columns like every precomputed model
        extra = dict(sv_idx=np.flatnonzero(keep).astype(np.int64),
                     n_train=n)
    model = SVMModel(
        x_sv=(np.zeros((int(keep.sum()), 0), np.float32) if precomp
              else np.ascontiguousarray(x[keep])),
        alpha=np.abs(delta[keep]),
        y_sv=np.sign(delta[keep]).astype(np.int32),
        b=float(result.b),
        gamma=float(result.gamma),
        kernel=result.kernel,
        coef0=float(result.coef0),
        degree=int(result.degree),
        task="svr",
        **extra,
    )
    return model, result


def predict_svr(model: SVMModel, x_test: np.ndarray,
                include_b: bool = True) -> np.ndarray:
    """Continuous predictions y(x) = sum_i delta_i K(x_i, x) - b."""
    if model.task != "svr":
        raise ValueError("predict_svr needs a task='svr' model; use "
                         "models.svm.predict for classifiers")
    return decision_function(model, x_test, include_b=include_b)


def regression_metrics(pred: np.ndarray, y: np.ndarray) -> dict:
    """MSE / MAE / R^2 — the one definition shared by the training
    report, the test CLI and cross-validation."""
    y = np.asarray(y, np.float32)
    err = np.asarray(pred, np.float32) - y
    ss_res = float(np.sum(err * err))
    ss_tot = float(np.sum((y - y.mean()) ** 2))
    return {
        "mse": float(np.mean(err * err)),
        "mae": float(np.mean(np.abs(err))),
        "r2": 1.0 - ss_res / ss_tot if ss_tot > 0 else 0.0,
    }


def evaluate_svr(model: SVMModel, x_test: np.ndarray, y_test: np.ndarray,
                 include_b: bool = True) -> dict:
    """MSE / MAE / R^2 on held-out targets."""
    return regression_metrics(
        predict_svr(model, x_test, include_b=include_b), y_test)

"""Model-file serialization, reference-compatible.

Format (the MPI trainer's, ``svmTrainMain.cpp:386-416``):

    line 1:  gamma
    line 2:  b
    line 3+: alpha,y,x1,...,xd        (one line per SV, alpha > 0)

The reference family is internally inconsistent: ``seq.cpp`` omits the b
line (``seq.cpp:302``) and ``seq_test.cpp`` expects only gamma before the
SVs (``seq_test.cpp:225-226``), so the stock tester misparses the MPI
trainer's files by one line (SURVEY §2c). This reader accepts both layouts
by sniffing whether line 2 is a lone scalar; the writer always emits the
full (gamma, b, SVs) form.

Writing goes through the native C++ serializer when available (large
models are many MB of text), with a pure-Python fallback.

Non-RBF kernels (beyond the reference, which is RBF-only): the file
gains a self-describing first line

    kernel <kind> <gamma> <coef0> <degree>

before the b line. RBF models keep the exact reference layout so the
reference's own tools can still read them; the "kernel" word cannot be
confused with the reference's bare-float gamma line, so the reader
dispatches on it safely.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from dpsvm_tpu.models.svm import SVMModel
from dpsvm_tpu.native import load_native_lib


def save_model(model: SVMModel, path: str) -> int:
    """Write the model file; returns the number of SV lines written.

    Approx models (``dpsvm_tpu/approx``) have no SV lines — they
    persist as one ``.npz`` (feature-map spec + primal weights) behind
    this same entry point, so every caller round-trips either model
    kind without knowing which it holds."""
    if getattr(model, "is_approx", False):
        from dpsvm_tpu.approx.model import save_approx_model
        return save_approx_model(model, path)
    alpha = np.ascontiguousarray(model.alpha, np.float32)
    y = np.ascontiguousarray(model.y_sv, np.int32)
    x = np.ascontiguousarray(model.x_sv, np.float32)
    n, d = x.shape
    if model.task != "svc" or model.kernel != "rbf":
        # Beyond-reference models (regression, or non-RBF kernels) use
        # the self-describing header; the native writer emits only the
        # reference's RBF layout, so SV lines go through Python here.
        with open(path, "w") as f:
            f.write(f"kernel {model.kernel} {model.gamma:.9g} "
                    f"{model.coef0:.9g} {int(model.degree)}\n")
            if model.task != "svc":
                f.write(f"task {model.task}\n")
            if model.kernel == "precomputed":
                # SVs are INDICES into the training set; the svidx line
                # carries them plus the width K(test, train) must have.
                # A '+' suffix marks a LOWER-BOUND width (model came
                # from a LIBSVM import without n_features), so the
                # relaxed width check survives a native round-trip.
                idx = " ".join(str(int(i)) for i in model.sv_idx)
                lb = "" if model.n_train_exact else "+"
                f.write(f"svidx {int(model.n_train)}{lb} {idx}\n")
            f.write(f"{model.b:.9g}\n")
            wrote = 0
            for i in range(n):
                if model.kernel == "precomputed":
                    # every stored row aligns with svidx — no skipping
                    f.write(f"{alpha[i]:.9g},{int(y[i])}\n")
                    wrote += 1
                    continue
                if not alpha[i] > 0:
                    continue
                row = ",".join(f"{v:.9g}" for v in x[i])
                f.write(f"{alpha[i]:.9g},{int(y[i])},{row}\n")
                wrote += 1
        return wrote
    lib = load_native_lib()
    if lib is not None:
        wrote = lib.dpsvm_write_model(
            path.encode(), float(model.gamma), float(model.b),
            alpha.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, d)
        if wrote >= 0:
            return int(wrote)
    with open(path, "w") as f:
        f.write(f"{model.gamma:.9g}\n{model.b:.9g}\n")
        wrote = 0
        for i in range(n):
            if not alpha[i] > 0:
                continue
            row = ",".join(f"{v:.9g}" for v in x[i])
            f.write(f"{alpha[i]:.9g},{int(y[i])},{row}\n")
            wrote += 1
    return wrote


def is_libsvm_model(path: str) -> bool:
    """True when the file is LIBSVM ``.model`` format (svm-train's
    output), which opens with an ``svm_type`` header line no reference-
    format file can start with (its line 1 is a bare gamma float or our
    ``kernel ...`` header)."""
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                return ln.startswith("svm_type")
    return False


def _native_load(path: str) -> "Optional[SVMModel]":
    """Reference-format fast path through the C++ reader (MNIST-scale
    RBF model files are tens of MB of text). Returns None whenever the
    native helper is absent, the file uses an extended layout (kernel/
    task/svidx headers — the C++ side reports -4), or anything fails to
    parse — the Python reader below is the format authority and the
    source of error messages, and the native path is never LOOSER."""
    lib = load_native_lib()
    if lib is None:
        return None
    n_sv = ctypes.c_long()
    d = ctypes.c_long()
    has_b = ctypes.c_int()
    gamma = ctypes.c_double()
    b = ctypes.c_double()
    rc = lib.dpsvm_model_shape(path.encode(), ctypes.byref(n_sv),
                               ctypes.byref(d), ctypes.byref(has_b),
                               ctypes.byref(gamma), ctypes.byref(b))
    if rc != 0 or n_sv.value <= 0 or d.value < 1:
        return None
    alpha = np.empty((n_sv.value,), np.float32)
    y = np.empty((n_sv.value,), np.int32)
    x = np.empty((n_sv.value, d.value), np.float32)
    got = lib.dpsvm_parse_model(
        path.encode(),
        alpha.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n_sv.value, d.value, has_b.value)
    if got != n_sv.value:
        return None
    return SVMModel(x_sv=x, alpha=alpha, y_sv=y, b=float(b.value),
                    gamma=float(gamma.value))


def load_model(path: str, n_features=None) -> SVMModel:
    """Read a model file (with or without the b line).

    LIBSVM ``.model`` files are detected and dispatched to
    ``models.libsvm_io`` (``n_features`` widens their sparse SV matrix;
    reference-format files carry explicit width and ignore it).
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    # Approx models are .npz archives — dispatch on the zip magic
    # BEFORE any text sniffing (reading a binary file as text would
    # produce a garbage error, not a model). Checked inline so the
    # jax-importing approx package only loads for actual approx files.
    with open(path, "rb") as f:
        if f.read(4) == b"PK\x03\x04":
            from dpsvm_tpu.approx.model import load_approx_model
            return load_approx_model(path)
    if is_libsvm_model(path):
        from dpsvm_tpu.models.libsvm_io import load_libsvm_model
        return load_libsvm_model(path, n_features=n_features)
    native = _native_load(path)   # load_native_lib honors DPSVM_NO_NATIVE
    if native is not None:
        return native
    with open(path) as f:
        lines = [ln.strip() for ln in f if ln.strip()]
    if len(lines) < 2:
        raise ValueError(f"{path}: not a model file (needs gamma + SVs)")
    kernel, coef0, degree = "rbf", 0.0, 3
    if lines[0].startswith("kernel "):
        parts = lines[0].split()
        if len(parts) != 5:
            raise ValueError(f"{path}: bad kernel header {lines[0]!r} "
                             "(want: kernel <kind> <gamma> <coef0> <degree>)")
        kernel, gamma, coef0, degree = (parts[1], float(parts[2]),
                                        float(parts[3]), int(parts[4]))
    else:
        gamma = float(lines[0])
    task = "svc"
    if len(lines) > 1 and lines[1].startswith("task "):
        task = lines[1].split()[1]
        if task not in ("svc", "svr", "oneclass"):
            raise ValueError(f"{path}: unknown task {task!r}")
        lines = [lines[0]] + lines[2:]
    sv_idx, n_train, n_train_exact = None, None, True
    if len(lines) > 1 and lines[1].startswith("svidx "):
        if kernel != "precomputed":
            raise ValueError(f"{path}: svidx line is precomputed-kernel "
                             "only")
        parts = lines[1].split()
        n_train_exact = not parts[1].endswith("+")
        n_train = int(parts[1].rstrip("+"))
        sv_idx = np.asarray(parts[2:], dtype=np.int64)
        lines = [lines[0]] + lines[2:]
    elif kernel == "precomputed":
        raise ValueError(f"{path}: precomputed-kernel model is missing "
                         "its svidx line")
    # After the header line(s): an optional lone-scalar b line, then SVs
    # (the reference's seq.cpp layout omits b — SURVEY §2c).
    has_b = len(lines) > 1 and "," not in lines[1]
    b = float(lines[1]) if has_b else 0.0
    sv_lines = lines[2:] if has_b else lines[1:]
    if not sv_lines:
        raise ValueError(f"{path}: model has no support vectors")
    n_sv = len(sv_lines)
    d = sv_lines[0].count(",") - 1
    alpha = np.empty((n_sv,), np.float32)
    y = np.empty((n_sv,), np.int32)
    x = np.empty((n_sv, d), np.float32)
    for i, ln in enumerate(sv_lines):
        parts = ln.split(",")
        if len(parts) != d + 2:
            raise ValueError(f"{path}: SV line {i} has {len(parts)} fields, "
                             f"expected {d + 2}")
        alpha[i] = float(parts[0])
        y[i] = int(float(parts[1]))
        x[i] = np.asarray(parts[2:], dtype=np.float32)
    if sv_idx is not None and len(sv_idx) != n_sv:
        raise ValueError(f"{path}: svidx lists {len(sv_idx)} indices "
                         f"but there are {n_sv} SV lines")
    return SVMModel(x_sv=x, alpha=alpha, y_sv=y, b=b, gamma=gamma,
                    kernel=kernel, coef0=coef0, degree=degree, task=task,
                    sv_idx=sv_idx, n_train=n_train,
                    n_train_exact=n_train_exact)

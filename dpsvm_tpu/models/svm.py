"""Trained-model representation and batched XLA inference.

The reference evaluates one test point at a time — an SGEMV against the SV
matrix per example on GPU (``svmTrain.cu:640-652``) or a doubly-nested
host loop with a fresh RBF per (example, SV) pair (``seq_test.cpp:187-210``).
On TPU the whole evaluation is one ``(m, d) @ (d, n_sv)`` MXU matmul with a
fused RBF epilogue and a reduction against alpha*y — batched, not per
example.

Decision rule parity: prediction is +1 iff dual >= 0 (``svmTrain.cu:650-656``).
The trainer's accuracy subtracts the intercept (``dual -= b``,
``svmTrain.cu:648``) while the standalone tester drops it
(``seq_test.cpp:197`` commented out); ``include_b`` selects, default True.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.config import TrainResult
from dpsvm_tpu.ops.kernels import KernelSpec, kernel_rows, row_norms_sq


@dataclasses.dataclass
class SVMModel:
    """Support vectors + duals: everything the model file holds
    (gamma, b, then per-SV alpha, y, x — ``svmTrainMain.cpp:386-416``)."""

    x_sv: np.ndarray      # (n_sv, d) float32
    alpha: np.ndarray     # (n_sv,) float32, all > 0
    y_sv: np.ndarray      # (n_sv,) int32 +/-1
    b: float
    gamma: float
    kernel: str = "rbf"   # LIBSVM -t family; "rbf" = reference parity
    coef0: float = 0.0
    degree: int = 3
    task: str = "svc"     # "svc" (classification) | "svr" (regression,
                          # coefficients encode delta = a - a*)
    sv_idx: "Optional[np.ndarray]" = None   # precomputed kernel only:
                          # SV indices into the TRAINING set (LIBSVM's
                          # "0:serial"); prediction input is K(test,
                          # train) and the decision gathers its columns
    n_train: "Optional[int]" = None         # precomputed only: training
                          # n, i.e. the width K(test, train) must have
    n_train_exact: bool = True              # False only for LIBSVM
                          # imports without an n_features hint, where
                          # n_train is max(serial)+1 — a LOWER bound
                          # (the .model format stores no n_train) — and
                          # wider K(test, train) is legitimate. The
                          # native format persists the flag as a '+'
                          # suffix on the svidx width token.

    @property
    def kernel_spec(self) -> KernelSpec:
        return KernelSpec(kind=self.kernel, gamma=float(self.gamma),
                          coef0=float(self.coef0), degree=int(self.degree))

    @property
    def n_sv(self) -> int:
        return int(self.alpha.shape[0])

    @property
    def num_attributes(self) -> int:
        """Width the evaluation input must have: d for vector kernels,
        n_train (K(test, train) columns) for precomputed."""
        if self.kernel == "precomputed":
            return int(self.n_train)
        return int(self.x_sv.shape[1])

    @classmethod
    def from_train_result(cls, x: np.ndarray, y: np.ndarray,
                          result: TrainResult) -> "SVMModel":
        """Compact SVs (alpha > 0) out of the full training set — the
        ``aggregate_sv`` step (``svmTrain.cu:595-631``) as one boolean mask.

        For the precomputed kernel x is the (n, n) kernel matrix; the
        model keeps SV INDICES (prediction gathers columns of the
        user-supplied K(test, train)) instead of SV rows."""
        alpha = np.asarray(result.alpha, dtype=np.float32)
        keep = alpha > 0
        if result.kernel == "precomputed":
            return cls(
                x_sv=np.zeros((int(keep.sum()), 0), np.float32),
                alpha=alpha[keep],
                y_sv=np.asarray(y, np.int32)[keep],
                b=float(result.b),
                gamma=float(result.gamma),
                kernel=result.kernel,
                coef0=float(result.coef0),
                degree=int(result.degree),
                sv_idx=np.flatnonzero(keep).astype(np.int64),
                n_train=int(np.asarray(x).shape[0]),
            )
        return cls(
            x_sv=np.ascontiguousarray(np.asarray(x, np.float32)[keep]),
            alpha=alpha[keep],
            y_sv=np.asarray(y, np.int32)[keep],
            b=float(result.b),
            gamma=float(result.gamma),
            kernel=result.kernel,
            coef0=float(result.coef0),
            degree=int(result.degree),
        )


@functools.partial(jax.jit, static_argnames=("kind", "degree",
                                             "include_b",
                                             "num_segments",
                                             "precision_name"))
def _pairwise_decisions_jit(x_test, sv_all, coef, seg_ids, b_vec, gamma,
                            coef0, kind: str, degree: int,
                            include_b: bool, num_segments: int,
                            precision_name: str = "HIGHEST"):
    """All P pairwise decisions in one pass (models/multiclass.py's
    batched path): one (m, d) @ (d, S) kernel matmul over the
    concatenated SV rows, then a sorted segment_sum per pair — O(m*S)
    like the per-model loop (no dense (S, P) reduction matrix), and a
    non-finite kernel value stays confined to its own pair's decision
    exactly as in the loop. ``precision_name`` is the serving engine's
    MXU-mode knob (HIGHEST = exact f32 parity, the default — the
    segment_sum reduction stays float32 in either mode)."""
    precision = getattr(jax.lax.Precision, precision_name)
    spec = KernelSpec(kind=kind, gamma=gamma, coef0=coef0, degree=degree)
    t2 = row_norms_sq(x_test)
    sv2 = row_norms_sq(sv_all)
    k = kernel_rows(x_test, t2, sv_all, sv2, spec,
                    precision=precision)              # (m, S)
    dual = jax.ops.segment_sum((k * coef[None, :]).T, seg_ids,
                               num_segments=num_segments,
                               indices_are_sorted=True).T    # (m, P)
    if include_b:
        dual = dual - b_vec[None, :]
    return dual


@functools.partial(jax.jit, static_argnames=("kind", "degree",
                                             "include_b",
                                             "precision_name"))
def _decision_jit(x_test, x_sv, coef, sv2, b, gamma, coef0,
                  kind: str, degree: int, include_b: bool,
                  precision_name: str = "HIGHEST"):
    # kind/degree select the program (static); gamma/coef0 are traced so
    # a hyperparameter sweep reuses one compilation per kernel kind.
    # precision_name (serving's --precision knob): HIGHEST = exact f32
    # (the default, bitwise decision_function parity); DEFAULT = bf16
    # multiplies with f32 MXU accumulation for the (m, n_sv) pass.
    precision = getattr(jax.lax.Precision, precision_name)
    spec = KernelSpec(kind=kind, gamma=gamma, coef0=coef0, degree=degree)
    t2 = row_norms_sq(x_test)
    k = kernel_rows(x_test, t2, x_sv, sv2, spec,
                    precision=precision)              # (m, n_sv)
    dual = jnp.matmul(k, coef, precision=precision)
    if include_b:
        dual = dual - b
    return dual


def decision_function(model: SVMModel, x_test: np.ndarray,
                      include_b: bool = True,
                      batch_size: Optional[int] = 8192) -> np.ndarray:
    """dual_i = sum_j alpha_j y_j K(x_j, t_i) [- b], batched on the MXU.

    Approx models (``dpsvm_tpu/approx``) dispatch to their
    featurize-and-dot program here, so every consumer written against
    this signature — CV, multiclass, ``dpsvm test``, calibration —
    evaluates either model kind through the one entry point."""
    if getattr(model, "is_approx", False):
        from dpsvm_tpu.approx.model import decision_function as _approx
        return _approx(model, x_test, include_b=include_b,
                       batch_size=batch_size)
    x_test = np.asarray(x_test, np.float32)
    if model.kernel == "precomputed":
        # x_test is K(test, train): the decision is a column gather of
        # the SV serials plus one (m, n_sv) @ (n_sv,) product.
        # When n_train is known exactly (native models), a width
        # mismatch means the wrong matrix — stay strict. For LIBSVM
        # imports without an n_features hint num_attributes is merely
        # max(serial)+1 — a lower bound — so wider valid K(test, train)
        # is accepted there (the decision only gathers SV columns).
        if model.n_train_exact:
            if x_test.shape[1] != model.num_attributes:
                raise ValueError(
                    f"precomputed evaluation needs K(test, train) with "
                    f"{model.num_attributes} columns (the training n), "
                    f"got {x_test.shape[1]}")
        elif x_test.shape[1] < model.num_attributes:
            raise ValueError(
                f"precomputed evaluation needs K(test, train) with at "
                f"least {model.num_attributes} columns (this model came "
                f"from a LIBSVM file, which stores no n_train; "
                f"max SV serial + 1 is a lower bound — pass n_features "
                f"to load_libsvm_model for an exact check), got "
                f"{x_test.shape[1]}")
        coef_np = (model.alpha * model.y_sv.astype(np.float32))
        dual = x_test[:, model.sv_idx] @ coef_np
        if include_b:
            dual = dual - np.float32(model.b)
        return dual.astype(np.float32)
    coef = jnp.asarray(model.alpha * model.y_sv.astype(np.float32))
    x_sv = jnp.asarray(model.x_sv)
    sv2 = row_norms_sq(x_sv)
    m = x_test.shape[0]
    if batch_size is None or m <= batch_size:
        return np.asarray(_decision_jit(
            jnp.asarray(x_test), x_sv, coef, sv2,
            jnp.float32(model.b), jnp.float32(model.gamma),
            jnp.float32(model.coef0), model.kernel, int(model.degree),
            include_b))
    # Pad to a full batch grid so jit compiles exactly once.
    out = np.empty((m,), np.float32)
    for lo in range(0, m, batch_size):
        hi = min(lo + batch_size, m)
        block = np.zeros((batch_size, x_test.shape[1]), np.float32)
        block[: hi - lo] = x_test[lo:hi]
        vals = np.asarray(_decision_jit(
            jnp.asarray(block), x_sv, coef, sv2,
            jnp.float32(model.b), jnp.float32(model.gamma),
            jnp.float32(model.coef0), model.kernel, int(model.degree),
            include_b))
        out[lo:hi] = vals[: hi - lo]
    return out


def predict(model: SVMModel, x_test: np.ndarray,
            include_b: bool = True) -> np.ndarray:
    """+1 iff dual >= 0 (svmTrain.cu:650-656)."""
    dual = decision_function(model, x_test, include_b=include_b)
    return np.where(dual < 0, -1, 1).astype(np.int32)


def evaluate(model: SVMModel, x_test: np.ndarray, y_test: np.ndarray,
             include_b: bool = True) -> float:
    """Fraction of correct predictions (get_train_accuracy /
    get_test_accuracy semantics)."""
    pred = predict(model, x_test, include_b=include_b)
    return float(np.mean(pred == np.asarray(y_test, np.int32)))

"""``python -m dpsvm_tpu.tuning`` — the autotuning selfcheck CI gate
(sibling of ``python -m dpsvm_tpu.telemetry``, ``-m .resilience``,
``-m .serving``, ``-m .approx`` and ``-m .data``)."""

import sys

from dpsvm_tpu.tuning import main

sys.exit(main())

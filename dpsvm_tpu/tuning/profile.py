"""Per-backend tuned-knob profiles: the persisted half of the
observe -> act loop (docs/PERF.md "Autotuning").

``dpsvm tune`` (tuning/tuner.py) measures a bounded grid of
throughput-critical knobs on THIS machine's backend and persists the
winners here — one JSON file, keyed by ``device_kind`` (the same
identity the roofline peak table keys on), each entry carrying full
provenance: the git sha and timestamp of the tuning run, the probe
ledger rows that produced every decision, and the measured end-to-end
win over the hand-set defaults. Resolution then consults the profile
whenever a knob is still at its built-in default:

    explicit value  >  tuned profile  >  built-in default

* **Explicit always wins** — the CLI marks knobs the operator set
  (even to the default value) and ``apply_tuned`` never touches them;
  any non-default config value is likewise left alone.
* **Opt-out** — ``--no-tuned`` on the consuming commands, or
  ``DPSVM_NO_TUNED=1`` process-wide. An EMPTY ``DPSVM_TUNED_PROFILE``
  disables profile resolution entirely (the ledger's env convention;
  the test suite runs disabled).
* **Backend mismatch invalidates** — an entry tuned on ``TPU v5e``
  is never applied on ``cpu``: a tuned point is a fact about one
  backend's economics ("Parallel SVMs in Practice", arXiv:1404.1066 —
  tune per deployment, don't ship one magic constant).
* **Provenance or nothing** — an entry missing its schema, git_sha,
  timestamp or knob dict fails ``validate_entry`` and is ignored (a
  hand-edited profile degrades to the defaults, never to a crash).

``dpsvm doctor`` prints which entry (if any) is active for the visible
backend — see ``doctor_lines``.

Knob namespace (what resolution consumes today):

    chunk_iters      -> SVMConfig.chunk_iters   (host poll cadence)
    cache_lines      -> SVMConfig.cache_size    (kernel-row cache)
    serve_max_batch  -> serve --max-batch       (bucket-ladder top rung)
    serve_hedge_ms   -> serve --hedge-ms        (hedged re-dispatch)

The file format carries arbitrary knob names (a profile written by a
newer tuner stays loadable); unknown names are simply not consumed.

Dependency-free (stdlib only): imported by the CLI and doctor before
any backend init — reading a profile must never force one. The only
jax touch is ``current_device_kind()``, which reads an ALREADY
initialized backend and returns None otherwise.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

PROFILE_ENV = "DPSVM_TUNED_PROFILE"
NO_TUNED_ENV = "DPSVM_NO_TUNED"
PROFILE_SCHEMA = 1

#: profile knob name -> SVMConfig field consumed by ``apply_tuned``.
TRAIN_KNOBS = {
    "chunk_iters": "chunk_iters",
    "cache_lines": "cache_size",
}

#: serving-side knob names consumed by ``cmd_serve`` (not SVMConfig
#: fields — the serving stack has its own constructor plumbing).
SERVE_KNOBS = ("serve_max_batch", "serve_hedge_ms")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_profile_path() -> str:
    return os.path.join(repo_root(), "benchmarks", "results",
                        "tuned_profile.json")


def profile_path(explicit: Optional[str] = None) -> Optional[str]:
    """Resolve the profile file: explicit argument, else the env var
    (EMPTY env value = profiles disabled -> None), else the in-repo
    default (the ledger's resolution convention)."""
    if explicit:
        return explicit
    env = os.environ.get(PROFILE_ENV)
    if env is not None:
        return env or None
    return default_profile_path()


def opted_out() -> bool:
    return os.environ.get(NO_TUNED_ENV, "").strip() not in ("", "0")


def current_device_kind() -> Optional[str]:
    """The running backend's device kind (e.g. "cpu", "TPU v5e") —
    read from an already-initialized jax only; None when no backend is
    up (never forces an init)."""
    import sys
    jx = sys.modules.get("jax")
    if jx is None:
        return None
    try:
        d = jx.devices()[0]
    except Exception:
        return None
    return str(getattr(d, "device_kind", None) or d.platform)


def validate_entry(entry: dict) -> List[str]:
    """Provenance problems with one profile entry (empty = valid).
    An entry that cannot say where it came from is not applied."""
    problems: List[str] = []
    if not isinstance(entry, dict):
        return ["entry is not an object"]
    if entry.get("schema") != PROFILE_SCHEMA:
        problems.append(f"schema {entry.get('schema')!r} != "
                        f"{PROFILE_SCHEMA}")
    if not entry.get("device_kind"):
        problems.append("missing device_kind")
    if not entry.get("git_sha"):
        problems.append("missing git_sha provenance")
    if not entry.get("time"):
        problems.append("missing timestamp")
    knobs = entry.get("knobs")
    if not isinstance(knobs, dict):
        problems.append("knobs is not an object")
    else:
        for k, v in knobs.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                problems.append(f"knob {k!r} has non-numeric value "
                                f"{v!r}")
    if not isinstance(entry.get("probes", []), list):
        problems.append("probes is not a list")
    return problems


def load_profiles(path: Optional[str] = None) -> Dict[str, dict]:
    """Every entry in the profile file, keyed by device_kind
    ({} for a missing/disabled/unparseable file — a damaged profile
    degrades to the built-in defaults, never to a crash)."""
    p = profile_path(path)
    if p is None or not os.path.exists(p):
        return {}
    try:
        with open(p) as fh:
            data = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return {}
    if not isinstance(data, dict):
        return {}
    profiles = data.get("profiles")
    return profiles if isinstance(profiles, dict) else {}


def active_entry(device_kind: Optional[str] = None,
                 path: Optional[str] = None) -> Optional[dict]:
    """The profile entry resolution would consult right now: the
    current backend's entry, provenance-valid, not opted out — None
    otherwise. ``device_kind=None`` reads the running backend."""
    if opted_out():
        return None
    profiles = load_profiles(path)
    if not profiles:
        return None
    dk = device_kind or current_device_kind()
    if not dk:
        return None
    entry = None
    for key, val in profiles.items():
        if str(key).lower() == str(dk).lower():
            entry = val
            break
    if entry is None:
        return None
    if validate_entry(entry):
        return None
    # Backend-mismatch invalidation: the entry's own recorded
    # device_kind must agree with the key it sits under (a copied or
    # hand-renamed entry is a provenance lie, not a tuning fact).
    if str(entry.get("device_kind", "")).lower() != str(dk).lower():
        return None
    return entry


def tuned_value(entry: Optional[dict], knob: str):
    """The entry's value for one knob name, or None."""
    if not entry:
        return None
    v = (entry.get("knobs") or {}).get(knob)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return v


def apply_tuned(config, explicit: Sequence[str] = (),
                device_kind: Optional[str] = None,
                path: Optional[str] = None) -> Tuple[object, dict]:
    """Resolve an SVMConfig against the active profile.

    Returns ``(config, applied)`` where ``applied`` maps the SVMConfig
    field names that were replaced to their tuned values ({} when
    nothing applied). Precedence per knob:

    * named in ``explicit`` (the CLI's set-by-the-operator list, even
      when set TO the default value) -> untouched;
    * config value differs from the SVMConfig field default (an API
      caller chose it) -> untouched;
    * tuned value fails ``config.validate()`` against the rest of the
      config (e.g. a cache on a decomposition run) -> skipped, the
      remaining knobs still apply;
    * otherwise -> replaced with the tuned value.

    The numpy golden-reference backend is never resolved: its
    economics are not the compiled backend's, and the oracle must stay
    knob-stable."""
    import dataclasses

    if getattr(config, "backend", "xla") == "numpy":
        return config, {}
    entry = active_entry(device_kind=device_kind, path=path)
    if entry is None:
        return config, {}
    defaults = type(config)()
    explicit = set(explicit)
    applied: dict = {}
    for knob, field in TRAIN_KNOBS.items():
        v = tuned_value(entry, knob)
        if v is None or field in explicit:
            continue
        if getattr(config, field) != getattr(defaults, field):
            continue
        cand = dataclasses.replace(config, **{field: int(v)})
        try:
            cand.validate()
        except ValueError:
            continue
        config = cand
        applied[field] = int(v)
    return config, applied


def make_entry(device_kind: str, knobs: dict,
               probes: Optional[List[dict]] = None,
               win: Optional[dict] = None) -> dict:
    """One schema-valid profile entry with full provenance."""
    from dpsvm_tpu.observability.ledger import git_sha
    return {
        "schema": PROFILE_SCHEMA,
        "device_kind": str(device_kind),
        "git_sha": git_sha() or "unknown",
        "time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "knobs": dict(knobs),
        "probes": list(probes or []),
        "win": win,
    }


def save_entry(entry: dict, path: Optional[str] = None) -> str:
    """Merge one entry into the profile file under its device_kind
    (atomic tmp+rename; other backends' entries are preserved)."""
    p = profile_path(path)
    if p is None:
        raise ValueError(
            f"tuned profiles are disabled ({PROFILE_ENV} is empty); "
            "pass an explicit --out path")
    problems = validate_entry(entry)
    if problems:
        raise ValueError(f"refusing to persist an invalid profile "
                         f"entry: {problems}")
    profiles = load_profiles(p)
    profiles[str(entry["device_kind"])] = entry
    os.makedirs(os.path.dirname(os.path.abspath(p)) or ".",
                exist_ok=True)
    tmp = f"{p}.tmp{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump({"schema": PROFILE_SCHEMA, "profiles": profiles},
                  fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, p)
    return p


def provenance_tag(device_kind: Optional[str] = None,
                   path: Optional[str] = None) -> Optional[str]:
    """Compact "<device_kind>@<git_sha>" tag of the entry resolution
    would consult, or None — bench rows carry it so ledger history
    stays attributable to the knob set that produced each number."""
    try:
        entry = active_entry(device_kind=device_kind, path=path)
    except Exception:
        return None
    if entry is None:
        return None
    return f"{entry['device_kind']}@{entry.get('git_sha', 'unknown')}"


def doctor_lines(device_kind: Optional[str] = None,
                 path: Optional[str] = None) -> List[str]:
    """What ``dpsvm doctor`` prints about profile resolution: which
    entry is active (knobs + provenance), or exactly why none is."""
    p = profile_path(path)
    if p is None:
        return [f"tuned profiles disabled ({PROFILE_ENV} is empty)"]
    if opted_out():
        return [f"tuned profile OPT-OUT active ({NO_TUNED_ENV}=1) — "
                "built-in defaults in effect"]
    if not os.path.exists(p):
        return [f"no tuned profile at {p} (run `dpsvm tune` to "
                "measure one for this backend)"]
    profiles = load_profiles(p)
    if not profiles:
        return [f"tuned profile {p} is unreadable or empty — "
                "built-in defaults in effect"]
    dk = device_kind or current_device_kind()
    entry = active_entry(device_kind=dk, path=p)
    if entry is None:
        have = ", ".join(sorted(profiles))
        return [f"profile {p} has no valid entry for this backend "
                f"({dk!r}; entries: {have}) — built-in defaults in "
                "effect"]
    knobs = ", ".join(f"{k}={v}" for k, v in
                      sorted(entry["knobs"].items())) or "(no knobs)"
    lines = [f"active profile for {entry['device_kind']!r}: {knobs}",
             f"provenance: git {entry['git_sha']} at {entry['time']}, "
             f"{len(entry.get('probes', []))} probe row(s) [{p}]"]
    win = entry.get("win")
    if isinstance(win, dict) and win.get("speedup") is not None:
        lines.append(
            f"measured win: {win['speedup']:.2f}x vs defaults "
            f"({win.get('case', 'tuned_vs_default')}; compare gate "
            f"{'OK' if win.get('compare_ok') else 'NOT RUN'})")
    return lines

"""``dpsvm tune``: deterministic, deadline-bounded knob measurement —
the acting half of the observe -> act loop (docs/PERF.md "Autotuning").

Every throughput-critical knob in this repo started life as a hand-set
constant backed by one machine's measurement (``chunk_iters=512``,
``cache_size=0``, the serving ladder's ``max_batch=256``...). The
PR 8/11 observability stack can *measure* all of them — perf-ledger
history, compile accounting, roofline facts — but nothing acted on the
measurements ("GPU-Accelerated Primal Learning", arXiv:2008.03433, is
the precedent for tuning the primal path to the hardware;
"Parallel SVMs in Practice", arXiv:1404.1066, for tuning per
deployment backend instead of shipping one magic constant). The tuner
closes the loop:

* **Probes ride the existing plumbing.** A train probe is a short,
  seeded run through ``api.train`` — the shared host driver — with
  ``trace_out`` armed, so every probe gets run-telemetry, compilewatch
  accounting and the metrics-registry feed for free. Probe rates are
  **compile-corrected**: the probe's trace records how many seconds of
  its wall were XLA compilation (a knob that changes the compiled
  program, like ``cache_lines``, pays its compile exactly once per
  process and must not be charged for it at measurement time), and the
  rate divides by the post-compile wall only. A serving probe drives a
  real warmed ``PredictionEngine`` bucket ladder with a fixed
  deterministic request-size schedule.
* **Successive halving over a bounded grid.** Each knob gets a small
  value grid and a geometric budget ladder: every rung measures the
  survivors at double the previous budget and keeps the faster half,
  so cheap early rungs discard losers and the expensive final budget
  is spent on finalists only. The built-in default ALWAYS survives to
  the final rung — the winner must beat the measured default by
  ``min_win_pct`` on the same budget or the default is kept (a planted
  slower-than-default candidate is structurally unable to win).
* **Deadline-bounded.** The whole run carries one wall deadline; when
  it expires, finished knobs keep their verdicts and unfinished knobs
  keep their defaults — a tune run degrades to "less tuned", never to
  a hang (the bench preflight lesson, BENCH_r03–r05).
* **The win is proved end-to-end, then persisted.** After the per-knob
  grids, one A/B run — built-in defaults vs the tuned knob set, same
  data, same iteration budget, each with its own trace — measures the
  combined effect; the speedup lands as a ``tuned_vs_default``
  perf-ledger row (kind ``tune``) with both traces as provenance and
  the ``dpsvm compare`` regression verdicts as the gate. The knob set,
  every probe row, and the win are persisted as this backend's profile
  entry (tuning/profile.py) for config resolution to consult.
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

#: bounded default grids (the default value of each knob is always
#: forced into its grid, so the probe comparison is always anchored).
DEFAULT_GRIDS: Dict[str, Tuple[int, ...]] = {
    "chunk_iters": (128, 256, 512, 1024, 2048, 4096),
    "cache_lines": (0, 64, 256, 1024),
    "serve_max_batch": (64, 128, 256, 512),
}

#: built-in defaults the winners must beat (SVMConfig field defaults
#: for the train knobs; the serve parser's hand-set constant for the
#: ladder rung).
KNOB_DEFAULTS = {"chunk_iters": 512, "cache_lines": 0,
                 "serve_max_batch": 256}

TRAIN_KNOB_FIELDS = {"chunk_iters": "chunk_iters",
                     "cache_lines": "cache_size"}

#: deterministic request-size schedule for the serving-ladder probe:
#: fixed ABSOLUTE sizes (independent of the candidate rung) spanning
#: single rows to multi-pass streams, so every candidate serves the
#: same workload and only the ladder shape differs.
SERVE_SIZES = (1, 3, 8, 17, 40, 64, 96, 160, 256, 384, 512, 700)


class DeadlineExpired(Exception):
    """Internal control flow: the tune deadline ran out mid-knob."""


def _remaining(deadline_ts: float) -> float:
    return deadline_ts - time.monotonic()


def _registry_facts() -> dict:
    """Single-series ``dpsvm_train_*`` gauge readings from the process
    metrics registry — the instrument API metrics.py reserved for this
    consumer. Defensive: a missing instrument reads as absent, never
    as a probe failure."""
    out = {}
    try:
        from dpsvm_tpu.observability.metrics import default_registry
        snap = default_registry().snapshot()
        for name in ("dpsvm_train_iterations",
                     "dpsvm_train_iters_per_sec",
                     "dpsvm_train_gap"):
            fam = snap.get(name) or {}
            series = fam.get("series") or []
            if len(series) == 1 and "value" in series[0]:
                out[name] = series[0]["value"]
    except Exception:
        pass
    return out


def _trace_compile_seconds(trace_path: Optional[str]) -> float:
    """Seconds of XLA compilation recorded in a probe's trace (0.0
    when untraced/unreadable — the correction degrades to raw wall)."""
    if not trace_path or not os.path.exists(trace_path):
        return 0.0
    try:
        from dpsvm_tpu.observability.record import read_trace
        records = read_trace(trace_path)
    except Exception:
        return 0.0
    return float(sum(r.get("seconds") or 0.0 for r in records
                     if r.get("kind") == "compile"))


def probe_train(x, y, base_config, knob: str, value: int,
                budget_iters: int, rung: int,
                trace_dir: Optional[str] = None) -> dict:
    """One train probe: a short seeded run through the shared host
    driver at ``knob=value``, returning a ledger-shaped probe row with
    the compile-corrected rate."""
    from dpsvm_tpu.api import train
    from dpsvm_tpu.observability import ledger

    field = TRAIN_KNOB_FIELDS[knob]
    trace_out = None
    if trace_dir:
        os.makedirs(trace_dir, exist_ok=True)
        trace_out = os.path.join(
            trace_dir, f"probe_{knob}_{value}_r{rung}.jsonl")
    cfg = dataclasses.replace(
        base_config, **{field: int(value)}, max_iter=int(budget_iters),
        trace_out=trace_out, verbose=False)
    t0 = time.perf_counter()
    r = train(x, y, cfg)
    wall = time.perf_counter() - t0
    compile_s = _trace_compile_seconds(trace_out)
    eff = max(min(r.train_seconds, wall) - compile_s, 1e-9)
    rate = r.n_iter / eff
    metrics = {
        "knob": knob, "candidate": int(value), "rung": int(rung),
        "budget_iters": int(budget_iters), "n_iter": int(r.n_iter),
        "seconds": round(r.train_seconds, 4),
        "compile_seconds": round(compile_s, 4),
        "rate": round(rate, 2), "converged": bool(r.converged),
        "registry": _registry_facts(),
    }
    return ledger.make_record(f"tune_probe_{knob}", metrics,
                              kind="tune", value=round(rate, 2),
                              unit="iter/s", direction="higher",
                              trace=trace_out)


def probe_serve(model, max_batch: int, rung: int, repeats: int,
                rows) -> dict:
    """One serving-ladder probe: a warmed ``PredictionEngine`` at the
    candidate top rung, timed over the fixed request-size schedule
    (``repeats`` full passes). Warmup compiles happen inside engine
    construction and are excluded from the timed window by
    construction."""
    from dpsvm_tpu.observability import ledger
    from dpsvm_tpu.serving.engine import PredictionEngine

    eng = PredictionEngine(model, name="tune-probe",
                           max_batch=int(max_batch))
    total_rows = 0
    t0 = time.perf_counter()
    for _ in range(int(repeats)):
        for m in SERVE_SIZES:
            eng.decision_values(rows[:m])
            total_rows += m
    dt = max(time.perf_counter() - t0, 1e-9)
    rate = total_rows / dt
    metrics = {
        "knob": "serve_max_batch", "candidate": int(max_batch),
        "rung": int(rung), "repeats": int(repeats),
        "rows": int(total_rows), "seconds": round(dt, 4),
        "rate": round(rate, 1),
        "warmup_compiles": len(eng.warmup_compiles),
        "buckets": list(eng.buckets),
    }
    return ledger.make_record("tune_probe_serve_max_batch", metrics,
                              kind="tune", value=round(rate, 1),
                              unit="rows/s", direction="higher")


def select_winner(default_value: int, rates: Dict[int, float],
                  min_win_pct: float) -> Tuple[int, bool]:
    """The probe comparison: the fastest candidate wins ONLY when it
    beats the measured default by ``min_win_pct`` percent on the same
    budget — otherwise the default stands. A candidate slower than the
    default can never be selected, no matter what the grid held."""
    if default_value not in rates:
        raise ValueError(
            f"default {default_value} was not measured at the final "
            f"rung (measured: {sorted(rates)}) — the comparison is "
            "unanchored")
    base = rates[default_value]
    best = max(rates, key=lambda v: rates[v])
    if best == default_value:
        return default_value, False
    if rates[best] < base * (1.0 + float(min_win_pct) / 100.0):
        return default_value, False
    return int(best), True


def successive_halving(values: Sequence[int], default_value: int,
                       measure: Callable[[int, int, int], dict],
                       budgets: Sequence[int], deadline_ts: float,
                       log: Callable[[str], None]
                       ) -> Tuple[Dict[int, float], List[dict]]:
    """Halving rounds over ``values``: every rung measures the
    survivors at ``budgets[rung]`` and keeps the faster half; the
    default always survives so the final comparison stays anchored.
    Raises DeadlineExpired when the wall budget runs out (the caller
    keeps the default for this knob)."""
    alive = list(dict.fromkeys(list(values) + [default_value]))
    probes: List[dict] = []
    rung_rates: Dict[int, float] = {}
    for rung, budget in enumerate(budgets):
        rung_rates = {}
        for v in list(alive):
            if _remaining(deadline_ts) <= 0:
                raise DeadlineExpired(
                    f"deadline expired at rung {rung} "
                    f"({len(probes)} probe(s) done)")
            row = measure(v, int(budget), rung)
            probes.append(row)
            rung_rates[v] = float(row["value"])
        alive.sort(key=lambda v: -rung_rates[v])
        if rung < len(budgets) - 1:
            keep = max(2, math.ceil(len(alive) / 2))
            cut = alive[keep:]
            alive = alive[:keep]
            if default_value not in alive:
                alive.append(default_value)
            cut = [v for v in cut if v not in alive]
            if cut:
                log(f"  rung {rung}: kept {alive}, cut {cut}")
    # Only the FINAL rung's readings anchor the verdict: every
    # surviving value (the default included, by construction) was
    # measured at the same final budget.
    return dict(rung_rates), probes


def run_tune(x, y, *, base_config=None, knobs: Sequence[str] = (),
             grids: Optional[Dict[str, Sequence[int]]] = None,
             probe_iters: int = 2000, rungs: int = 3,
             deadline_s: float = 300.0, min_win_pct: float = 2.0,
             profile_out: Optional[str] = None,
             trace_dir: Optional[str] = None, ledger_on: bool = True,
             device_kind: Optional[str] = None,
             log: Callable[[str], None] = print) -> Tuple[dict, int]:
    """The full tune run (see module docstring). Returns
    ``(profile_entry, exit_code)``; exit 0 = a profile was persisted
    (tuned or default-confirming), 1 = the deadline expired before any
    knob finished."""
    import numpy as np

    from dpsvm_tpu.config import SVMConfig
    from dpsvm_tpu.observability import ledger
    from dpsvm_tpu.tuning import profile as prof

    base_config = base_config or SVMConfig()
    knobs = list(knobs) or list(DEFAULT_GRIDS)
    grids = {**DEFAULT_GRIDS, **(grids or {})}
    deadline_ts = time.monotonic() + float(deadline_s)
    dk = device_kind or prof.current_device_kind()
    if not dk:
        raise ValueError("no initialized backend to tune for — "
                         "tune runs after backend init")
    if trace_dir is None:
        # next to the RESOLVED profile file, so the default run lands
        # its provenance beside the ledger's trace archive
        out_hint = prof.profile_path(profile_out)
        if out_hint:
            trace_dir = os.path.join(
                os.path.dirname(os.path.abspath(out_hint)) or ".",
                "traces", "tune")
    budgets = [int(probe_iters) * (2 ** r) for r in range(max(1,
                                                              rungs))]
    log(f"tune: backend {dk!r}, knobs {knobs}, rung budgets {budgets},"
        f" deadline {deadline_s:g}s")

    def _ledger(row):
        if not ledger_on:
            return
        try:
            path = ledger.ledger_path()
            if path is None:
                return
            import json
            os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                        exist_ok=True)
            with open(path, "a") as fh:
                fh.write(json.dumps(row) + "\n")
        except OSError:
            pass

    # Warmup: pay the shared chunk-runner compile before any timed
    # probe (chunk_iters probes share ONE program — the poll limit is
    # a traced operand — so only program-changing knobs compile again,
    # and those compiles are subtracted via the probe trace anyway).
    from dpsvm_tpu.api import train
    train(x, y, dataclasses.replace(base_config, max_iter=64,
                                    verbose=False))

    tuned: Dict[str, int] = {}
    all_probes: List[dict] = []
    finished = 0
    cfg = base_config
    for knob in [k for k in knobs if k in TRAIN_KNOB_FIELDS]:
        default_v = KNOB_DEFAULTS[knob]
        log(f"tune: {knob} over {sorted(set(grids[knob]))} "
            f"(default {default_v})")

        def measure(v, budget, rung, _knob=knob, _cfg=cfg):
            row = probe_train(x, y, _cfg, _knob, v, budget, rung,
                              trace_dir=trace_dir)
            log(f"  {_knob}={v} rung {rung}: "
                f"{row['metrics']['rate']:,.0f} it/s "
                f"({row['metrics']['n_iter']} iters, "
                f"{row['metrics']['seconds']:.3f}s wall, "
                f"{row['metrics']['compile_seconds']:.3f}s compile)")
            _ledger(row)
            return row

        try:
            final, probes = successive_halving(
                grids[knob], default_v, measure, budgets, deadline_ts,
                log)
        except DeadlineExpired as e:
            log(f"tune: {knob}: {e} — keeping the default")
            continue
        all_probes.extend(probes)
        winner, improved = select_winner(default_v, final, min_win_pct)
        finished += 1
        if improved:
            gain = (final[winner] / final[default_v] - 1.0) * 100.0
            log(f"tune: {knob}: {winner} beats default {default_v} "
                f"by {gain:.1f}% -> tuned")
            tuned[knob] = winner
            cfg = dataclasses.replace(
                cfg, **{TRAIN_KNOB_FIELDS[knob]: winner})
        else:
            log(f"tune: {knob}: default {default_v} stands "
                f"(best candidate within {min_win_pct:g}%)")

    if "serve_max_batch" in knobs and _remaining(deadline_ts) > 0:
        log(f"tune: serve_max_batch over "
            f"{sorted(set(grids['serve_max_batch']))} (default "
            f"{KNOB_DEFAULTS['serve_max_batch']})")
        from dpsvm_tpu.api import fit
        n_model = min(len(y), 2000)
        model, _ = fit(x[:n_model], y[:n_model],
                       dataclasses.replace(cfg, max_iter=20_000,
                                           trace_out=None,
                                           verbose=False))
        rng = np.random.default_rng(0)
        rows = np.asarray(
            rng.standard_normal((max(SERVE_SIZES), x.shape[1])),
            np.float32)

        def measure_serve(v, budget, rung):
            # budget here is repeats of the schedule; scale it down
            # from the iteration budgets to keep rungs comparable.
            repeats = max(1, budget // int(probe_iters))
            row = probe_serve(model, v, rung, repeats, rows)
            log(f"  serve_max_batch={v} rung {rung}: "
                f"{row['metrics']['rate']:,.0f} rows/s")
            _ledger(row)
            return row

        try:
            final, probes = successive_halving(
                grids["serve_max_batch"],
                KNOB_DEFAULTS["serve_max_batch"], measure_serve,
                budgets, deadline_ts, log)
            all_probes.extend(probes)
            winner, improved = select_winner(
                KNOB_DEFAULTS["serve_max_batch"], final, min_win_pct)
            finished += 1
            if improved:
                tuned["serve_max_batch"] = winner
                log(f"tune: serve_max_batch: {winner} -> tuned")
            else:
                log("tune: serve_max_batch: default stands")
        except DeadlineExpired as e:
            log(f"tune: serve_max_batch: {e} — keeping the default")

    if finished == 0:
        log("tune: deadline expired before any knob finished — "
            "nothing to persist")
        return {}, 1

    # End-to-end A/B: defaults vs the tuned train-knob set, one trace
    # each — THE row that proves (or refuses to claim) the win.
    win = None
    train_tuned = {k: v for k, v in tuned.items()
                   if k in TRAIN_KNOB_FIELDS}
    if train_tuned and _remaining(deadline_ts) > 0:
        ab_iters = budgets[-1] * 2
        tdir = trace_dir or "."
        os.makedirs(tdir, exist_ok=True)
        t_def = os.path.join(tdir, "tuned_vs_default_default.jsonl")
        t_tun = os.path.join(tdir, "tuned_vs_default_tuned.jsonl")
        cfg_d = dataclasses.replace(base_config, max_iter=ab_iters,
                                    trace_out=t_def, verbose=False)
        cfg_t = dataclasses.replace(
            base_config,
            **{TRAIN_KNOB_FIELDS[k]: v for k, v in train_tuned.items()},
            max_iter=ab_iters, trace_out=t_tun, verbose=False)
        t0 = time.perf_counter()
        r_d = train(x, y, cfg_d)
        s_d = time.perf_counter() - t0
        t0 = time.perf_counter()
        r_t = train(x, y, cfg_t)
        s_t = time.perf_counter() - t0
        rate_d = r_d.n_iter / max(s_d, 1e-9)
        rate_t = r_t.n_iter / max(s_t, 1e-9)
        speedup = rate_t / max(rate_d, 1e-9)
        verdicts: List[str] = []
        try:
            from dpsvm_tpu.observability.compare import (compare_paths,
                                                         regressions)
            cmp, _ra, _rb = compare_paths(t_def, t_tun)
            verdicts = regressions(cmp, pct=5.0)
        except Exception as e:                  # noqa: BLE001
            verdicts = [f"compare failed: {e}"]
        compare_ok = not verdicts
        log(f"tune: tuned_vs_default: {rate_d:,.0f} -> {rate_t:,.0f} "
            f"it/s ({speedup:.3f}x) over {ab_iters} iters; compare "
            f"gate {'OK' if compare_ok else 'FAILED: ' + '; '.join(verdicts)}")
        win = {"case": "tuned_vs_default", "speedup": round(speedup, 4),
               "default_rate": round(rate_d, 1),
               "tuned_rate": round(rate_t, 1),
               "budget_iters": int(ab_iters),
               "trace_default": t_def, "trace_tuned": t_tun,
               "compare_ok": bool(compare_ok),
               "compare_regressions": verdicts}
        ab_row = ledger.make_record(
            "tuned_vs_default",
            {**win, "knobs": dict(train_tuned)}, kind="tune",
            value=round(speedup, 4), unit="x", direction="higher",
            trace=t_tun)
        _ledger(ab_row)
        all_probes.append(ab_row)
        if speedup < 1.0:
            # The combined set failed end-to-end: refuse to persist a
            # knob set the A/B could not confirm (probe wins that do
            # not survive composition are noise, not tuning facts).
            log("tune: A/B shows no end-to-end win — persisting a "
                "default-confirming entry instead")
            for k in train_tuned:
                tuned.pop(k, None)
            win["rejected"] = True

    entry = prof.make_entry(dk, tuned, probes=all_probes, win=win)
    out_path = prof.save_entry(entry, profile_out)
    log(f"tune: profile entry for {dk!r} written to {out_path} "
        f"(knobs: {tuned or 'none — defaults confirmed'})")
    return entry, 0

"""Ledger-driven autotuning: measure the hand-set knobs, persist the
winners per backend, resolve them at config time (docs/PERF.md
"Autotuning").

The pieces:

* ``tuner``   — ``dpsvm tune``: deterministic, deadline-bounded
                successive-halving probes over a bounded per-knob grid,
                each probe a short run through the existing driver /
                serving plumbing (traces, compilewatch and the metrics
                registry come for free), compile-corrected rates, an
                end-to-end ``tuned_vs_default`` A/B gated by
                ``dpsvm compare`` and appended to the perf ledger.
* ``profile`` — the persisted per-``device_kind`` profile (JSON with
                git_sha / timestamp / probe-row provenance + the
                measured win) and its resolution precedence:
                explicit value > tuned profile > built-in default,
                ``--no-tuned`` / ``DPSVM_NO_TUNED=1`` opt-out,
                backend-mismatch invalidation. ``dpsvm doctor``
                reports the active entry.

CI gate: ``python -m dpsvm_tpu.tuning --selfcheck`` — sibling of the
telemetry/resilience/serving/approx/data gates. Asserts (1) a real
tiny-grid tune run persists a provenance-valid profile whose probe
rows carry traces and land in the perf ledger; (2) config resolution
picks a planted profile up, explicit values and the opt-outs win over
it, and a wrong-backend entry is never applied; (3) the probe
comparison structurally rejects a planted slower-than-default
candidate — at the selection rule AND through a full successive-
halving round.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

__all__ = ["main", "selfcheck"]


def selfcheck(tmp_dir: Optional[str] = None) -> List[str]:
    """Returns a list of problems (empty = gate passes)."""
    import json
    import tempfile

    problems: List[str] = []
    base = tmp_dir or tempfile.mkdtemp(prefix="dpsvm_tune_selfcheck_")
    old_ledger = os.environ.get("DPSVM_PERF_LEDGER")
    old_noenv = os.environ.pop("DPSVM_NO_TUNED", None)
    ledger_path = os.path.join(base, "ledger.jsonl")
    os.environ["DPSVM_PERF_LEDGER"] = ledger_path
    try:
        import dataclasses

        from dpsvm_tpu.config import SVMConfig
        from dpsvm_tpu.data.synthetic import make_blobs
        from dpsvm_tpu.tuning import profile as prof
        from dpsvm_tpu.tuning import tuner

        logged: List[str] = []
        x, y = make_blobs(n=800, d=16, seed=0, separation=0.5)
        base_cfg = SVMConfig(c=10.0, epsilon=1e-5, max_iter=100_000)
        out = os.path.join(base, "tuned_profile.json")

        # (1) real tiny-grid tune run -> provenance-valid profile.
        entry, rc = tuner.run_tune(
            x, y, base_config=base_cfg, knobs=("chunk_iters",),
            grids={"chunk_iters": (128, 512)}, probe_iters=400,
            rungs=2, deadline_s=180.0, min_win_pct=1.0,
            profile_out=out, trace_dir=os.path.join(base, "traces"),
            log=logged.append)
        if rc != 0:
            problems.append(f"tiny tune run exited {rc}")
        if not os.path.exists(out):
            problems.append("tune run wrote no profile file")
        else:
            dk = prof.current_device_kind()
            saved = prof.load_profiles(out).get(dk)
            if saved is None:
                problems.append(
                    f"profile has no entry for backend {dk!r}")
            else:
                bad = prof.validate_entry(saved)
                if bad:
                    problems.append(f"persisted entry invalid: {bad}")
                if not saved.get("probes"):
                    problems.append("entry carries no probe rows")
                elif not any(p.get("trace") for p in saved["probes"]):
                    problems.append("no probe row carries a trace "
                                    "pointer")
        if not os.path.exists(ledger_path):
            problems.append("probes appended no perf-ledger rows")
        else:
            from dpsvm_tpu.observability import ledger as ledgerlib
            rows = ledgerlib.read(ledger_path)
            if not any(r.get("kind") == "tune" and
                       r.get("case") == "tune_probe_chunk_iters"
                       for r in rows):
                problems.append("ledger has no tune_probe_chunk_iters "
                                "row")

        # (2) resolution picks a planted profile up; precedence and
        # invalidation rules hold.
        dk = prof.current_device_kind() or "cpu"
        planted_path = os.path.join(base, "planted_profile.json")
        prof.save_entry(prof.make_entry(dk, {"chunk_iters": 2048}),
                        planted_path)
        cfg, applied = prof.apply_tuned(SVMConfig(), path=planted_path)
        if applied != {"chunk_iters": 2048} or cfg.chunk_iters != 2048:
            problems.append(
                f"resolution did not pick up the planted profile "
                f"(applied={applied})")
        cfg, applied = prof.apply_tuned(
            SVMConfig(), explicit={"chunk_iters"}, path=planted_path)
        if applied or cfg.chunk_iters != 512:
            problems.append("explicit CLI knob did not win over the "
                            "profile")
        cfg, applied = prof.apply_tuned(SVMConfig(chunk_iters=64),
                                        path=planted_path)
        if applied or cfg.chunk_iters != 64:
            problems.append("non-default config value did not win "
                            "over the profile")
        os.environ["DPSVM_NO_TUNED"] = "1"
        try:
            if prof.active_entry(path=planted_path) is not None:
                problems.append("DPSVM_NO_TUNED=1 did not opt out")
        finally:
            os.environ.pop("DPSVM_NO_TUNED", None)
        mism_path = os.path.join(base, "mismatch_profile.json")
        prof.save_entry(prof.make_entry("TPU v99", {"chunk_iters": 9}),
                        mism_path)
        cfg, applied = prof.apply_tuned(SVMConfig(), path=mism_path)
        if applied:
            problems.append("wrong-backend entry was applied")
        # provenance-or-nothing: strip git_sha and the entry must die
        broken = prof.make_entry(dk, {"chunk_iters": 7})
        broken["git_sha"] = ""
        with open(os.path.join(base, "broken.json"), "w") as fh:
            json.dump({"schema": prof.PROFILE_SCHEMA,
                       "profiles": {dk: broken}}, fh)
        if prof.active_entry(path=os.path.join(base,
                                               "broken.json")):
            problems.append("entry without git_sha provenance was "
                            "accepted")
        if prof.provenance_tag(path=planted_path) is None:
            problems.append("provenance_tag returned None for an "
                            "active entry")

        # (3) planted slower-than-default candidate is rejected — at
        # the rule and through a full halving round.
        w, imp = tuner.select_winner(512, {512: 100.0, 2048: 80.0},
                                     2.0)
        if imp or w != 512:
            problems.append("select_winner accepted a slower-than-"
                            "default candidate")
        planted_rates = {512: 100.0, 128: 60.0, 2048: 90.0}

        def fake_measure(v, budget, rung):
            from dpsvm_tpu.observability import ledger as ledgerlib
            return ledgerlib.make_record(
                "tune_probe_chunk_iters",
                {"knob": "chunk_iters", "candidate": int(v),
                 "rung": int(rung), "budget_iters": int(budget)},
                kind="tune", value=planted_rates[v], unit="iter/s")

        import time as _time
        final, _ = tuner.successive_halving(
            (128, 2048), 512, fake_measure, (100, 200),
            _time.monotonic() + 60.0, lambda s: None)
        w, imp = tuner.select_winner(512, final, 2.0)
        if imp or w != 512:
            problems.append(
                "successive halving + comparison accepted a planted "
                f"slower-than-default grid (winner {w})")
    except Exception as e:                  # noqa: BLE001
        import traceback
        traceback.print_exc()
        problems.append(f"selfcheck crashed: {type(e).__name__}: {e}")
    finally:
        if old_ledger is None:
            os.environ.pop("DPSVM_PERF_LEDGER", None)
        else:
            os.environ["DPSVM_PERF_LEDGER"] = old_ledger
        if old_noenv is not None:
            os.environ["DPSVM_NO_TUNED"] = old_noenv
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="python -m dpsvm_tpu.tuning")
    p.add_argument("--selfcheck", action="store_true",
                   help="run the autotuning CI gate (see module "
                        "docstring)")
    args = p.parse_args(argv)
    if not args.selfcheck:
        p.print_help()
        return 2
    problems = selfcheck()
    if problems:
        print("tuning selfcheck FAILED:", file=sys.stderr)
        for prob in problems:
            print(f"  - {prob}", file=sys.stderr)
        return 1
    print("tuning selfcheck OK")
    return 0

"""dpsvm_tpu — a TPU-native framework for distributed kernel-SVM training.

A brand-new JAX/XLA implementation with the capabilities of the reference
CUDA+OpenMPI DPSVM (binary SVM, RBF kernel, modified-SMO solver with
Keerthi-style first-order working-set selection — see /root/reference,
``svmTrainMain.cpp``, ``svmTrain.cu``, ``seq.cpp``).

Design (TPU-first, not a port):

* the entire SMO loop runs inside one compiled XLA program
  (``lax.while_loop`` under ``jit``) — no host round-trip per iteration,
  unlike the reference which pays kernel-launch + MPI latency every
  iteration (``svmTrainMain.cpp:235-310``);
* kernel rows come off the MXU as a single ``(2, d) @ (d, n)`` matmul
  (the reference issues two ``cublasSgemv`` on separate CUDA streams,
  ``svmTrain.cu:222,247``);
* distribution is SPMD ``shard_map`` over a 1-D ``jax.sharding.Mesh``
  axis; the per-iteration MPI ``Allgather`` of 4 floats per rank
  (``svmTrainMain.cpp:244``) becomes a ``lax.all_gather`` of per-shard
  extrema over ICI, fused into the same compiled loop;
* the kernel-row LRU cache (``cache.cu``) becomes a fixed-shape
  HBM-resident table updated with masked dynamic-slice writes inside jit.

Public API
----------
``train(X, y, config)``            -> TrainResult (solver dispatch: 1 device or mesh)
``SVMConfig``                      config dataclass (reference flag parity)
``SVMModel``                       trained model pytree + decision function
``load_model`` / ``save_model``    reference-compatible model file I/O
``predict`` / ``evaluate``         batched XLA inference
``DPSVMClassifier``                sklearn-protocol estimator facade
``DPSVMRegressor``                 epsilon-SVR facade (models/svr.py)
``train_svr`` / ``predict_svr``    epsilon-SVR (LIBSVM -s 3)
``train_oneclass`` / ``predict_oneclass``  one-class SVM (LIBSVM -s 2)
``train_nusvc`` / ``train_nusvr``  nu-SVM family (LIBSVM -s 1 / -s 4)
``cross_validate``                 k-fold CV (LIBSVM -v)
``sweep_c``                        whole (C, gamma) grid in one batched
                                   program (grid.py analog)
``cross_validate_c_sweep``         CV accuracy over the grid, folds x
                                   points in one batch; reports best
``train_multiclass``               one-vs-one multiclass (batched=True:
                                   all pairs in one compiled program)
``warm_start``                     continue training from a previous alpha
``serving``                        online prediction subsystem — the
                                   micro-batching engine behind
                                   ``dpsvm serve`` (import
                                   ``dpsvm_tpu.serving`` explicitly;
                                   docs/SERVING.md)
"""

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.models.svm import SVMModel, decision_function, predict, evaluate
from dpsvm_tpu.models.io import save_model, load_model
from dpsvm_tpu.models.estimator import DPSVMClassifier, DPSVMRegressor
from dpsvm_tpu.api import train, fit, sweep_c, warm_start
from dpsvm_tpu.models.svr import train_svr, predict_svr, evaluate_svr
from dpsvm_tpu.models.oneclass import (train_oneclass, predict_oneclass,
                                       score_oneclass)
from dpsvm_tpu.models.nusvm import train_nusvc, train_nusvr
from dpsvm_tpu.models.cv import cross_validate, cross_validate_c_sweep
from dpsvm_tpu.models.multiclass import train_multiclass

__version__ = "0.1.0"

__all__ = [
    "SVMConfig",
    "TrainResult",
    "SVMModel",
    "train",
    "fit",
    "warm_start",
    "decision_function",
    "predict",
    "evaluate",
    "save_model",
    "load_model",
    "DPSVMClassifier",
    "DPSVMRegressor",
    "train_svr",
    "predict_svr",
    "evaluate_svr",
    "train_oneclass",
    "predict_oneclass",
    "score_oneclass",
    "train_nusvc",
    "train_nusvr",
    "cross_validate",
    "cross_validate_c_sweep",
    "sweep_c",
    "train_multiclass",
]

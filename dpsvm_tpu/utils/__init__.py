"""Utilities: structured logging, phase timing, input coercion."""

from dpsvm_tpu.utils.logging import log_progress, get_logger
from dpsvm_tpu.utils.timing import PhaseTimer


def densify(x):
    """scipy.sparse input -> dense ndarray; anything else passes through.

    The TPU compute path is dense (kernel rows are MXU matmuls over a
    dense X), and ``np.asarray`` on a sparse matrix produces a useless
    0-d object array — every user-facing entry point (api, estimators,
    decision functions) densifies up front instead."""
    if hasattr(x, "toarray") and hasattr(x, "tocsr"):
        return x.toarray()
    return x


__all__ = ["log_progress", "get_logger", "PhaseTimer", "densify"]

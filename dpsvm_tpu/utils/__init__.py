"""Utilities: structured logging, phase timing."""

from dpsvm_tpu.utils.logging import log_progress, get_logger
from dpsvm_tpu.utils.timing import PhaseTimer

__all__ = ["log_progress", "get_logger", "PhaseTimer"]

"""Device-stall watchdog for benchmark harnesses on a tunneled TPU.

The axon tunnel flaps (round 3: down for the whole round; round 4: up
for ~60 s, long enough to start a run and then hang it mid-chunk). A
harness blocked inside a device call cannot time itself out from
Python, so a hung tunnel burns the arm's entire outer wall-clock
timeout and leaves no distinguishing evidence behind. This watchdog
turns that failure mode into a fast, labeled exit:

* ``arm(timeout_s)`` starts a daemon thread holding a deadline;
* ``pet()`` pushes the deadline forward — called from the one place
  every solver path's host loop touches the device result stream
  (``solver.driver._read_stats``, the per-chunk stats poll);
* on expiry the thread prints a ``STALL`` diagnostic to stderr and
  ``os._exit(124)`` — the same exit code as ``timeout(1)``, so sweep
  tooling treats "device stopped answering" and "killed by outer
  timeout" uniformly (``benchmarks/sweep_retry.sh`` scrubs rc=124
  records with no measurement on stdout before re-running a tag).

Never armed by library code: only ``require_devices()`` arms it, and
only when ``BENCH_STALL_TIMEOUT`` is set (``benchmarks/chip_sweep.sh``
pins it). Tests and API users are unaffected; ``pet()`` while disarmed
is a no-op costing one attribute read.
"""

from __future__ import annotations

import os
import sys
import threading
import time

_lock = threading.Lock()
_deadline: float | None = None      # None = disarmed
_timeout = 0.0
_thread: threading.Thread | None = None
_POLL_S = 5.0


def arm(timeout_s: float) -> None:
    global _deadline, _timeout, _thread
    with _lock:
        _timeout = float(timeout_s)
        _deadline = time.monotonic() + _timeout
        if _thread is None:
            _thread = threading.Thread(
                target=_watch, name="dpsvm-stall-watchdog", daemon=True)
            _thread.start()


def pet() -> None:
    """Reset the deadline; no-op while disarmed."""
    global _deadline
    if _deadline is None:
        return
    with _lock:
        if _deadline is not None:
            _deadline = time.monotonic() + _timeout


def disarm() -> None:
    global _deadline
    with _lock:
        _deadline = None


def _watch() -> None:
    while True:
        time.sleep(_POLL_S)
        with _lock:
            expired = _deadline is not None and time.monotonic() > _deadline
            timeout = _timeout
    # os._exit inside the lock would be fine too, but keep the exit
    # path trivially deadlock-free.
        if expired:
            print(f"STALL: no device response for {timeout:.0f}s "
                  f"(watchdog armed via BENCH_STALL_TIMEOUT); exiting 124",
                  file=sys.stderr, flush=True)
            # Dist-aware verdict: when a multi-shard run is active, its
            # heartbeat state distinguishes a collective hang (the
            # whole mesh stopped answering together) from a straggler
            # shard (resilience/elastic.stall_extras). Empty for
            # single-device runs — the stall event is unchanged there.
            extras = {}
            try:
                from dpsvm_tpu.resilience import elastic
                extras = elastic.stall_extras()
                if extras:
                    print(f"STALL: dist verdict "
                          f"{extras.get('dist_verdict')} "
                          f"(shard ages {extras.get('shard_ages')})",
                          file=sys.stderr, flush=True)
            except Exception:
                pass
            # Stamp a terminal `stall` event into any open run trace so
            # `dpsvm report` can render the stalled run (an abandoned
            # trace with no terminal record looks identical to a live
            # one). Best-effort: the trace layer never raises here, and
            # the import is deferred so the watchdog stays usable in
            # processes that never touch telemetry.
            try:
                from dpsvm_tpu.telemetry import flush_open_traces
                flushed = flush_open_traces("stall", timeout_s=timeout,
                                            **extras)
                if flushed:
                    print(f"STALL: flushed {flushed} open run trace(s)",
                          file=sys.stderr, flush=True)
            except Exception:
                pass
            os._exit(124)

"""Phase timing.

The reference times training with an rdtsc cycle counter
(``CycleTimer.h:44-73``, used at ``svmTrainMain.cpp:206-208,312-314``) and
left per-phase instrumentation commented out in the solver
(``svmTrain.cu:218-293`` margins). On an async accelerator runtime,
wall-clock around dispatch is meaningless without a fence, so PhaseTimer
pairs ``time.perf_counter`` with ``block_until_ready`` on a sentinel value
and accumulates named buckets (select / collective / update / io ...).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Callable, Dict, Optional

import jax


class PhaseTimer:
    def __init__(self, annotate: Optional[Callable[[str], object]] = None
                 ) -> None:
        """annotate: optional hook returning a context manager for a
        phase name — the profiler integration point
        (observability/profiler.ProfileSession.annotation wraps each
        phase in a jax.profiler.TraceAnnotation span of the SAME name,
        so the device timeline and the host buckets share one
        vocabulary). None = timing only."""
        self.seconds: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self._annotate = annotate

    @contextlib.contextmanager
    def phase(self, name: str, fence: Optional[Callable[[], object]] = None):
        """fence: zero-arg callable evaluated at block exit; its result is
        block_until_ready'd so the bucket measures completed device work,
        not dispatch. (A callable, because the arrays to fence on are
        usually created inside the block.)"""
        ann = (self._annotate(name) if self._annotate is not None
               else contextlib.nullcontext())
        t0 = time.perf_counter()
        try:
            with ann:
                try:
                    yield
                finally:
                    # fence inside the annotation span: the blocked
                    # device wait is attributed to the phase it ends
                    if fence is not None:
                        jax.block_until_ready(fence())
        finally:
            self.seconds[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def summary(self) -> str:
        total = sum(self.seconds.values()) or 1.0
        parts = [
            f"{k}={v:.3f}s({100 * v / total:.0f}%/{self.counts[k]}x)"
            for k, v in sorted(self.seconds.items(), key=lambda kv: -kv[1])
        ]
        return " ".join(parts)

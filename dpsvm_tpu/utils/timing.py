"""Phase timing.

The reference times training with an rdtsc cycle counter
(``CycleTimer.h:44-73``, used at ``svmTrainMain.cpp:206-208,312-314``) and
left per-phase instrumentation commented out in the solver
(``svmTrain.cu:218-293`` margins). On an async accelerator runtime,
wall-clock around dispatch is meaningless without a fence, so PhaseTimer
pairs ``time.perf_counter`` with ``block_until_ready`` on a sentinel value
and accumulates named buckets (select / collective / update / io ...).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Callable, Dict, Optional

import jax


class PhaseTimer:
    def __init__(self) -> None:
        self.seconds: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def phase(self, name: str, fence: Optional[Callable[[], object]] = None):
        """fence: zero-arg callable evaluated at block exit; its result is
        block_until_ready'd so the bucket measures completed device work,
        not dispatch. (A callable, because the arrays to fence on are
        usually created inside the block.)"""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if fence is not None:
                jax.block_until_ready(fence())
            self.seconds[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def summary(self) -> str:
        total = sum(self.seconds.values()) or 1.0
        parts = [
            f"{k}={v:.3f}s({100 * v / total:.0f}%/{self.counts[k]}x)"
            for k, v in sorted(self.seconds.items(), key=lambda kv: -kv[1])
        ]
        return " ".join(parts)

"""Fail-fast backend initialization for benchmark entry points.

The axon-tunneled TPU backend can wedge in a state where
``jax.devices()`` blocks forever rather than raising (observed after
Pallas in-kernel-loop compile hangs — round-1 ``BENCH_r01.json`` died
with an UNAVAILABLE error; a wedged tunnel just hangs). A benchmark
harness must never hang the driver: device discovery runs in a daemon
thread with a deadline, and on timeout or error the process exits with
a one-line diagnostic on stderr and a nonzero code instead of a stack
trace (or silence).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import List, Optional


def require_devices(timeout_s: Optional[float] = None) -> List:
    """Return ``jax.devices()`` or exit(1) with a clear one-line error.

    Timeout default: BENCH_BACKEND_TIMEOUT env var, else 180 s (first
    contact with the tunneled TPU can legitimately take tens of
    seconds; a healthy backend never takes minutes).
    """
    if timeout_s is None:
        timeout_s = _positive_seconds_env("BENCH_BACKEND_TIMEOUT", "180")

    devices, reason = probe_devices(timeout_s)
    if devices is None:
        print(f"error: {reason} "
              f"(platform={os.environ.get('JAX_PLATFORMS', 'default')!r})"
              " — not producing a number rather than a bogus one",
              file=sys.stderr, flush=True)
        # A hung probe thread holds jax's init lock; a normal exit
        # could block on atexit hooks that touch the backend.
        os._exit(1)

    # On a flapping tunnel a device call can hang AFTER a successful
    # probe; arm the stall watchdog (pet at every chunk-stats poll,
    # exit 124 with a STALL diagnostic on expiry) when the harness asks
    # for it. Library/tests never set the env var.
    if os.environ.get("BENCH_STALL_TIMEOUT"):
        from dpsvm_tpu.utils import watchdog
        watchdog.arm(_positive_seconds_env("BENCH_STALL_TIMEOUT", "0"))
    return devices


def _positive_seconds_env(name: str, default: str) -> float:
    raw = os.environ.get(name, default)
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if val <= 0:
        print(f"error: {name}={raw!r} must be a positive number of "
              "seconds", file=sys.stderr, flush=True)
        sys.exit(1)
    return val


# Machine-checkable prefix for the hung-probe reason: a hung probe
# thread HOLDS jax's init lock, so callers that go on to a normal
# interpreter exit can block in jax atexit hooks — they must os._exit
# after printing (require_devices does; cli._init_backend checks this
# prefix to do the same).
HUNG_PREFIX = "backend initialization hung"

_UNSET = object()


def probe_devices(timeout_s: float, override=_UNSET,
                  override_label: str = "platform override"):
    """(devices, None) or (None, reason) — the CATCHABLE probe.

    ``require_devices`` hard-exits (os._exit) by design so a wedged
    tunnel can never leave a benchmark half-running; diagnostics like
    ``cli info`` need to report the failure and keep printing instead.

    ``override``: platform to force before first device use. The
    default reads BENCH_PLATFORM (benchmark-harness behavior); pass an
    explicit name (CLI --platform) or None (no change, ambient
    backend) to take that decision away from the environment.
    ``override_label`` names the knob in diagnostics so a failure
    blames the flag the user actually set.
    """
    result: dict = {}

    # BENCH_PLATFORM=cpu lets any benchmark harness run off-TPU (smoke
    # tests of the sweep path, iteration-economy runs). The env var
    # alone is not enough: this image's sitecustomize pre-imports jax
    # with the axon backend baked into JAX_PLATFORMS, so the switch
    # must go through jax.config BEFORE the first device use.
    if override is _UNSET:
        override = os.environ.get("BENCH_PLATFORM", "").strip()
        override_label = "BENCH_PLATFORM"
    prev_platforms = None
    if override:
        try:
            import jax
            prev_platforms = jax.config.jax_platforms
            jax.config.update("jax_platforms", override)
        except Exception as e:
            return None, (f"{override_label}={override!r} could not be "
                          f"applied: {e}")

    def restore() -> None:
        # A failed override must not poison jax_platforms for the rest
        # of the process: later callers (tests in one run, notebook
        # cells, harness retries) would crash initializing the bogus
        # platform instead of their own.
        if override:
            import jax
            try:
                jax.config.update("jax_platforms", prev_platforms)
            except Exception:
                pass

    def probe() -> None:
        try:
            import jax
            result["devices"] = jax.devices()
        except Exception as e:
            result["error"] = str(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        # No restore: the wedged thread is mid-initialization with the
        # override applied; callers must hard-exit anyway (the thread
        # holds jax's init lock — see exit_if_hung).
        return None, (f"{HUNG_PREFIX} for >{timeout_s:.0f}s "
                      "— the TPU tunnel is unresponsive")
    if "error" in result:
        restore()
        # With an override applied, the raw jax error ("Unknown backend
        # ...") does not name the knob that caused it; blame it here so
        # a bad --platform/BENCH_PLATFORM is diagnosable from the
        # message alone.
        blame = (f" (with {override_label}={override!r} applied)"
                 if override else "")
        return None, f"jax backend unavailable: {result['error']}{blame}"
    devices = result["devices"]
    if override:
        # jax.config.update silently no-ops once a backend is already
        # initialized; verify the override actually took so a run can
        # never record numbers attributed to the wrong platform.
        got = devices[0].platform.lower() if devices else "none"
        want = override.split(",")[0].strip().lower()
        if got != want:
            restore()
            return None, (f"{override_label}={override!r} did not take "
                          f"effect (backend already initialized as "
                          f"{got!r}) — refusing to measure on the "
                          "wrong platform")
    return devices, None


def exit_if_hung(reason: "Optional[str]", code: int) -> None:
    """os._exit(code) when ``reason`` is a hung-probe diagnosis.

    The wedged probe thread holds jax's init lock, so a normal
    interpreter exit can block in jax atexit hooks on that lock —
    callers print everything they have to say first, then call this.
    No-op for None or any other failure reason.
    """
    if reason and reason.startswith(HUNG_PREFIX):
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(code)


def compile_cache_dir() -> str:
    """The persistent compile-cache directory a run will actually use —
    the single source for enable_compile_cache and `cli info`."""
    return os.environ.get("JAX_CACHE_DIR", "/tmp/dpsvm_jaxcache")


def enable_compile_cache() -> None:
    """Point jax at a persistent on-disk compile cache.

    Saves ~1.4 s of the per-process first-execution cost on the
    tunneled TPU (measured, benchmarks/profile_train_path.py; the
    remaining ~4.4 s is server-side program load that no client-side
    cache can touch). Shared by every benchmark entry point so the
    flag set stays in one place. Best-effort: the flag names vary
    across jax versions."""
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          compile_cache_dir())
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:
        print(f"note: persistent compile cache unavailable: {e}",
              file=sys.stderr, flush=True)

"""Run-trace JSONL format: writer, reader, schema validation.

One training run = one JSONL file (``SVMConfig.trace_out`` / the train
CLI's ``--trace-out``): a ``manifest`` record (what was asked for and on
what hardware), then ``chunk`` records at every host poll (the solver's
packed-stats transfer already carries n_iter/gap/SV-count/cache
counters, so tracing adds ZERO device->host transfers — see
solver/driver.py "Poll economics" and docs/OBSERVABILITY.md), optional
``event`` records (checkpoint / program swap / shrink), and a final
``summary`` record.

This module is deliberately dependency-free (no jax import): the
``report`` CLI subcommand and the schema self-check must run without
initializing any backend. The recorder that knows about solvers lives
in ``dpsvm_tpu.telemetry``.

The schema is versioned and validated by ``validate_trace`` — the same
function backs ``python -m dpsvm_tpu.telemetry --selfcheck`` (tier-1:
tests/test_telemetry.py), so a drifting producer fails loudly instead
of silently writing traces the report renderer can no longer read.
"""

from __future__ import annotations

import json
from typing import IO, List, Optional

TRACE_SCHEMA_VERSION = 1

# Required keys per record kind. Values may be null where noted in
# docs/OBSERVABILITY.md (e.g. env.device_kind on an uninitialized
# backend); presence is the contract.
MANIFEST_KEYS = ("schema", "version", "solver", "n", "d", "gamma",
                 "kernel", "mesh", "env", "config", "it0", "time")
CHUNK_KEYS = ("n_iter", "b_lo", "b_hi", "gap", "n_sv", "cache_hits",
              "cache_misses", "rounds", "t", "phases")
EVENT_KEYS = ("event", "n_iter", "t")
SUMMARY_KEYS = ("converged", "n_iter", "iters", "iters_per_sec", "b",
                "b_lo", "b_hi", "gap", "n_sv", "cache_hits",
                "cache_misses", "cache_hit_rate", "train_seconds",
                "phases", "t")
KINDS = ("manifest", "chunk", "event", "summary")


class TraceWriter:
    """Append-one-JSON-record-per-line writer, flushed per record so a
    killed run still leaves a parseable partial trace."""

    def __init__(self, path: str):
        self.path = path
        self._fh: Optional[IO[str]] = open(path, "w")

    def write(self, record: dict) -> None:
        if self._fh is None:
            return
        self._fh.write(json.dumps(record) + "\n")
        self._fh.flush()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "TraceWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_trace(path: str) -> List[dict]:
    """Parse a trace file into its records. Raises ValueError on a line
    that is not JSON (a truncated FINAL line — a run killed mid-write —
    is tolerated and dropped, matching the flush-per-record writer)."""
    records: List[dict] = []
    with open(path) as fh:
        lines = fh.read().splitlines()
    for i, raw in enumerate(lines):
        raw = raw.strip()
        if not raw:
            continue
        try:
            records.append(json.loads(raw))
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break                   # torn final write of a dead run
            raise ValueError(f"{path}:{i + 1}: not a JSON record")
    return records


def _missing(record: dict, keys) -> List[str]:
    return [k for k in keys if k not in record]


def validate_trace(records: List[dict]) -> List[str]:
    """Schema check; returns a list of problems (empty = valid).

    Contract (acceptance bar of docs/OBSERVABILITY.md): exactly one
    leading manifest at the current schema version; >= 0 chunk records
    with monotone non-decreasing n_iter and non-negative counters;
    at most one summary, and only as the final record. A ``rollback``
    event legitimately rewinds the run to its checkpoint's iteration
    (docs/ROBUSTNESS.md), so it resets the monotonicity baseline."""
    errors: List[str] = []
    if not records:
        return ["empty trace (no records)"]
    for i, r in enumerate(records):
        if not isinstance(r, dict) or r.get("kind") not in KINDS:
            errors.append(f"record {i}: unknown kind "
                          f"{r.get('kind') if isinstance(r, dict) else r!r}")
    head = records[0]
    if head.get("kind") != "manifest":
        errors.append("record 0: trace must start with a manifest")
    else:
        if head.get("schema") != TRACE_SCHEMA_VERSION:
            errors.append(f"manifest: schema {head.get('schema')!r} != "
                          f"supported {TRACE_SCHEMA_VERSION}")
        miss = _missing(head, MANIFEST_KEYS)
        if miss:
            errors.append(f"manifest: missing keys {miss}")
    if sum(r.get("kind") == "manifest" for r in records) > 1:
        errors.append("multiple manifest records")

    prev_iter = None
    for i, r in enumerate(records):
        kind = r.get("kind")
        if kind == "chunk":
            miss = _missing(r, CHUNK_KEYS)
            if miss:
                errors.append(f"record {i}: chunk missing keys {miss}")
                continue
            if prev_iter is not None and r["n_iter"] < prev_iter:
                errors.append(f"record {i}: n_iter {r['n_iter']} < "
                              f"previous {prev_iter} (not monotone)")
            prev_iter = r["n_iter"]
            for k in ("n_sv", "cache_hits", "cache_misses", "rounds"):
                if r[k] < 0:
                    errors.append(f"record {i}: {k} = {r[k]} < 0")
        elif kind == "event":
            miss = _missing(r, EVENT_KEYS)
            if miss:
                errors.append(f"record {i}: event missing keys {miss}")
            elif r.get("event") == "rollback":
                # The run restarted from a checkpoint at this iteration.
                prev_iter = r["n_iter"]
        elif kind == "summary":
            miss = _missing(r, SUMMARY_KEYS)
            if miss:
                errors.append(f"record {i}: summary missing keys {miss}")
            if i != len(records) - 1:
                errors.append(f"record {i}: summary must be the final "
                              "record")
    return errors

"""Back-compat shim: the trace schema moved to
``dpsvm_tpu.observability.schema`` when telemetry grew into a package
(PR 3). Existing importers (tests, external tooling reading PR 1
traces) keep working; new code should import the observability package
directly."""

from __future__ import annotations

from dpsvm_tpu.observability.schema import (CHUNK_KEYS,           # noqa: F401
                                            COMPILE_KEYS,
                                            EVENT_EXTRA_KEYS,
                                            EVENT_KEYS,
                                            KINDS, MANIFEST_KEYS,
                                            REWIND_EVENTS,
                                            SUMMARY_KEYS,
                                            SUPPORTED_SCHEMAS,
                                            TERMINAL_EVENTS,
                                            TRACE_SCHEMA_VERSION,
                                            TraceWriter, read_trace,
                                            validate_trace)

__all__ = [
    "TRACE_SCHEMA_VERSION", "SUPPORTED_SCHEMAS", "TraceWriter",
    "read_trace", "validate_trace", "MANIFEST_KEYS", "CHUNK_KEYS",
    "EVENT_KEYS", "COMPILE_KEYS", "SUMMARY_KEYS", "KINDS",
    "TERMINAL_EVENTS", "REWIND_EVENTS", "EVENT_EXTRA_KEYS",
]

"""Structured training-progress logging.

Replaces the reference's raw ``cout`` milestones (device banner
``svmTrain.cu:324-336``, shard table ``svmTrainMain.cpp:185-189``,
b/accuracy/time dump ``svmTrainMain.cpp:313-336``) with a standard-library
logger plus a compact per-chunk progress line: iteration count and the
optimality gap b_lo - b_hi (convergence is gap <= 2 epsilon).
"""

from __future__ import annotations

import logging
from typing import Optional

_logger = logging.getLogger("dpsvm_tpu")


def get_logger() -> logging.Logger:
    return _logger


def log_progress(config, n_iter: int, b_lo: float, b_hi: float,
                 final: bool = False,
                 prev_iter: Optional[int] = None) -> None:
    """final=True forces the line (convergence mid-chunk would otherwise
    skip the one report that matters).

    ``prev_iter`` is the iteration count at the CALLER's previous poll:
    when given, the line is emitted whenever the poll crossed an
    ``every`` boundary. The plain modulo cadence only fires when n_iter
    lands on an exact multiple, which is true for the 2-violator chunk
    loop but never for the decomposition/shrinking paths (their
    per-poll counts advance by block-round totals) — those callers pass
    prev_iter so --verbose shows progress there too."""
    if not config.verbose and not config.log_every:
        return
    every = config.log_every or config.chunk_iters
    if not final and n_iter < config.max_iter:
        if prev_iter is not None:
            if n_iter // every == prev_iter // every:
                return
        elif n_iter % every:
            return
    gap = b_lo - b_hi
    # Will the logging hierarchy actually EMIT this INFO record? Not just
    # "does a handler exist": a root handler at the default WARNING level
    # swallows it, and --verbose must never silently produce nothing.
    emitted = _logger.isEnabledFor(logging.INFO) and _logger.hasHandlers()
    _logger.info("iter=%d gap=%.6g (b_lo=%.6g b_hi=%.6g, converged at %.3g)",
                 n_iter, gap, b_lo, b_hi, 2 * config.epsilon)
    if config.verbose and not emitted:
        print(f"[dpsvm] iter={n_iter} gap={gap:.6g} "
              f"target={2 * config.epsilon:.3g}")

"""Mid-training checkpoint / resume, hardened for preemptible hosts.

The reference has NO mid-training persistence — its only artifact is the
final model file, and a killed `mpirun` job loses everything (SURVEY §5).
The complete solver state here is tiny — two n-vectors (alpha, f) plus a
handful of scalars — so checkpoints are a single .npz written every
``checkpoint_every`` iterations from the host polling loop, and a resumed
run continues the identical trajectory: the loop condition depends only on
(alpha, f, b_lo, b_hi, n_iter), all of which are saved.

Hardening (docs/ROBUSTNESS.md):

* **atomic write** — tmp + rename, so a crash mid-save never corrupts the
  previous checkpoint;
* **payload CRC32** — stored inside the .npz and verified on load, so a
  bit-flipped or truncated file raises ``CheckpointCorruptError`` instead
  of feeding garbage state back into the solver (or surfacing a raw
  ``BadZipFile``);
* **keep-N rotation** — ``save_checkpoint(..., keep=N)`` shifts the
  previous file to ``state.1.npz``, ``state.2.npz``, … before the rename,
  so one corrupted newest slot still leaves an intact older state for
  ``resume_state`` (solver/driver.py) to fall back to.

Hyperparameters are stored alongside the state and verified on load; a
checkpoint from a different problem shape or config raises
``CheckpointMismatchError`` (a ``ValueError``), not a silent wrong answer.

Shard-aware manifest (docs/DISTRIBUTED.md "Elastic training"): files
written since the elastic format (``CKPT_FORMAT_VERSION >= 2``) also
record the mesh they were saved under — shard count plus a per-shard
CRC32 over each shard's (alpha, f) region — so (a) a corrupted file can
name WHICH shard region is damaged, and (b) a resume on a different
mesh size is a recognized **re-shard**, not a mismatch: the state is the
global unpadded (alpha, f), so ``prepare_distributed_inputs`` re-pads it
for any device count and the trajectory continues bit-compatibly
(``reshard`` trace event). Pre-elastic files (no mesh fields) load
unchanged as single-shard records — pinned by
``tests/fixtures/ckpt_pre_elastic.npz``.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import zlib
from typing import Callable, List, Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig

# LIBSVM -t order; index = the integer stored in the checkpoint scalars.
# "precomputed" is -t 4 (the row data IS the (n, n) kernel matrix).
_KERNEL_T = ("linear", "poly", "rbf", "sigmoid", "precomputed")

#: On-disk format version stored in the ``mesh`` array. 3 = the
#: multi-host manifest (adds the saving group's host_count/host_id to
#: the mesh array — informational: a host-count difference alone is
#: NEVER a mismatch, resume re-shards exactly like a device-count
#: change); 2 = the elastic
#: shard-aware manifest (mesh shape + per-shard CRCs); files without the
#: array are version 1 (pre-elastic) and load as single-shard records.
CKPT_FORMAT_VERSION = 3


def shard_slices(n: int, shards: int) -> "List[tuple]":
    """The per-shard (lo, hi) row ranges of the save-time layout:
    contiguous equal shards of n padded up to a multiple of ``shards``,
    clipped to the true row count (the same contiguous protocol
    ``prepare_distributed_inputs`` pads to). The partition is part of
    the checkpoint FORMAT — per-shard CRCs are computed over exactly
    these slices, so a reader on any mesh can verify them."""
    shards = max(int(shards), 1)
    n_s = (n + shards - 1) // shards
    return [(min(k * n_s, n), min((k + 1) * n_s, n))
            for k in range(shards)]


def _shard_crcs(alpha: np.ndarray, f: np.ndarray,
                shards: int) -> np.ndarray:
    out = np.zeros((max(int(shards), 1),), np.uint32)
    for k, (lo, hi) in enumerate(shard_slices(len(alpha), shards)):
        crc = zlib.crc32(np.ascontiguousarray(alpha[lo:hi]).tobytes())
        out[k] = zlib.crc32(np.ascontiguousarray(f[lo:hi]).tobytes(),
                            crc)
    return out


class CheckpointError(Exception):
    """Base of every checkpoint failure this module raises."""


class CheckpointCorruptError(CheckpointError):
    """The file exists but its payload cannot be trusted: truncated or
    unreadable .npz, missing arrays, or a CRC32 mismatch."""


class CheckpointMismatchError(CheckpointError, ValueError):
    """An intact checkpoint for a DIFFERENT problem/config. Subclasses
    ValueError so pre-hardening callers' ``except ValueError`` (and the
    CLI's one-line error path) keep working."""


@dataclasses.dataclass
class SolverCheckpoint:
    alpha: np.ndarray      # (n,) f32
    f: np.ndarray          # (n,) f32
    n_iter: int
    b_lo: float
    b_hi: float
    c: float
    gamma: float
    epsilon: float
    n: int
    d: int
    weight_pos: float = 1.0
    weight_neg: float = 1.0
    kernel: str = "rbf"
    coef0: float = 0.0
    degree: int = 3
    # Elastic manifest (CKPT_FORMAT_VERSION 2): the mesh the state was
    # saved under + per-shard CRC32s over the shard_slices partition.
    # Pre-elastic files read as shards=1, shard_crcs=None.
    shards: int = 1
    shard_crcs: "Optional[np.ndarray]" = None
    # Multi-host manifest (CKPT_FORMAT_VERSION 3): the host group the
    # state was saved under. Informational — the state is the GLOBAL
    # (alpha, f) either way, so a different current group re-shards on
    # load exactly like a device-count change; never a mismatch.
    # Pre-v3 files read as host_count=1, host_id=0.
    host_count: int = 1
    host_id: int = 0

    def mesh_desc(self) -> str:
        """Human mesh summary for error messages and logs."""
        return (f"({self.shards},)-mesh / {self.shards} device"
                f"{'s' if self.shards != 1 else ''}")

    def validate_against(self, n: int, d: int, config: SVMConfig,
                         gamma: float,
                         shards: "Optional[int]" = None) -> None:
        """Raise ``CheckpointMismatchError`` on a permanent mismatch.

        ``shards`` is the CURRENT run's mesh size, used to make the
        error name expected-vs-found mesh shape and device count. A
        mesh-size difference ALONE is never a mismatch — the state is
        the global unpadded (alpha, f), so it re-shards onto any mesh
        (``needs_reshard`` / the driver's reshard path)."""
        here = (f"({shards},)-mesh / {shards} device"
                f"{'s' if shards != 1 else ''}"
                if shards is not None else "this run's mesh")
        if self.kernel == "precomputed" and self.n != self.d:
            # -t 4 trains on the square (n, n) kernel matrix; a
            # non-square record here is a damaged or hand-edited file.
            raise CheckpointMismatchError(
                f"checkpoint kernel='precomputed' must be square (n, n), "
                f"got ({self.n}, {self.d})")
        if (self.n, self.d) != (n, d):
            raise CheckpointMismatchError(
                f"checkpoint is for a ({self.n}, {self.d}) problem "
                f"saved on a {self.mesh_desc()}; "
                f"data is ({n}, {d}) on {here}")
        if self.kernel != config.kernel:
            raise CheckpointMismatchError(
                f"checkpoint kernel={self.kernel!r} != "
                f"configured kernel={config.kernel!r}")
        for name, mine, theirs in (
                ("c", self.c, config.c),
                ("gamma", self.gamma, gamma),
                ("coef0", self.coef0, config.coef0),
                ("degree", self.degree, config.degree),
                ("epsilon", self.epsilon, config.epsilon),
                ("weight_pos", self.weight_pos, config.weight_pos),
                ("weight_neg", self.weight_neg, config.weight_neg)):
            if abs(mine - theirs) > 1e-12 * max(1.0, abs(mine)):
                raise CheckpointMismatchError(
                    f"checkpoint {name}={mine} != configured {name}={theirs}")

    def needs_reshard(self, shards: int) -> bool:
        """True when the recorded mesh differs from the current one —
        the resume must re-slice (pad-aware) onto the new mesh. Not an
        error: the caller records a ``reshard`` trace event."""
        return int(self.shards) != int(shards)

    def verify_shard_crcs(self) -> "List[int]":
        """Indices of shard regions whose recorded CRC does not match
        the loaded payload (empty = all intact, or no manifest)."""
        if self.shard_crcs is None:
            return []
        actual = _shard_crcs(
            np.ascontiguousarray(self.alpha, np.float32),
            np.ascontiguousarray(self.f, np.float32), self.shards)
        want = np.asarray(self.shard_crcs, np.uint32)
        if len(actual) != len(want):
            return list(range(len(want)))
        return [k for k in range(len(want)) if actual[k] != want[k]]


def _payload(alpha: np.ndarray, f: np.ndarray,
             scalars: np.ndarray) -> tuple:
    return (np.ascontiguousarray(alpha, np.float32),
            np.ascontiguousarray(f, np.float32),
            np.ascontiguousarray(scalars, np.float64))


def _crc32(alpha: np.ndarray, f: np.ndarray, scalars: np.ndarray) -> int:
    crc = zlib.crc32(alpha.tobytes())
    crc = zlib.crc32(f.tobytes(), crc)
    return zlib.crc32(scalars.tobytes(), crc)


def rotation_path(path: str, k: int) -> str:
    """Slot k of a rotation set: ``state.npz`` -> ``state.1.npz``.
    k=0 is the path itself."""
    if k == 0:
        return path
    base, ext = os.path.splitext(path)
    return f"{base}.{k}{ext}" if ext else f"{path}.{k}"


def checkpoint_candidates(path: str, limit: int = 100) -> List[str]:
    """Existing rotation slots, newest first: [path, path.1, ...]. The
    primary path is listed even when absent (so the caller's error names
    what was asked for); rotated slots only when present."""
    out = [path]
    for k in range(1, limit):
        p = rotation_path(path, k)
        if not os.path.exists(p):
            break
        out.append(p)
    return out


def _rotate(path: str, keep: int) -> None:
    """Shift path -> path.1 -> ... keeping ``keep`` files total (the
    about-to-be-written newest counts as one)."""
    if keep <= 1 or not os.path.exists(path):
        return
    for k in range(keep - 1, 0, -1):
        src = rotation_path(path, k - 1)
        if os.path.exists(src):
            os.replace(src, rotation_path(path, k))


def save_checkpoint(path: str, ckpt: SolverCheckpoint,
                    keep: int = 1) -> None:
    """Atomic write (tmp + rename) with an embedded payload CRC32;
    ``keep > 1`` rotates the previous file(s) to ``.1``/``.2``/… slots
    first, so the newest write can never destroy the only intact state.

    Multi-host: every host builds the snapshot (the read-back is a
    collective all hosts must enter symmetrically) but only host 0
    touches the shared path — N hosts racing the same tmp+rename would
    interleave rotations. sys.modules, not an import: a process that
    never loaded parallel.multihost cannot be a non-zero host, and
    importing it here would cycle through dpsvm_tpu.parallel."""
    import sys
    mh = sys.modules.get("dpsvm_tpu.parallel.multihost")
    if mh is not None and mh.host_id() != 0:
        return
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    alpha, f, scalars = _payload(
        ckpt.alpha, ckpt.f,
        np.asarray(
            [ckpt.n_iter, ckpt.b_lo, ckpt.b_hi, ckpt.c, ckpt.gamma,
             ckpt.epsilon, ckpt.n, ckpt.d, ckpt.weight_pos,
             ckpt.weight_neg,
             # kernel family encoded as the LIBSVM -t integer
             _KERNEL_T.index(ckpt.kernel), ckpt.coef0,
             ckpt.degree], np.float64))
    # Elastic manifest: the save-time mesh + per-shard CRCs over the
    # shard_slices partition (docs/DISTRIBUTED.md "Elastic training").
    shards = max(int(getattr(ckpt, "shards", 1) or 1), 1)
    mesh = np.asarray(
        [CKPT_FORMAT_VERSION, shards,
         max(int(getattr(ckpt, "host_count", 1) or 1), 1),
         max(int(getattr(ckpt, "host_id", 0) or 0), 0)], np.int64)
    shard_crc = _shard_crcs(alpha, f, shards)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez(fh, alpha=alpha, f=f, scalars=scalars,
                     crc32=np.asarray([_crc32(alpha, f, scalars)],
                                      np.uint32),
                     mesh=mesh, shard_crc=shard_crc)
        # Deterministic fault injection (resilience/faultinject.py) fires
        # HERE — after the tmp write, before the rename — so an injected
        # "write failed" exercises both the tmp cleanup and the
        # old-file-stays-intact guarantee.
        from dpsvm_tpu.resilience import faultinject
        faultinject.on_checkpoint_write(path)
        _rotate(path, keep)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _bad_shards(alpha, f, mesh, shard_crc) -> "Optional[List[int]]":
    """Shard regions whose payload bytes fail the recorded per-shard
    CRC. None when the file predates the shard manifest (nothing to
    compare); an empty list when every region verifies — then any
    whole-payload mismatch lives in the scalars/metadata instead."""
    shards = int(mesh[1]) if mesh is not None and len(mesh) > 1 else 1
    if shard_crc is None or len(shard_crc) != shards:
        return None
    actual = _shard_crcs(np.asarray(alpha, np.float32),
                         np.asarray(f, np.float32), shards)
    want = np.asarray(shard_crc, np.uint32)
    return [k for k in range(shards) if actual[k] != want[k]]


def _integrity_detail(alpha, f, s, mesh, shard_crc) -> str:
    """The '; damaged shard region(s) …' suffix for corruption errors
    (empty when the file has no shard manifest)."""
    bad = _bad_shards(alpha, f, mesh, shard_crc)
    if bad is None:
        return ""
    shards = int(mesh[1]) if mesh is not None and len(mesh) > 1 else 1
    return (f"; damaged shard region(s) {bad or ['scalars']} "
            f"of {shards}")


def _salvage_npz(path: str) -> dict:
    """Read an .npz's member arrays BYPASSING the zip per-member CRC.

    A bit-flipped payload normally dies inside ``np.load`` as a
    ``BadZipFile`` ("Bad CRC-32 for file 'alpha.npy'"), which masks
    the much more useful per-shard diagnosis: WHICH shard region of
    the solver state is damaged. This reads each stored member's raw
    bytes straight from the local file headers (npz members are
    STORED; deflated members are inflated without the CRC gate) so the
    caller's own payload CRCs can produce the named-shard error. Only
    used on the diagnosis path — an intact file never comes through
    here."""
    import io
    import struct
    import zipfile

    out: dict = {}
    with zipfile.ZipFile(path) as zf, open(path, "rb") as fh:
        for info in zf.infolist():
            fh.seek(info.header_offset)
            hdr = fh.read(30)
            if len(hdr) < 30 or hdr[:4] != b"PK\x03\x04":
                raise ValueError(f"bad local header for {info.filename}")
            fn_len, extra_len = struct.unpack("<HH", hdr[26:30])
            fh.seek(info.header_offset + 30 + fn_len + extra_len)
            data = fh.read(info.compress_size)
            if info.compress_type == zipfile.ZIP_DEFLATED:
                data = zlib.decompressobj(-15).decompress(data)
            name = (info.filename[:-4]
                    if info.filename.endswith(".npy") else info.filename)
            out[name] = np.lib.format.read_array(io.BytesIO(data),
                                                 allow_pickle=False)
    return out


def load_checkpoint(path: str) -> SolverCheckpoint:
    """Read + integrity-check one checkpoint file.

    Raises ``FileNotFoundError`` for a missing path and
    ``CheckpointCorruptError`` for anything unreadable: truncated or
    empty file, bad zip structure, missing arrays, or CRC mismatch.
    Files written before the CRC field existed load without the check;
    files with the elastic shard manifest additionally name WHICH shard
    region(s) fail their per-shard CRC on a payload mismatch.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    try:
        with np.load(path) as z:
            alpha = np.asarray(z["alpha"], np.float32)
            f = np.asarray(z["f"], np.float32)
            s = np.asarray(z["scalars"], np.float64)
            stored_crc = (int(np.asarray(z["crc32"]).ravel()[0])
                          if "crc32" in z.files else None)
            mesh = (np.asarray(z["mesh"], np.int64)
                    if "mesh" in z.files else None)
            shard_crc = (np.asarray(z["shard_crc"], np.uint32)
                         if "shard_crc" in z.files else None)
    except FileNotFoundError:
        raise
    except Exception as e:     # BadZipFile, EOFError, KeyError, ValueError…
        # A flipped payload bit dies as the zip's OWN member CRC before
        # ours can run, masking the useful diagnosis (WHICH shard
        # region is damaged). Salvage the raw member bytes purely to
        # NAME the damage — a file the zip layer rejects is corrupt
        # regardless of what the salvage finds.
        where = ""
        try:
            z = _salvage_npz(path)
            where = _integrity_detail(
                np.asarray(z["alpha"], np.float32),
                np.asarray(z["f"], np.float32),
                np.asarray(z["scalars"], np.float64),
                z.get("mesh"), z.get("shard_crc"))
        except Exception:
            pass
        raise CheckpointCorruptError(
            f"unreadable checkpoint {path}: "
            f"{type(e).__name__}: {e}{where}") from e
    shards = int(mesh[1]) if mesh is not None and len(mesh) > 1 else 1
    # v3 host-group fields; v2 (and pre-elastic) files read as the
    # single-host defaults — back-compat pinned by tests/fixtures/
    # ckpt_pre_elastic.npz and ckpt_v2.npz.
    host_count = int(mesh[2]) if mesh is not None and len(mesh) > 2 else 1
    host_id = int(mesh[3]) if mesh is not None and len(mesh) > 3 else 0
    if stored_crc is not None:
        actual = _crc32(*_payload(alpha, f, s))
        if actual != stored_crc:
            where = _integrity_detail(alpha, f, s, mesh, shard_crc)
            raise CheckpointCorruptError(
                f"checkpoint {path} failed its integrity check "
                f"(crc32 {actual:#010x} != stored {stored_crc:#010x})"
                + where)
        # Whole payload verified: a per-shard mismatch now means the
        # shard-CRC MANIFEST itself is damaged — the slot still cannot
        # be trusted (the doctor and the re-shard path both read it).
        if _bad_shards(alpha, f, mesh, shard_crc):
            raise CheckpointCorruptError(
                f"checkpoint {path} has a damaged shard-CRC manifest "
                f"(payload verifies, shard records do not)")
    if s.ndim != 1 or len(s) < 8 or alpha.ndim != 1 or f.ndim != 1:
        raise CheckpointCorruptError(
            f"checkpoint {path} has a malformed payload "
            f"(scalars shape {s.shape}, alpha shape {alpha.shape})")
    return SolverCheckpoint(
        alpha=alpha, f=f,
        n_iter=int(s[0]), b_lo=float(s[1]), b_hi=float(s[2]),
        c=float(s[3]), gamma=float(s[4]), epsilon=float(s[5]),
        n=int(s[6]), d=int(s[7]),
        # files from before class weights existed carry 8 scalars;
        # from before kernel families, 10
        weight_pos=float(s[8]) if len(s) > 8 else 1.0,
        weight_neg=float(s[9]) if len(s) > 9 else 1.0,
        kernel=_KERNEL_T[int(s[10])] if len(s) > 10 else "rbf",
        coef0=float(s[11]) if len(s) > 11 else 0.0,
        degree=int(s[12]) if len(s) > 12 else 3,
        shards=shards,
        shard_crcs=shard_crc,
        host_count=host_count,
        host_id=host_id,
    )


def newest_intact_checkpoint(path: str) -> "tuple[Optional[str], List[str]]":
    """(newest rotation slot that loads cleanly, slots skipped as
    corrupt/missing). Validation against a config is the caller's job —
    intact-but-mismatched is a permanent error, not a fallback case."""
    skipped: List[str] = []
    for p in checkpoint_candidates(path):
        try:
            load_checkpoint(p)
            return p, skipped
        except (CheckpointError, FileNotFoundError, OSError):
            skipped.append(p)
    return None, skipped


def maybe_checkpoint(config: SVMConfig, last_saved_iter: int, n_iter: int,
                     make: Callable[[], SolverCheckpoint]) -> int:
    """Host-loop helper: save when an every-N boundary was crossed.
    Returns the new last_saved_iter. A FAILED periodic save is degraded
    to a warning — training state is intact and the rotation slots still
    hold the previous good file, so killing the run over it would be
    strictly worse (the failure is also injectable: faultinject)."""
    every = getattr(config, "checkpoint_every", 0)
    path: Optional[str] = getattr(config, "checkpoint_path", None)
    if not every or not path:
        return last_saved_iter
    if n_iter // every > last_saved_iter // every:
        try:
            save_checkpoint(path, make(),
                            keep=getattr(config, "checkpoint_keep", 1))
        except (OSError, CheckpointError) as e:
            import sys
            print(f"WARNING: checkpoint save failed at iter {n_iter} "
                  f"({e}); training continues, previous checkpoint kept",
                  file=sys.stderr, flush=True)
            return last_saved_iter
        return n_iter
    return last_saved_iter

"""Mid-training checkpoint / resume.

The reference has NO mid-training persistence — its only artifact is the
final model file, and a killed `mpirun` job loses everything (SURVEY §5).
The complete solver state here is tiny — two n-vectors (alpha, f) plus
three scalars — so checkpoints are a single .npz written every
``checkpoint_every`` iterations from the host polling loop, and a resumed
run continues the identical trajectory: the loop condition depends only on
(alpha, f, b_lo, b_hi, n_iter), all of which are saved.

Hyperparameters are stored alongside the state and verified on load; a
checkpoint from a different problem shape or config is an error, not a
silent wrong answer.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig

# LIBSVM -t order; index = the integer stored in the checkpoint scalars.
_KERNEL_T = ("linear", "poly", "rbf", "sigmoid")


@dataclasses.dataclass
class SolverCheckpoint:
    alpha: np.ndarray      # (n,) f32
    f: np.ndarray          # (n,) f32
    n_iter: int
    b_lo: float
    b_hi: float
    c: float
    gamma: float
    epsilon: float
    n: int
    d: int
    weight_pos: float = 1.0
    weight_neg: float = 1.0
    kernel: str = "rbf"
    coef0: float = 0.0
    degree: int = 3

    def validate_against(self, n: int, d: int, config: SVMConfig,
                         gamma: float) -> None:
        if (self.n, self.d) != (n, d):
            raise ValueError(
                f"checkpoint is for a ({self.n}, {self.d}) problem, "
                f"data is ({n}, {d})")
        if self.kernel != config.kernel:
            raise ValueError(f"checkpoint kernel={self.kernel!r} != "
                             f"configured kernel={config.kernel!r}")
        for name, mine, theirs in (
                ("c", self.c, config.c),
                ("gamma", self.gamma, gamma),
                ("coef0", self.coef0, config.coef0),
                ("degree", self.degree, config.degree),
                ("epsilon", self.epsilon, config.epsilon),
                ("weight_pos", self.weight_pos, config.weight_pos),
                ("weight_neg", self.weight_neg, config.weight_neg)):
            if abs(mine - theirs) > 1e-12 * max(1.0, abs(mine)):
                raise ValueError(
                    f"checkpoint {name}={mine} != configured {name}={theirs}")


def save_checkpoint(path: str, ckpt: SolverCheckpoint) -> None:
    """Atomic write (tmp + rename): a crash mid-save never corrupts the
    previous checkpoint."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez(
                fh,
                alpha=np.asarray(ckpt.alpha, np.float32),
                f=np.asarray(ckpt.f, np.float32),
                scalars=np.asarray(
                    [ckpt.n_iter, ckpt.b_lo, ckpt.b_hi, ckpt.c, ckpt.gamma,
                     ckpt.epsilon, ckpt.n, ckpt.d, ckpt.weight_pos,
                     ckpt.weight_neg,
                     # kernel family encoded as the LIBSVM -t integer
                     _KERNEL_T.index(ckpt.kernel), ckpt.coef0,
                     ckpt.degree], np.float64),
            )
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(path: str) -> SolverCheckpoint:
    with np.load(path) as z:
        s = z["scalars"]
        return SolverCheckpoint(
            alpha=z["alpha"], f=z["f"],
            n_iter=int(s[0]), b_lo=float(s[1]), b_hi=float(s[2]),
            c=float(s[3]), gamma=float(s[4]), epsilon=float(s[5]),
            n=int(s[6]), d=int(s[7]),
            # files from before class weights existed carry 8 scalars;
            # from before kernel families, 10
            weight_pos=float(s[8]) if len(s) > 8 else 1.0,
            weight_neg=float(s[9]) if len(s) > 9 else 1.0,
            kernel=_KERNEL_T[int(s[10])] if len(s) > 10 else "rbf",
            coef0=float(s[11]) if len(s) > 11 else 0.0,
            degree=int(s[12]) if len(s) > 12 else 3,
        )


def maybe_checkpoint(config: SVMConfig, last_saved_iter: int, n_iter: int,
                     make: "callable") -> int:
    """Host-loop helper: save when an every-N boundary was crossed.
    Returns the new last_saved_iter."""
    every = getattr(config, "checkpoint_every", 0)
    path: Optional[str] = getattr(config, "checkpoint_path", None)
    if not every or not path:
        return last_saved_iter
    if n_iter // every > last_saved_iter // every:
        save_checkpoint(path, make())
        return n_iter
    return last_saved_iter

"""Mid-training checkpoint / resume, hardened for preemptible hosts.

The reference has NO mid-training persistence — its only artifact is the
final model file, and a killed `mpirun` job loses everything (SURVEY §5).
The complete solver state here is tiny — two n-vectors (alpha, f) plus a
handful of scalars — so checkpoints are a single .npz written every
``checkpoint_every`` iterations from the host polling loop, and a resumed
run continues the identical trajectory: the loop condition depends only on
(alpha, f, b_lo, b_hi, n_iter), all of which are saved.

Hardening (docs/ROBUSTNESS.md):

* **atomic write** — tmp + rename, so a crash mid-save never corrupts the
  previous checkpoint;
* **payload CRC32** — stored inside the .npz and verified on load, so a
  bit-flipped or truncated file raises ``CheckpointCorruptError`` instead
  of feeding garbage state back into the solver (or surfacing a raw
  ``BadZipFile``);
* **keep-N rotation** — ``save_checkpoint(..., keep=N)`` shifts the
  previous file to ``state.1.npz``, ``state.2.npz``, … before the rename,
  so one corrupted newest slot still leaves an intact older state for
  ``resume_state`` (solver/driver.py) to fall back to.

Hyperparameters are stored alongside the state and verified on load; a
checkpoint from a different problem shape or config raises
``CheckpointMismatchError`` (a ``ValueError``), not a silent wrong answer.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import zlib
from typing import Callable, List, Optional

import numpy as np

from dpsvm_tpu.config import SVMConfig

# LIBSVM -t order; index = the integer stored in the checkpoint scalars.
# "precomputed" is -t 4 (the row data IS the (n, n) kernel matrix).
_KERNEL_T = ("linear", "poly", "rbf", "sigmoid", "precomputed")


class CheckpointError(Exception):
    """Base of every checkpoint failure this module raises."""


class CheckpointCorruptError(CheckpointError):
    """The file exists but its payload cannot be trusted: truncated or
    unreadable .npz, missing arrays, or a CRC32 mismatch."""


class CheckpointMismatchError(CheckpointError, ValueError):
    """An intact checkpoint for a DIFFERENT problem/config. Subclasses
    ValueError so pre-hardening callers' ``except ValueError`` (and the
    CLI's one-line error path) keep working."""


@dataclasses.dataclass
class SolverCheckpoint:
    alpha: np.ndarray      # (n,) f32
    f: np.ndarray          # (n,) f32
    n_iter: int
    b_lo: float
    b_hi: float
    c: float
    gamma: float
    epsilon: float
    n: int
    d: int
    weight_pos: float = 1.0
    weight_neg: float = 1.0
    kernel: str = "rbf"
    coef0: float = 0.0
    degree: int = 3

    def validate_against(self, n: int, d: int, config: SVMConfig,
                         gamma: float) -> None:
        if self.kernel == "precomputed" and self.n != self.d:
            # -t 4 trains on the square (n, n) kernel matrix; a
            # non-square record here is a damaged or hand-edited file.
            raise CheckpointMismatchError(
                f"checkpoint kernel='precomputed' must be square (n, n), "
                f"got ({self.n}, {self.d})")
        if (self.n, self.d) != (n, d):
            raise CheckpointMismatchError(
                f"checkpoint is for a ({self.n}, {self.d}) problem, "
                f"data is ({n}, {d})")
        if self.kernel != config.kernel:
            raise CheckpointMismatchError(
                f"checkpoint kernel={self.kernel!r} != "
                f"configured kernel={config.kernel!r}")
        for name, mine, theirs in (
                ("c", self.c, config.c),
                ("gamma", self.gamma, gamma),
                ("coef0", self.coef0, config.coef0),
                ("degree", self.degree, config.degree),
                ("epsilon", self.epsilon, config.epsilon),
                ("weight_pos", self.weight_pos, config.weight_pos),
                ("weight_neg", self.weight_neg, config.weight_neg)):
            if abs(mine - theirs) > 1e-12 * max(1.0, abs(mine)):
                raise CheckpointMismatchError(
                    f"checkpoint {name}={mine} != configured {name}={theirs}")


def _payload(alpha: np.ndarray, f: np.ndarray,
             scalars: np.ndarray) -> tuple:
    return (np.ascontiguousarray(alpha, np.float32),
            np.ascontiguousarray(f, np.float32),
            np.ascontiguousarray(scalars, np.float64))


def _crc32(alpha: np.ndarray, f: np.ndarray, scalars: np.ndarray) -> int:
    crc = zlib.crc32(alpha.tobytes())
    crc = zlib.crc32(f.tobytes(), crc)
    return zlib.crc32(scalars.tobytes(), crc)


def rotation_path(path: str, k: int) -> str:
    """Slot k of a rotation set: ``state.npz`` -> ``state.1.npz``.
    k=0 is the path itself."""
    if k == 0:
        return path
    base, ext = os.path.splitext(path)
    return f"{base}.{k}{ext}" if ext else f"{path}.{k}"


def checkpoint_candidates(path: str, limit: int = 100) -> List[str]:
    """Existing rotation slots, newest first: [path, path.1, ...]. The
    primary path is listed even when absent (so the caller's error names
    what was asked for); rotated slots only when present."""
    out = [path]
    for k in range(1, limit):
        p = rotation_path(path, k)
        if not os.path.exists(p):
            break
        out.append(p)
    return out


def _rotate(path: str, keep: int) -> None:
    """Shift path -> path.1 -> ... keeping ``keep`` files total (the
    about-to-be-written newest counts as one)."""
    if keep <= 1 or not os.path.exists(path):
        return
    for k in range(keep - 1, 0, -1):
        src = rotation_path(path, k - 1)
        if os.path.exists(src):
            os.replace(src, rotation_path(path, k))


def save_checkpoint(path: str, ckpt: SolverCheckpoint,
                    keep: int = 1) -> None:
    """Atomic write (tmp + rename) with an embedded payload CRC32;
    ``keep > 1`` rotates the previous file(s) to ``.1``/``.2``/… slots
    first, so the newest write can never destroy the only intact state."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    alpha, f, scalars = _payload(
        ckpt.alpha, ckpt.f,
        np.asarray(
            [ckpt.n_iter, ckpt.b_lo, ckpt.b_hi, ckpt.c, ckpt.gamma,
             ckpt.epsilon, ckpt.n, ckpt.d, ckpt.weight_pos,
             ckpt.weight_neg,
             # kernel family encoded as the LIBSVM -t integer
             _KERNEL_T.index(ckpt.kernel), ckpt.coef0,
             ckpt.degree], np.float64))
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez(fh, alpha=alpha, f=f, scalars=scalars,
                     crc32=np.asarray([_crc32(alpha, f, scalars)],
                                      np.uint32))
        # Deterministic fault injection (resilience/faultinject.py) fires
        # HERE — after the tmp write, before the rename — so an injected
        # "write failed" exercises both the tmp cleanup and the
        # old-file-stays-intact guarantee.
        from dpsvm_tpu.resilience import faultinject
        faultinject.on_checkpoint_write(path)
        _rotate(path, keep)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(path: str) -> SolverCheckpoint:
    """Read + integrity-check one checkpoint file.

    Raises ``FileNotFoundError`` for a missing path and
    ``CheckpointCorruptError`` for anything unreadable: truncated or
    empty file, bad zip structure, missing arrays, or CRC mismatch.
    Files written before the CRC field existed load without the check.
    """
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    try:
        with np.load(path) as z:
            alpha = np.asarray(z["alpha"], np.float32)
            f = np.asarray(z["f"], np.float32)
            s = np.asarray(z["scalars"], np.float64)
            stored_crc = (int(np.asarray(z["crc32"]).ravel()[0])
                          if "crc32" in z.files else None)
    except FileNotFoundError:
        raise
    except Exception as e:     # BadZipFile, EOFError, KeyError, ValueError…
        raise CheckpointCorruptError(
            f"unreadable checkpoint {path}: {type(e).__name__}: {e}") from e
    if stored_crc is not None:
        actual = _crc32(*_payload(alpha, f, s))
        if actual != stored_crc:
            raise CheckpointCorruptError(
                f"checkpoint {path} failed its integrity check "
                f"(crc32 {actual:#010x} != stored {stored_crc:#010x})")
    if s.ndim != 1 or len(s) < 8 or alpha.ndim != 1 or f.ndim != 1:
        raise CheckpointCorruptError(
            f"checkpoint {path} has a malformed payload "
            f"(scalars shape {s.shape}, alpha shape {alpha.shape})")
    return SolverCheckpoint(
        alpha=alpha, f=f,
        n_iter=int(s[0]), b_lo=float(s[1]), b_hi=float(s[2]),
        c=float(s[3]), gamma=float(s[4]), epsilon=float(s[5]),
        n=int(s[6]), d=int(s[7]),
        # files from before class weights existed carry 8 scalars;
        # from before kernel families, 10
        weight_pos=float(s[8]) if len(s) > 8 else 1.0,
        weight_neg=float(s[9]) if len(s) > 9 else 1.0,
        kernel=_KERNEL_T[int(s[10])] if len(s) > 10 else "rbf",
        coef0=float(s[11]) if len(s) > 11 else 0.0,
        degree=int(s[12]) if len(s) > 12 else 3,
    )


def newest_intact_checkpoint(path: str) -> "tuple[Optional[str], List[str]]":
    """(newest rotation slot that loads cleanly, slots skipped as
    corrupt/missing). Validation against a config is the caller's job —
    intact-but-mismatched is a permanent error, not a fallback case."""
    skipped: List[str] = []
    for p in checkpoint_candidates(path):
        try:
            load_checkpoint(p)
            return p, skipped
        except (CheckpointError, FileNotFoundError, OSError):
            skipped.append(p)
    return None, skipped


def maybe_checkpoint(config: SVMConfig, last_saved_iter: int, n_iter: int,
                     make: Callable[[], SolverCheckpoint]) -> int:
    """Host-loop helper: save when an every-N boundary was crossed.
    Returns the new last_saved_iter. A FAILED periodic save is degraded
    to a warning — training state is intact and the rotation slots still
    hold the previous good file, so killing the run over it would be
    strictly worse (the failure is also injectable: faultinject)."""
    every = getattr(config, "checkpoint_every", 0)
    path: Optional[str] = getattr(config, "checkpoint_path", None)
    if not every or not path:
        return last_saved_iter
    if n_iter // every > last_saved_iter // every:
        try:
            save_checkpoint(path, make(),
                            keep=getattr(config, "checkpoint_keep", 1))
        except (OSError, CheckpointError) as e:
            import sys
            print(f"WARNING: checkpoint save failed at iter {n_iter} "
                  f"({e}); training continues, previous checkpoint kept",
                  file=sys.stderr, flush=True)
            return last_saved_iter
        return n_iter
    return last_saved_iter

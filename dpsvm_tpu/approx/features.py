"""Kernel-approximating feature maps: RFF and Nystrom.

The exact solvers pay O(n^2) kernel work per training run — the dual
problem touches K one (or q) rows at a time, which caps the "millions
of rows" north star at tens of thousands. The fast-large-scale-SVM
recipe (arXiv:2207.01016; GPU primal learning, arXiv:2008.03433) trades
the dual kernel solve for an EXPLICIT finite-dimensional feature map
phi with phi(x).phi(z) ~= K(x, z), then solves the linearized problem
in the primal (approx/primal.py) — one O(n*D) dense matmul pipeline,
exactly the shape the MXU is built for.

Two maps, both deterministic in (seed, shape) so a persisted model
rebuilds the identical map at serving time:

* **RFF** (Rahimi-Recht random Fourier features, RBF only): the RBF
  kernel's spectral measure is N(0, 2*gamma*I), so with W ~ that law,
  phi(x) = sqrt(2/D) [cos(xW), sin(xW)] gives E[phi(x).phi(z)] =
  exp(-gamma ||x-z||^2). The cos/sin pairing (rather than random
  phases) halves the estimator variance and makes ||phi(x)||^2 == 1
  exactly — which the primal solver exploits for its step size. The
  map is (d, D/2) float32 of pure seed-derived noise: nothing about
  the data is stored.
* **Nystrom** (any vector kernel): m <= D landmark rows subsampled
  from the training set, K_mm eigendecomposed, phi(x) =
  K(x, landmarks) @ U diag(lambda^-1/2) (rank-truncated at numerical
  zero, so the effective dim can come out below approx_dim). Data-
  adaptive — tighter than RFF at equal D on clustered data — at the
  cost of persisting the (m, d) landmarks with the model.

Featurization is CHUNKED: X is streamed through one compiled
fixed-shape block transform (pad-to-chunk, the decision_function
scheme), so X never needs to sit in memory alongside its full (n, D)
feature matrix during the transform, and the block program compiles
exactly once. With ``shards > 1`` the resulting feature matrix is laid
out row-sharded over the existing 1-D data mesh
(``parallel/mesh.make_data_mesh``), which makes every downstream
primal matmul a sharded MXU pass with XLA-inserted reductions.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional

import jax
import numpy as np

from dpsvm_tpu.ops.kernels import KernelSpec

# Rank cutoff for the Nystrom eigenspectrum, relative to the largest
# eigenvalue: below this a direction is numerical noise and dividing by
# sqrt(lambda) would amplify it into the features.
_NYSTROM_RCOND = 1e-6


@dataclasses.dataclass(frozen=True)
class FeatureMap:
    """One built feature map — everything needed to featurize new rows
    (and to persist / rebuild the map bit-identically)."""

    kind: str                       # "rff" | "nystrom"
    d: int                          # input width
    dim: int                        # output feature dim (post-truncation
                                    # for nystrom; always the built value)
    seed: int
    gamma: float
    kernel: str = "rbf"             # base kernel family (nystrom may use
                                    # any vector kernel)
    coef0: float = 0.0
    degree: int = 3
    # rff: (d, dim/2) frequency matrix, derived from seed (re-derivable,
    # but kept so featurize never re-runs the RNG). nystrom: None.
    omega: Optional[np.ndarray] = None
    # nystrom only: (m, d) landmark rows and the (m, dim) whitening
    # projection U diag(lambda^-1/2).
    landmarks: Optional[np.ndarray] = None
    proj: Optional[np.ndarray] = None

    @property
    def kernel_spec(self) -> KernelSpec:
        return KernelSpec(kind=self.kernel, gamma=float(self.gamma),
                          coef0=float(self.coef0), degree=int(self.degree))


def rff_omega(d: int, dim: int, gamma: float, seed: int) -> np.ndarray:
    """The (d, dim/2) RFF frequency matrix — N(0, 2*gamma) i.i.d.,
    deterministic in (d, dim, gamma, seed)."""
    rng = np.random.default_rng(seed)
    return (rng.standard_normal((d, dim // 2))
            * math.sqrt(2.0 * gamma)).astype(np.float32)


def build_feature_map(kind: str, x: np.ndarray, dim: int, seed: int,
                      spec: KernelSpec) -> FeatureMap:
    """Build a map for training data ``x`` (rff only reads its width)."""
    x = np.asarray(x, np.float32)
    n, d = x.shape
    if kind == "rff":
        if spec.kind != "rbf":
            raise ValueError("rff approximates the RBF kernel only")
        return FeatureMap(kind="rff", d=d, dim=int(dim), seed=int(seed),
                          gamma=float(spec.gamma),
                          omega=rff_omega(d, int(dim), float(spec.gamma),
                                          int(seed)))
    if kind != "nystrom":
        raise ValueError(f"unknown feature map kind {kind!r}")
    m = min(int(dim), n)
    rng = np.random.default_rng(seed)
    idx = np.sort(rng.choice(n, size=m, replace=False))
    landmarks = np.ascontiguousarray(x[idx])
    kmm = _host_kernel(landmarks, landmarks, spec).astype(np.float64)
    # Symmetrize against float noise before eigh; truncate the spectrum
    # at numerical zero so 1/sqrt(lambda) never amplifies noise.
    lam, u = np.linalg.eigh((kmm + kmm.T) / 2.0)
    keep = lam > max(lam[-1], 0.0) * _NYSTROM_RCOND
    if not keep.any():
        raise ValueError("nystrom landmark kernel is numerically zero — "
                         "check gamma / feature scaling")
    lam, u = lam[keep], u[:, keep]
    proj = (u / np.sqrt(lam)[None, :]).astype(np.float32)
    return FeatureMap(kind="nystrom", d=d, dim=int(proj.shape[1]),
                      seed=int(seed), gamma=float(spec.gamma),
                      kernel=spec.kind, coef0=float(spec.coef0),
                      degree=int(spec.degree), landmarks=landmarks,
                      proj=proj)


def _host_kernel(a: np.ndarray, b: np.ndarray,
                 spec: KernelSpec) -> np.ndarray:
    """Small dense K(a, b) on the host (landmark-sized only)."""
    dots = a.astype(np.float64) @ b.astype(np.float64).T
    if spec.kind == "linear":
        return dots
    if spec.kind == "poly":
        return (spec.gamma * dots + spec.coef0) ** spec.degree
    if spec.kind == "sigmoid":
        return np.tanh(spec.gamma * dots + spec.coef0)
    a2 = np.sum(a.astype(np.float64) ** 2, axis=1)
    b2 = np.sum(b.astype(np.float64) ** 2, axis=1)
    return np.exp(-spec.gamma * np.maximum(
        a2[:, None] - 2.0 * dots + b2[None, :], 0.0))


@functools.partial(jax.jit, static_argnames=("kind", "degree",
                                             "precision_name"))
def _featurize_block_jit(block, omega_or_landmarks, proj, gamma, coef0,
                         kind: str, degree: int,
                         precision_name: str = "HIGHEST"):
    """One fixed-shape featurization block. rff: proj is unused (pass a
    dummy); nystrom: omega_or_landmarks holds the landmark rows.

    ``precision_name`` selects the MXU mode of the featurization GEMMs
    (the jax.lax.Precision name, like the solvers' matmul_precision):
    "HIGHEST" = exact f32, the default and the reference-parity path;
    "DEFAULT" = bf16 multiplies with f32 MXU accumulation — the
    transcendental epilogue (cos/sin, the kernel epilogues) and the
    feature values themselves stay float32 either way."""
    import jax.numpy as jnp

    precision = getattr(jax.lax.Precision, precision_name)
    from dpsvm_tpu.ops.kernels import kernel_rows, row_norms_sq

    if kind == "rff":
        z = jnp.matmul(block, omega_or_landmarks,
                       precision=precision)                # (m, D/2)
        scale = jnp.float32(math.sqrt(2.0 / (2 * z.shape[1])))
        return scale * jnp.concatenate([jnp.cos(z), jnp.sin(z)], axis=1)
    spec = KernelSpec(kind=kind, gamma=gamma, coef0=coef0, degree=degree)
    b2 = row_norms_sq(block)
    l2 = row_norms_sq(omega_or_landmarks)
    k = kernel_rows(block, b2, omega_or_landmarks, l2, spec,
                    precision=precision)                   # (m, L)
    return jnp.matmul(k, proj, precision=precision)


def _block_args(fmap: FeatureMap):
    import jax.numpy as jnp
    if fmap.kind == "rff":
        return (jnp.asarray(fmap.omega), jnp.zeros((1,), jnp.float32),
                jnp.float32(fmap.gamma), jnp.float32(fmap.coef0))
    return (jnp.asarray(fmap.landmarks), jnp.asarray(fmap.proj),
            jnp.float32(fmap.gamma), jnp.float32(fmap.coef0))


def featurize_fn(fmap: FeatureMap, precision: str = "highest"):
    """A ``block -> phi_block`` callable over device arrays, suitable
    for ``observability/compilewatch.instrument`` wrapping (the serving
    engine's approx decider builds on this). ``precision`` is the
    matmul_precision of the featurization GEMMs ("highest" = exact f32
    reference parity, the default)."""
    args = _block_args(fmap)
    kind, degree = fmap.kind, int(fmap.degree)
    pname = str(precision).upper()
    # rff's base kernel kind is irrelevant to the block program; the
    # static `kind` IS the map kind so both maps share one jit site.
    base = "rff" if kind == "rff" else fmap.kernel

    def run(block):
        return _featurize_block_jit(block, *args,
                                    kind=base if kind != "rff" else "rff",
                                    degree=degree, precision_name=pname)

    return run


def featurize(fmap: FeatureMap, x: np.ndarray,
              chunk: int = 8192, precision: str = "highest") -> np.ndarray:
    """phi(x) as host float32, streamed in fixed-shape chunks.

    Pads the tail chunk to the block shape (one compile total) and
    never materializes more than one (chunk, D) block on device beside
    the accumulating host output — X never sits next to its full
    feature matrix on the accelerator.
    """
    import jax.numpy as jnp

    x = np.asarray(x, np.float32)
    n = x.shape[0]
    run = featurize_fn(fmap, precision=precision)
    if n <= chunk:
        return np.asarray(run(jnp.asarray(x)))
    out = np.empty((n, fmap.dim), np.float32)
    block = np.zeros((chunk, x.shape[1]), np.float32)
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        block[: hi - lo] = x[lo:hi]
        block[hi - lo:] = 0.0
        out[lo:hi] = np.asarray(run(jnp.asarray(block)))[: hi - lo]
    return out


def featurize_padded(fmap: FeatureMap, x: np.ndarray, n_pad: int,
                     chunk: int = 8192,
                     precision: str = "highest") -> np.ndarray:
    """featurize + zero-pad rows to ``n_pad`` (the primal solver's
    aligned-minibatch layout; padding rows are masked out of the loss
    by the row-weight vector, not by their feature values)."""
    phi = featurize(fmap, x, chunk=chunk, precision=precision)
    if n_pad == phi.shape[0]:
        return phi
    out = np.zeros((n_pad, phi.shape[1]), np.float32)
    out[: phi.shape[0]] = phi
    return out


def shard_rows(arr: np.ndarray, shards: int):
    """Place a host array on the 1-D data mesh, sharded along rows
    (replicated trailing dims) — the layout every primal-solver matmul
    consumes. Returns a device array; shards == 1 returns a plain
    single-device put."""
    import jax
    import jax.numpy as jnp

    if shards <= 1:
        return jnp.asarray(arr)
    from jax.sharding import NamedSharding, PartitionSpec

    from dpsvm_tpu.parallel.mesh import SHARD_AXIS, make_data_mesh
    mesh = make_data_mesh(shards)
    spec = PartitionSpec(SHARD_AXIS, *([None] * (arr.ndim - 1)))
    return jax.device_put(np.asarray(arr), NamedSharding(mesh, spec))

"""``python -m dpsvm_tpu.approx`` — the kernel-approximation selfcheck
CI gate (sibling of ``python -m dpsvm_tpu.telemetry``,
``-m dpsvm_tpu.resilience`` and ``-m dpsvm_tpu.serving``)."""

import sys

from dpsvm_tpu.approx import main

sys.exit(main())

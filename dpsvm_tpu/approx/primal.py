"""Primal linear solver for feature-mapped problems.

With an explicit feature map (approx/features.py) the kernel SVM
collapses to a LINEAR model over phi(x), solvable in the primal:

    SVC:  min_w  lam/2 ||w||^2 + (1/n) sum_i r_i max(0, 1 - y_i f_i)^2
    SVR:  min_w  lam/2 ||w||^2 + (1/n) sum_i r_i max(0, |f_i - y_i| - p)^2

with f_i = phi_i.w (bias folded in as a constant feature, excluded
from the regularizer), lam = 1/(C n) so C keeps its LIBSVM meaning,
r_i the per-class cost weights (weight_pos/weight_neg), and p the SVR
tube half-width. Squared hinge (L2-SVM) rather than plain hinge: the
objective is differentiable and strongly convex, which is what lets a
plain first-order method converge fast and gives a trustworthy
gradient-norm stopping test (the primal analog of the dual gap) —
the choice both scale references make (arXiv:2207.01016, 2008.03433).

The optimizer is deterministic mini-batch SGD with momentum and
plateau-adaptive step decay:

* batches are CONTIGUOUS aligned slices of the (padded, shuffled-once)
  feature matrix, indexed by iteration count — so the trajectory is a
  pure function of the carry, which is what makes checkpoint/resume
  bitwise-identical (the repo's resume contract) and the whole loop
  jittable as one ``lax.while_loop`` chunk runner;
* the step size is set from a KNOWN squared-hinge smoothness bound
  with a fixed conservative momentum, so there is no learning-rate
  knob to tune. Minibatch mode uses the trace bound
  (L = lam + 2 max(r) E||phi||^2 — valid for every slice; RFF rows
  have ||phi||^2 == 1 exactly), full-batch mode tightens it to a
  spectral bound (deterministic power iteration on (1/n) Phi'Phi,
  typically 10-20x smaller on clustered data — proportionally bigger
  steps);
* constant-step minibatch SGD orbits a noise ball whose radius floors
  the reachable gradient norm, so each time a metric refresh fails to
  beat the best-seen norm by 20%, the step factor halves (carried in
  solver state — deterministic, resume-exact). The model is the LAST
  iterate: the stopping test evaluates the exact gradient at that very
  iterate, so a converged run returns a certified near-optimum rather
  than a lagging average.

The host side is NOT new machinery: the chunk runner plugs into the
shared ``solver/driver.host_training_loop``, so tracing, the packed
(7,)-stats poll, checkpoints, preemption snapshots, health guards,
retry supervision and compile accounting all work unchanged. The
packed stats map as: ``b_lo`` = the EXACT full-objective gradient
L2 norm (the RKHS gradient norm — invariant in approx_dim, unlike the
infinity norm whose coordinate scale shrinks ~1/sqrt(D)), refreshed
every few epochs on device (minibatch
gradients have a variance floor at the optimum, so no minibatch-
derived metric can reach a tight epsilon; ``b_hi`` = 0, so the
driver's ``gap`` IS the metric and its `b_lo > b_hi + 2 eps` verdict
applies verbatim) and ``n_sv`` = margin-violating rows in the last
minibatch (the primal shadow of the SV count, feeding the SV-collapse
health guard).

``shards > 1`` — and any single-shard problem at or above
``_FULLBATCH_ROWS`` — switches to deterministic FULL-batch gradient
steps (sharded: on a row-sharded feature matrix over the
parallel/mesh axes): each step is then one global (n, D) matmul pair
with XLA-inserted cross-shard reductions — the shape every backend
runs at full tilt, and the distributed shape this path exists for.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from dpsvm_tpu.approx.features import (FeatureMap, _featurize_block_jit,
                                       build_feature_map,
                                       featurize_padded, shard_rows)
from dpsvm_tpu.approx.model import ApproxSVMModel
from dpsvm_tpu.config import SENTINEL, SVMConfig, TrainResult
from dpsvm_tpu.observability import compilewatch
from dpsvm_tpu.solver.driver import (host_training_loop, pack_stats,
                                     resume_state)

# Minibatch rows per step (single-shard path). Aligned power of two so
# the dynamic_slice start is a cheap modular index; bounded so small
# problems still take several steps per epoch.
_BATCH = 1024
# Above this row count the single-shard path switches to FULL-batch
# steps: one (n, D) matmul pair per step is the shape both the MXU and
# the CPU thread pool are efficient at, while per-step slice+GEMV
# granularity starves them (measured on this CPU backend: 8.8 us vs
# 1.35 us per row-epoch, a 6.5x gap at 100k rows). Full-batch mode
# also unlocks the spectral step size and the every-step exact metric
# below — measured 24x faster to the same epsilon at n=8000 (1.24 s
# vs 30 s) and the only mode that converges at 100k. The threshold
# keeps the minibatch path live for the window just above one batch
# (and as the template for a future streaming variant); everything
# bigger runs full-batch.
_FULLBATCH_ROWS = 2048
# Power-iteration steps for the spectral curvature estimate. The
# estimate converges from below, so the step size carries a safety
# margin (and the plateau decay recovers from any residual
# overestimate of 1/L).
_POWER_ITERS = 24
# The convergence metric is the EXACT full-batch gradient L2 norm
# (minibatch gradients have a variance floor at the optimum, so
# any minibatch-derived metric stalls above epsilon on hard data).
# Refreshing it every _CHECK_EPOCHS epochs costs ~1/(_CHECK_EPOCHS)
# of an epoch's matmul work — a few percent — via a lax.cond that only
# executes the full pass on refresh iterations.
_CHECK_EPOCHS = 4


# Momentum: fixed, deliberately conservative. The accelerated
# (Nesterov-from-(mu, L)) schedule was tried and rejected: with
# mu = lam it limit-cycles on the squared hinge's kinks at the huge
# condition numbers weak regularization produces, while beta = 0.9 at
# lr = 1/L is unconditionally stable there (measured on the XOR/
# planted suites). The plateau decay below supplies the tail
# convergence a fixed schedule lacks.
_MOMENTUM = 0.9


class PrimalCarry(NamedTuple):
    w: jax.Array        # (Dp,) f32 weights (bias = last entry)
    v: jax.Array        # (Dp,) f32 momentum
    metric: jax.Array   # () f32 exact ||grad||_2 at the last refresh
                        # (SENTINEL = not yet evaluated)
    best: jax.Array     # () f32 best refreshed metric (plateau ref)
    lrf: jax.Array      # () f32 adaptive step factor (halves on
                        # refreshes that fail to beat `best` by 20%)
    n_iter: jax.Array   # () i32
    nact: jax.Array     # () i32 margin violators in the last minibatch


def init_carry(dp: int) -> PrimalCarry:
    """Host-side NumPy init (the solvers' zero-compile policy)."""
    return PrimalCarry(
        w=np.zeros((dp,), np.float32),
        v=np.zeros((dp,), np.float32),
        metric=np.float32(SENTINEL),
        best=np.float32(SENTINEL),
        lrf=np.float32(1.0),
        n_iter=np.int32(0),
        nact=np.int32(0),
    )


def pack_state(carry_host: PrimalCarry) -> Tuple[np.ndarray, np.ndarray]:
    """Carry -> the checkpoint's (alpha, f) slots: alpha = w, f =
    [v, metric, best, lrf] — everything the trajectory is a function
    of, so resume is bitwise-identical."""
    w = np.asarray(carry_host.w, np.float32)
    f = np.concatenate([
        np.asarray(carry_host.v, np.float32),
        np.asarray([float(carry_host.metric), float(carry_host.best),
                    float(carry_host.lrf)], np.float32),
    ])
    return w, f


def unpack_state(ck, dp: int) -> PrimalCarry:
    """Checkpoint slots -> carry (pack_state's inverse)."""
    f = np.asarray(ck.f, np.float32)
    if ck.alpha.shape != (dp,) or f.shape != (dp + 3,):
        raise ValueError(
            f"checkpoint state shapes {ck.alpha.shape}/{f.shape} do not "
            f"match this problem's packed dim {dp} — was it written by "
            "a different approx_dim?"
            + (" (shape dp + 4 is a LIVE streaming checkpoint; resume "
               "it with fit_approx_stream(live=True))"
               if f.shape == (dp + 4,) else ""))
    return PrimalCarry(
        w=np.asarray(ck.alpha, np.float32),
        v=f[:dp].copy(),
        metric=np.float32(f[dp]),
        best=np.float32(f[dp + 1]),
        lrf=np.float32(f[dp + 2]),
        n_iter=np.int32(ck.n_iter),
        nact=np.int32(0),
    )


def pack_state_live(carry_host: PrimalCarry, generation: int
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Live-streaming checkpoint state: ``pack_state`` plus one lane
    carrying the shard-log generation the trajectory had CONSUMED —
    so a killed live run resumes with exactly the shard set it had
    admitted (generations are small ints, exact in f32)."""
    w, f = pack_state(carry_host)
    return w, np.concatenate(
        [f, np.asarray([np.float32(generation)], np.float32)])


def unpack_state_live(ck, dp: int) -> Tuple[PrimalCarry, int]:
    """(carry, consumed generation) from a live streaming checkpoint
    (``pack_state_live``'s inverse)."""
    f = np.asarray(ck.f, np.float32)
    if ck.alpha.shape != (dp,) or f.shape != (dp + 4,):
        raise ValueError(
            f"live checkpoint state shapes {ck.alpha.shape}/{f.shape} "
            f"do not match packed dim {dp} + the generation lane — "
            "written by a frozen-stream run (resume with live=False) "
            "or a different approx_dim?")
    carry = PrimalCarry(
        w=np.asarray(ck.alpha, np.float32),
        v=f[:dp].copy(),
        metric=np.float32(f[dp]),
        best=np.float32(f[dp + 1]),
        lrf=np.float32(f[dp + 2]),
        n_iter=np.int32(ck.n_iter),
        nact=np.int32(0),
    )
    return carry, int(f[dp + 3])


def warm_start_vector(model: ApproxSVMModel) -> np.ndarray:
    """The packed (dp,) primal weight vector of an approx model — the
    ``init_w`` a warm-started (re)train starts from. The bias rides as
    the last lane (the model stores ``b = -w[-1]``), so a fit seeded
    with this vector begins at exactly the model's decision function."""
    return np.concatenate([np.asarray(model.w, np.float32),
                           np.asarray([-float(model.b)], np.float32)])


def _apply_init_w(carry: PrimalCarry, init_w, dp: int) -> PrimalCarry:
    iw = np.asarray(init_w, np.float32)
    if iw.shape != (dp,):
        raise ValueError(
            f"init_w must be ({dp},) — the packed weight vector "
            "including the bias lane (warm_start_vector(model)); got "
            f"shape {iw.shape}")
    if not np.isfinite(iw).all():
        raise ValueError("init_w holds non-finite values")
    return carry._replace(w=iw.copy())


@functools.lru_cache(maxsize=32)
def _build_primal_runner(task: str, n_pad: int, dp: int, batch: int,
                         n_real: int, lam: float, big_l: float,
                         epsilon: float, svr_eps: float,
                         precision_name: str):
    """Compiled chunk runner: primal SGD steps until the (periodically
    refreshed, exact) gradient norm closes or the iteration limit,
    entirely on device — the same contract as the SMO chunk runners,
    driven by the same host loop.

    ``batch == n_pad`` is the full-batch (sharded) variant: the slice
    disappears, every matmul runs over the global feature matrix, and
    the step's own gradient IS the exact metric.
    """
    precision = getattr(lax.Precision, precision_name)
    lr, beta = 1.0 / big_l, _MOMENTUM
    n_batches = n_pad // batch
    # The data term's divisor makes a batch step an UNBIASED estimate
    # of the real-row mean loss: pad rows contribute zero, and each
    # real row appears in exactly one of the n_batches slices, so the
    # per-slice sum over denom averages to sum/n_real across an epoch.
    # Dividing by `batch` instead (the padded slice width) silently
    # inflates the regularizer by n_pad/n_real relative to the data
    # term — the step then converges to the optimum of a DIFFERENT
    # objective, a fixed point where the true-gradient metric floors
    # at ~(n_pad/n - 1)*lam*||w|| and the run never meets epsilon
    # (observed at 0.0038 on a 400-row/512-pad problem).
    denom = n_real / n_batches
    check_every = 1 if n_batches == 1 else _CHECK_EPOCHS * n_batches
    # Step-decay cadence: at metric refreshes for minibatch mode; a
    # longer window for full-batch mode (whose metric refreshes every
    # step, but momentum descent is not per-step monotone — comparing
    # adjacent steps would collapse the factor spuriously). With the
    # gradient restart below, full-batch decay is only the safety net
    # for a spectral-L underestimate, so the window errs long: even at
    # high kappa a 256-step window shows real progress, keeping the
    # decay from misfiring during the legitimate slow phase.
    adapt_every = 256 if n_batches == 1 else check_every
    reg_mask = np.ones((dp,), np.float32)
    reg_mask[-1] = 0.0          # the bias feature is not regularized

    def residual_grad(f, yb, rb):
        """Per-row dLoss/df (masked/weighted) + the activity mask."""
        if task == "svr":
            r = f - yb
            z = jnp.abs(r) - svr_eps
            act = z > 0
            return jnp.where(act, 2.0 * jnp.sign(r) * z, 0.0) * rb, act
        z = 1.0 - yb * f
        act = z > 0
        return jnp.where(act, -2.0 * z * yb, 0.0) * rb, act

    def cond(s: PrimalCarry, limit):
        return (s.metric > 2.0 * epsilon) & (s.n_iter < limit)

    def body(s: PrimalCarry, phi, yv, rw) -> PrimalCarry:
        if n_batches == 1:
            pb, yb, rb = phi, yv, rw
        else:
            start = (s.n_iter % n_batches) * batch
            pb = lax.dynamic_slice(phi, (start, 0), (batch, dp))
            yb = lax.dynamic_slice(yv, (start,), (batch,))
            rb = lax.dynamic_slice(rw, (start,), (batch,))
        # Nesterov: gradient at the lookahead point w + beta*v.
        u = s.w + beta * s.v
        f = jnp.matmul(pb, u, precision=precision)
        g, act = residual_grad(f, yb, rb)
        data = jnp.matmul(g, pb, precision=precision)
        grad = data / jnp.float32(denom) + lam * u * reg_mask
        v = beta * s.v - (lr * s.lrf) * grad
        w = s.w + v
        t = s.n_iter + 1

        if n_batches == 1:
            # Full-batch step: `grad` (denom == n_real) IS the exact
            # objective gradient at the lookahead point — which
            # coincides with w as v -> 0 near the optimum, exactly
            # where the stopping test matters. The metric is the
            # gradient's L2 norm, NOT the infinity norm: per-coordinate
            # feature scale shrinks ~1/sqrt(D), so an inf-norm test
            # gets LOOSER as approx_dim grows (observed: premature
            # "convergence" at D=1024 on problems D=32 solves), while
            # ||grad||_2^2 = sum_ij c_i c_j phi_i.phi_j ~= the RKHS
            # gradient norm — invariant in D, so epsilon means the
            # same thing at every approx_dim.
            full = grad
            metric = jnp.sqrt(jnp.sum(full * full))
            # Adaptive gradient restart (O'Donoghue-Candes): zero the
            # momentum when it points uphill. Constant-beta Nesterov
            # limit-cycles with period ~pi*sqrt(kappa) on the squared
            # hinge's kinks (observed: the metric froze at ~2x target
            # while the plateau decay, aliased with the cycle, ground
            # lrf to the floor); the restart kills the cycle at zero
            # cost — the exact gradient is already in hand.
            v = jnp.where(jnp.vdot(full, v) > 0, jnp.zeros_like(v), v)
        else:
            def exact_metric(_):
                ff = jnp.matmul(phi, w, precision=precision)
                gg, _a = residual_grad(ff, yv, rw)
                full = (jnp.matmul(gg, phi, precision=precision)
                        / jnp.float32(n_real) + lam * w * reg_mask)
                return jnp.sqrt(jnp.sum(full * full))

            metric = lax.cond(t % check_every == 0, exact_metric,
                              lambda _: s.metric, operand=None)
        # Plateau-adaptive step decay: a refresh with NO improvement
        # over the best-seen exact norm means the iterate is orbiting
        # the constant-step noise ball (minibatch) or a momentum limit
        # cycle — halve the factor and keep going. Anything stricter
        # (e.g. demanding 20% progress per window) misfires during the
        # legitimate slow phase of ill-conditioned problems. The floor
        # keeps a pathological plateau from freezing the step at
        # denormal scale.
        refresh = (t % adapt_every) == 0
        fresh = s.best >= jnp.float32(SENTINEL) * 0.5
        decay = refresh & ~fresh & (metric >= s.best)
        lrf = jnp.maximum(jnp.where(decay, s.lrf * 0.5, s.lrf),
                          jnp.float32(1.0 / 4096.0))
        best = jnp.where(refresh, jnp.minimum(s.best, metric), s.best)
        nact = jnp.sum(act & (rb > 0), dtype=jnp.int32)
        return PrimalCarry(w=w, v=v, metric=metric, best=best, lrf=lrf,
                           n_iter=t, nact=nact)

    def stats(final: PrimalCarry):
        return pack_stats(final.n_iter, final.metric, jnp.float32(0.0),
                          n_sv=final.nact)

    def run(carry: PrimalCarry, phi, yv, rw, limit):
        final = lax.while_loop(lambda s: cond(s, limit),
                               lambda s: body(s, phi, yv, rw), carry)
        return final, stats(final)

    return jax.jit(run, donate_argnums=(0,))


@functools.lru_cache(maxsize=32)
def _build_stream_programs(task: str, dp: int, epsilon: float,
                           svr_eps: float, precision_name: str):
    """Compiled programs for the OUT-OF-CORE full-batch path
    (``fit_approx_stream``): the host streams shards through ``acc``
    (partial data-gradient at the Nesterov lookahead point, one fixed
    shape for every shard) and applies ``upd`` once per step (the
    in-memory full-batch body — spectral metric, gradient restart,
    plateau decay — gated on the same ``metric > 2 eps & n_iter <
    limit`` condition the in-memory while_loop checks, so a converged
    carry passes through untouched). ``stats_of`` packs the poll
    array for the zero-step edge (a speculative chunk dispatched after
    max_iter). All three compile exactly once per geometry.

    The problem-scale facts — row count ``n_real``, regularizer
    ``lam`` and step size ``lr`` — ride as TRACED f32 scalars rather
    than baked constants: a live shard log growing mid-run
    (``fit_approx_stream(live=True)``, docs/DATA.md "Live shard
    logs") changes only these operands, so ingest growth pins ZERO
    retraces by construction (same values bitwise on frozen runs —
    the scalars land in the identical f32 ops the constants did)."""
    precision = getattr(lax.Precision, precision_name)
    beta = _MOMENTUM
    reg_mask = np.ones((dp,), np.float32)
    reg_mask[-1] = 0.0          # the bias lane is not regularized

    def residual_grad(f, yb, rb):
        if task == "svr":
            r = f - yb
            z = jnp.abs(r) - svr_eps
            act = z > 0
            return jnp.where(act, 2.0 * jnp.sign(r) * z, 0.0) * rb, act
        z = 1.0 - yb * f
        act = z > 0
        return jnp.where(act, -2.0 * z * yb, 0.0) * rb, act

    def acc(gacc, nacc, w, v, phi, yb, rb, scale):
        # Pad rows ride with rb == 0, which zeroes their residual
        # gradient — so neither the feature values a zero-padded row
        # featurizes to nor the constant bias lane (folded in here as
        # `+ u[-1]`, never materialized as a column) can leak into the
        # accumulated gradient.
        u = w + beta * v
        f = jnp.matmul(phi, u[:-1], precision=precision) + u[-1]
        g, act = residual_grad(f, yb, rb)
        gpart = jnp.concatenate(
            [jnp.matmul(g, phi, precision=precision),
             jnp.reshape(jnp.sum(g), (1,))])
        npart = jnp.sum(act & (rb > 0), dtype=jnp.int32)
        return (gacc * scale + gpart,
                jnp.where(scale > 0, nacc, 0) + npart)

    def upd(s: PrimalCarry, gacc, nacc, limit, n_real, lam, lr):
        u = s.w + beta * s.v
        grad = gacc / n_real + lam * u * reg_mask
        metric = jnp.sqrt(jnp.sum(grad * grad))
        alive = (s.metric > 2.0 * epsilon) & (s.n_iter < limit)
        v_new = beta * s.v - (lr * s.lrf) * grad
        w_new = s.w + v_new
        # Adaptive gradient restart (the in-memory full-batch move):
        # zero the momentum when it points uphill.
        v_new = jnp.where(jnp.vdot(grad, v_new) > 0,
                          jnp.zeros_like(v_new), v_new)
        t = s.n_iter + 1
        refresh = (t % 256) == 0        # full-batch decay window
        fresh = s.best >= jnp.float32(SENTINEL) * 0.5
        decay = refresh & ~fresh & (metric >= s.best)
        lrf = jnp.maximum(jnp.where(decay, s.lrf * 0.5, s.lrf),
                          jnp.float32(1.0 / 4096.0))
        best = jnp.where(refresh, jnp.minimum(s.best, metric), s.best)
        stepped = PrimalCarry(w=w_new, v=v_new, metric=metric,
                              best=best, lrf=lrf, n_iter=t, nact=nacc)
        out = jax.tree_util.tree_map(
            lambda a, b: jnp.where(alive, a, b), stepped, s)
        return out, pack_stats(out.n_iter, out.metric,
                               jnp.float32(0.0), n_sv=out.nact)

    def stats_of(s: PrimalCarry):
        return pack_stats(s.n_iter, s.metric, jnp.float32(0.0),
                          n_sv=s.nact)

    return (jax.jit(acc, donate_argnums=(0,)),
            jax.jit(upd, donate_argnums=(0,)),
            jax.jit(stats_of))


def fit_approx_stream(ds, config: Optional[SVMConfig] = None,
                      task: str = "svc",
                      allow_nonfinite: bool = False, *,
                      live: Optional[bool] = None,
                      init_w=None,
                      watcher=None
                      ) -> Tuple[ApproxSVMModel, TrainResult]:
    """Featurize + primal-solve a ``data.stream.ShardedDataset`` that
    never fully materializes — the out-of-core training path
    (docs/DATA.md, docs/APPROX.md "Streaming").

    Deterministic FULL-batch gradient steps: each iteration streams
    every live shard through one compiled fixed-shape featurize +
    accumulate pass (the shard geometry is fixed by the manifest, so
    steady state pins ZERO retraces) and applies one compiled update —
    the exact global-gradient metric of the in-memory full-batch mode,
    evaluated every step. The host side is the shared
    ``solver/driver.host_training_loop``: traces, the packed-stats
    poll (count pinned equal to an in-memory run's — ingest accounting
    adds no transfers), checkpoints/preemption, health guards, retry
    supervision and compile accounting all work unchanged, and resume
    is bitwise-identical (the trajectory is a pure function of the
    carry, the shard bytes, and the manifest order — there is no
    shuffle: full-batch gradients are order-independent up to the
    fixed shard reduction order).

    Robustness semantics: shard reads apply ``config.on_bad_shard``
    (quarantine emits a ``quarantine`` trace event at the next poll
    and the shard is skipped by every later epoch; the data-term
    divisor stays the manifest's n so the objective does not silently
    renormalize around lost rows), transient I/O errors retry with
    backoff, and ``config.mem_budget_mb`` refuses an over-budget
    per-shard working set up front.

    ``live=True`` (or ``config.live``) trains the dataset as a LIVE
    shard log (docs/DATA.md "Live shard logs"): a ``ShardLogWatcher``
    polls the manifest at every sweep boundary and admits new durable
    shards into the in-progress run — the admitted delta is traced
    (``append_admitted`` per shard, one ``ingest_grow`` per growing
    boundary), the divisor/regularizer/step-size math re-derives from
    the grown view host-side, and because the update program takes
    those scalars as traced operands growth causes ZERO retraces and
    ZERO extra packed-stats polls (pinned in tests/test_live.py).
    Checkpoints carry the CONSUMED generation, so a SIGKILL at any
    boundary resumes bitwise: the resumed run re-admits exactly the
    shards the dead run had admitted before the watcher sees anything
    newer. Resume contract: open the dataset pinned at the same entry
    generation the original run started from
    (``ShardedDataset.open(dir, at_generation=g0)``).

    ``init_w`` warm-starts the weights (``warm_start_vector(model)``)
    — the continuous-learning loop's incremental refresh; a configured
    ``resume_from`` checkpoint takes precedence.
    """
    from dpsvm_tpu.data import stream as streamlib
    from dpsvm_tpu.solver.driver import queue_trace_event

    config = config or SVMConfig()
    config.validate()
    live = bool(config.live) if live is None else bool(live)
    if config.solver == "exact":
        raise ValueError(
            "streaming training is the approx primal path (the exact "
            "dual solvers touch O(n^2) kernel state and need X "
            "materialized): use solver='approx-rff'/'approx-nystrom', "
            "or materialize the shards via data.loader.load_dataset")
    if task not in ("svc", "svr"):
        raise ValueError(f"task must be 'svc' or 'svr', got {task!r}")
    if config.shards != 1:
        raise ValueError(
            "fit_approx_stream is single-process: the sharded "
            "full-batch path (config.shards > 1) consumes in-memory "
            "arrays — materialize, or stream on one process")
    # n is the ENTRY view's row count and stays the run's identity
    # (trace manifest, checkpoint validation) even as a live log
    # grows: growth is recorded by events + the generation lane, and
    # a resume re-enters at the same pinned view.
    n, d = ds.n, ds.d
    gamma = float(config.resolve_gamma(d))
    spec = config.kernel_spec(d)
    kind = config.solver.split("-", 1)[1]
    streamlib.check_stream_budget(
        config.mem_budget_mb, n=n, d=d,
        rows_per_shard=ds.rows_per_shard, feat_dim=config.approx_dim,
        what=ds.directory)

    if kind == "rff":
        # The RFF map only reads the input width — no data touched.
        fmap = build_feature_map("rff", np.zeros((1, d), np.float32),
                                 config.approx_dim, config.approx_seed,
                                 spec)
    else:
        # Nystrom landmarks: a deterministic global subsample gathered
        # from only the shards that hold them (strict integrity — the
        # persisted map must be rebuildable forever).
        m = min(int(config.approx_dim), n)
        rng = np.random.default_rng(config.approx_seed)
        idx = np.sort(rng.choice(n, size=m, replace=False))
        fmap = build_feature_map("nystrom", ds.gather_rows(idx), m,
                                 config.approx_seed, spec)
    dp = fmap.dim + 1
    srows = ds.rows_per_shard

    feat_raw = compilewatch.instrument(_featurize_block_jit,
                                       "stream-featurize")
    feat_args = _feat_call_args(fmap,
                                precision=config.matmul_precision)

    def featurize_block(xk: np.ndarray):
        block = xk
        if xk.shape[0] != srows:
            block = np.zeros((srows, d), np.float32)
            block[: xk.shape[0]] = xk
        return feat_raw(block, *feat_args[0], **feat_args[1])

    policy = config.on_bad_shard

    def padded(arrs, fill=0.0):
        out = np.full((srows,), np.float32(fill))
        out[: len(arrs)] = arrs
        return out

    def shard_lanes(k, y):
        if task == "svc":
            labels = np.unique(y)
            if not np.all(np.isin(labels, (-1, 1))):
                raise ValueError(
                    f"shard {k}: labels must be +/-1 for binary "
                    f"training, got {labels[:10]} — multiclass shard "
                    "sets train via materialization")
            yv = np.asarray(y, np.float32)
            rw = np.where(yv > 0, np.float32(config.weight_pos),
                          np.float32(config.weight_neg))
        else:
            yv = np.asarray(y, np.float32)
            rw = np.ones((len(yv),), np.float32)
        return padded(yv), padded(rw)

    # Prologue epoch: every shard verified once (quarantine fires HERE
    # first — deterministically, so an interrupted run and its resume
    # see the identical live set) while the curvature stat accumulates
    # over real rows. One extra I/O pass buys the same tuning-free
    # step size the in-memory path measures. Live admission reuses the
    # same absorb step per appended shard, so the curvature stat's
    # accumulation order (shard index order) is identical whether a
    # shard arrived in the seed view or as an append — the bitwise
    # resume contract's arithmetic half.
    scale_state = {"msq_num": 0.0, "seen": 0}

    def absorb_shard(k: int) -> int:
        got = ds.read_shard_checked(k, on_bad_shard=policy,
                                    allow_nonfinite=allow_nonfinite)
        if got is None:
            return 0
        xk, yk = got
        shard_lanes(k, yk)              # label sanity up front
        phi = np.asarray(featurize_block(xk))
        scale_state["msq_num"] += float(
            np.sum(phi[: len(yk)].astype(np.float64) ** 2))
        scale_state["seen"] += len(yk)
        return len(yk)

    for k in range(ds.n_shards):
        absorb_shard(k)
    if scale_state["seen"] == 0:
        raise streamlib.IngestAbortError(
            f"{ds.directory}: no readable shard survived the prologue")
    maxrw = (max(float(config.weight_pos), float(config.weight_neg))
             if task == "svc" else 1.0)
    live_state = {"n": ds.n, "gen": int(getattr(ds, "generation", 0))}

    def scale_params() -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(n_real, lam, lr) as f32 scalars for the update program —
        re-derived host-side from the CURRENT admitted view, so live
        growth changes operand values, never programs. The divisor is
        the admitted manifest n (quarantined rows included — the
        objective does not silently renormalize around lost rows) and
        the step size keeps the trace curvature bound
        (docs/APPROX.md): the spectral estimate would need
        power-iteration I/O epochs."""
        n_live = int(live_state["n"])
        msq = scale_state["msq_num"] / scale_state["seen"] + 1.0
        lam = 1.0 / (float(config.c) * n_live)
        big_l = lam + 2.0 * maxrw * msq
        return (np.float32(n_live), np.float32(lam),
                np.float32(1.0 / big_l))

    acc_j, upd_j, stats_j = _build_stream_programs(
        task, dp, float(config.epsilon),
        float(config.svr_epsilon), config.matmul_precision.upper())
    acc = compilewatch.instrument(acc_j, "stream-acc")
    upd = compilewatch.instrument(upd_j, "stream-upd")

    if live and watcher is None:
        from dpsvm_tpu.data.live import ShardLogWatcher
        watcher = ShardLogWatcher(
            ds, on_bad_shard=policy,
            allow_nonfinite=allow_nonfinite,
            # absorb_shard below verifies (and may quarantine) every
            # admitted shard — a second integrity read would be waste
            verify_appends=False,
            # admissions land in THIS run's trace at the next poll
            on_event=lambda e, **kw: queue_trace_event(e, **kw))
    if watcher is not None and watcher.ds is not ds:
        raise ValueError("watcher must wrap the SAME ShardedDataset "
                         "handle this run trains on")

    def admit_new() -> None:
        """Sweep-boundary admission (live mode): one manifest poll —
        pure host I/O, zero device transfers. Newly durable shards are
        absorbed (verified under the on_bad_shard policy, curvature
        stat grown) and the boundary is traced as ONE ingest_grow
        event carrying the new generation and row delta."""
        admitted = watcher.poll()
        if not admitted:
            return
        grown = 0
        for k in admitted:
            grown += absorb_shard(k)
        live_state["n"] = ds.n
        live_state["gen"] = int(ds.generation)
        queue_trace_event("ingest_grow",
                          generation=int(ds.generation),
                          n_new_rows=int(grown),
                          shards=int(ds.n_shards),
                          quarantined=len(ds.quarantined))

    carry = init_carry(dp)
    if init_w is not None:
        carry = _apply_init_w(carry, init_w, dp)
    ckpt = resume_state(config, n, dp, gamma)
    if ckpt is not None:
        if live:
            carry, gen_ck = unpack_state_live(ckpt, dp)
            if gen_ck > ds.generation:
                # Re-admit EXACTLY the shards the dead run had
                # consumed (entries stamped <= the checkpoint's
                # generation) before the watcher may see anything
                # newer — the bitwise-resume contract's ingest half.
                from dpsvm_tpu.data.live import read_manifest_checked
                manifest = read_manifest_checked(ds.directory)
                pinned = streamlib.pin_manifest_generation(manifest,
                                                           gen_ck)
                for k in ds.admit_manifest(pinned):
                    absorb_shard(k)
                live_state["n"] = ds.n
                live_state["gen"] = int(ds.generation)
            queue_trace_event("ingest_resume",
                              n_iter=int(ckpt.n_iter),
                              shards=int(ds.n_shards),
                              generation=int(ds.generation),
                              quarantined=len(ds.quarantined))
        else:
            carry = unpack_state(ckpt, dp)
            queue_trace_event("ingest_resume", n_iter=int(ckpt.n_iter),
                              shards=int(ds.n_shards),
                              quarantined=len(ds.quarantined))
    carry = jax.device_put(carry)
    it0 = int(ckpt.n_iter) if ckpt is not None else 0

    state = {"it": it0, "carry": carry,
             "gacc": jnp.zeros((dp,), jnp.float32),
             "nacc": jnp.zeros((), jnp.int32)}

    def step_chunk(c, limit):
        limit = int(limit)
        g, na = state["gacc"], state["nacc"]
        stats = None
        while state["it"] < limit:
            if live:
                admit_new()
            nf, lamf, lr32 = scale_params()
            first = True
            for k in range(ds.n_shards):
                got = ds.read_shard_checked(
                    k, on_bad_shard=policy,
                    allow_nonfinite=allow_nonfinite)
                if got is None:
                    continue
                xk, yk = got
                yp, rp = shard_lanes(k, yk)
                phi = featurize_block(xk)
                g, na = acc(g, na, c.w, c.v, phi, yp, rp,
                            np.float32(0.0 if first else 1.0))
                first = False
            if first:
                raise streamlib.IngestAbortError(
                    f"{ds.directory}: every shard is quarantined")
            c, stats = upd(c, g, na, np.int32(limit), nf, lamf, lr32)
            state["it"] += 1
        if stats is None:
            # Zero-step dispatch (speculative chunk at max_iter):
            # report the carry as-is — no data pass, no extra reads.
            stats = stats_j(c)
        state["gacc"], state["nacc"] = g, na
        state["carry"] = c
        return c, stats

    def carry_from_ckpt(ck):
        # Rollback restores BOTH halves of the trajectory state: the
        # device carry and the host epoch cursor. (Live mode: the
        # admitted view never shrinks — a rollback to an older
        # generation keeps the grown view, which is the superset the
        # original trajectory was about to admit anyway.)
        state["it"] = int(ck.n_iter)
        if live:
            restored, _gen = unpack_state_live(ck, dp)
            return jax.device_put(restored)
        return jax.device_put(unpack_state(ck, dp))

    def carry_to_host(c):
        host = jax.tree_util.tree_map(np.asarray, c)
        if live:
            return pack_state_live(host, live_state["gen"])
        return pack_state(host)

    result = host_training_loop(
        config, gamma, n, dp, carry,
        step_chunk=step_chunk,
        carry_to_host=carry_to_host,
        it0=it0,
        carry_from_ckpt=carry_from_ckpt,
    )

    final = jax.tree_util.tree_map(np.asarray, state["carry"])
    w_out = np.asarray(final.w, np.float32)
    model = ApproxSVMModel(fmap=fmap, w=w_out[:-1].copy(),
                           b=-float(w_out[-1]), task=task)
    result = dataclasses.replace(
        result, b=model.b, n_sv=int(final.nact), gamma=gamma,
        kernel=config.kernel, coef0=float(config.coef0),
        degree=int(config.degree))
    return model, result


def _feat_call_args(fmap: FeatureMap, precision: str = "highest"):
    """(positional, keyword) arguments binding ``_featurize_block_jit``
    for one map — the streaming path calls the SHARED jit directly
    (instead of a per-fit closure) so compilewatch's cache probe sees a
    warm second run as zero compiles. ``precision`` is the GEMM
    matmul_precision ("highest" = exact f32 parity, the default)."""
    from dpsvm_tpu.approx.features import _block_args
    kind = "rff" if fmap.kind == "rff" else fmap.kernel
    return ((*_block_args(fmap),),
            {"kind": kind, "degree": int(fmap.degree),
             "precision_name": str(precision).upper()})


def _power_lambda_max(phi: np.ndarray, n: int) -> float:
    """lambda_max((1/n) Phi'Phi) by seeded power iteration — the data
    term's true curvature scale (pad rows are zero, so they drop out).
    Deterministic, so the derived step size (and with it the whole
    trajectory) stays a pure function of the config + data: the
    bitwise checkpoint/resume contract."""
    rng = np.random.default_rng(0)
    v = rng.standard_normal(phi.shape[1]).astype(np.float32)
    v /= np.linalg.norm(v)
    lmax = 0.0
    for _ in range(_POWER_ITERS):
        w = (phi @ v) @ phi / np.float32(n)
        lmax = float(np.linalg.norm(w))
        if lmax <= 0.0:            # all-zero features: regularizer only
            return 0.0
        v = w / lmax
    return lmax


def _check_svc_labels(y: np.ndarray) -> np.ndarray:
    labels = np.unique(y)
    if not np.all(np.isin(labels, (-1, 1))):
        raise ValueError(
            f"labels must be +/-1 for binary training, got "
            f"{labels[:10]} — for multi-class data use "
            "models.multiclass.train_multiclass (CLI: train --multiclass)")
    return np.asarray(y, np.float32)


def fit_approx(x: np.ndarray, y: np.ndarray,
               config: Optional[SVMConfig] = None,
               task: str = "svc", *,
               init_w=None
               ) -> Tuple[ApproxSVMModel, TrainResult]:
    """Featurize + primal-solve; the approx path's ``api.fit``.

    Returns ``(ApproxSVMModel, TrainResult)``: the result's
    ``b_lo``/``b_hi`` carry the final (metric, 0) pair — its ``gap``
    IS the gradient-norm metric — and ``n_sv`` counts the last
    minibatch's margin violators (there is no SV set). ``init_w``
    warm-starts the weights from a packed (dp,) vector
    (``warm_start_vector(model)``) — the continuous-learning loop's
    cheap refresh and the cascade's warm-started full retrain; a
    configured ``resume_from`` checkpoint takes precedence.
    """
    from dpsvm_tpu.utils import densify

    config = config or SVMConfig()
    config.validate()
    if config.solver == "exact":
        raise ValueError("fit_approx needs solver='approx-rff' or "
                         "'approx-nystrom'")
    if task not in ("svc", "svr"):
        raise ValueError(f"task must be 'svc' or 'svr', got {task!r}")
    x = np.asarray(densify(x), np.float32)
    if x.ndim != 2:
        raise ValueError(f"x must be (n, d), got shape {x.shape}")
    y = np.asarray(y)
    if y.shape != (x.shape[0],):
        raise ValueError(f"y must be ({x.shape[0]},), got {y.shape}")
    yv = (_check_svc_labels(y) if task == "svc"
          else np.asarray(y, np.float32))
    n, d = x.shape
    gamma = float(config.resolve_gamma(d))
    spec = config.kernel_spec(d)
    kind = config.solver.split("-", 1)[1]

    fmap = build_feature_map(kind, x, config.approx_dim,
                             config.approx_seed, spec)
    dp = fmap.dim + 1                      # + bias feature

    shards = int(config.shards)
    if shards > 1:
        # Full-batch sharded steps: pad rows to the mesh.
        batch = n_pad = -(-n // shards) * shards
    elif n >= _FULLBATCH_ROWS:
        # Large single-shard problems also run full-batch (see
        # _FULLBATCH_ROWS); pad to a lane-aligned row count.
        batch = n_pad = -(-n // 256) * 256
    else:
        batch = min(_BATCH, 1 << (n - 1).bit_length())
        n_pad = -(-n // batch) * batch
    # Shuffle ONCE, deterministically: contiguous minibatch slices over
    # class-sorted input files would otherwise be class-pure batches.
    # Seeded by approx_seed so the whole trajectory (map + order) is one
    # reproducible function of the config.
    perm = np.random.default_rng(config.approx_seed).permutation(n)
    x, yv = x[perm], yv[perm]
    phi = featurize_padded(fmap, x, n_pad,
                           precision=config.matmul_precision)
    # Mean squared feature-row norm over REAL rows: the curvature bound
    # behind the tuning-free step size (module docstring).
    msq = float(np.mean(np.sum(phi[:n].astype(np.float64) ** 2, axis=1)))
    phi = np.concatenate(
        [phi, np.zeros((n_pad, 1), np.float32)], axis=1)
    phi[:n, -1] = 1.0                      # bias feature (pad rows 0)
    msq += 1.0
    lam = 1.0 / (float(config.c) * n)
    maxrw = (max(float(config.weight_pos), float(config.weight_neg))
             if task == "svc" else 1.0)
    if batch == n_pad:
        # Full-batch steps see the GLOBAL curvature, so the trace
        # bound (mean sq row norm >= lambda_max of (1/n) Phi'Phi,
        # typically 10-20x too big on clustered RBF data) can be
        # replaced by a spectral estimate: a few deterministic power
        # iterations at featurize cost. The estimate converges from
        # below — the 1.1 margin plus the plateau decay covers the
        # residual; the trace bound stays as a hard ceiling.
        curv = min(msq, 1.1 * _power_lambda_max(phi, n))
    else:
        # Minibatch slices can concentrate curvature well above the
        # global lambda_max (one tight cluster in one batch), but the
        # trace bound holds for EVERY slice: each step's data Hessian
        # is (2/denom) Phi_b' diag(act r) Phi_b with trace at most
        # (batch/denom) * msq = (n_pad/n) * msq.
        curv = msq * (n_pad / n)
    big_l = lam + 2.0 * maxrw * curv   # squared-hinge smoothness bound

    yp = np.zeros((n_pad,), np.float32)
    yp[:n] = yv
    rw = np.zeros((n_pad,), np.float32)
    if task == "svc":
        rw[:n] = np.where(yv > 0, np.float32(config.weight_pos),
                          np.float32(config.weight_neg))
    else:
        rw[:n] = 1.0

    phi_d = shard_rows(phi, shards)
    yp_d = shard_rows(yp, shards)
    rw_d = shard_rows(rw, shards)

    runner = compilewatch.instrument(
        _build_primal_runner(task, n_pad, dp, batch, n, lam, big_l,
                             float(config.epsilon),
                             float(config.svr_epsilon),
                             config.matmul_precision.upper()),
        "approx-primal-chunk")

    carry = init_carry(dp)
    if init_w is not None:
        carry = _apply_init_w(carry, init_w, dp)
    # Checkpoint identity: (n, Dp) names the packed primal problem the
    # way (n, d) names a dual one. The feature map itself is not
    # persisted in the checkpoint — it is deterministic in the config
    # (approx_seed/approx_dim), exactly as the training data is assumed
    # unchanged across a dual resume.
    ckpt = resume_state(config, n, dp, gamma)
    if ckpt is not None:
        carry = unpack_state(ckpt, dp)
    # Commit the host-built carry before the first dispatch: the chunk
    # runner's donated outputs are committed arrays, and a numpy-typed
    # first call would key a SECOND identical compile in the jit cache
    # (observed; the selfcheck pins the count at one).
    carry = jax.device_put(carry)

    def carry_from_ckpt(ck):
        return jax.device_put(unpack_state(ck, dp))

    last = {}

    def step_chunk(c, limit):
        c, stats = runner(c, phi_d, yp_d, rw_d, np.int32(limit))
        last["carry"] = c
        return c, stats

    result = host_training_loop(
        config, gamma, n, dp, carry,
        step_chunk=step_chunk,
        carry_to_host=lambda c: pack_state(
            jax.tree_util.tree_map(np.asarray, c)),
        it0=int(ckpt.n_iter) if ckpt is not None else 0,
        carry_from_ckpt=carry_from_ckpt,
    )

    final = jax.tree_util.tree_map(np.asarray, last["carry"])
    w_out = np.asarray(final.w, np.float32)
    model = ApproxSVMModel(fmap=fmap, w=w_out[:-1].copy(),
                           b=-float(w_out[-1]), task=task)
    result = dataclasses.replace(
        result, b=model.b, n_sv=int(final.nact), gamma=gamma,
        kernel=config.kernel, coef0=float(config.coef0),
        degree=int(config.degree))
    return model, result

"""Stage 2 of the cascade: margin-band SV screening.

The cheap approx solution predicts the support-vector set: a row whose
approx margin ``y_i * f(x_i)`` clears ``1 + screen_margin`` is a
confident non-SV — its exact dual variable is almost surely 0 and it
can be dropped from the exact subproblem. The keep rule

    y_i * f(x_i) <= 1 + delta          (delta = config.screen_margin)

is the margin band ``|f(x)| <= 1 + delta`` completed on the wrong
side: for a correctly classified row ``y f == |f|`` so the two agree,
and a misclassified row (``y f < 0``, an at-bound SV in the exact
dual) is always kept no matter how far past the band it sits. The
margins are tested after CALIBRATION (``margin_scale`` below): the
approx stage's squared-hinge objective compresses decision values
relative to the exact hinge dual, and banding the raw values
over-keeps by 2-3x. The parallel-shrinking literature
(arXiv:1406.5161) screens on exactly this one-sided test; the
polishing recipe (arXiv:2207.01016) supplies the repair loop that
makes the band a performance knob instead of a correctness one —
``solver/cascade.py`` KKT-checks every screened-out row against the
polished model and re-admits violators.

Everything here is pure NumPy over already-computed decision values;
the scorers that produce those values (in-memory batches or a
shard-by-shard ``data/stream.py`` sweep) live in ``solver/cascade.py``
next to the orchestration that consumes them.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def apply_cap(idx: np.ndarray, yf: np.ndarray,
              cap: Optional[int]) -> Tuple[np.ndarray, bool]:
    """Enforce the hard row cap on a band selection.

    ``idx`` are the band rows' global indices, ``yf`` their margins.
    Over-cap rows are dropped LARGEST-margin-first — the rows kept are
    the ones most likely to be SVs (violators and at-bound rows have
    the smallest ``y f``). Deterministic: ties break on the global
    index, so the same data always screens to the same subproblem.
    Returns (sorted kept indices, whether the cap actually trimmed).
    """
    idx = np.asarray(idx, np.int64)
    if cap is None or cap <= 0 or len(idx) <= cap:
        return np.sort(idx), False
    order = np.lexsort((idx, np.asarray(yf, np.float32)))
    return np.sort(idx[order[:cap]]), True


def margin_scale(yf_exact: np.ndarray, yf_approx: np.ndarray,
                 floor: float = 0.2) -> float:
    """Calibration factor between approx and exact decision scales.

    The approx stage solves the SQUARED hinge (L2-SVM) primal, whose
    optimum has a systematically different weight scale from the L1
    hinge dual the exact solver certifies — measured on the planted
    8000x32 bench shape: approx margins compressed to ~0.67x the
    exact ones, so the raw band ``y f_a <= 1 + delta`` over-kept 52%
    of the rows where the true SV fraction was 20%. Dividing the
    approx margins by this factor before banding recovers the exact
    margin geometry (the cascade estimates it from a small exact
    PROBE solve — solver/cascade.py ``_calibrate``).

    The estimator is the median ratio over rows both models place
    confidently on the correct side (``y f > floor`` for both —
    ratio-stable, outlier-immune), clamped to [0.2, 5] so one
    degenerate probe can never nuke the band.
    """
    a = np.asarray(yf_approx, np.float64)
    e = np.asarray(yf_exact, np.float64)
    mask = (a > floor) & (e > floor)
    if mask.sum() < 8:
        return 1.0
    return float(np.clip(np.median(a[mask] / e[mask]), 0.2, 5.0))


def kkt_zero_violations(decisions: np.ndarray, y: np.ndarray,
                        tol: float) -> np.ndarray:
    """Mask of screened-out rows violating the ``alpha = 0`` KKT
    condition against a polished model: ``y f < 1 - tol``. The
    tolerance is the exact solver's own stopping slack (``2 epsilon``
    — the polished subproblem's interior rows satisfy no more), so a
    clean verify pass certifies the screened-out rows to the same bar
    the polish certifies the kept rows."""
    yf = np.asarray(decisions, np.float32) * np.asarray(y, np.float32)
    return yf < np.float32(1.0 - tol)

"""Approx-model representation, decision math and persistence.

An approx model has NO support vectors: it is a feature map plus one
(D,) primal weight vector and an intercept. Its decision keeps the SV
models' sign convention — ``decision = phi(x).w - b`` — so everything
downstream that folds intercepts (``serving/engine._with_b``, Platt
sidecars, ``--no-b``) works unchanged on either model kind.

Persistence is one ``.npz`` (the text SV format has no place for a
frequency matrix): ``models/io.save_model``/``load_model`` dispatch on
the zip magic, so every consumer — ``dpsvm test``, the serving engine,
multiclass directories — round-trips approx models through the same
entry points as SV models. RFF maps persist only (seed, dims, gamma):
the frequency matrix is re-derived bit-identically on load. Nystrom
persists its landmarks and whitening projection.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dpsvm_tpu.approx.features import (FeatureMap, _block_args,
                                       _featurize_block_jit, rff_omega)

_FORMAT = "dpsvm-approx-v1"


@dataclasses.dataclass
class ApproxSVMModel:
    """Feature map + primal weights (see module docstring)."""

    fmap: FeatureMap
    w: np.ndarray                 # (fmap.dim,) f32 feature weights
    b: float                      # decision = phi.w - b (SV convention)
    task: str = "svc"             # "svc" | "svr"

    # Duck-typed markers consumed by the dispatch sites (models/svm.py,
    # serving/engine.py, models/multiclass.py).
    is_approx: bool = dataclasses.field(default=True, init=False,
                                        repr=False)

    @property
    def model_kind(self) -> str:
        return f"approx-{self.fmap.kind}"

    @property
    def kernel(self) -> str:
        return self.fmap.kernel

    @property
    def gamma(self) -> float:
        return float(self.fmap.gamma)

    @property
    def coef0(self) -> float:
        return float(self.fmap.coef0)

    @property
    def degree(self) -> int:
        return int(self.fmap.degree)

    @property
    def num_attributes(self) -> int:
        return int(self.fmap.d)

    @property
    def n_sv(self) -> int:
        # No SV set exists; 0 keeps n_sv-printing surfaces truthful.
        return 0


@functools.partial(jax.jit, static_argnames=("kind", "degree",
                                             "include_b",
                                             "precision_name"))
def _approx_decision_jit(block, omega_or_landmarks, proj, gamma, coef0,
                         w, b, kind: str, degree: int, include_b: bool,
                         precision_name: str = "HIGHEST"):
    """Featurize one fixed-shape block and dot with the weights — ONE
    program, shared by ``decision_function`` and the serving engine's
    approx decider, so matched shapes are bitwise-identical between
    the two (the SV engine's parity property, kept here).
    ``precision_name``: the serving --precision knob threaded into the
    featurize GEMMs and the phi.w dot (HIGHEST = exact f32 parity,
    the default — ``decision_function`` always evaluates there)."""
    precision = getattr(jax.lax.Precision, precision_name)
    phi = _featurize_block_jit(block, omega_or_landmarks, proj, gamma,
                               coef0, kind=kind, degree=degree,
                               precision_name=precision_name)
    dual = jnp.matmul(phi, w, precision=precision)
    if include_b:
        dual = dual - b
    return dual


def _decider_args(model: ApproxSVMModel):
    fmap = model.fmap
    kind = "rff" if fmap.kind == "rff" else fmap.kernel
    return (_block_args(fmap) + (jnp.asarray(model.w),
                                 jnp.float32(model.b)),
            dict(kind=kind, degree=int(fmap.degree)))


def decision_function(model: ApproxSVMModel, x_test: np.ndarray,
                      include_b: bool = True,
                      batch_size: Optional[int] = 8192) -> np.ndarray:
    """phi(t_i).w [- b], streamed at a fixed block shape."""
    x_test = np.asarray(x_test, np.float32)
    if x_test.ndim == 1:
        x_test = x_test[None, :]
    if x_test.shape[1] != model.num_attributes:
        raise ValueError(
            f"approx evaluation needs {model.num_attributes} "
            f"attributes, got {x_test.shape[1]}")
    args, kw = _decider_args(model)
    m = x_test.shape[0]
    if batch_size is None or m <= batch_size:
        return np.asarray(_approx_decision_jit(
            jnp.asarray(x_test), *args, include_b=include_b, **kw))
    out = np.empty((m,), np.float32)
    block = np.zeros((batch_size, x_test.shape[1]), np.float32)
    for lo in range(0, m, batch_size):
        hi = min(lo + batch_size, m)
        block[: hi - lo] = x_test[lo:hi]
        block[hi - lo:] = 0.0
        out[lo:hi] = np.asarray(_approx_decision_jit(
            jnp.asarray(block), *args, include_b=include_b,
            **kw))[: hi - lo]
    return out


def predict(model: ApproxSVMModel, x_test: np.ndarray,
            include_b: bool = True) -> np.ndarray:
    dec = decision_function(model, x_test, include_b=include_b)
    if model.task == "svr":
        return dec
    return np.where(dec < 0, -1, 1).astype(np.int32)


def save_approx_model(model: ApproxSVMModel, path: str) -> int:
    """Write the one-file .npz; returns 0 (no SV lines exist — callers
    printing the count report the honest zero)."""
    fmap = model.fmap
    arrays = dict(
        format=np.str_(_FORMAT),
        task=np.str_(model.task),
        kind=np.str_(fmap.kind),
        kernel=np.str_(fmap.kernel),
        w=np.asarray(model.w, np.float32),
        b=np.float64(model.b),
        gamma=np.float64(fmap.gamma),
        coef0=np.float64(fmap.coef0),
        degree=np.int64(fmap.degree),
        seed=np.int64(fmap.seed),
        dim=np.int64(fmap.dim),
        d=np.int64(fmap.d),
    )
    if fmap.kind == "nystrom":
        arrays["landmarks"] = np.asarray(fmap.landmarks, np.float32)
        arrays["proj"] = np.asarray(fmap.proj, np.float32)
    import os
    import tempfile
    # tmp + rename: a crash mid-save never leaves a half-written model
    # (the checkpoint writer's policy).
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               suffix=".npz")
    os.close(fd)
    try:
        np.savez(tmp, **arrays)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return 0


def load_approx_model(path: str) -> ApproxSVMModel:
    with np.load(path, allow_pickle=False) as z:
        if "format" not in z.files or str(z["format"]) != _FORMAT:
            raise ValueError(f"{path}: not a dpsvm approx model "
                             "(missing/unknown format marker)")
        kind = str(z["kind"])
        d, dim, seed = int(z["d"]), int(z["dim"]), int(z["seed"])
        gamma = float(z["gamma"])
        if kind == "rff":
            fmap = FeatureMap(kind="rff", d=d, dim=dim, seed=seed,
                              gamma=gamma,
                              omega=rff_omega(d, dim, gamma, seed))
        else:
            fmap = FeatureMap(kind="nystrom", d=d, dim=dim, seed=seed,
                              gamma=gamma, kernel=str(z["kernel"]),
                              coef0=float(z["coef0"]),
                              degree=int(z["degree"]),
                              landmarks=np.asarray(z["landmarks"],
                                                   np.float32),
                              proj=np.asarray(z["proj"], np.float32))
        w = np.asarray(z["w"], np.float32)
        if w.shape != (fmap.dim,):
            raise ValueError(f"{path}: weight vector {w.shape} does not "
                             f"match feature dim {fmap.dim}")
        return ApproxSVMModel(fmap=fmap, w=w, b=float(z["b"]),
                              task=str(z["task"]))


def is_approx_model_file(path: str) -> bool:
    """Approx models are .npz (zip) files; every text model format
    (reference / LIBSVM) cannot start with the zip magic."""
    try:
        with open(path, "rb") as f:
            return f.read(4) == b"PK\x03\x04"
    except OSError:
        return False

"""Kernel approximation subsystem: explicit feature maps + a primal
linear solver — the million-row training path.

The exact SMO/decomposition paths reproduce the paper but are
quadratic in kernel work; this package opens the first workload they
cannot reach (docs/APPROX.md). The pieces:

* ``features`` — Random Fourier Features (RBF) and Nystrom feature
                 maps: deterministic in ``approx_seed``, chunked
                 featurization (X never sits beside its full feature
                 matrix), row-sharded layout over the existing
                 ``parallel/mesh`` axes.
* ``primal``   — squared-hinge SVC / epsilon-insensitive SVR solved by
                 deterministic mini-batch averaged SGD in one compiled
                 ``lax.while_loop`` chunk runner, driven through the
                 shared ``solver/driver.host_training_loop`` — so
                 tracing, packed-stats polls, checkpoints/preemption,
                 health guards and compile accounting work unchanged.
* ``model``    — ``ApproxSVMModel`` (feature map + primal weights, no
                 SV buffers) with one-file ``.npz`` persistence behind
                 the same ``models/io.save_model``/``load_model``
                 entry points, so ``dpsvm test``, CV, multiclass and
                 the serving engine all consume approx models through
                 their existing code paths.

Selected by ``SVMConfig.solver = "approx-rff" | "approx-nystrom"``
(+ ``approx_dim`` / ``approx_seed``; CLI ``train --solver ...``).

CI gate: ``python -m dpsvm_tpu.approx --selfcheck`` — sibling of the
telemetry/resilience/serving gates. Asserts (1) the RFF kernel-
approximation error bound on an embedded sample, and that it shrinks
as approx_dim grows; (2) the jit-compile economy: a second identical
training triggers ZERO new compiles (the chunk-runner builder is
warm); (3) checkpoint/resume bitwise-identity of the final weights;
(4) the cascade gate (solver/cascade.py): screen -> polish -> zero
remaining screened-out KKT violators, plus the bitwise
stage-boundary kill->resume drill at every boundary.
"""

from __future__ import annotations

import sys
from typing import List, Optional

__all__ = ["ApproxSVMModel", "FeatureMap", "build_feature_map",
           "featurize", "fit_approx", "fit_approx_stream",
           "load_approx_model", "save_approx_model", "selfcheck",
           "main"]

_LAZY = {
    "ApproxSVMModel": ("dpsvm_tpu.approx.model", "ApproxSVMModel"),
    "load_approx_model": ("dpsvm_tpu.approx.model", "load_approx_model"),
    "save_approx_model": ("dpsvm_tpu.approx.model", "save_approx_model"),
    "FeatureMap": ("dpsvm_tpu.approx.features", "FeatureMap"),
    "build_feature_map": ("dpsvm_tpu.approx.features",
                          "build_feature_map"),
    "featurize": ("dpsvm_tpu.approx.features", "featurize"),
    "fit_approx": ("dpsvm_tpu.approx.primal", "fit_approx"),
    "fit_approx_stream": ("dpsvm_tpu.approx.primal",
                          "fit_approx_stream"),
}


def __getattr__(name: str):
    """PEP 562 lazy re-exports (the serving package's pattern): jax
    only loads when something actually trains or featurizes."""
    try:
        mod, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute "
                             f"{name!r}") from None
    import importlib
    return getattr(importlib.import_module(mod), attr)


def selfcheck(tmp_dir: Optional[str] = None) -> List[str]:
    """Run the subsystem end to end on an embedded sample; return a
    list of problems (empty = healthy). See module docstring."""
    import dataclasses as _dc
    import os
    import tempfile

    import numpy as np

    problems: List[str] = []
    ctx = tempfile.TemporaryDirectory() if tmp_dir is None else None
    base = tmp_dir if tmp_dir is not None else ctx.name
    try:
        from dpsvm_tpu.approx.features import build_feature_map, featurize
        from dpsvm_tpu.approx.primal import fit_approx
        from dpsvm_tpu.config import SVMConfig
        from dpsvm_tpu.data.synthetic import make_blobs
        from dpsvm_tpu.ops.kernels import KernelSpec

        # 1. RFF error bound, and monotone improvement with dim: the
        # Monte-Carlo kernel estimate tightens as D grows.
        x, y = make_blobs(n=192, d=6, seed=11)
        gamma = 0.25
        spec = KernelSpec(kind="rbf", gamma=gamma, coef0=0.0, degree=3)
        sub = x[:64]
        d2 = (np.sum(sub ** 2, 1)[:, None] - 2.0 * sub @ sub.T
              + np.sum(sub ** 2, 1)[None, :])
        k_exact = np.exp(-gamma * np.maximum(d2, 0.0))
        errs = {}
        for dim in (64, 2048):
            fm = build_feature_map("rff", x, dim, 0, spec)
            phi = featurize(fm, sub)
            errs[dim] = float(np.max(np.abs(phi @ phi.T - k_exact)))
        if errs[2048] > 0.12:
            problems.append(
                f"RFF error bound: max |phi.phi' - K| = {errs[2048]:.3f} "
                "at D=2048 (expected <= 0.12)")
        if errs[2048] >= errs[64]:
            problems.append(
                f"RFF error did not shrink with dim: D=64 -> {errs[64]:.3f}, "
                f"D=2048 -> {errs[2048]:.3f}")

        # 2. Compile economy, read from the run traces (the driver
        # drains compile observations into the trace at poll
        # boundaries — and discards them for untraced runs, so the
        # trace IS the ledger): the first training pays the
        # chunk-runner compile; an identical second run must pay ZERO
        # (warm lru_cached builder + jit cache).
        import json

        def traced_compiles(trace_path):
            with open(trace_path) as fh:
                return sum(1 for ln in fh
                           if json.loads(ln).get("kind") == "compile")

        cfg = SVMConfig(solver="approx-rff", approx_dim=128,
                        approx_seed=3, gamma=gamma, c=1.0,
                        epsilon=1e-3, max_iter=2000, chunk_iters=256)
        t1 = os.path.join(base, "approx_cold.jsonl")
        t2 = os.path.join(base, "approx_warm.jsonl")
        fit_approx(x, y, _dc.replace(cfg, trace_out=t1))
        model2, _ = fit_approx(x, y, _dc.replace(cfg, trace_out=t2))
        if traced_compiles(t1) != 1:
            problems.append(
                f"cold training traced {traced_compiles(t1)} compiles, "
                "expected exactly 1 (the primal chunk runner)")
        if traced_compiles(t2) != 0:
            problems.append(
                f"warm identical training traced {traced_compiles(t2)} "
                "compile(s), expected 0")

        # 3. Checkpoint/resume bitwise identity: a run checkpointed
        # mid-flight and resumed must land on the exact same weights
        # as the uninterrupted run.
        ck = os.path.join(base, "approx_ck.npz")
        full_cfg = _dc.replace(cfg, approx_seed=5, max_iter=600,
                               epsilon=1e-9)
        model_full, _ = fit_approx(x, y, full_cfg)
        half_cfg = _dc.replace(full_cfg, max_iter=300,
                               checkpoint_path=ck, checkpoint_every=100)
        fit_approx(x, y, half_cfg)
        resume_cfg = _dc.replace(full_cfg, resume_from=ck)
        model_res, res = fit_approx(x, y, resume_cfg)
        if res.n_iter != 600:
            problems.append(
                f"resumed run stopped at iter {res.n_iter}, expected 600")
        if not np.array_equal(model_full.w, model_res.w) or \
                model_full.b != model_res.b:
            problems.append(
                "checkpoint/resume is not bitwise-identical: "
                f"max |dw| = "
                f"{float(np.max(np.abs(model_full.w - model_res.w)))}")

        # Round-trip sanity (save -> load -> identical decisions).
        from dpsvm_tpu.approx.model import (decision_function,
                                            load_approx_model,
                                            save_approx_model)
        path = os.path.join(base, "approx_selfcheck.npz")
        save_approx_model(model2, path)
        loaded = load_approx_model(path)
        if not np.array_equal(decision_function(model2, x[:32]),
                              decision_function(loaded, x[:32])):
            problems.append("save/load round trip changed decisions")

        # 4. Cascade gate (solver/cascade.py, docs/APPROX.md
        # "Cascade"): screen -> polish -> ZERO remaining screened-out
        # KKT violators, then the stage-boundary kill->resume drill —
        # a run killed right after each durable stage boundary must
        # resume to a BITWISE-identical model.
        from dpsvm_tpu.resilience import faultinject
        from dpsvm_tpu.solver.cascade import (CascadeInterrupted,
                                              fit_cascade)

        xc, yc = make_blobs(n=320, d=8, seed=23)
        casc_cfg = SVMConfig(solver="cascade", approx_dim=64,
                             c=5.0, gamma=0.25, epsilon=1e-3,
                             max_iter=100_000)
        model_c, res_c = fit_cascade(xc, yc, casc_cfg)
        if not res_c.converged or res_c.kkt_violators != 0:
            problems.append(
                f"cascade gate: converged={res_c.converged}, "
                f"{res_c.kkt_violators} screened-out KKT violator(s) "
                "after repair (expected a converged run with zero)")
        if not (0 < res_c.n_kept <= 320):
            problems.append(
                f"cascade gate: implausible kept count {res_c.n_kept}")
        prior_plan = faultinject.current()
        try:
            for stage in (1, 2, 3):
                ck = os.path.join(base, f"casc_s{stage}.npz")
                cfg_k = _dc.replace(casc_cfg, checkpoint_path=ck)
                faultinject.install(faultinject.FaultPlan(
                    cascade_stop_stage=stage))
                try:
                    fit_cascade(xc, yc, cfg_k)
                    problems.append(
                        f"cascade stage-{stage} kill point never fired")
                except CascadeInterrupted:
                    pass
                faultinject.install(None)
                model_r, _res_r = fit_cascade(xc, yc, cfg_k)
                if not (np.array_equal(model_c.alpha, model_r.alpha)
                        and np.array_equal(model_c.x_sv, model_r.x_sv)
                        and model_c.b == model_r.b):
                    problems.append(
                        f"cascade stage-{stage} kill->resume is not "
                        "bitwise-identical to the uninterrupted run")
        finally:
            faultinject.install(prior_plan)
    except Exception as e:                      # pragma: no cover
        problems.append(f"selfcheck crashed: {type(e).__name__}: {e}")
    finally:
        if ctx is not None:
            ctx.cleanup()
    return problems


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="python -m dpsvm_tpu.approx")
    p.add_argument("--selfcheck", action="store_true",
                   help="run the kernel-approximation subsystem gate "
                        "(docs/APPROX.md)")
    args = p.parse_args(argv)
    if not args.selfcheck:
        p.print_help()
        return 2
    problems = selfcheck()
    if problems:
        print("approx selfcheck FAILED:", file=sys.stderr)
        for q in problems:
            print(f"  - {q}", file=sys.stderr)
        return 1
    print("approx selfcheck OK (RFF error bound + monotone dim "
          "improvement, zero warm-path recompiles, bitwise "
          "checkpoint/resume, save/load parity, cascade "
          "screen->polish->zero-violators + bitwise stage-boundary "
          "resume)")
    return 0
